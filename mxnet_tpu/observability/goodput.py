"""Goodput accounting + analytic FLOPs/MFU model (docs/OBSERVABILITY.md
"Fleet view").

Two independent pieces that the fleet report composes:

  - the **goodput ledger** — classify every interval of a run's wall clock
    into a small, exhaustive taxonomy (productive training, checkpoint
    save, restore, re-formation downtime, data stall, idle) from the
    events the subsystems already emit. The ledger is a boundary sweep
    over the classified intervals, so the buckets partition wall time
    exactly: ``sum(buckets) == wall`` by construction, and
    ``goodput = train / wall``;

  - the **FLOPs model** — price every dot-like op of a
    :class:`~mxnet_tpu.analysis.ProgramReport` from its parsed
    contraction structure ("Operator Fusion in XLA", arXiv:2301.13062:
    op-level cost accounting as the substrate for optimization
    decisions). ``TrainStep`` uses it to export model FLOPs/step and —
    against the ``peak_flops`` config knob (``MXNET_TPU_PEAK_FLOPS``) —
    the ``train_mfu`` gauge.

Cost convention (dot-like ops only — elementwise traffic is not model
FLOPs):

  ============  =========================================================
  dot_general   2 x prod(result shape) x prod(lhs contracted dim sizes)
  convolution   2 x prod(result shape) x prod(kernel) / kernel_out_dim
                / batch_group_count  (= multiply-accumulates per output
                element; feature groups already fold into the kernel's
                input-feature dim)
  ============  =========================================================

Dots whose contraction attributes could not be parsed (or parsed
inconsistently with the operand shapes) fall back to the sqrt-derived
contracted size (exact for unbatched dots, approximate for batched ones)
and are counted in ``FlopsEstimate.n_approx``; a convolution whose kernel
layout could not be parsed has no usable fallback and is counted in
``FlopsEstimate.n_unpriced`` (contributing zero — the estimate is then a
lower bound).

A ``lax.scan`` body appears ONCE in the program text, so the census of a
fused k-step window program is the FLOPs of one step (one microbatch when
``accum`` > 1) — callers multiply back up (``TrainStep`` does).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["FlopsEstimate", "op_flops", "program_flops",
           "GoodputReport", "classify_events", "goodput_ledger",
           "GOODPUT_CATEGORIES"]

_DOT_LIKE = ("dot_general", "dot", "convolution")


# -- FLOPs model -------------------------------------------------------------
@dataclasses.dataclass
class FlopsEstimate:
    """Analytic FLOPs of one program's dot census."""

    total: float = 0.0
    by_op: Dict[str, float] = dataclasses.field(default_factory=dict)
    n_dots: int = 0
    n_approx: int = 0  # dots priced via the sqrt fallback
    n_unpriced: int = 0  # dot-like ops with no priceable structure at all

    def summary(self) -> dict:
        return {"total": self.total, "by_op": dict(self.by_op),
                "n_dots": self.n_dots, "n_approx": self.n_approx,
                "n_unpriced": self.n_unpriced}


def _prod(shape: Sequence[int]) -> int:
    out = 1
    for d in shape:
        out *= d
    return out


def _price(op) -> Tuple[Optional[float], bool]:
    """(flops, exact) for one dot-like op; (None, False) when the op has
    no priceable structure (non-dot, too few tensors, unparsed conv)."""
    if op.name not in _DOT_LIKE or len(op.shapes) < 3:
        return None, False
    lhs, rhs, result = op.shapes[0], op.shapes[-2], op.shapes[-1]
    meta = op.dot_meta
    if op.name == "convolution":
        # a conv's per-output multiply count needs the kernel layout; the
        # dot fallback's sqrt identity does not hold for windowed
        # contractions, so an unparsed conv stays unpriced
        if meta is None or meta["kernel_out_dim"] >= len(rhs):
            return None, False
        out_features = rhs[meta["kernel_out_dim"]] or 1
        return (2.0 * _prod(result) * _prod(rhs) / out_features
                / max(1, meta.get("batch_groups", 1))), True
    if meta is not None and all(d < len(lhs)
                                for d in meta["lhs_contracting"]):
        contracted = _prod([lhs[d] for d in meta["lhs_contracting"]])
        return 2.0 * _prod(result) * contracted, True
    # fallback: prod(lhs)*prod(rhs)/prod(result) == K^2 for an unbatched
    # dot (overcounts batched dots by sqrt(batch) — flagged as approx)
    denom = _prod(result) or 1
    return 2.0 * _prod(result) * math.sqrt(
        max(0.0, _prod(lhs) * _prod(rhs) / denom)), False


def op_flops(op) -> Optional[float]:
    """Analytic FLOPs of one dot-like :class:`~mxnet_tpu.analysis.Op`
    (None for non-dot ops or unpriceable lines)."""
    return _price(op)[0]


def program_flops(report) -> FlopsEstimate:
    """Price every dot-like op of a :class:`ProgramReport` (use the
    *lowered* report: compiled HLO hides dots inside fusions)."""
    est = FlopsEstimate()
    for op in report.ops:
        if op.name not in _DOT_LIKE:
            continue
        f, exact = _price(op)
        if f is None:
            est.n_unpriced += 1
            continue
        est.n_dots += 1
        if not exact:
            est.n_approx += 1
        est.total += f
        est.by_op[op.name] = est.by_op.get(op.name, 0.0) + f
    return est


# -- goodput ledger ----------------------------------------------------------
#: interval taxonomy, highest classification priority first — when two
#: classified intervals overlap, the earlier category wins the overlap
#: (the most *specific* classification first: a checkpoint restore inside
#: the re-formation gap is restore time, the rest of the gap downtime)
GOODPUT_CATEGORIES = ("restore", "checkpoint", "reformation", "data_stall",
                      "train", "idle")

# event name -> (category, duration payload field); the interval is
# [ts - duration, ts] (every emitter stamps ts at the END of the region)
_EVENT_INTERVALS = {
    "train_step": ("train", "step_seconds"),
    "train_window": ("train", "window_seconds"),
    "checkpoint_save": ("checkpoint", "seconds"),
    "checkpoint_restore": ("restore", "seconds"),
    "elastic_restore": ("restore", "seconds"),
}


@dataclasses.dataclass
class GoodputReport:
    """Wall-clock partition of one run (buckets sum to ``wall`` exactly)."""

    wall_start: float
    wall_end: float
    buckets: Dict[str, float]
    n_intervals: int = 0

    @property
    def wall(self) -> float:
        return self.wall_end - self.wall_start

    @property
    def goodput(self) -> float:
        """Fraction of wall time spent in productive training steps."""
        return (self.buckets.get("train", 0.0) / self.wall) if self.wall > 0 \
            else 0.0

    def summary(self) -> dict:
        return {"wall_seconds": round(self.wall, 6),
                "goodput": round(self.goodput, 6),
                "buckets": {k: round(v, 6)
                            for k, v in sorted(self.buckets.items())},
                "n_intervals": self.n_intervals}


def classify_events(events: Sequence[dict],
                    generation_key: str = "_gen"
                    ) -> List[Tuple[str, float, float]]:
    """Turn an event stream into classified ``(category, start, end)``
    intervals. Re-formation downtime is the fleet-level gap between the
    last event of generation g and the first event of generation g+1
    (events tagged by the aggregator with ``generation_key``)."""
    out: List[Tuple[str, float, float]] = []
    gen_span: Dict[int, Tuple[float, float]] = {}
    for e in events:
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        g = e.get(generation_key)
        if isinstance(g, int):
            lo, hi = gen_span.get(g, (ts, ts))
            gen_span[g] = (min(lo, ts), max(hi, ts))
        kind = _EVENT_INTERVALS.get(e.get("event"))
        if kind is not None:
            cat, field = kind
            dur = e.get(field)
            if isinstance(dur, (int, float)) and dur > 0:
                out.append((cat, ts - dur, ts))
            continue
        if e.get("event") == "data_stall":
            dur = e.get("wait_seconds")
            if isinstance(dur, (int, float)) and dur > 0:
                out.append(("data_stall", ts - dur, ts))
    gens = sorted(gen_span)
    for a, b in zip(gens, gens[1:]):
        end_prev, start_next = gen_span[a][1], gen_span[b][0]
        if start_next > end_prev:
            out.append(("reformation", end_prev, start_next))
    return out


def goodput_ledger(events: Sequence[dict],
                   generation_key: str = "_gen") -> Optional[GoodputReport]:
    """Build the wall-clock ledger for one (merged) event stream: a
    boundary sweep over the classified intervals, residual time = idle.
    Returns None when the stream holds no usable timestamps."""
    ts_all = [e["ts"] for e in events
              if isinstance(e.get("ts"), (int, float))]
    if not ts_all:
        return None
    intervals = classify_events(events, generation_key=generation_key)
    wall_start = min(ts_all + [s for _c, s, _e in intervals])
    wall_end = max(ts_all + [e for _c, _s, e in intervals])
    buckets = {c: 0.0 for c in GOODPUT_CATEGORIES}
    if wall_end <= wall_start:
        return GoodputReport(wall_start, wall_end, buckets, len(intervals))
    # boundary sweep with per-category active counters — every elementary
    # segment belongs to exactly one bucket (the highest-priority interval
    # covering it, else idle), so the buckets partition wall time with no
    # double counting; O(n log n), so the supervisor's poll cadence stays
    # cheap on runs with tens of thousands of step intervals
    points: List[Tuple[float, int, str]] = []
    for c, s, e in intervals:
        s = max(wall_start, min(wall_end, s))
        e = max(wall_start, min(wall_end, e))
        if e > s:
            points.append((s, 1, c))
            points.append((e, -1, c))
    points.sort(key=lambda p: p[0])
    bounds = sorted({wall_start, wall_end} | {p[0] for p in points})
    active = {c: 0 for c in GOODPUT_CATEGORIES}
    i = 0
    for a, b in zip(bounds, bounds[1:]):
        while i < len(points) and points[i][0] <= a:
            _t, d, c = points[i]
            active[c] += d
            i += 1
        best = "idle"
        for c in GOODPUT_CATEGORIES[:-1]:  # priority order, idle = residual
            if active[c] > 0:
                best = c
                break
        buckets[best] += b - a
    return GoodputReport(wall_start, wall_end, buckets, len(intervals))
