"""Multi-replica serving tier: telemetry-driven routing, health-gated
drain/replace (docs/INFERENCE.md "Fleet serving").

Every serving-resilience mechanism below this layer (deadlines, shed,
watchdog, degrade-to-safe speculation — PR 15) protects exactly one
engine on one chip; a wedged replica is still a total outage for every
request routed at it. This package is the thin policy tier over
*unmodified* engines (the TVM/Relay deploy-tier split: routing policy
stays declarative above the compiled engines, never inside them):

  - :class:`ServingReplica` (``replica.py``) — wraps one
    :class:`~mxnet_tpu.inference.ContinuousBatcher` behind a replica id
    and publishes its health signals (free pages, admission-queue depth,
    live queue-age p95, stuck-dispatch count) plus a liveness heartbeat
    through the FleetSnapshotter shared-dir transport
    (``{fleet_dir}/telemetry-h{replica}/metrics-g{gen}.json``) — the
    router trusts only what a replica *published*, exactly what a
    multi-process deployment would see.
  - :class:`FleetRouter` (``router.py``) — admits by priority class,
    load-balances with power-of-two-choices over a free-pages/queue-age
    score computed from the published telemetry, keeps session affinity
    (multi-turn traffic lands on the replica holding its prefix pages),
    and re-enqueues in-deadline requests pulled back from a draining or
    lost replica.
  - :class:`FleetHealth` (``health.py``) — per-replica state machine
    ``LIVE -> DEGRADED -> DRAINING -> DEAD``: missed heartbeats or a
    ``gen_stuck_dispatch`` attribution degrade a replica; a persistently
    degraded replica is drained (no new admissions, in-flight finish or
    expire, queued work redistributed) and finally declared dead.

``make chaos-fleet`` (tools/servedrill.py ``--fleet``) is the tier-level
gate: one replica killed and one wedged mid-burst must lose zero
in-deadline requests, walk the wedged replica through
DEGRADED→DRAINING→DEAD with its work redistributed, and leave the
survivors fully drained with explicit finish reasons everywhere.
"""
from __future__ import annotations

from . import health, replica, router  # noqa: F401
from .health import (DEAD, DEGRADED, DRAINING, LIVE,  # noqa: F401
                     STATE_CODES, STATE_NAMES, FleetHealth, ReplicaHealth)
from .replica import ServingReplica, read_fleet_views  # noqa: F401
from .router import FleetRouter, RouterRequest  # noqa: F401

__all__ = ["ServingReplica", "read_fleet_views", "FleetRouter",
           "RouterRequest", "FleetHealth", "ReplicaHealth",
           "LIVE", "DEGRADED", "DRAINING", "DEAD",
           "STATE_CODES", "STATE_NAMES", "replica", "router", "health"]
