"""Multi-process distributed: N local processes over jax.distributed
(SURVEY §4 fixture #5 — the reference tested ps-lite with N localhost
processes the same way)."""
import os
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist_init
    dist_init()
    assert jax.process_count() == 2, jax.process_count()

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rank = jax.process_index()
    kv = mx.kv.create("dist_sync")
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.full((4,), float(rank + 1)))   # 1 + 2 = 3
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    expected = 3.0
    assert abs(float(out.asnumpy()[0]) - expected) < 1e-6, out.asnumpy()

    import mxnet_tpu.horovod as hvd
    s = hvd.allreduce(nd.full((2,), float(rank)), average=True)  # (0+1)/2
    assert abs(float(s.asnumpy()[0]) - 0.5) < 1e-6
    assert hvd.local_rank() == rank and hvd.local_size() == 2

    # batched grad reduction: a full Trainer.step must issue exactly ONE
    # cross-process collective for the whole parameter list
    from jax.experimental import multihost_utils
    calls = []
    orig_ag = multihost_utils.process_allgather
    multihost_utils.process_allgather = lambda *a, **k: (calls.append(1), orig_ag(*a, **k))[1]

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(5, in_units=3), nn.Dense(2, in_units=5))
    net.initialize()
    tr = hvd.DistributedTrainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
    x = nd.full((2, 3), float(rank + 1))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    calls.clear()
    tr.step(2)
    multihost_utils.process_allgather = orig_ag
    assert len(calls) == 1, f"expected 1 collective for 4 params, got {len(calls)}"

    print(f"RANK{rank}-OK", flush=True)
""")


@pytest.mark.timeout(180)
def test_two_process_dist_sync(tmp_path):
    child = tmp_path / "child.py"
    child.write_text(_CHILD)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root
    res = subprocess.run(
        [sys.executable, "tools/launch.py", "-n", "2", sys.executable, str(child)],
        capture_output=True, text=True, timeout=170, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-2000:]
    assert "RANK0-OK" in out and "RANK1-OK" in out, out[-2000:]
