"""Device mesh construction.

Axes follow the scaling-book convention: ``dp`` (data), ``fsdp`` (optional
param/optimizer sharding on the data axis), ``tp`` (tensor/model), ``sp``
(sequence/context), ``pp`` (pipeline stages), ``ep`` (experts). A config
names the axes it uses; unused axes have size 1 and cost nothing.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["MeshConfig", "make_mesh", "local_mesh", "refit_config"]

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


@dataclasses.dataclass
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    @property
    def total(self) -> int:
        return math.prod(self.sizes())

    @staticmethod
    def auto(n_devices: int, tp: int = 1, sp: int = 1) -> "MeshConfig":
        """All leftover devices go to dp (the ResNet/BERT DP default)."""
        rest = n_devices // (tp * sp)
        return MeshConfig(dp=rest, tp=tp, sp=sp)


def make_mesh(config: Optional[MeshConfig] = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig(dp=len(devices))
    if config.total < len(devices):
        devices = devices[: config.total]
    if config.total != len(devices):
        raise ValueError(f"mesh {config} needs {config.total} devices, "
                         f"got {len(devices)}")
    arr = np.asarray(devices).reshape(config.sizes())
    return Mesh(arr, AXES)


def local_mesh(n: Optional[int] = None, **axis_sizes) -> Mesh:
    """Mesh over the first n local devices (test/dry-run helper)."""
    devs = jax.devices()[: n or len(jax.devices())]
    cfg = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig(dp=len(devs))
    return make_mesh(cfg, devs)


def refit_config(config: MeshConfig, n_devices: int) -> MeshConfig:
    """Scale a mesh config to a new device count (elastic re-formation).

    The re-formation rule: world-size changes resize the *data* axes only
    (``dp``/``fsdp`` — state along them is resharded from the checkpoint
    manifest), while the model axes (``tp``/``sp``/``pp``/``ep``) encode
    how the network is cut up and must survive unchanged — a world that
    can't hold them is an error, not a silent re-partition.

    The data capacity goes to ``fsdp`` when the old config sharded state
    there (keeping the ZeRO layout, at the new width), else to ``dp``.
    """
    model = config.tp * config.sp * config.pp * config.ep
    if n_devices % model != 0:
        raise ValueError(
            f"cannot re-form: model axes need multiples of {model} devices "
            f"(tp={config.tp} sp={config.sp} pp={config.pp} ep={config.ep}), "
            f"got {n_devices}")
    data = n_devices // model
    new = dataclasses.replace(config)
    if config.fsdp > 1:
        if config.dp > 1 and data % config.fsdp == 0:
            new.fsdp, new.dp = config.fsdp, data // config.fsdp
        else:
            new.fsdp, new.dp = data, 1
    else:
        new.dp, new.fsdp = data, 1
    return new
