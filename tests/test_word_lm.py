"""Word-language-model example smoke: the LSTM LM trains to a falling loss
on the synthetic corpus (reference shape: example/gluon/word_language_model)."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


def test_synthetic_corpus_and_batchify():
    from train_word_lm import batchify, synthetic_corpus

    corpus = synthetic_corpus(n_tokens=1000, vocab=50)
    assert corpus.dtype == np.int32
    assert corpus.min() >= 0 and corpus.max() < 50
    data = batchify(corpus, 8)
    assert data.shape == (1000 // 8, 8)


@pytest.mark.slow
def test_word_lm_trains_to_falling_loss():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from train_word_lm import RNNModel, batchify, synthetic_corpus

    mx.random.seed(0)
    corpus = synthetic_corpus(n_tokens=4000, vocab=40)
    vocab = int(corpus.max()) + 1
    data = batchify(corpus, 8)
    model = RNNModel(vocab, embed_size=32, hidden_size=32, num_layers=1,
                     dropout=0.0)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    bptt = 10
    losses = []
    for i in range(0, min(data.shape[0] - 1 - bptt, 15 * bptt), bptt):
        x = nd.array(data[i:i + bptt], dtype="int32")
        y = nd.array(data[i + 1:i + 1 + bptt], dtype="int32")
        with autograd.record():
            out = model(x)
            loss = loss_fn(out.reshape(-1, vocab), y.reshape(-1))
        loss.backward()
        trainer.step(x.shape[1])
        losses.append(float(loss.mean().asnumpy()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.1, losses


def test_word_lm_tied_weights():
    import mxnet_tpu as mx
    from train_word_lm import RNNModel

    import pytest

    with pytest.raises(ValueError):
        RNNModel(100, embed_size=32, hidden_size=64, tie_weights=True)
    m = RNNModel(50, embed_size=32, hidden_size=32, tie_weights=True)
    m.initialize(mx.init.Xavier())
    from mxnet_tpu import nd

    out = m(nd.array(np.zeros((5, 2), np.int32), dtype="int32"))
    assert out.shape == (5, 2, 50)
    # decoder weight IS the embedding table (shared Parameter object)
    enc_w = m.encoder.params.get("weight")
    dec_w = m.decoder.params.get("weight")
    assert enc_w is dec_w
    # the tie lives under each sharer's local name, and collect_params
    # dedupes by object identity — Trainer must see the table exactly once
    # (no double optimizer state / double allreduce)
    all_params = m.collect_params()
    hits = [n for n, p in all_params.items() if p is enc_w]
    assert len(hits) == 1, hits
