"""Image augmentation pipeline (reference: ``python/mxnet/image/image.py``).

The reference's augmenters are host-side OpenCV calls. Here they are
jax-array ops (device or host), with the same composable Augmenter list
protocol so ``ImageIter``-style pipelines port.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .ndarray import NDArray, array

__all__ = ["imresize", "resize_short", "center_crop", "random_crop",
           "color_normalize", "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "RandomCropAug", "CenterCropAug", "ResizeAug", "CreateAugmenter"]


def _raw(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def imresize(src, w, h, interp=1):
    x = _raw(src).astype(jnp.float32)
    out = jax.image.resize(x, (h, w, x.shape[2]), method="linear")
    return NDArray(out.astype(_raw(src).dtype))


def resize_short(src, size, interp=1):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def center_crop(src, size, interp=1):
    h, w = src.shape[:2]
    cw, ch = size
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    out = src[y0:y0 + ch, x0:x0 + cw]
    return out, (x0, y0, cw, ch)


def random_crop(src, size, interp=1):
    h, w = src.shape[:2]
    cw, ch = size
    x0 = np.random.randint(0, w - cw + 1)
    y0 = np.random.randint(0, h - ch + 1)
    return src[y0:y0 + ch, x0:x0 + cw], (x0, y0, cw, ch)


def color_normalize(src, mean, std=None):
    out = _raw(src).astype(jnp.float32) - _raw(mean)
    if std is not None:
        out = out / _raw(std)
    return NDArray(out)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return NDArray(jnp.flip(_raw(src), axis=1))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = jnp.asarray(mean), jnp.asarray(std)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_mirror=False,
                    mean=None, std=None, **kwargs):
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = (data_shape[2], data_shape[1])
    auglist.append(RandomCropAug(crop_size) if rand_crop else CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std if std is not None else 1.0))
    return auglist
