"""Model zoo forward shapes (reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

from mxnet_tpu import gluon, nd


@pytest.mark.parametrize("name,size", [
    ("resnet34_v2", 32), ("vgg11", 32), ("vgg11_bn", 32),
    ("mobilenet0.25", 32), ("mobilenetv2_0.5", 32),
    ("squeezenet1.1", 64), ("densenet121", 32), ("alexnet", 224),
    ("inceptionv3", 299),
])
def test_zoo_forward(name, size):
    net = gluon.model_zoo.get_model(name, classes=11)
    net.initialize()
    out = net(nd.ones((1, 3, size, size)))
    assert out.shape == (1, 11), name


def test_zoo_unknown_model():
    with pytest.raises(ValueError, match="not in zoo"):
        gluon.model_zoo.get_model("resnext9000")
