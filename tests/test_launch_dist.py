"""Multi-process distributed: N local processes over jax.distributed
(SURVEY §4 fixture #5 — the reference tested ps-lite with N localhost
processes the same way)."""
import os
import subprocess
import sys
import textwrap

import pytest

# One launch, many assertions (reference: tests/nightly/dist_sync_kvstore.py
# style — round-4 verdict ask #9 folded the old n=2 child's checks in here).
_CHILD4 = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist_init
    dist_init()
    N = 4
    assert jax.process_count() == N, jax.process_count()

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rank = jax.process_index()

    # --- 1. sync: push REPLACES with the per-step all-worker sum ----------
    kv = mx.kv.create("dist_sync")
    kv.init("w", nd.zeros((4,)))
    for step in range(3):
        kv.push("w", nd.full((4,), float(rank + 1)))   # 1+2+3+4 = 10
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        assert abs(float(out.asnumpy()[0]) - 10.0) < 1e-6, out.asnumpy()

    # --- 2. async: pushes ACCUMULATE across steps (no replace barrier) ----
    kva = mx.kv.create("dist_async")
    kva.init("a", nd.zeros((2,)))
    for step in range(3):
        kva.push("a", nd.full((2,), float(rank + 1)))
    out = nd.zeros((2,))
    kva.pull("a", out=out)
    # 3 steps x sum(1..4) accumulated, NOT replaced
    assert abs(float(out.asnumpy()[0]) - 30.0) < 1e-6, out.asnumpy()

    # --- 3. 2-bit compression with error feedback converges at n=4 --------
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.1})
    target = 2.0
    w = 0.0
    kvc.init("g", nd.zeros((1,)))
    lr = 0.2
    for step in range(80):
        grad = (w - target) / N  # same grad on all workers, tiny magnitude
        kvc.push("g", nd.full((1,), grad))
        out = nd.zeros((1,))
        kvc.pull("g", out=out)
        w = w - lr * float(out.asnumpy()[0])
    # quantized to +-threshold with residual carry: must still converge near
    assert abs(w - target) < 0.05, w

    # --- 4. row_sparse pull at n=4 ----------------------------------------
    from mxnet_tpu.ndarray import sparse as sp
    kvr = mx.kv.create("dist_sync")
    table = np.arange(12, dtype=np.float32).reshape(6, 2)
    kvr.init("emb", nd.array(table))
    rows = nd.array(np.array([1, 4]), dtype="int32")
    out_r = sp.zeros("row_sparse", (6, 2))
    got = kvr.row_sparse_pull("emb", out=out_r, row_ids=rows)
    vals = np.asarray(jax.device_get(got._data if hasattr(got, "_data") else out_r._data))
    np.testing.assert_allclose(vals, table[[1, 4]], rtol=1e-6)

    # --- 5. horovod allreduce + one-collective-per-step Trainer (folded
    # from the retired n=2 child; identical semantics at n=4) --------------
    import mxnet_tpu.horovod as hvd
    s = hvd.allreduce(nd.full((2,), float(rank)), average=True)  # mean(0..3)
    assert abs(float(s.asnumpy()[0]) - 1.5) < 1e-6
    assert hvd.local_rank() == rank and hvd.local_size() == N

    # batched grad reduction: a full Trainer.step must issue exactly ONE
    # cross-process collective for the whole parameter list
    from jax.experimental import multihost_utils
    calls = []
    orig_ag = multihost_utils.process_allgather
    multihost_utils.process_allgather = lambda *a, **k: (calls.append(1), orig_ag(*a, **k))[1]

    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(5, in_units=3), nn.Dense(2, in_units=5))
    net.initialize()
    tr = hvd.DistributedTrainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
    x = nd.full((2, 3), float(rank + 1))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    calls.clear()
    tr.step(2)
    multihost_utils.process_allgather = orig_ag
    assert len(calls) == 1, f"expected 1 collective for 4 params, got {len(calls)}"

    # --- 6. observability: KVStore byte/latency metrics on the REAL
    # multi-process DCN path (ISSUE 2 acceptance) --------------------------
    from mxnet_tpu import observability as obs
    obs.enable(os.path.join(os.environ["OBS_DIR"]))
    kv.push("w", nd.full((4,), float(rank + 1)))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    lat = obs.REGISTRY.get("kv_psum_seconds")
    assert lat is not None and lat.stats(op="psum")["count"] >= 1
    assert lat.stats(op="psum")["sum"] > 0
    assert obs.REGISTRY.get("kv_psum_bytes_total").value(op="psum") == 16  # 4xf32
    # the batched Trainer path again, instrumented this time
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    tr.step(2)
    assert lat.stats(op="psum_batch")["count"] >= 1
    assert obs.REGISTRY.get("kv_psum_dtype_buckets_total").value(dtype="float32") == 4
    obs.shutdown()

    # --- 7. sharding/comm audit on the REAL 4-process dp mesh (ISSUE 8):
    # zero contract violations, and the dp gradient all-reduce spans ONLY
    # the dp axis moving exactly 2 x (param + loss) bytes ------------------
    from mxnet_tpu import optimizer
    from mxnet_tpu.parallel import MeshConfig, TrainStep, make_mesh

    mesh = make_mesh(MeshConfig(dp=4))
    mx.random.seed(3)
    anet = nn.HybridSequential()
    anet.add(nn.Dense(5, in_units=3), nn.Dense(2, in_units=5))
    anet.initialize()
    ats = TrainStep(anet, lambda o, y: ((o - y) ** 2).mean(),
                    optimizer.SGD(learning_rate=0.1), mesh=mesh)
    audit = ats.audit(nd.ones((4, 3)), nd.zeros((4, 2)))
    assert audit.contract == [], [str(v) for v in audit.contract]
    comm = audit.comm
    assert comm and comm.costs, "empty CommReport on the dp mesh"
    ars = [c for c in comm.costs if c.kind == "all_reduce"]
    assert ars, comm.summary()
    assert all(c.axes == ("dp",) for c in ars), \
        [(c.kind, c.axes) for c in comm.costs]
    param_bytes = sum(int(np.prod(v.shape)) * 4 for v in ats.params.values())
    want = 2 * (param_bytes + 4)   # grads + the scalar loss psum
    got = sum(c.bytes for c in ars)
    assert got == want, (got, want, comm.summary())
    assert comm.by_axis() == {"dp": got}, comm.by_axis()

    print(f"RANK{rank}-OK4", flush=True)
""")


# Elastic chaos drill child (docs/RESILIENCE.md "Elastic training"): a
# deterministic fsdp-sharded Adam run whose batches depend only on the step
# number, so a re-formed generation replays the exact trajectory from its
# restore point. Gen 0 SIGKILLs DRILL_KILL_RANK at DRILL_KILL_STEP.
_ELASTIC_CHILD = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import json
    import signal

    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist_init
    dist_init()

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, observability as obs, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import (MeshConfig, ShardingRules, TrainStep,
                                    make_mesh)
    from mxnet_tpu.resilience import elastic

    rank = jax.process_index()
    world = jax.process_count()

    CKPT = os.environ["DRILL_CKPT"]
    OUT = os.environ["DRILL_OUT"]
    LOSSES = os.environ["DRILL_LOSSES"]
    TOTAL = int(os.environ.get("DRILL_STEPS", "12"))
    SAVE_EVERY = int(os.environ.get("DRILL_SAVE_EVERY", "3"))
    KILL_RANK = int(os.environ.get("DRILL_KILL_RANK", "-1"))
    KILL_STEP = int(os.environ.get("DRILL_KILL_STEP", "-1"))

    ctx = elastic.context()
    gen = ctx.generation if ctx else 0
    obs.enable(os.path.join(os.environ["DRILL_OBS"], f"g{gen}-r{rank}"))
    if ctx:
        ctx.start()
        ctx.install_preemption()

    # deterministic model: same init whatever the generation or world size
    mx.random.seed(11)
    net = nn.HybridSequential()
    net.add(nn.Dense(24, in_units=12, activation="relu"),
            nn.Dense(12, in_units=24))
    net.initialize()
    _ = net(nd.ones((2, 12)))

    mesh = make_mesh(MeshConfig(fsdp=world))
    rules = ShardingRules(fsdp_axis="fsdp", min_fsdp_size=1)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    ts = TrainStep(net, lambda o, y: loss_fn(o, y),
                   optimizer.Adam(learning_rate=1e-2), mesh=mesh,
                   rules=rules)


    def batch(step):
        rng = np.random.RandomState(1000 + step)
        x = rng.randn(12, 12).astype(np.float32)
        y = rng.randint(0, 12, size=(12,)).astype(np.float32)
        return nd.array(x), nd.array(y)


    def _restore():
        ts.restore(CKPT)
        return int(ts.optimizer.num_update)


    if ctx is not None and gen > 0:
        start = ctx.resume(_restore)  # times + announces elastic_restore
    else:
        ts.restore(CKPT)
        start = int(ts.optimizer.num_update)

    for step in range(start + 1, TOTAL + 1):
        if gen == 0 and rank == KILL_RANK and step == KILL_STEP:
            os.kill(os.getpid(), signal.SIGKILL)
        x, y = batch(step)
        try:
            loss = ts(x, y)
            lval = float(np.asarray(loss))
            if step % SAVE_EVERY == 0:
                ts.save(CKPT)
        except SystemExit:
            raise
        except Exception as e:  # peer died mid-collective: ask to re-form
            if ctx is not None:
                elastic.exit_for_reform(f"step_error:{type(e).__name__}")
            raise

        if rank == 0:
            with open(LOSSES, "a") as f:
                f.write(json.dumps({"step": step, "loss": lval, "gen": gen,
                                    "world": world}) + "\\n")
        if ctx is not None:
            ctx.check()  # peer loss / preemption -> ReformExit(75)

    from jax.experimental import multihost_utils

    # collective: every rank participates in the gather; rank 0 writes
    params = {k: multihost_utils.process_allgather(v, tiled=True).tolist()
              for k, v in sorted(ts.params.items())}
    if rank == 0:
        reformations = 0.0
        if ctx is not None and gen > 0:
            reformations = obs.REGISTRY.get(
                "mesh_reformations_total").value(
                    cause=ctx.cause or "unknown")
        with open(OUT, "w") as f:
            json.dump({"gen": gen, "world": world,
                       "num_update": int(ts.optimizer.num_update),
                       "params": params, "reformations": reformations}, f)
    print(f"DRILL-RANK{rank}-DONE gen={gen} world={world}", flush=True)
""")


def _run_drill(tmp, name, elastic_args=(), kill_rank=-1, kill_step=-1):
    """One supervised drill run; returns (result, out.json dict, losses)."""
    import json

    d = tmp / name
    d.mkdir(parents=True, exist_ok=True)
    child = d / "child.py"
    child.write_text(_ELASTIC_CHILD)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root
    env.update({
        "DRILL_CKPT": str(d / "ckpt"), "DRILL_OUT": str(d / "out.json"),
        "DRILL_LOSSES": str(d / "losses.jsonl"), "DRILL_OBS": str(d / "obs"),
        "DRILL_KILL_RANK": str(kill_rank), "DRILL_KILL_STEP": str(kill_step),
        # the world-size-agnostic manifest format + a fast failover window
        "MXNET_TPU_CKPT_SHARDED": "1", "MXNET_TPU_ELASTIC_HB_TIMEOUT": "3",
        # fleet view (ISSUE 9): a test-owned fleet dir (the supervisor's
        # default lives under its heartbeat tempdir and is removed with
        # it) + a snapshot cadence fast enough for a 12-step drill
        "MXNET_TPU_FLEET_DIR": str(d / "fleet"),
        "MXNET_TPU_FLEET_SNAPSHOT_INTERVAL": "0.5",
    })
    res = subprocess.run(
        [sys.executable, "tools/launch.py", "-n", "4", *elastic_args,
         sys.executable, str(child)],
        capture_output=True, text=True, timeout=280, env=env, cwd=repo_root)
    out = losses = None
    if (d / "out.json").exists():
        out = json.loads((d / "out.json").read_text())
    if (d / "losses.jsonl").exists():
        losses = {}
        for line in (d / "losses.jsonl").read_text().splitlines():
            r = json.loads(line)
            losses[r["step"]] = r["loss"]  # replayed steps: last write wins
    return res, out, losses


@pytest.fixture(scope="module")
def _elastic_baseline(tmp_path_factory):
    """The never-killed 4-process run every drill compares against."""
    res, out, losses = _run_drill(
        tmp_path_factory.mktemp("elastic"), "base")
    assert res.returncode == 0, (res.stdout + res.stderr)[-3000:]
    assert out is not None and losses is not None
    return out, losses


@pytest.mark.timeout(600)
@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("policy,expect_world", [("replace", 4),
                                                 ("shrink", 3)])
def test_chaos_elastic_kill_worker(tmp_path, _elastic_baseline, policy,
                                   expect_world):
    """`make chaos-elastic` (ISSUE 7 acceptance): SIGKILL rank 2 at step 7
    of 12; the supervisor re-forms the mesh (1:1 replacement, and scaled
    down to 3 under the shrink policy), the job resumes from ckpt-6 and
    finishes — with final params matching the never-killed baseline
    (replace: bit-identical — same world, same deterministic replay;
    shrink: 1e-5, the fsdp reduction order changes at world 3), the loss
    trajectory checkpoint-consistent, mesh_reformations_total >= 1, and an
    elastic_restore event carrying cause + old/new world size."""
    import json

    import numpy as np

    base_out, base_losses = _elastic_baseline
    res, out, losses = _run_drill(
        tmp_path, policy,
        elastic_args=("--elastic", "--elastic-policy", policy,
                      "--max-restarts", "2", "--grace", "3"),
        kill_rank=2, kill_step=7)
    tail = (res.stdout + res.stderr)[-3000:]
    assert res.returncode == 0, tail
    assert "[elastic] job complete" in res.stderr, tail
    assert out is not None, tail

    # the job finished on a re-formed mesh at the policy's world size
    assert out["gen"] == 1 and out["world"] == expect_world, out
    assert out["num_update"] == 12, out
    assert out["reformations"] >= 1  # mesh_reformations_total, gen-1 rank 0

    # final params vs the never-killed run's trajectory
    atol = 0.0 if policy == "replace" else 1e-5
    for k in base_out["params"]:
        np.testing.assert_allclose(
            np.array(out["params"][k]), np.array(base_out["params"][k]),
            atol=atol, rtol=0, err_msg=k)
    # per-step losses (replayed steps overwrote gen-0's rows): the resumed
    # trajectory is the checkpoint-consistent one
    assert set(losses) == set(base_losses)
    for step, want in base_losses.items():
        assert abs(losses[step] - want) <= (0.0 if policy == "replace"
                                            else 1e-5), step

    # the elastic_restore event: cause + old/new world (acceptance contract)
    evdir = tmp_path / policy / "obs" / "g1-r0"
    events = [json.loads(line)
              for f in sorted(evdir.glob("events*.jsonl"))
              for line in f.read_text().splitlines()]
    restore = [e for e in events if e["event"] == "elastic_restore"]
    reform = [e for e in events if e["event"] == "mesh_reformation"]
    assert len(restore) == 1 and len(reform) == 1, events
    for e in restore + reform:
        assert e["cause"] == "worker_killed:sig9"
        assert (e["old_world"], e["new_world"]) == (4, expect_world)
    assert restore[0]["ckpt_step"] == 6  # killed at 7, saved every 3


# Straggler drill child (ISSUE 9, docs/OBSERVABILITY.md "Fleet view"):
# four ranks train locally (no collectives — the SIGSTOPped rank's own
# step time is the signal under test, not induced peer waits) with fleet
# snapshots armed; rank 2 publishes its pid so the TEST can SIGSTOP it
# mid-run. No elastic context: a stopped rank must look *slow*, not dead.
_STRAGGLER_CHILD = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, observability as obs, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    rank = int(os.environ["MXNET_TPU_PROCID"])
    obs.enable(os.path.join(os.environ["STRAG_OBS"], f"r{rank}"))

    mx.random.seed(5)
    net = nn.HybridSequential()
    net.add(nn.Dense(512, in_units=512, activation="relu"),
            nn.Dense(512, in_units=512))
    net.initialize()
    _ = net(nd.ones((2, 512)))
    ts = TrainStep(net, lambda o, y: ((o - y) ** 2).mean(),
                   optimizer.SGD(learning_rate=0.01))
    x = nd.array(np.random.RandomState(0).rand(256, 512).astype("float32"))
    y = nd.zeros((256, 512))

    STEPS = int(os.environ.get("STRAG_STEPS", "80"))
    for step in range(1, STEPS + 1):
        ts(x, y)
        if step == 5 and rank == 2:
            # warmed up (compile done): tell the test it may SIGSTOP us
            with open(os.path.join(os.environ["MXNET_TPU_FLEET_DIR"],
                                   "pid-r2"), "w") as f:
                f.write(str(os.getpid()))

    # straggler-triggered capture (ISSUE 14): the aggregator flags rank 2
    # and drops a prof-request into the fleet dir; the flagged rank's
    # step-capture probe consumes it and traces its next step. Rank 2
    # keeps stepping (bounded) until its snapshot lands so the
    # supervisor's 3s poll cadence can't race the loop's natural end.
    if rank == 2:
        import glob, time
        fdir = os.environ["MXNET_TPU_FLEET_DIR"]
        deadline = time.time() + 90
        while time.time() < deadline and not glob.glob(os.path.join(
                fdir, "telemetry-h2", "prof-*", "profile.json")):
            ts(x, y)
            time.sleep(0.02)
    obs.shutdown()
    print(f"STRAG-RANK{rank}-DONE", flush=True)
""")


def _fleetreport_json(fleet_dir):
    import json

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "tools/fleetreport.py", str(fleet_dir), "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo_root)
    assert res.returncode == 0, (res.stdout + res.stderr)[-3000:]
    return json.loads(res.stdout)


@pytest.mark.timeout(420)
@pytest.mark.slow
def test_fleet_straggler_sigstop(tmp_path):
    """`make obsfleet` (ISSUE 9 acceptance): a 4-process launch where the
    test SIGSTOPs rank 2 for ~1s mid-run twice; the fleet aggregator must
    flag rank 2 as a straggler from the merged per-step timings, and the
    elastic supervisor must surface the finding in its own log."""
    import signal
    import time

    fleet = tmp_path / "fleet"
    fleet.mkdir()
    child = tmp_path / "child.py"
    child.write_text(_STRAGGLER_CHILD)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root
    env["MXNET_TPU_FLEET_DIR"] = str(fleet)
    env["MXNET_TPU_FLEET_SNAPSHOT_INTERVAL"] = "0.5"
    env["STRAG_OBS"] = str(tmp_path / "obs")
    proc = subprocess.Popen(
        [sys.executable, "tools/launch.py", "-n", "4", "--elastic",
         "--max-restarts", "0", "--grace", "3",
         sys.executable, str(child)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo_root)
    try:
        # wait for rank 2 to report warm, then freeze it twice: a stopped
        # process's in-flight step spans the pause, so ITS step time blows
        # past the fleet median while the other ranks keep normal pace
        pidfile = fleet / "pid-r2"
        deadline = time.time() + 180
        while not pidfile.exists():
            assert proc.poll() is None, proc.communicate()[1][-3000:]
            assert time.time() < deadline, "rank 2 never reported warm"
            time.sleep(0.05)
        pid = int(pidfile.read_text())
        for _ in range(2):
            os.kill(pid, signal.SIGSTOP)
            time.sleep(1.0)
            os.kill(pid, signal.SIGCONT)
            time.sleep(0.3)
        out, err = proc.communicate(timeout=300)
    except BaseException:
        proc.kill()
        raise
    tail = (out + err)[-3000:]
    assert proc.returncode == 0, tail
    for r in range(4):
        assert f"STRAG-RANK{r}-DONE" in out, tail

    s = _fleetreport_json(fleet)
    steps = [t for t in s["stragglers"] if t["kind"] == "step"]
    assert any(t["rank"] == 2 for t in steps), s["stragglers"]
    worst = max((t for t in steps if t["rank"] == 2),
                key=lambda t: t["ratio"] or 0)
    assert worst["ratio"] >= 3.0, worst
    assert s["skew_timeline"], "skew timeline empty"
    # supervisor-side surfacing: the elastic log names the slow rank
    assert "[fleet] straggler: rank=2" in err, tail

    # straggler-triggered capture (ISSUE 14 acceptance): the aggregator's
    # prof-request made the flagged rank trace one step and snapshot the
    # measured timeline into the fleet dir — with real device op rows
    import glob as _glob
    import json

    snaps = _glob.glob(str(fleet / "telemetry-h2" / "prof-*"
                           / "profile.json"))
    assert snaps, "no straggler-triggered trace snapshot in the fleet dir"
    prof = json.loads(open(snaps[0]).read())
    assert prof["meta"]["trigger"] == "straggler"
    assert prof["meta"]["rank"] == 2
    assert prof["report"]["n_op_rows"] > 0
    # and the merged fleet report carries the measured hot-op snapshot
    assert "2" in s.get("profiles", {}), list(s.get("profiles", {}))


@pytest.mark.timeout(600)
@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_goodput_reformation(tmp_path):
    """`make obsfleet` (ISSUE 9 acceptance): on the 4-process elastic
    chaos drill (SIGKILL rank 2 at step 7), tools/fleetreport.py produces
    ONE merged report covering all ranks and generations whose goodput
    buckets sum to wall time (±1%), with the re-formation interval
    attributed to downtime (goodput < 1.0, nonzero reformation bucket)."""
    res, out, _losses = _run_drill(
        tmp_path, "fleet",
        elastic_args=("--elastic", "--max-restarts", "2", "--grace", "3"),
        kill_rank=2, kill_step=7)
    tail = (res.stdout + res.stderr)[-3000:]
    assert res.returncode == 0, tail
    assert "[elastic] job complete" in res.stderr, tail
    assert out is not None and out["gen"] == 1, tail
    # the supervisor's final fleet pass prints the goodput one-liner
    assert "[fleet] goodput=" in res.stderr, tail

    s = _fleetreport_json(tmp_path / "fleet" / "fleet")
    assert sorted(int(r) for r in s["ranks"]) == [0, 1, 2, 3]
    assert s["generations"] == [0, 1]
    for r, rs in s["ranks"].items():
        assert rs["step_seconds"]["count"] > 0, (r, rs)
    g = s["goodput"]
    assert g is not None
    total = sum(g["buckets"].values())
    assert abs(total - g["wall_seconds"]) <= 0.01 * g["wall_seconds"], g
    assert g["buckets"]["reformation"] > 0, g
    assert g["buckets"]["train"] > 0, g
    assert 0.0 < g["goodput"] < 1.0, g
    # every rank's FLOPs/step gauge made it into the merged report
    assert any(rs.get("flops_per_step") for rs in s["ranks"].values()), s


@pytest.mark.timeout(300)
@pytest.mark.slow
def test_four_process_dist_matrix(tmp_path):
    """Round-3 verdict ask #6 (reference: tests/nightly/dist_sync_kvstore.py
    / dist_async_kvstore.py run as 4 localhost processes): sync replace vs
    async accumulate, 2-bit compression error-feedback convergence, and
    row_sparse pull — all at n=4."""
    child = tmp_path / "child4.py"
    child.write_text(_CHILD4)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root
    env["OBS_DIR"] = str(tmp_path / "obs")
    res = subprocess.run(
        [sys.executable, "tools/launch.py", "-n", "4", sys.executable, str(child)],
        capture_output=True, text=True, timeout=290, env=env, cwd=repo_root)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    for r in range(4):
        assert f"RANK{r}-OK4" in out, out[-3000:]
