"""Fused multi-step training: the compiled k-step scan window
(``TrainStep.run``) and the async device-prefetch queue (``io.prefetch``).

The contract under test (ISSUE 3 acceptance):
  - a k-step window is numerically equivalent to k sequential ``__call__``s
    (params, opt-state, step-count, losses, fixed RNG stream), including a
    gradient-accumulation case;
  - ``run(steps=K)`` with ``window=K`` issues exactly ONE compiled program
    per (window, shapes) signature and one dispatch per window
    (``train_recompiles_total{reason="window"}`` + dispatch counter);
  - the prefetch queue preserves order, propagates errors, and shuts down
    cleanly mid-stream;
  - the window path runs on the virtual 8-way mesh with params staying in
    the storage layout.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, observability as obs, optimizer as opt
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.io.prefetch import DevicePrefetcher
from mxnet_tpu.parallel import MeshConfig, TrainStep, make_mesh

IN, OUT = 6, 4


def _mlp(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(OUT))
    net.initialize()
    _ = net(nd.ones((2, IN)))
    return net


def _loss(out, *labels):
    return ((out - labels[0]) ** 2).mean()


def _make_step(optimizer=None, mesh=None, seed=0):
    return TrainStep(_mlp(seed), _loss,
                     optimizer or opt.Adam(learning_rate=1e-2), mesh=mesh)


def _batches(k, b=4, seed=123):
    rs = np.random.RandomState(seed)
    return [(rs.normal(size=(b, IN)).astype(np.float32),
             rs.normal(size=(b, OUT)).astype(np.float32)) for _ in range(k)]


def _param_values(ts):
    # the Dense name counter is process-global, so two structurally
    # identical nets carry different param names — compare by sorted order
    return [np.asarray(v) for _, v in sorted(ts.params.items())]


def _state_leaves(ts):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        {k: ts.opt_state[k] for k in sorted(ts.opt_state)})]


# -- numerical equivalence ---------------------------------------------------
def test_window_matches_sequential_steps():
    data = _batches(4)
    ts_seq = _make_step()
    seq_losses = [float(ts_seq(nd.array(x), nd.array(y))) for x, y in data]

    ts_win = _make_step()  # reseeded: identical init + identical key stream
    losses = ts_win.run(iter(data), steps=4, window=4)
    losses = np.asarray(jax.device_get(losses))

    assert losses.shape == (4,)
    np.testing.assert_allclose(losses, seq_losses, rtol=2e-5, atol=1e-6)
    assert int(ts_win.step_count) == 4 == int(ts_seq.step_count)
    assert ts_win.optimizer.num_update == 4
    for a, b in zip(_param_values(ts_seq), _param_values(ts_win)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)
    for a, b in zip(_state_leaves(ts_seq), _state_leaves(ts_win)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)


def test_window_accum_matches_full_batch_steps():
    # 2 steps x accum=2 over microbatches of 4 == 2 plain steps over the
    # concatenated batches of 8 (mean-of-microbatch-grads == full-batch grad)
    micro = _batches(4, b=4)
    full = [(np.concatenate([micro[2 * i][0], micro[2 * i + 1][0]]),
             np.concatenate([micro[2 * i][1], micro[2 * i + 1][1]]))
            for i in range(2)]

    ts_seq = _make_step()
    seq_losses = [float(ts_seq(nd.array(x), nd.array(y))) for x, y in full]

    ts_win = _make_step()
    losses = np.asarray(jax.device_get(
        ts_win.run(iter(micro), steps=2, window=2, accum=2)))

    np.testing.assert_allclose(losses, seq_losses, rtol=5e-5, atol=1e-6)
    assert int(ts_win.step_count) == 2
    for a, b in zip(_param_values(ts_seq), _param_values(ts_win)):
        np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-6)


def test_partial_tail_with_accum_stays_accumulated():
    # 3 steps, window=2, accum=2: one full window (2 steps) + a k=1 window
    # for the tail — NEVER un-accumulated singles (which would train at a
    # different effective batch size)
    ts = _make_step()
    losses = np.asarray(jax.device_get(
        ts.run(iter(_batches(6)), steps=3, window=2, accum=2)))
    assert losses.shape == (3,)
    assert ts._window_dispatches == 2 and int(ts.step_count) == 3

    # a sub-group remainder is dropped (and counted), not mis-trained
    from mxnet_tpu import observability as obs2
    dropped = obs2.counter("prefetch_dropped_batches_total")
    before = dropped.total()
    ts2 = _make_step()
    losses2 = np.asarray(jax.device_get(
        ts2.run(iter(_batches(5)), window=2, accum=2)))  # steps=None
    assert losses2.shape == (2,) and int(ts2.step_count) == 2
    assert dropped.total() == before + 1


def test_partial_tail_falls_back_to_single_steps():
    ts = _make_step()
    losses = np.asarray(jax.device_get(
        ts.run(iter(_batches(5)), steps=5, window=2)))
    assert losses.shape == (5,)
    assert ts._window_dispatches == 2  # 2 full windows + 1 single tail
    assert int(ts.step_count) == 5 and ts.optimizer.num_update == 5


# -- one program per signature, one dispatch per window ----------------------
def test_one_program_per_window_signature(tmp_path):
    obs.enable(str(tmp_path))
    try:
        rc = obs.counter("train_recompiles_total")
        before = rc.value(reason="window")
        ts = _make_step()
        ts.run(iter(_batches(8)), steps=8, window=4)
        wkeys = [k for k in ts._compiled if k[0] == "window"]
        assert len(wkeys) == 1, "window=4 x2 must lower exactly one program"
        assert ts._window_dispatches == 2  # one dispatch (+sync) per window
        assert rc.value(reason="window") == before + 1

        # same (window, shapes) signature again: fully cached
        ts.run(iter(_batches(4)), steps=4, window=4)
        assert len([k for k in ts._compiled if k[0] == "window"]) == 1
        assert rc.value(reason="window") == before + 1
        assert ts._window_dispatches == 3

        # a NEW window size lowers a new program, counted reason="window"
        ts.run(iter(_batches(4)), steps=4, window=2)
        assert len([k for k in ts._compiled if k[0] == "window"]) == 2
        assert rc.value(reason="window") == before + 2
    finally:
        obs.shutdown()


def test_window_telemetry_records_run_window_loop(tmp_path):
    obs.enable(str(tmp_path))
    try:
        # the registry is process-global: count deltas, not absolutes
        h = obs.histogram("train_step_seconds")
        s0 = h.stats(loop="run_window")
        h_before = s0["count"] if s0 else 0
        c_before = obs.counter("train_steps_total").value(loop="run_window")
        ts = _make_step()
        ts.run(iter(_batches(4)), steps=4, window=2)
        assert h.stats(loop="run_window")["count"] == h_before + 2
        assert obs.counter("train_steps_total").value(
            loop="run_window") == c_before + 4
        assert obs.gauge("train_loss").value() is not None
        assert obs.gauge("train_grad_norm").value() is not None
    finally:
        obs.shutdown()
    recs = [e for e in obs.read_events(str(tmp_path))
            if e["event"] == "train_window"]
    assert len(recs) == 2
    for r in recs:
        assert r["window"] == 2 and r["window_seconds"] > 0
        assert r["step_seconds_amortized"] < r["window_seconds"]


def test_window_matches_sequential_with_lr_scheduler():
    from mxnet_tpu import lr_scheduler

    def sched_opt():
        return opt.SGD(learning_rate=0.1,
                       lr_scheduler=lr_scheduler.FactorScheduler(
                           step=2, factor=0.5))

    data = _batches(4)
    ts_seq = _make_step(optimizer=sched_opt())
    seq_losses = [float(ts_seq(nd.array(x), nd.array(y))) for x, y in data]
    ts_win = _make_step(optimizer=sched_opt())
    losses = np.asarray(jax.device_get(ts_win.run(iter(data), steps=4, window=4)))
    # each window step i must read the scheduler at num_update + i, exactly
    # like i sequential __call__s (the lr decays INSIDE the window)
    np.testing.assert_allclose(losses, seq_losses, rtol=2e-5, atol=1e-6)
    for a, b in zip(_param_values(ts_seq), _param_values(ts_win)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)


# -- device prefetch queue ---------------------------------------------------
def test_prefetcher_handles_ragged_tail_batch():
    # DataLoader last_batch="keep" tails are smaller: a ragged batch inside
    # a would-be-full group must flush the group, not crash np.stack
    data = _batches(4, b=4) + _batches(1, b=2)
    pf = DevicePrefetcher(iter(data), window=2)
    kinds = []
    while True:
        kind, payload, n = pf.next_group()
        if kind is None:
            break
        kinds.append((kind, n, np.asarray(payload[0]).shape[-3:]
                      if kind == "window" else np.asarray(payload[0]).shape))
    assert [(k, n) for k, n, _ in kinds] == \
        [("window", 2), ("window", 2), ("single", 1)]
    assert kinds[-1][2][0] == 2  # the ragged 2-sample tail survived intact
    pf.close()


def test_run_rejects_mismatched_prefetcher_config():
    ts = _make_step()
    pf = DevicePrefetcher(iter(_batches(4)), train_step=ts, window=2)
    with pytest.raises(ValueError, match="window=4"):
        ts.run(pf, steps=4, window=4)
    with pytest.raises(ValueError, match="accum=2"):
        ts.run(pf, steps=4, accum=2)
    pf.close()
def test_prefetcher_orders_windows_and_tail():
    data = _batches(5, b=2)
    pf = DevicePrefetcher(iter(data), window=2)
    groups = []
    while True:
        kind, payload, n = pf.next_group()
        if kind is None:
            break
        groups.append((kind, payload, n))
    assert [(k, n) for k, _, n in groups] == \
        [("window", 2), ("window", 2), ("single", 1)]
    # stacking preserves source order: window i holds batches 2i, 2i+1
    np.testing.assert_allclose(np.asarray(groups[0][1][0][0]), data[0][0])
    np.testing.assert_allclose(np.asarray(groups[0][1][0][1]), data[1][0])
    np.testing.assert_allclose(np.asarray(groups[1][1][1][0]), data[2][1])
    np.testing.assert_allclose(np.asarray(groups[2][1][0]), data[4][0])
    # exhausted: stays exhausted, and the iterator protocol agrees
    assert pf.next_group()[0] is None
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()
    pf.close()  # idempotent


def test_prefetcher_propagates_source_error():
    def bad():
        yield (np.ones((2, 3), np.float32),)
        raise ValueError("boom")

    pf = DevicePrefetcher(bad(), window=2)
    with pytest.raises(ValueError, match="boom"):
        while pf.next_group()[0] is not None:
            pass
    pf.close()


def test_prefetcher_close_mid_stream_joins_producer():
    pf = DevicePrefetcher(iter(_batches(64, b=2)), window=2, depth=2)
    kind, _payload, _n = pf.next_group()
    assert kind == "window"
    pf.close()  # must unblock the producer's put and join without hanging
    assert not pf._thread.is_alive()
    assert pf.next_group()[0] is None


def test_dataloader_prefetch_to_device_adapter():
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset

    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    y = np.arange(16, dtype=np.float32)
    loader = DataLoader(ArrayDataset(x, y), batch_size=4)
    pf = loader.prefetch_to_device(window=2)
    wins = list(pf)
    assert len(wins) == 2  # 4 batches -> 2 stacked windows
    assert tuple(np.asarray(wins[0][0]).shape) == (2, 4, 2)
    np.testing.assert_allclose(np.asarray(wins[0][0][0]), x[:4])
    np.testing.assert_allclose(np.asarray(wins[1][1][1]), y[12:])
    pf.close()


def test_ndarrayiter_prefetch_to_device_flattens_databatch():
    x = np.arange(24, dtype=np.float32).reshape(8, 3)
    y = np.arange(8, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    pf = it.prefetch_to_device(window=2)
    wins = list(pf)
    assert len(wins) == 1
    assert tuple(np.asarray(wins[0][0]).shape) == (2, 4, 3)  # data
    assert tuple(np.asarray(wins[0][1]).shape) == (2, 4)     # label
    pf.close()


def test_run_with_attached_prefetcher_skips_caller_device_put():
    mesh = make_mesh(MeshConfig(dp=8))
    ts = _make_step(mesh=mesh)
    x, y = _batches(1, b=8)[0]
    placed = (jax.device_put(x, ts.batch_sharding),
              jax.device_put(y, ts.batch_sharding))
    calls = {"n": 0}
    orig = jax.device_put

    def counting(arr, *a, **kw):
        if any(arr is p for p in placed):
            calls["n"] += 1
        return orig(arr, *a, **kw)

    jax.device_put = counting
    try:
        ts.attach_prefetcher(object())  # batches marked device-resident
        ts(placed[0], placed[1])
        assert calls["n"] == 0, "device_put ran despite attached prefetcher"
        ts._prefetcher = None
        ts(placed[0], placed[1])
        assert calls["n"] == 2  # detached: per-call placement is back
    finally:
        jax.device_put = orig


# -- multichip (virtual 8-way mesh) ------------------------------------------
def test_run_window_on_virtual_mesh():
    mesh = make_mesh(MeshConfig(dp=8))
    ts = _make_step(mesh=mesh)
    losses = np.asarray(jax.device_get(
        ts.run(iter(_batches(4, b=8)), steps=4, window=2)))
    assert losses.shape == (4,) and np.isfinite(losses).all()
    # params stayed pinned to the storage layout across windows
    for v in ts.params.values():
        assert v.sharding.mesh.shape == mesh.shape


def test_window_matches_sequential_on_mesh():
    data = _batches(4, b=8)
    mesh = make_mesh(MeshConfig(dp=8))
    ts_seq = _make_step(mesh=mesh)
    seq_losses = [float(ts_seq(nd.array(x), nd.array(y))) for x, y in data]
    ts_win = _make_step(mesh=mesh)
    losses = np.asarray(jax.device_get(ts_win.run(iter(data), steps=4, window=4)))
    np.testing.assert_allclose(losses, seq_losses, rtol=2e-5, atol=1e-6)
    for a, b in zip(_param_values(ts_seq), _param_values(ts_win)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)


# -- lower_hlo shares the __call__ program (satellite bugfix) ----------------
def test_lower_hlo_shares_call_cache():
    ts = _make_step()
    x, y = _batches(1)[0]
    lowered = ts.lower_hlo(nd.array(x), nd.array(y))
    assert len(ts._compiled) == 1, "lower_hlo must populate the jit cache"
    assert "hlo" in lowered.as_text().lower() or lowered.compile()
    ts(nd.array(x), nd.array(y))
    assert len(ts._compiled) == 1, "__call__ compiled a second program"


def test_lower_hlo_applies_mesh_shardings():
    mesh = make_mesh(MeshConfig(dp=8))
    ts = _make_step(mesh=mesh)
    x, y = _batches(1, b=8)[0]
    text = ts.lower_hlo(nd.array(x), nd.array(y)).compile().as_text()
    assert "all-reduce" in text, "dp grad all-reduce missing from lowering"


# -- Trainer.run -------------------------------------------------------------
def test_trainer_run_matches_train_step_and_refreshes_states():
    net = _mlp()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    data = _batches(4)
    losses = np.asarray(jax.device_get(
        trainer.run(net, _loss, iter(data), steps=4, window=2)))
    assert losses.shape == (4,) and np.isfinite(losses).all()
    assert trainer.optimizer.num_update == 4
    assert all(trainer._states_created)

    # same training as a plain TrainStep sequence — and run() synced the
    # updated params back into the Gluon block
    ts = _make_step(optimizer=opt.SGD(learning_rate=0.1))
    seq_losses = [float(ts(nd.array(x), nd.array(y))) for x, y in data]
    np.testing.assert_allclose(losses, seq_losses, rtol=2e-5, atol=1e-6)
    net_vals = [p.data().asnumpy() for _, p in sorted(net.collect_params().items())]
    for a, b in zip(_param_values(ts), net_vals):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)


def test_trainer_run_reseeds_from_net_between_runs():
    # params replaced between run() calls (what an interleaved imperative
    # step() does) must be picked up by the cached TrainStep, not clobbered
    # by its stale device copies
    data = _batches(2)
    net = _mlp()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    trainer.run(net, _loss, iter(data), steps=2, window=2)
    ts_cached = trainer._fused[1]

    plist = [p for _, p in sorted(net.collect_params().items())]
    snap = []
    for i, p in enumerate(plist):
        new = np.random.RandomState(50 + i).normal(
            0, 0.1, p._nd._data.shape).astype(np.float32)
        p._nd._data = jnp.asarray(new)
        snap.append(new)
    trainer.run(net, _loss, iter(data), steps=2, window=2)
    assert trainer._fused[1] is ts_cached  # same signature: cache hit

    # reference: a fresh TrainStep started from the same snapshot
    net2 = _mlp()
    plist2 = [p for _, p in sorted(net2.collect_params().items())]
    for p, v in zip(plist2, snap):
        p._nd._data = jnp.asarray(v)
    ts_ref = TrainStep(net2, _loss, opt.SGD(learning_rate=0.1))
    for x, y in data:
        ts_ref(nd.array(x), nd.array(y))
    ref_vals = _param_values(ts_ref)
    got_vals = [p.data().asnumpy()
                for _, p in sorted(net.collect_params().items())]
    for a, b in zip(ref_vals, got_vals):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)

    # a different loss_fn is a different program family: cache rebuilds
    trainer.run(net, lambda o, *l: ((o - l[0]) ** 2).sum(), iter(data),
                steps=2, window=2)
    assert trainer._fused[1] is not ts_cached
