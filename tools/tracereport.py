#!/usr/bin/env python
"""Render per-request trace waterfalls from a fleet directory
(docs/OBSERVABILITY.md "Request tracing & SLO ledger").

Joins the span JSONL the router (``router/spans-g*.jsonl``) and every
replica (``telemetry-h*/spans-g*.jsonl``) appended, assembles one span
tree per trace id, reconciles each tree against its end record (the
router-level spans must cover submit → finish contiguously and sum to
the end-to-end latency within tolerance), and prints the top-K tail
offenders — deadline breaches and redistribution victims first, then
thinnest deadline margin, then slowest — with per-phase attribution:
how much of each request went to router backlog, replica queue,
prefill, decode, and redistribution hops.

Usage::

    python tools/tracereport.py FLEET_DIR              # top offenders
    python tools/tracereport.py FLEET_DIR --top 10
    python tools/tracereport.py FLEET_DIR --json       # machine-readable
    python tools/tracereport.py FLEET_DIR --check      # exit 1 on any
                                                       # broken/orphan trace

Exits non-zero when the directory holds no trace records, or (with
``--check``) when any assembled trace fails reconciliation — the
chaos-fleet drill leans on the same library checks.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _fmt_s(v):
    if v is None:
        return "-"
    return f"{v * 1e3:.2f} ms" if abs(v) < 1.0 else f"{v:.3f} s"


def _offender_key(trace, chk):
    """Sort key: broken first, then anomalous outcome, then thinnest
    margin, then slowest."""
    end = trace.get("end") or {}
    margin = end.get("margin")
    return (
        0 if not chk["ok"] else 1,
        0 if end.get("outcome") not in ("eos", "length") else 1,
        0 if int(end.get("hops") or 0) > 0 else 1,
        margin if margin is not None else float("inf"),
        -(end.get("e2e") or 0.0),
    )


def render_trace(tid, trace, chk):
    from mxnet_tpu.observability.tracing import ROUTER_LEVEL_SPANS

    out = []
    w = out.append
    end = trace.get("end") or {}
    margin = end.get("margin")
    head = (f"== trace {tid} [{end.get('cls', '?')}] "
            f"outcome={end.get('outcome', '?')} "
            f"e2e={_fmt_s(end.get('e2e'))}")
    if margin is not None:
        head += f" margin={'+' if margin >= 0 else ''}{_fmt_s(margin)}"
    head += (f" hops={end.get('hops', 0)}"
             f" keep={end.get('why', '?')}")
    w(head)
    base = end.get("t0")
    if base is None and trace["spans"]:
        base = trace["spans"][0].get("t0", 0.0)
    base = base or 0.0
    for s in trace["spans"]:
        t0, t1 = float(s.get("t0", 0.0)), float(s.get("t1", 0.0))
        top = s["name"] in ROUTER_LEVEL_SPANS or s["name"] == "redistribution"
        # replica detail spans are nested attribution inside an attempt;
        # they share the router timebase only when the processes share a
        # clock, so they render indented, offsets on their own clock
        pad = "   " if top else "     "
        attrs = {k: v for k, v in s.items()
                 if k not in ("kind", "trace", "name", "t0", "t1", "src")}
        w(f"{pad}{t0 - base:+9.3f}s {t1 - t0:8.3f}s  {s['name']:<16} "
          f"({s.get('src', '?')})"
          + ("  " + " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
             if attrs else ""))
    phases = chk["phases"]
    if phases:
        w("   phases: " + "  ".join(
            f"{name}={_fmt_s(total)}"
            for name, total in sorted(phases.items(),
                                      key=lambda kv: -kv[1])))
    if chk["e2e"] is not None:
        w(f"   phase sum {_fmt_s(chk['phase_sum'])} vs e2e "
          f"{_fmt_s(chk['e2e'])} "
          f"({chk['rel_err'] * 100:.2f}% err)" if chk["rel_err"] is not None
          else "   phase sum: -")
    for p in chk["problems"]:
        w(f"   !! {p}")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fleet_dir",
                    help="shared fleet directory holding span JSONL files")
    ap.add_argument("--top", type=int, default=5,
                    help="waterfalls to print (worst offenders first)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="relative phase-sum vs e2e tolerance (default 5%%)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: per-trace checks + SLO ledger")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when any trace fails "
                         "reconciliation or any span is orphaned")
    args = ap.parse_args(argv)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.observability import tracing

    records = tracing.collect_records(args.fleet_dir)
    if not records:
        print(f"tracereport: no trace records under {args.fleet_dir!r} "
              "(expected router/spans-g*.jsonl / "
              "telemetry-h*/spans-g*.jsonl)", file=sys.stderr)
        return 1
    assembled = tracing.assemble(records)
    checks = {tid: tracing.check_trace(t, tol=args.tolerance)
              for tid, t in assembled.items()}
    # a trace with spans but no end record either is still in flight or
    # lost its request — surfaced, and fatal under --check
    orphans = [tid for tid, t in assembled.items()
               if t["end"] is None and t["spans"]]
    broken = [tid for tid, t in assembled.items()
              if t["end"] is not None and not checks[tid]["ok"]]
    ends = [t["end"] for t in assembled.values() if t["end"] is not None]
    ledger = tracing.slo_ledger(ends)

    if args.json:
        print(json.dumps({
            "traces": len(assembled), "ends": len(ends),
            "orphans": orphans, "broken": broken,
            "checks": {tid: checks[tid] for tid in sorted(checks)},
            "slo": ledger,
        }, indent=1, sort_keys=True))
    else:
        print(f"== tracereport: {os.path.abspath(args.fleet_dir)}")
        kept = sum(1 for e in ends if e.get("keep"))
        print(f"   traces={len(assembled)} ends={len(ends)} kept={kept} "
              f"dropped={len(ends) - kept} orphans={len(orphans)} "
              f"broken={len(broken)}")
        if ledger:
            tot = ledger.get("total", {})
            print(f"   slo: target={ledger['target']:.4g} "
                  f"attainment={tot.get('attainment')} "
                  f"burn={tot.get('burn')}")
        ranked = sorted(
            ((tid, t) for tid, t in assembled.items()
             if t["end"] is not None or t["spans"]),
            key=lambda kv: _offender_key(kv[1], checks[kv[0]]))
        for tid, t in ranked[:max(0, args.top)]:
            print(render_trace(tid, t, checks[tid]))
        for tid in orphans:
            if not any(tid == r for r, _ in ranked[:args.top]):
                print(f"== trace {tid}: ORPHAN — {len(assembled[tid]['spans'])} "
                      "span(s), no end record")
    if args.check and (orphans or broken):
        print(f"tracereport: FAIL — {len(broken)} broken, "
              f"{len(orphans)} orphaned trace(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
