"""LeNet-5 — driver config #1 (BASELINE.md: Gluon HybridSequential on MNIST)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import Conv2D, Dense, Flatten, HybridSequential, MaxPool2D

__all__ = ["LeNet", "lenet"]


class LeNet(HybridBlock):
    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            self.features.add(Conv2D(channels=6, kernel_size=5, padding=2, activation="tanh"))
            self.features.add(MaxPool2D(pool_size=2, strides=2))
            self.features.add(Conv2D(channels=16, kernel_size=5, activation="tanh"))
            self.features.add(MaxPool2D(pool_size=2, strides=2))
            self.features.add(Flatten())
            self.features.add(Dense(120, activation="tanh"))
            self.features.add(Dense(84, activation="tanh"))
            self.output = Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def lenet(**kwargs):
    return LeNet(**kwargs)
