"""Checkpoint / resume of full training state (SURVEY §5.4).

Two formats:
  - ``.params`` (reference-compatible dict-of-arrays; ``mx.nd.save/load``)
    for model-zoo interop;
  - a *training checkpoint* of (params, opt_state, step) for resume —
    orbax-backed async+sharded when orbax is importable, npz otherwise.

Failure recovery story (SURVEY §5.3), hardened by the resilience
subsystem (docs/RESILIENCE.md):

  - saves stage into ``ckpt-{step}.tmp`` and are published with one atomic
    ``os.replace`` — a crash mid-save can never shadow the previous good
    checkpoint with a torn one;
  - every committed checkpoint carries ``manifest.json`` (per-array sha256
    + shapes/dtypes, plus file-level sha256/sizes) written *before* the
    commit rename; ``load_train_state`` verifies the restored leaves
    against it and raises :class:`CheckpointCorruptError` on any mismatch;
  - ``latest_checkpoint`` validates candidates (manifest file hashes;
    ``meta.json`` presence for legacy dirs) and falls back to the newest
    checkpoint that passes, so a partial/corrupt newest dir degrades to
    "resume one checkpoint earlier" instead of "crash at restore";
  - reads and writes run under the retry policy and are fault-injection
    sites (``ckpt.save`` / ``ckpt.load``) so all of the above is exercised
    by tests and ``make chaos`` on CPU.

World-size-agnostic checkpoints (the elastic-training contract,
docs/RESILIENCE.md "Elastic training"): the manifest records each array's
*global* shape, dtype and partition spec, and the ``npz-shards`` format
additionally stores every shard with its index window — so a checkpoint
written by a world of N reassembles at any world size M (scale-down to a
smaller mesh, scale back up later), with the restore side re-applying the
current mesh's layout (reshard-on-restore; the storage layout being
reshaped is the cross-replica sharded weight-update layout of
arXiv:2004.13336). Multi-host saves are *collective*: every host writes
its addressable shards into the stage dir, a cross-host barrier confirms
they all landed, and only then does rank 0 write the manifest and
``meta.json`` (last) and commit — ``latest_checkpoint`` can never adopt a
checkpoint another host only half-wrote.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Optional

import numpy as np

from . import observability as _obs
from .resilience import faults, integrity, retry
from .resilience.integrity import CheckpointCorruptError  # noqa: F401  (re-export)

__all__ = ["save_train_state", "load_train_state", "latest_checkpoint",
           "validate_checkpoint", "checkpoint_layout",
           "CheckpointCorruptError"]

logger = logging.getLogger("mxnet_tpu.checkpoint")


def _orbax():
    # orbax async/sharded checkpointing is opt-in for now (multi-host runs);
    # the npz path is the default single-controller format
    if os.environ.get("MXNET_TPU_USE_ORBAX") != "1":
        return None
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


def _barrier(name: str) -> None:
    """Cross-host sync point for collective saves (no-op single-process).
    Module-level so tests can observe/replace the barrier sequence."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def _spec_of(a):
    """Serialized partition spec of a leaf (None for host-local arrays):
    list entries are mesh-axis names, axis-name lists, or None — enough for
    any world size to know how the array was cut when it reassembles."""
    spec = getattr(getattr(a, "sharding", None), "spec", None)
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _norm_index(index, shape) -> list:
    """A shard's index window as [[start, stop], ...] (JSON-friendly)."""
    out = []
    for sl, dim in zip(index, shape):
        out.append([sl.start or 0, dim if sl.stop is None else sl.stop])
    return out


def _local_shards(a, leader: bool, nproc: int):
    """(host_data, index_window) pairs this process owns for leaf ``a``.

    Globally-sharded jax Arrays contribute their addressable
    ``replica_id == 0`` shards — exactly one process owns each index
    window, however the array is sharded/replicated. A *fully-addressable*
    leaf in a multi-process run is process-local state (every host holds
    the same whole array — e.g. the KVStore data-parallel layout), so the
    leader alone owns the single full window; in a single-process run a
    fully-addressable leaf still records its per-device shard windows —
    that IS the world-size-agnostic layout the elastic restore consumes.
    """
    fully_local = getattr(a, "is_fully_addressable", True)
    if hasattr(a, "addressable_shards") and \
            getattr(a, "sharding", None) is not None and \
            (nproc == 1 or not fully_local):
        out = []
        for s in a.addressable_shards:
            if s.replica_id != 0:
                continue
            out.append((np.asarray(s.data), _norm_index(s.index, a.shape)))
        return out
    if not leader:
        return []
    host = np.asarray(a)
    return [(host, [[0, d] for d in host.shape])]


def save_train_state(directory: str, step: int, params, opt_state,
                     extra: Optional[dict] = None,
                     keep_last: Optional[int] = None,
                     sharded: Optional[bool] = None,
                     layout: Optional[dict] = None) -> str:
    """Write checkpoint ``directory/ckpt-{step}``; returns the path.

    The write is crash-safe: all payload lands in ``ckpt-{step}.tmp`` and
    one ``os.replace`` publishes it. ``keep_last`` (default: the
    ``ckpt_keep_last`` config knob; 0 = keep all) prunes older committed
    checkpoints after a successful commit.

    ``layout`` (a :meth:`Layout.to_dict` record) is stored in the
    manifest's ``layout`` key: the checkpoint *declares* the parallelism
    spec that produced it, and the restore side validates the declared
    layout against the current one (model axes + rules must match; data
    axes are free — that is the elastic contract) instead of inferring
    compatibility from shard shapes.

    Format selection: orbax when opted in; else the world-size-agnostic
    ``npz-shards`` layout when this is a multi-process run, any leaf is
    not fully addressable, or ``sharded=True`` (/ the ``ckpt_sharded``
    knob); else flat npz. In a multi-process run this call is
    **collective** — every host must call it (hosts with no shards to
    contribute still participate in the save barrier).
    """
    import jax

    from . import config

    nproc = jax.process_count()
    if sharded is None:
        sharded = config.get("ckpt_sharded")
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt-{step}")
    tmp = path + ".tmp"
    ocp = _orbax()
    state = {"params": params, "opt_state": opt_state}
    flat, treedef = jax.tree_util.tree_flatten(state)
    hashable = all(getattr(a, "is_fully_addressable", True) for a in flat)

    t0 = time.perf_counter()
    if ocp is None and (nproc > 1 or sharded or not hashable):
        _save_sharded(path, tmp, step, flat, treedef, extra, nproc, layout)
    else:
        _save_flat(path, tmp, step, state, flat, treedef, extra, ocp,
                   hashable, layout)
    dt = time.perf_counter() - t0
    # checkpoint IO is rare — record telemetry unconditionally so retention
    # and duration trends exist even when full telemetry is off
    nbytes = _dir_bytes(path)
    _obs.histogram("ckpt_save_seconds", "checkpoint write+commit wall clock",
                   unit="s").observe(dt)
    _obs.counter("ckpt_saves_total").inc()
    _obs.counter("ckpt_bytes_total", unit="bytes").inc(nbytes, op="save")
    _obs.emit("checkpoint_save", path=path, ckpt_step=step,
              seconds=round(dt, 6), bytes=nbytes)
    # always sweep: keep=0 prunes nothing but still clears .tmp/.stale
    # debris abandoned by earlier crashed saves. Leader-only when
    # multi-process (concurrent rmtree of the same dirs races).
    if jax.process_index() == 0:
        keep = keep_last if keep_last is not None \
            else config.get("ckpt_keep_last")
        integrity.sweep_retention(directory, keep)
    return path


def _save_flat(path, tmp, step, state, flat, treedef, extra, ocp, hashable,
               layout=None):
    """Single-controller formats: orbax, or whole-array flat npz."""
    import jax

    # per-array digests need the bytes on host: fine for the npz path (it
    # materializes anyway — do it once, reused for savez + manifest), but a
    # non-addressable sharded leaf can't be np.asarray'd; those checkpoints
    # get a file-level manifest only and skip the array-hash tier
    host_flat = [np.asarray(a) for a in flat] if ocp is None else \
        (flat if hashable else [])
    specs = [_spec_of(a) for a in flat]

    def _write():
        shutil.rmtree(tmp, ignore_errors=True)
        if ocp is not None:
            ckptr = ocp.StandardCheckpointer()
            ckptr.save(os.path.abspath(tmp), state, force=True)
            ckptr.wait_until_finished()
            payload_files = []
            fmt = "orbax"
        else:  # flat npz fallback
            os.makedirs(tmp, exist_ok=True)
            np.savez(os.path.join(tmp, "arrays.npz"),
                     **{str(i): a for i, a in enumerate(host_flat)})
            with open(os.path.join(tmp, "treedef.txt"), "w") as f:
                f.write(str(treedef))
            payload_files = ["arrays.npz", "treedef.txt"]
            fmt = "npz"
        # chaos site: a crash here leaves a torn .tmp (arrays written, no
        # manifest, no commit) — exactly the mid-save kill the recovery
        # tests simulate; latest_checkpoint never sees .tmp dirs
        faults.fire("ckpt.save")
        manifest = integrity.build_manifest(host_flat, fmt, tmp,
                                            payload_files, specs=specs)
        if layout is not None:
            manifest["layout"] = layout
        integrity.write_manifest(tmp, manifest)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, "world_size": jax.process_count(),
                       **(extra or {})}, f)
            f.flush()
            os.fsync(f.fileno())
        integrity.commit_dir(tmp, path)

    retry.retry_call(_write, site="ckpt.save")


def _save_sharded(path, tmp, step, flat, treedef, extra, nproc, layout=None):
    """World-size-agnostic ``npz-shards`` save (collective when nproc>1).

    Every host stages ``shards-h{pid}.npz`` (its ``replica_id==0`` shards)
    plus a tiny JSON sidecar indexing them; after the all-shards barrier,
    rank 0 merges the sidecars into the manifest, writes ``meta.json``
    **last**, and commits — so a reader can never adopt a checkpoint some
    host only half-wrote. A final barrier holds every host until the
    commit is visible.
    """
    import jax

    pid = jax.process_index()
    leader = pid == 0
    fname = f"shards-h{pid}.npz"

    def _write():
        if leader:
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
        _barrier("ckpt.save.stage")
        # chaos site: a crash past here leaves a torn .tmp (shards written,
        # no manifest/meta, no commit) that is never a restore candidate
        faults.fire("ckpt.save")
        payload = {}
        records = {}
        for i, a in enumerate(flat):
            entries = []
            for j, (data, index) in enumerate(_local_shards(a, leader,
                                                            nproc)):
                key = f"{i}.{j}"
                payload[key] = data
                entries.append({"key": key, "file": fname, "index": index,
                                "sha256": integrity.array_digest(data)})
            dt = getattr(a, "dtype", None)
            records[str(i)] = {
                "global_shape": list(np.shape(a)),
                # np.asarray as a getattr default would run eagerly — and a
                # non-addressable leaf can't be np.asarray'd at all
                "dtype": str(dt if dt is not None else np.asarray(a).dtype),
                "spec": _spec_of(a),
                "shards": entries,
            }
        if payload:
            np.savez(os.path.join(tmp, fname), **payload)
        with open(os.path.join(tmp, f"shards-h{pid}.json"), "w") as f:
            json.dump({"arrays": records}, f)
            f.flush()
            os.fsync(f.fileno())
        if leader:
            with open(os.path.join(tmp, "treedef.txt"), "w") as f:
                f.write(str(treedef))
        _barrier("ckpt.save.shards")  # every host's shards have landed
        if leader:
            manifest = _merge_shard_sidecars(tmp)
            if layout is not None:
                manifest["layout"] = layout
            integrity.write_manifest(tmp, manifest)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"step": step, "world_size": nproc,
                           **(extra or {})}, f)
                f.flush()
                os.fsync(f.fileno())
            integrity.commit_dir(tmp, path)
        _barrier("ckpt.save.commit")  # nobody resumes before the commit

    if nproc > 1:
        # collective write: a per-host retry would re-enter the barrier
        # sequence on one side only and desync every host; a failed host
        # dies and the elastic supervisor re-forms instead (RESILIENCE.md)
        _write()
    else:
        retry.retry_call(_write, site="ckpt.save")


def _merge_shard_sidecars(tmp: str) -> dict:
    """Rank 0, post-barrier: union all hosts' shard indexes + hash the
    payload files into the manifest ``files`` tier."""
    manifest: dict = {"format": "npz-shards", "files": {}, "arrays": {}}
    sidecars = sorted(n for n in os.listdir(tmp)
                      if n.startswith("shards-h") and n.endswith(".json"))
    for name in sidecars:
        with open(os.path.join(tmp, name)) as f:
            recs = json.load(f)["arrays"]
        for idx, rec in recs.items():
            tgt = manifest["arrays"].setdefault(
                idx, {"global_shape": rec["global_shape"],
                      "dtype": rec["dtype"], "spec": rec["spec"],
                      "shards": []})
            tgt["shards"].extend(rec["shards"])
    for name in sorted(os.listdir(tmp)):
        if name == integrity.MANIFEST_NAME or name == "meta.json":
            continue
        p = os.path.join(tmp, name)
        manifest["files"][name] = {"sha256": integrity.file_digest(p),
                                   "size": os.path.getsize(p)}
    return manifest


def _undo_npz_void(data, dtype):
    """np.savez writes ml_dtypes leaves (bfloat16, float8_*) as raw void
    records ('|V2') — the bytes are intact (per-shard sha256 still
    matches), so reinterpret against the manifest-recorded dtype instead
    of letting the window assignment die on 'no cast function'."""
    if data.dtype != dtype and data.dtype.kind == "V" \
            and data.dtype.itemsize == dtype.itemsize:
        return data.view(dtype)
    return data


def _assemble_shards(path: str, manifest: dict):
    """Reassemble host-global leaves from an ``npz-shards`` checkpoint —
    at *any* world size: each shard is verified (sha256) and placed at its
    recorded index window; coverage must tile the global shape exactly."""
    arrays = manifest.get("arrays", {})
    opened: dict = {}
    problems = []
    flat = []
    try:
        _assemble_into(path, arrays, opened, problems, flat)
    finally:
        for npz in opened.values():  # zip handles don't wait for GC
            try:
                npz.close()
            except Exception:
                pass
    if problems:
        raise CheckpointCorruptError(path, problems)
    return flat


def _assemble_into(path, arrays, opened, problems, flat):
    import zipfile
    import zlib

    for i in range(len(arrays)):
        rec = arrays[str(i)]
        shape = tuple(rec["global_shape"])
        out = np.empty(shape, dtype=np.dtype(rec["dtype"]))
        covered = 0
        for s in rec.get("shards", ()):
            fp = os.path.join(path, s["file"])
            try:
                if s["file"] not in opened:
                    opened[s["file"]] = np.load(fp)
                data = opened[s["file"]][s["key"]]
            except (zipfile.BadZipFile, zlib.error, ValueError, KeyError,
                    FileNotFoundError) as e:
                # torn/flipped bytes inside the zip container — or a shard
                # file lost post-commit — are the same corruption class as
                # a sha mismatch: deterministic, so non-retryable
                # (retryable=False on CheckpointCorruptError)
                problems.append(f"array {i} shard {s['key']} unreadable: "
                                f"{type(e).__name__}: {e}")
                continue
            if integrity.array_digest(data) != s["sha256"]:
                problems.append(f"array {i} shard {s['key']} sha256 mismatch")
                continue
            data = _undo_npz_void(data, out.dtype)
            out[tuple(slice(a, b) for a, b in s["index"])] = data
            covered += int(np.prod([b - a for a, b in s["index"]])) \
                if s["index"] else 1
        want = int(np.prod(shape)) if shape else 1
        if covered != want:
            problems.append(f"array {i} shard coverage {covered} != {want} "
                            "elements")
        flat.append(out)


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def load_train_state(path: str, like=None):
    """Load a checkpoint; ``like`` = a (params, opt_state) template pytree
    with target shardings/dtypes (required for the orbax path).

    Restored leaves are verified against the checkpoint's manifest
    (per-array sha256; per-shard for ``npz-shards``, verified during
    reassembly); any mismatch raises :class:`CheckpointCorruptError`
    rather than silently resuming from corrupt state.

    ``npz-shards`` checkpoints reassemble to host-global arrays whatever
    world size wrote them — the caller (e.g. ``TrainStep.restore``)
    re-applies the *current* mesh layout, which is how elastic scale-down/
    scale-up reshards fsdp state.
    """
    import jax

    ocp = _orbax()

    def _read():
        faults.fire("ckpt.load")
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        try:
            mf = integrity.read_manifest(path)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                path, [f"unreadable manifest: {e}"]) from e
        if mf is not None and mf.get("format") == "npz-shards":
            assert like is not None, "shard restore requires a template pytree"
            flat = _assemble_shards(path, mf)
            template = {"params": like[0], "opt_state": like[1]}
            treedef = jax.tree_util.tree_structure(template)
            state = jax.tree_util.tree_unflatten(treedef, flat)
        elif ocp is not None and not os.path.exists(os.path.join(path, "arrays.npz")):
            ckptr = ocp.StandardCheckpointer()
            template = None
            if like is not None:
                template = {"params": like[0], "opt_state": like[1]}
            state = ckptr.restore(os.path.abspath(path), template)
        else:
            import zipfile
            import zlib

            try:
                data = np.load(os.path.join(path, "arrays.npz"))
                flat = [data[str(i)] for i in range(len(data.files))]
            except (zipfile.BadZipFile, zlib.error, ValueError) as e:
                # a torn zip container is deterministic corruption, not a
                # transient read failure — surface it non-retryably
                raise CheckpointCorruptError(
                    path, [f"unreadable arrays.npz: "
                           f"{type(e).__name__}: {e}"]) from e
            assert like is not None, "npz restore requires a template pytree"
            if mf is not None and mf.get("arrays"):
                flat = [_undo_npz_void(a, np.dtype(
                            mf["arrays"][str(i)]["dtype"]))
                        if str(i) in mf["arrays"] else a
                        for i, a in enumerate(flat)]
            template = {"params": like[0], "opt_state": like[1]}
            treedef = jax.tree_util.tree_structure(template)
            state = jax.tree_util.tree_unflatten(treedef, flat)
        return state, meta, mf

    t0 = time.perf_counter()
    state, meta, manifest = retry.retry_call(_read, site="ckpt.load")
    verify_dt = 0.0
    if manifest is not None and manifest.get("arrays") \
            and manifest.get("format") != "npz-shards":
        # (npz-shards leaves were already sha-verified shard-by-shard
        # inside _assemble_shards — no whole-array digest exists for them)
        flat, _ = jax.tree_util.tree_flatten(state)
        if all(getattr(a, "is_fully_addressable", True) for a in flat):
            v0 = time.perf_counter()
            problems = integrity.verify_arrays(flat, manifest)
            verify_dt = time.perf_counter() - v0
            if problems:
                raise CheckpointCorruptError(path, problems)
    dt = time.perf_counter() - t0
    _obs.histogram("ckpt_load_seconds", "checkpoint restore wall clock "
                   "(read + manifest verify)", unit="s").observe(dt)
    _obs.histogram("ckpt_verify_seconds", "manifest sha256 verification",
                   unit="s").observe(verify_dt)
    _obs.counter("ckpt_loads_total").inc()
    _obs.counter("ckpt_bytes_total", unit="bytes").inc(_dir_bytes(path), op="load")
    _obs.emit("checkpoint_restore", path=path, ckpt_step=meta["step"],
              seconds=round(dt, 6), verify_seconds=round(verify_dt, 6))
    return state["params"], state["opt_state"], meta["step"]


def checkpoint_layout(path: str) -> Optional[dict]:
    """The parallelism-layout record a checkpoint declared at save time
    (``Layout.to_dict`` form), or None for layout-less/legacy checkpoints.
    Cheap: reads the manifest only, no array payload."""
    try:
        mf = integrity.read_manifest(path)
    except (OSError, ValueError):
        return None
    return (mf or {}).get("layout")


def validate_checkpoint(path: str) -> bool:
    """Cheap is-this-checkpoint-usable check (no deserialization).

    A committed dir must have a parseable ``meta.json`` (partial pre-
    resilience writes lack it); when a manifest is present, every listed
    payload file must exist with the recorded size and sha256. Manifest-less
    dirs with a valid ``meta.json`` are accepted as legacy checkpoints.
    """
    meta_p = os.path.join(path, "meta.json")
    try:
        with open(meta_p) as f:
            json.load(f)
        manifest = integrity.read_manifest(path)
    except (OSError, ValueError):
        return False  # unreadable/corrupt meta or manifest -> not a candidate
    if manifest is None:
        return True
    try:
        problems = integrity.verify_files(path, manifest)
    except OSError:
        return False
    if problems:
        logger.warning("checkpoint %s failed validation: %s",
                       path, "; ".join(problems))
        return False
    return True


def latest_checkpoint(directory: str, validate: bool = True) -> Optional[str]:
    """Newest *valid* ``ckpt-N`` under ``directory`` (None when none pass).

    Unverifiable candidates — in-progress/abandoned ``.tmp`` stages, dirs
    with no ``meta.json``, manifest mismatches — are skipped, falling back
    to the next-newest valid checkpoint.
    """
    for _step, path in integrity.list_checkpoints(directory):
        if not validate or validate_checkpoint(path):
            return path
        logger.warning("skipping unverifiable checkpoint %s", path)
    return None
