"""Request tracing + SLO ledger (ISSUE 17, docs/OBSERVABILITY.md
"Request tracing & SLO ledger"):

  - TailSampler policy: anomalous outcomes / redistributions / thin
    deadline margins / the slow percentile always kept, the healthy rest
    sampled by a deterministic (seed, trace id) hash — two samplers with
    the same seed agree record-for-record, a different seed diverges;
  - Tracer mechanics: spans buffer until the local end, a kept trace
    flushes spans + verdict in one append, a dropped trace writes only
    the verdict (the ledger measures the population), owner writes
    ``end`` / non-owner ``local_end``, discard drops silently,
    capture_cb fires on thin margins;
  - torn-final-line span files parse (crash-mid-write signature);
  - assemble/check_trace: telescoping router-level spans reconcile
    exactly, gaps / phase-sum drift / hop mismatches / missing end
    records (orphans) are each flagged;
  - slo_ledger: attainment excludes client cancellations from the
    denominator, margins aggregate per class, burn = miss-rate over
    window / error budget;
  - the one-read hot-path gate: ``maybe_tracer`` is None unless the
    ``trace`` knob is on, and the emitting methods are registered in the
    AST-lint EXTRA_HOT_PATHS tier.
"""
import json
import os

import pytest

from mxnet_tpu.observability import tracing
from mxnet_tpu.observability.tracing import (ANOMALY_OUTCOMES, TailSampler,
                                             Tracer, assemble, check_trace,
                                             slo_ledger)


def _sampler(**kw):
    kw.setdefault("sample", 0.0)
    kw.setdefault("seed", 0)
    kw.setdefault("slow_pct", 95.0)
    kw.setdefault("margin_floor", 0.0)
    return TailSampler(**kw)


class TestTailSampler:
    def test_anomalous_outcomes_always_kept(self):
        s = _sampler()
        for outcome in sorted(ANOMALY_OUTCOMES):
            keep, why = s.decide("t1", outcome)
            assert keep and why == f"outcome:{outcome}"

    def test_redistributed_kept_even_when_served(self):
        keep, why = _sampler().decide("t1", "eos", redistributed=True)
        assert keep and why == "redistributed"

    def test_margin_floor(self):
        s = _sampler(margin_floor=0.5)
        assert s.decide("t1", "eos", margin=0.4) == (True, "margin")
        keep, why = s.decide("t2", "eos", margin=0.6)
        assert why != "margin"
        # floor 0 disables the rule entirely
        assert _sampler().decide("t3", "eos", margin=-5.0)[1] != "margin"

    def test_slow_percentile_needs_history(self):
        s = _sampler(min_history=4)
        # cold reservoir: nothing flagged slow
        for i in range(4):
            assert s.decide(f"w{i}", "eos", e2e=1.0)[1] == "dropped"
        # now a clear outlier lands above p95 of the recent window
        keep, why = s.decide("slowpoke", "eos", e2e=50.0)
        assert keep and why == "slow"

    def test_healthy_sampling_is_deterministic_per_seed(self):
        a = [_sampler(sample=0.5, seed=7).decide(f"t{i}", "eos")[0]
             for i in range(200)]
        b = [_sampler(sample=0.5, seed=7).decide(f"t{i}", "eos")[0]
             for i in range(200)]
        c = [_sampler(sample=0.5, seed=8).decide(f"t{i}", "eos")[0]
             for i in range(200)]
        assert a == b          # same seed: identical keep set, any process
        assert a != c          # different seed: different subset
        assert 40 < sum(a) < 160   # ...and roughly the configured rate

    def test_sample_bounds(self):
        assert _sampler(sample=1.0).decide("t", "eos") == (True, "sampled")
        assert _sampler(sample=0.0).decide("t", "eos") == (False, "dropped")
        with pytest.raises(ValueError):
            _sampler(sample=1.5)
        with pytest.raises(ValueError):
            _sampler(slow_pct=0.0)


class TestTracer:
    def _tracer(self, tmp_path, **kw):
        kw.setdefault("sampler", _sampler(sample=1.0))
        return Tracer(str(tmp_path / "spans.jsonl"), "h0", **kw)

    def test_spans_buffer_until_finish(self, tmp_path):
        tr = self._tracer(tmp_path)
        tr.span("t1", "prefill", 1.0, 2.0, slot=0)
        assert not os.path.exists(tr.path)  # nothing written yet
        assert tr.finish("t1", "eos", 0.0, 3.0) is True
        recs = tracing.read_span_records(tr.path)
        assert [r["kind"] for r in recs] == ["span", "local_end"]
        assert recs[0]["name"] == "prefill" and recs[0]["slot"] == 0
        assert recs[1]["e2e"] == 3.0 and recs[1]["keep"] is True

    def test_dropped_trace_writes_only_the_verdict(self, tmp_path):
        tr = self._tracer(tmp_path, sampler=_sampler(sample=0.0))
        tr.span("t1", "prefill", 1.0, 2.0)
        assert tr.finish("t1", "eos", 0.0, 3.0) is False
        recs = tracing.read_span_records(tr.path)
        # the end record survives for the SLO ledger; the spans do not
        assert [r["kind"] for r in recs] == ["local_end"]
        assert recs[0]["keep"] is False and recs[0]["why"] == "dropped"

    def test_owner_writes_end_kind(self, tmp_path):
        tr = self._tracer(tmp_path, owner=True)
        tr.finish("t1", "eos", 0.0, 1.0, cls="interactive", deadline=5.0)
        rec = tracing.read_span_records(tr.path)[0]
        assert rec["kind"] == "end"
        assert rec["cls"] == "interactive" and rec["margin"] == 4.0

    def test_discard_drops_silently(self, tmp_path):
        tr = self._tracer(tmp_path)
        tr.span("t1", "prefill", 1.0, 2.0)
        tr.discard("t1")
        tr.finish("t2", "eos", 0.0, 1.0)
        assert all(r["trace"] == "t2"
                   for r in tracing.read_span_records(tr.path))

    def test_capture_cb_fires_below_margin_floor(self, tmp_path):
        hits = []
        tr = self._tracer(tmp_path,
                          sampler=_sampler(sample=1.0, margin_floor=1.0),
                          capture_cb=lambda tid, m: hits.append((tid, m)))
        tr.finish("fat", "eos", 0.0, 1.0, deadline=10.0)
        tr.finish("thin", "eos", 0.0, 1.0, deadline=1.5)
        assert hits == [("thin", 0.5)]

    def test_torn_final_line_is_skipped(self, tmp_path):
        tr = self._tracer(tmp_path)
        tr.span("t1", "prefill", 1.0, 2.0)
        tr.finish("t1", "eos", 0.0, 3.0)
        tr.close()
        with open(tr.path, "a") as f:
            f.write('{"kind": "span", "trace": "t2", "na')  # crash mid-write
        recs = tracing.read_span_records(tr.path)
        assert len(recs) == 2 and all(r["trace"] == "t1" for r in recs)


def _mk_end(tid, outcome="eos", t0=0.0, t1=10.0, deadline=None, cls=None,
            hops=0):
    margin = None if deadline is None else deadline - t1
    return {"kind": "end", "trace": tid, "outcome": outcome, "cls": cls,
            "t0": t0, "t1": t1, "e2e": t1 - t0, "deadline": deadline,
            "margin": margin, "hops": hops, "keep": True, "why": "sampled",
            "src": "router"}


def _span(tid, name, t0, t1, **attrs):
    rec = {"kind": "span", "trace": tid, "name": name, "t0": t0, "t1": t1,
           "src": "router"}
    rec.update(attrs)
    return rec


class TestAssembleAndCheck:
    def test_telescoping_trace_reconciles_exactly(self):
        recs = [
            _span("t", "router.backlog", 0.0, 2.0),
            _span("t", "router.attempt", 2.0, 5.0, replica=0),
            _span("t", "redistribution", 5.0, 5.0, hop=1),
            _span("t", "router.backlog", 5.0, 6.0),
            _span("t", "router.attempt", 6.0, 10.0, replica=1),
            _span("t", "prefill", 6.5, 7.0),  # nested detail, not summed
            _mk_end("t", t1=10.0, hops=1),
        ]
        trace = assemble(recs)["t"]
        chk = check_trace(trace)
        assert chk["ok"], chk["problems"]
        assert chk["phase_sum"] == pytest.approx(10.0)
        assert chk["rel_err"] == pytest.approx(0.0)
        assert chk["hops"] == 1
        assert chk["phases"]["router.attempt"] == pytest.approx(7.0)

    def test_gap_between_router_spans_flags(self):
        recs = [_span("t", "router.backlog", 0.0, 2.0),
                _span("t", "router.attempt", 3.0, 10.0),  # 1s hole
                _mk_end("t")]
        chk = check_trace(assemble(recs)["t"])
        assert not chk["ok"]
        assert any("gap/overlap" in p for p in chk["problems"])

    def test_phase_sum_drift_flags(self):
        recs = [_span("t", "router.backlog", 0.0, 8.0), _mk_end("t")]
        chk = check_trace(assemble(recs)["t"])
        assert any("phase sum" in p for p in chk["problems"])

    def test_hop_count_mismatch_flags(self):
        recs = [_span("t", "router.backlog", 0.0, 10.0),
                _mk_end("t", hops=2)]
        chk = check_trace(assemble(recs)["t"])
        assert any("hops" in p for p in chk["problems"])

    def test_orphan_trace(self):
        trace = assemble([_span("ghost", "router.backlog", 0.0, 1.0)])
        chk = check_trace(trace["ghost"])
        assert not chk["ok"]
        assert chk["problems"] == ["orphan: no end record"]

    def test_collect_records_walks_router_and_replica_files(self, tmp_path):
        os.makedirs(tmp_path / "router")
        os.makedirs(tmp_path / "telemetry-h1")
        for p, tid in ((tmp_path / "router" / "spans-g0.jsonl", "a"),
                       (tmp_path / "telemetry-h1" / "spans-g0.jsonl", "b")):
            with open(p, "w") as f:
                f.write(json.dumps(_span(tid, "router.backlog", 0, 1))
                        + "\n")
        recs = tracing.collect_records(str(tmp_path))
        assert sorted(r["trace"] for r in recs) == ["a", "b"]


class TestSloLedger:
    def test_attainment_margins_and_burn(self):
        ends = [
            _mk_end("a", t1=10.0, deadline=14.0, cls="interactive"),
            _mk_end("b", t1=20.0, deadline=22.0, cls="interactive"),
            _mk_end("c", outcome="deadline", t1=30.0, deadline=29.0,
                    cls="interactive"),
            _mk_end("d", outcome="cancelled", t1=30.0, cls="interactive"),
            _mk_end("e", outcome="length", t1=30.0, deadline=40.0,
                    cls="batch", hops=2),
        ]
        led = slo_ledger(ends, windows=[100.0], target=0.9, now=30.0)
        it = led["classes"]["interactive"]
        # cancelled is exempt: 3 eligible, 2 attained
        assert it["count"] == 4 and it["eligible"] == 3
        assert it["attainment"] == pytest.approx(2 / 3, abs=1e-4)
        assert it["margin"]["min"] == pytest.approx(-1.0)
        assert led["classes"]["batch"]["redistributed"] == 1
        # burn: 1 miss / 3 eligible in window over a 0.1 error budget
        assert it["burn"]["100s"] == pytest.approx((1 / 3) / 0.1,
                                                   abs=1e-3)
        assert led["total"]["eligible"] == 4
        assert led["windows"] == ["100s"]

    def test_empty_ends(self):
        assert slo_ledger([]) == {}
        # span records never count as ledger material
        assert slo_ledger([_span("t", "router.backlog", 0, 1)]) == {}

    def test_parse_windows(self):
        assert tracing.parse_windows("60, 300,junk,-5,") == [60.0, 300.0]


class TestHotPathGate:
    def test_maybe_tracer_none_unless_knob_on(self, tmp_path, monkeypatch):
        monkeypatch.delenv("MXNET_TPU_TRACE", raising=False)
        assert tracing.maybe_tracer(str(tmp_path / "s.jsonl"), "h0") is None
        monkeypatch.setenv("MXNET_TPU_TRACE", "1")
        tr = tracing.maybe_tracer(str(tmp_path / "s.jsonl"), "h0",
                                  owner=True)
        assert isinstance(tr, Tracer) and tr.owner

    def test_emitters_registered_in_lint_hot_paths(self):
        # the structural contract: the tracing emitters stay on the
        # AST-lint hot-path tier, and the registered qualnames exist
        from mxnet_tpu.analysis import astlint

        names = astlint.EXTRA_HOT_PATHS.get("observability/tracing.py")
        assert names is not None
        assert "Tracer.span" in names and "Tracer.finish" in names
        for qual in names:
            cls_name, meth = qual.split(".")
            assert callable(getattr(getattr(tracing, cls_name), meth))
