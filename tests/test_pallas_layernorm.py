"""Fused Pallas LayerNorm vs the jnp oracle (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops import pallas_layernorm as pln


def _oracle(x, g, b, eps=1e-5):
    xf = np.asarray(x, np.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (xf - mean) / np.sqrt(var + eps) * np.asarray(g, np.float32) \
        + np.asarray(b, np.float32)


@pytest.mark.parametrize("shape", [(4, 128), (2, 3, 256), (512, 128)])
def test_ln_kernel_matches_oracle(shape):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(*shape), jnp.float32)
    g = jnp.asarray(rs.rand(shape[-1]), jnp.float32)
    b = jnp.asarray(rs.rand(shape[-1]), jnp.float32)
    out = pln.layer_norm_fused(x, g, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(x, g, b), rtol=2e-5,
                               atol=2e-5)


def test_ln_kernel_row_padding():
    """Row counts that don't divide the block size go through the pad/slice
    path and must still be exact."""
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(300, 128), jnp.float32)  # 300 % 256 != 0
    g = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.float32)
    out = pln.layer_norm_fused(x, g, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(x, g, b), rtol=2e-5,
                               atol=2e-5)


def test_ln_kernel_bf16():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(8, 256), jnp.bfloat16)
    g = jnp.ones((256,), jnp.bfloat16)
    b = jnp.zeros((256,), jnp.bfloat16)
    out = pln.layer_norm_fused(x, g, b, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _oracle(np.asarray(x, np.float32), g, b),
                               rtol=3e-2, atol=3e-2)


def test_ln_custom_vjp_matches_jnp_grads():
    """Analytic backward vs autodiff of the naive composition."""
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(6, 128), jnp.float32)
    g = jnp.asarray(rs.rand(128) + 0.5, jnp.float32)
    b = jnp.asarray(rs.rand(128), jnp.float32)

    def fused(x, g, b):
        return pln.layer_norm_fused(x, g, b, interpret=True).sum()

    def naive(x, g, b):
        xf = x.astype(jnp.float32)
        mean = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        return ((xf - mean) / jnp.sqrt(var + 1e-5) * g + b).sum()

    gx1, gg1, gb1 = jax.grad(fused, argnums=(0, 1, 2))(x, g, b)
    gx2, gg2, gb2 = jax.grad(naive, argnums=(0, 1, 2))(x, g, b)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gg1), np.asarray(gg2), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb1), np.asarray(gb2), rtol=1e-4,
                               atol=1e-5)


def test_ln_gate_on_cpu():
    """On the CPU backend the registered LayerNorm op must NOT take the
    kernel path (backend gate), and still be exact."""
    from mxnet_tpu import nd

    x = nd.array(np.random.RandomState(4).randn(4, 128).astype(np.float32))
    g = nd.ones((128,))
    b = nd.zeros((128,))
    assert not pln.ln_kernel_supported(x._data)
    out = nd.LayerNorm(x, g, b)
    np.testing.assert_allclose(out.asnumpy(),
                               _oracle(x.asnumpy(), g.asnumpy(), b.asnumpy()),
                               rtol=2e-5, atol=2e-5)
