"""Unfused RNN cells (reference: ``python/mxnet/gluon/rnn/rnn_cell.py``)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell"]


class _BaseCell(HybridBlock):
    def __init__(self, hidden_size, input_size=0, ngates=1, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._ng = ngates
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(ngates * hidden_size, input_size),
                                              init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(ngates * hidden_size, hidden_size),
                                              init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(ngates * hidden_size,),
                                            init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(ngates * hidden_size,),
                                            init=h2h_bias_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._ng * self._hidden_size, x.shape[-1])

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        n = 2 if isinstance(self, LSTMCell) else 1
        return [nd.zeros((batch_size, self._hidden_size)) for _ in range(n)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None,
               valid_length=None):
        """Unroll over time. With ``valid_length`` (reference semantics):
        outputs at padded positions are zeroed (SequenceMask) and the
        returned states are the states AT each sequence's last valid step
        (not after consuming padding)."""
        from ... import ndarray as nd

        axis = layout.find("T")
        states = begin_state or self.begin_state(inputs.shape[1 - axis if axis == 0 else 0])
        outputs = []
        state_trace = [] if valid_length is not None else None
        for t in range(length):
            x_t = inputs.slice_axis(axis=axis, begin=t, end=t + 1).squeeze(axis=axis)
            out, states = self(x_t, states)
            outputs.append(out)
            if state_trace is not None:
                state_trace.append(states)
        if valid_length is not None:
            # states at the last VALID step of each sequence
            states = [
                nd.SequenceLast(nd.stack(*[st[i] for st in state_trace], axis=0),
                                valid_length, use_sequence_length=True)
                for i in range(len(states))
            ]
        merged = nd.stack(*outputs, axis=axis)
        if valid_length is not None:
            merged = nd.SequenceMask(merged, valid_length,
                                     use_sequence_length=True, axis=axis)
        if merge_outputs or merge_outputs is None:
            outputs = merged
        else:
            outputs = [merged.slice_axis(axis=axis, begin=t, end=t + 1)
                       .squeeze(axis=axis) for t in range(length)] \
                if valid_length is not None else outputs
        return outputs, states


class RNNCell(_BaseCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, input_size, 1, **kwargs)
        self._activation = activation

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h = states[0] if isinstance(states, (list, tuple)) else states
        out = F.Activation(
            F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=self._hidden_size)
            + F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=self._hidden_size),
            act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, input_size, 4, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h, c = states
        gates = (F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * self._hidden_size)
                 + F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=4 * self._hidden_size))
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        c_new = F.sigmoid(f) * c + F.sigmoid(i) * F.tanh(g)
        h_new = F.sigmoid(o) * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, input_size, 3, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h = states[0] if isinstance(states, (list, tuple)) else states
        xz = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * self._hidden_size)
        hz = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=3 * self._hidden_size)
        xr, xu, xn = F.split(xz, num_outputs=3, axis=-1)
        hr, hu, hn = F.split(hz, num_outputs=3, axis=-1)
        r = F.sigmoid(xr + hr)
        u = F.sigmoid(xu + hu)
        n = F.tanh(xn + r * hn)
        h_new = (1 - u) * n + u * h
        return h_new, [h_new]


class SequentialRNNCell(_BaseCell):
    def __init__(self, prefix=None, params=None):
        HybridBlock.__init__(self, prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for c in self._children.values():
            states.append(c.begin_state(batch_size, **kwargs))
        return states

    def hybrid_forward(self, F, x, states):
        next_states = []
        for cell, s in zip(self._children.values(), states):
            x, ns = cell(x, s)
            next_states.append(ns)
        return x, next_states


class ModifierCell(_BaseCell):
    """Wraps a base cell, delegating state handling (reference:
    ``rnn_cell.py ModifierCell`` — the base of Dropout/Zoneout/Residual)."""

    def __init__(self, base_cell):
        HybridBlock.__init__(self)
        self.base_cell = base_cell  # attribute assignment registers the child

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)

    def infer_shape(self, x, *args):
        if hasattr(self.base_cell, "infer_shape"):
            self.base_cell.infer_shape(x, *args)


class DropoutCell(ModifierCell):
    """Applies dropout on the OUTPUT of the wrapped cell per step."""

    def __init__(self, base_cell, rate=0.5):
        super().__init__(base_cell)
        self._rate = float(rate)

    def hybrid_forward(self, F, x, states):
        from ... import autograd as _ag

        out, ns = self.base_cell(x, states)
        if self._rate:
            out = F.Dropout(out, p=self._rate, training=_ag.is_training())
        return out, ns


class ResidualCell(ModifierCell):
    """Adds the input to the wrapped cell's output (reference ResidualCell)."""

    def hybrid_forward(self, F, x, states):
        out, ns = self.base_cell(x, states)
        return out + x, ns


class ZoneoutCell(ModifierCell):
    """Zoneout regularization: randomly keep previous states
    (reference ZoneoutCell; Krueger et al. 2017)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = float(zoneout_outputs)
        self._zs = float(zoneout_states)

    def hybrid_forward(self, F, x, states):
        out, ns = self.base_cell(x, states)
        prev = states if isinstance(states, (list, tuple)) else [states]

        from ... import autograd as _ag

        def mix(new, old, rate):
            if not rate or not _ag.is_training():
                return new
            # dropout of ones gives the keep/replace mask with the right
            # scaling removed (mask is 0 or 1/(1-p); normalize back)
            mask = F.Dropout(F.ones_like(new), p=rate,
                             training=True) * (1.0 - rate)
            return mask * new + (1 - mask) * old

        out = mix(out, prev[0], self._zo)
        ns = [mix(n, p, self._zs) for n, p in zip(ns, prev)]
        return out, ns


class BidirectionalCell(_BaseCell):
    """Runs two cells over the sequence in opposite directions and concats
    outputs (reference BidirectionalCell; unroll-only, like the reference)."""

    def __init__(self, l_cell, r_cell):
        HybridBlock.__init__(self)
        self.l_cell, self.r_cell = l_cell, r_cell  # assignment registers

    def begin_state(self, batch_size=0, **kwargs):
        return [self.l_cell.begin_state(batch_size, **kwargs),
                self.r_cell.begin_state(batch_size, **kwargs)]

    def __call__(self, *args, **kwargs):
        raise NotImplementedError(
            "BidirectionalCell supports unroll() only (reference behavior)")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd

        axis = layout.find("T")
        bs = begin_state or self.begin_state(
            inputs.shape[1 - axis if axis == 0 else 0])
        l_out, l_states = self.l_cell.unroll(length, inputs, bs[0], layout,
                                             merge_outputs=True,
                                             valid_length=valid_length)
        rev = nd.SequenceReverse(inputs, axis=axis) if valid_length is None \
            else nd.SequenceReverse(inputs, valid_length,
                                    use_sequence_length=True, axis=axis)
        r_out, r_states = self.r_cell.unroll(length, rev, bs[1], layout,
                                             merge_outputs=True,
                                             valid_length=valid_length)
        r_out = nd.SequenceReverse(r_out, axis=axis) if valid_length is None \
            else nd.SequenceReverse(r_out, valid_length,
                                    use_sequence_length=True, axis=axis)
        out = nd.concat(l_out, r_out, dim=-1)
        return out, [l_states, r_states]


__all__ += ["ModifierCell", "DropoutCell", "ResidualCell", "ZoneoutCell",
            "BidirectionalCell"]
