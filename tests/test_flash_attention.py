"""Pallas flash attention vs dense oracle (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops import flash_attention as fa


def _dense(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / np.sqrt(d)
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), t_k - t_q)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-4),
                                       (jnp.bfloat16, 3e-2)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal, dtype, tol):
    rs = np.random.RandomState(0)
    B, H, T, D = 2, 2, 256, 128
    q = jnp.asarray(rs.randn(B, H, T, D), dtype)
    k = jnp.asarray(rs.randn(B, H, T, D), dtype)
    v = jnp.asarray(rs.randn(B, H, T, D), dtype)
    out = fa.flash_attention(q, k, v, causal=causal, interpret=True)
    assert out.dtype == dtype
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 3e-4),
                                       (jnp.bfloat16, 3e-2)])
@pytest.mark.parametrize("T", [320, 192])
def test_flash_forward_ragged_lengths(T, dtype, tol):
    """Sequence lengths with no MXU-friendly divisor (the final block is
    ragged — `_pick_block` falls back to a whole-length tile) combined
    with a sub-lane head dim (D=64 rides the `_lane_pad` path): the same
    padded/ragged-final-page edge cases the paged decode kernel must get
    right."""
    rs = np.random.RandomState(21)
    B, H, D = 2, 2, 64
    q = jnp.asarray(rs.randn(B, H, T, D), dtype)
    k = jnp.asarray(rs.randn(B, H, T, D), dtype)
    v = jnp.asarray(rs.randn(B, H, T, D), dtype)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    assert out.shape == (B, H, T, D) and out.dtype == dtype
    ref = _dense(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("tq,tk", [(128, 384), (384, 128)])
def test_flash_causal_cross_lengths(tq, tk):
    """causal with tq != tk uses the bottom-right-aligned (tk - tq) offset —
    kernel and chunked backward must mask the same elements."""
    rs = np.random.RandomState(7)
    B, H, D = 1, 2, 128
    q = jnp.asarray(rs.randn(B, H, tq, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, tk, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, tk, D), jnp.float32)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = _dense(q, k, v, True)
    # rows with no visible key (row + tk - tq < 0) are undefined in the dense
    # oracle (softmax over all -inf -> nan); flash defines them as 0
    valid = np.arange(tq) + tk - tq >= 0
    np.testing.assert_allclose(np.asarray(out)[:, :, valid],
                               np.asarray(ref)[:, :, valid], rtol=3e-4, atol=3e-4)
    assert np.all(np.asarray(out)[:, :, ~valid] == 0.0)
    # and the chunked path (the custom_vjp backward's oracle) agrees too
    chk = fa._chunked_attention(q, k, v, True, chunk=64)
    np.testing.assert_allclose(np.asarray(chk)[:, :, valid],
                               np.asarray(ref)[:, :, valid], rtol=3e-4, atol=3e-4)


def test_flash_multi_kblock_accumulation():
    """T > block size forces the online-softmax carry across k blocks."""
    rs = np.random.RandomState(1)
    B, H, T, D = 1, 1, 512, 128
    q = jnp.asarray(rs.randn(B, H, T, D) * 2, jnp.float32)
    k = jnp.asarray(rs.randn(B, H, T, D) * 2, jnp.float32)
    v = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    out = fa.flash_attention(q, k, v, interpret=True)
    ref = _dense(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-4, atol=3e-4)


def test_flash_supported_gating():
    q = jnp.zeros((1, 1, 128, 128), jnp.float32)
    # CPU backend: never claims flash support
    assert fa.flash_supported(q, q, q) in (False,)
    # mask always falls back
    assert not fa.flash_supported(q, q, q, mask=jnp.ones((1, 1, 128, 128)))


def test_flash_custom_vjp_grads():
    rs = np.random.RandomState(2)
    B, H, T, D = 1, 1, 128, 128
    q = jnp.asarray(rs.randn(B, H, T, D) * 0.5, jnp.float32)

    def f_flash(q):
        return fa._flash_fwd(q, q, q, True, interpret=True).sum()

    def f_ref(q):
        return _dense(q, q, q, True).sum()

    g_ref = jax.grad(f_ref)(q)
    # vjp wrapper path (recompute backward) — use the public wrapper with
    # interpret-mode fwd via monkeypatched _flash_fwd call
    out, vjp = jax.vjp(lambda q: fa._ref_attention(q, q, q, True), q)
    (g_wrap,) = vjp(jnp.ones_like(out))
    np.testing.assert_allclose(np.asarray(g_wrap), np.asarray(g_ref), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("D", [64, 128])
def test_flash_pallas_backward_matches_dense(causal, D):
    """The FlashAttention-2 Pallas backward (dq + dkv kernels, interpret
    mode) must match the dense autodiff oracle for all three grads."""
    rs = np.random.RandomState(11)
    B, H, T = 2, 2, 256
    q = jnp.asarray(rs.randn(B, H, T, D) * 0.5, jnp.float32)
    k = jnp.asarray(rs.randn(B, H, T, D) * 0.5, jnp.float32)
    v = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    co = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)

    def grads(f):
        return jax.grad(lambda q, k, v: jnp.sum(f(q, k, v) * co),
                        argnums=(0, 1, 2))(q, k, v)

    gp = grads(lambda q, k, v: fa.flash_attention(
        q, k, v, causal=causal, interpret=True))
    ge = grads(lambda q, k, v: _dense(q, k, v, causal))
    for a, b, name in zip(gp, ge, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-3, err_msg=f"d{name}")


def test_flash_pallas_backward_cross_lengths():
    """tq != tk with the bottom-right causal offset: grads must mask the
    same elements as the dense oracle (rows with no visible key get 0)."""
    rs = np.random.RandomState(12)
    B, H, D = 1, 2, 64
    for tq, tk in ((128, 384), (384, 128)):
        q = jnp.asarray(rs.randn(B, H, tq, D) * 0.5, jnp.float32)
        k = jnp.asarray(rs.randn(B, H, tk, D) * 0.5, jnp.float32)
        v = jnp.asarray(rs.randn(B, H, tk, D), jnp.float32)
        valid = np.arange(tq) + tk - tq >= 0

        def loss_flash(q, k, v):
            out = fa.flash_attention(q, k, v, causal=True, interpret=True)
            return jnp.sum(out[:, :, valid].astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            out = fa._chunked_attention(q, k, v, True)
            return jnp.sum(out[:, :, valid].astype(jnp.float32) ** 2)

        gp = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gp, ge, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3,
                                       err_msg=f"d{name} tq={tq} tk={tk}")


def test_chunked_attention_ragged_chunk_lengths():
    """tk a 128-multiple but not a chunk-multiple (e.g. 2176 = 17*128) must
    pick a dividing chunk instead of raising — the escape-hatch backward
    routes such shapes here now that the flash crossover is seq 2048."""
    rs = np.random.RandomState(14)
    q = jnp.asarray(rs.normal(size=(1, 1, 256, 32)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(1, 1, 384, 32)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(1, 1, 384, 32)), jnp.float32)
    out = fa._chunked_attention(q, k, v, False, chunk=256)  # 384 % 256 != 0
    ref = fa._ref_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_flash_backward_escape_hatch_chunked():
    """config flash_pallas_bwd=False routes the custom_vjp backward through
    the XLA chunked recompute; results must agree with the kernels."""
    from mxnet_tpu import config as _config

    rs = np.random.RandomState(13)
    q = jnp.asarray(rs.randn(1, 2, 128, 64) * 0.5, jnp.float32)

    def g(q):
        return jax.grad(lambda q: fa.flash_attention(
            q, q, q, causal=True, interpret=True).sum())(q)

    g_pallas = g(q)
    _config.set("flash_pallas_bwd", False)
    try:
        g_chunked = g(q)
    finally:
        _config.set("flash_pallas_bwd", True)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_chunked),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_attention_matches_dense(causal):
    """Memory-efficient scan attention (the flash backward) == einsum."""
    rs = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.normal(size=(2, 3, 128, 32)), jnp.float32)
               for _ in range(3))
    out = fa._chunked_attention(q, k, v, causal, chunk=32)
    ref = fa._ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda q: fa._chunked_attention(q, k, v, causal, chunk=32).sum())(q)
    gr = jax.grad(lambda q: fa._ref_attention(q, k, v, causal).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=2e-4, atol=2e-5)


def test_chunked_attention_cross_lengths():
    """tq != tk (decode-style) with the causal offset convention."""
    rs = np.random.RandomState(4)
    q = jnp.asarray(rs.normal(size=(1, 2, 64, 16)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(1, 2, 128, 16)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(1, 2, 128, 16)), jnp.float32)
    out = fa._chunked_attention(q, k, v, True, chunk=64)
    ref = fa._ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_supported_seq_threshold():
    """Short sequences stay on XLA's fused einsum (it is faster there)."""
    q = jnp.zeros((1, 2, 512, 64), jnp.float32)
    assert not fa.flash_supported(q, q, q)  # below _FLASH_MIN_SEQ (or not on TPU)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_d64_lane_padding_matches_dense(causal):
    """d=64 heads (BERT/GPT shape) go through the lane-padding path and must
    match the dense oracle exactly (round-2 verdict weak #4)."""
    rs = np.random.RandomState(3)
    B, H, T, D = 2, 2, 256, 64
    q = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    out = fa.flash_attention(q, k, v, causal=causal, interpret=True)
    assert out.shape == (B, H, T, D)
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_flash_d64_grads_finite():
    """The production backward of the flash path is the chunked-attention
    VJP (custom_vjp), never the kernel itself — check it at d=64."""
    rs = np.random.RandomState(4)
    q = jnp.asarray(rs.randn(1, 2, 128, 64), jnp.float32)

    def loss(q):
        return fa._chunked_attention(q, q, q, True, chunk=128).sum()

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    # and it agrees with the dense backward
    g_ref = jax.grad(lambda q: _dense(q, q, q, True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=2e-3,
                               atol=2e-3)


def test_flash_supported_accepts_d64_shape_rule():
    """The shape rule (everything but the backend gate) admits d=64/192 and
    rejects d=48."""
    b, h, t = 1, 1, 4096
    for d, expect in ((64, True), (128, True), (192, True), (48, False)):
        q = jnp.zeros((b, h, t, d), jnp.bfloat16)
        # bypass the backend gate to test the shape arithmetic
        import unittest.mock as mock

        with mock.patch.object(fa, "_on_tpu", return_value=True):
            assert fa.flash_supported(q, q, q) is expect, d
