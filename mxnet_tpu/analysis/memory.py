"""Static buffer-liveness & peak-residency analysis (docs/ANALYSIS.md).

The analysis subsystem already prices compute (the FLOPs model in
``observability.goodput``) and communication (:mod:`.comm`); this module
prices **memory** — the resource that actually caps batch size, window
length and page-pool size. It sweeps the def/use tables the HLO auditor
parses (:class:`~mxnet_tpu.analysis.hlo_audit.ValueDef`, both dialects)
in program order and computes, per instruction, the set of live buffers:

  - a value is live from its defining instruction to its last use;
  - program inputs are pinned for the whole program (the caller owns
    them), categorized by the flat-input category map the audit entry
    points provide (params / opt_state / kv_pages / batch / ...);
  - **donation-aware**: an output that aliases a donated input
    (``input_output_alias`` / ``tf.aliasing_output``) writes the input's
    buffer in place and costs zero extra bytes — for a scan carry the
    aliased *element* of the ``while`` result is subtracted, so donated
    carries are never double-counted;
  - in-place ops (``while``, ``dynamic-update-slice``,
    ``optimization-barrier``) free their dying operands *before* the
    result is counted — XLA reuses the buffer, the sweep must too;
  - structural ops (``tuple`` / ``get-tuple-element`` / ``bitcast`` /
    ``reshape`` / non-entry ``parameter``) are zero-cost aliases;
  - control-flow subcomputations (``while`` body/cond, ``conditional``
    branches, ``func.call`` targets) contribute their own *internal*
    liveness peak at the call instruction (recursively); **fusion bodies
    do not** — fused intermediates live in registers, which is exactly
    the materialization boundary of arXiv:2301.13062.

The result is a :class:`MemoryReport`: estimated ``peak_bytes``, the
residency ``timeline``, ``largest_buffers(n)``, an at-peak ``by_category``
breakdown, and the **materialization detectors**:

  ``kv_gather_materialize``  a gather whose result is pool-sized — the
                             XLA gather-materialize of the paged KV cache
                             the ROADMAP's Pallas decode kernel removes
  ``f32_upcast``             a large f32 copy converted from a
                             bf16-stored tensor (the AMP storage win
                             silently undone at compute time)
  ``long_lived_temp``        a big non-input buffer live across most of
                             the program — the remat-defeating pattern
                             (an activation ``jax.checkpoint`` was
                             supposed to drop is being kept anyway)

Compiled-dialect text is scheduled (``is_scheduled=true``), so text order
is the schedule and the sweep is faithful; the lowered dialect gives a
pre-fusion upper bound. The estimate is cross-validated against
``jax.stages.Compiled.memory_analysis()`` on CPU: ``peak_bytes`` must
agree with ``arguments + outputs + temps − aliased`` within
:data:`VALIDATION_TOLERANCE` (tests/test_memory.py, ``make memcheck``).
"""
from __future__ import annotations

import dataclasses
from collections import Counter as _Counter
from typing import Dict, List, Optional, Sequence, Tuple

from .hlo_audit import ProgramReport, ValueDef, _ASYNC_DONE, tensor_bytes

__all__ = ["BufferLife", "Materialization", "MemoryReport", "memory_report",
           "jax_expected_peak", "VALIDATION_TOLERANCE"]

#: documented tolerance of the cross-validation against
#: ``Compiled.memory_analysis()`` on CPU: the liveness estimate and XLA's
#: buffer assignment must agree on peak residency within this relative
#: error on the gated step/decode programs (measured: +6.4% on the MLP
#: Adam step, +7.6% on the dense decode step, +10/15% on the T=1024
#: GPT-2 step without/with remat). The gap is real, not noise: XLA pads
#: and aligns allocations, shares same-sized buffers the sweep keeps
#: distinct, and schedules fusions the text can't see inside. Fused
#: k-step window (scan) programs sit outside this bound by design — the
#: sweep counts the body working set against the carry without modeling
#: XLA's in-loop buffer sharing, an upper bound the goldens pin instead.
VALIDATION_TOLERANCE = 0.25

# result is an alias/view of an existing buffer — zero allocation
ZERO_COST_OPS = frozenset({
    "parameter", "region_arg", "tuple", "get_tuple_element", "bitcast",
    "reshape", "return", "after_all", "partition_id", "replica_id",
})

# in-place ops: the result reuses the storage of operands dying at the
# same instruction (XLA compiles while carries and top-level DUS in place)
ALIAS_OPS = frozenset({"while", "dynamic_update_slice",
                       "optimization_barrier", "opt_barrier"})

# ops whose subcomputations' internal temps are live at the call point
# (fusion deliberately NOT here: fused intermediates are registers)
RECURSE_OPS = frozenset({"while", "conditional", "case", "call"})

# KV-cache input categories the gather-materialize detector watches
_KV_CATEGORIES = frozenset({"kv_pages", "kv_cache", "draft_pages"})


@dataclasses.dataclass
class BufferLife:
    """One allocated buffer's life: the liveness engine's per-value view
    (zero-cost aliases excluded)."""

    vid: str
    op: str
    bytes: int       # allocation charged to this value (alias-reduced)
    category: str
    line: int        # source line of the defining instruction
    t_def: int       # timeline index of the def
    t_end: int       # timeline index of the last use (inclusive)

    @property
    def span(self) -> int:
        return self.t_end - self.t_def

    def describe(self) -> str:
        return (f"%{self.vid} ({self.op}, {self.category}): {self.bytes} B"
                f" live [{self.t_def}, {self.t_end}]")


@dataclasses.dataclass
class Materialization:
    """One detected materialization hazard (see module docstring)."""

    kind: str
    bytes: int
    line: int
    detail: str

    def __str__(self):
        return f"{self.kind} @L{self.line}: {self.detail}"


@dataclasses.dataclass
class MemoryReport:
    """Estimated memory residency of one program (docs/ANALYSIS.md)."""

    dialect: str
    peak_bytes: int          # max resident bytes (inputs pinned + live)
    temp_peak_bytes: int     # max live bytes EXCLUDING the pinned inputs
    peak_index: int          # timeline index of the peak
    peak_line: int           # source line of the peak instruction
    timeline: List[Tuple[int, int, int]]  # (line, total, non-input) per t
    buffers: List[BufferLife]             # allocations, program order
    by_category: Dict[str, int]           # live bytes per category AT peak
    input_bytes: int
    output_bytes: int
    donated_bytes: int       # input bytes whose outputs write in place
    materializations: List[Materialization]
    n_values: int

    def largest_buffers(self, n: int = 10) -> List[BufferLife]:
        """The ``n`` biggest allocations, descending — where the peak
        actually lives."""
        return sorted(self.buffers, key=lambda b: -b.bytes)[:n]

    def materialization_kinds(self) -> Dict[str, int]:
        return dict(_Counter(m.kind for m in self.materializations))

    def category_share(self, category: str) -> float:
        if not self.peak_bytes:
            return 0.0
        return self.by_category.get(category, 0) / self.peak_bytes

    def summary(self) -> dict:
        """JSON-safe digest (what tools/memcheck.py snapshots)."""
        return {
            "dialect": self.dialect,
            "peak_bytes": self.peak_bytes,
            "temp_peak_bytes": self.temp_peak_bytes,
            "peak_line": self.peak_line,
            "input_bytes": self.input_bytes,
            "output_bytes": self.output_bytes,
            "donated_bytes": self.donated_bytes,
            "by_category": dict(self.by_category),
            "top_buffers": [[b.op, b.bytes]
                            for b in self.largest_buffers(5)],
            "materializations": self.materialization_kinds(),
            "n_values": self.n_values,
        }


class _Inst:
    """One live instance of an SSA value (regions re-bind short names, so
    instances — not vids — are the liveness unit)."""

    __slots__ = ("v", "t_def", "t_end", "cost", "category", "is_output")

    def __init__(self, v: ValueDef, t: int):
        self.v = v
        self.t_def = t
        self.t_end = t
        self.cost = 0
        self.category = ""
        self.is_output = False


def _zero_cost(v: ValueDef) -> bool:
    return (v.op in ZERO_COST_OPS or v.op in _ASYNC_DONE
            or v.param is not None)


def _build_instances(values: Sequence[ValueDef]):
    """(instances, final vid->instance map) with def/last-use indices."""
    instances: List[_Inst] = []
    cur: Dict[str, _Inst] = {}
    for t, v in enumerate(values):
        for u in v.uses:
            inst = cur.get(u)
            if inst is not None:
                inst.t_end = t
        if v.vid:
            inst = _Inst(v, t)
            instances.append(inst)
            cur[v.vid] = inst
    return instances, cur


def _subcomp_peak(name: str, subs: Dict[str, List[ValueDef]],
                  memo: Dict[str, int], visiting: frozenset) -> int:
    """Internal liveness peak of one subcomputation: its own temps (its
    parameters alias caller buffers and cost nothing) plus any nested
    control-flow contribution."""
    if name in memo:
        return memo[name]
    values = subs.get(name)
    if values is None or name in visiting:
        return 0
    visiting = visiting | {name}
    instances, _ = _build_instances(values)
    by_def = {inst.t_def: inst for inst in instances}
    expiring: Dict[int, List[_Inst]] = {}
    for inst in instances:
        inst.cost = 0 if _zero_cost(inst.v) else inst.v.bytes
        expiring.setdefault(inst.t_end, []).append(inst)
    live = 0
    peak = 0
    for t, v in enumerate(values):
        callee_extra = 0
        if v.callees and v.op in RECURSE_OPS:
            callee_extra = max(
                _subcomp_peak(c, subs, memo, visiting) for c in v.callees)
        inst = by_def.get(t)
        released = 0
        if inst is not None and v.op in ALIAS_OPS:
            for d in expiring.get(t, ()):
                if d is not inst:
                    live -= d.cost
            released = 1
        if inst is not None:
            live += inst.cost
        peak = max(peak, live + callee_extra)
        if not released:
            for d in expiring.get(t, ()):
                live -= d.cost
    memo[name] = peak
    return peak


def memory_report(report: ProgramReport,
                  categories: Optional[Dict[int, str]] = None,
                  default_category: str = "activations",
                  detect: bool = True,
                  gather_frac: float = 0.75,
                  upcast_min_bytes: int = 1 << 20,
                  long_lived_min_bytes: int = 1 << 20,
                  long_lived_frac: float = 0.5) -> MemoryReport:
    """Sweep ``report``'s def/use tables into a :class:`MemoryReport`.

    ``categories`` maps flat input index -> category label (``params`` /
    ``opt_state`` / ``kv_pages`` / ``batch`` ...); unmapped inputs land
    under ``"inputs"`` and every non-input allocation under
    ``default_category``. The detector thresholds are keyword-tunable;
    defaults are sized so tiny CI programs stay quiet (1 MiB floors) while
    real serving/training programs are caught.
    """
    categories = categories or {}
    values = report.values
    n = len(values)
    inputs = report.inputs
    pinned = sum(tensor_bytes(dt, sh) for dt, sh in inputs)
    instances, cur = _build_instances(values)

    # -- pass-through carries: a while whose carry element k is fed
    # directly by an entry parameter aliases that pinned buffer (XLA
    # compiles the loop in place; had the body needed a private copy, the
    # operand would BE a copy instruction, which allocates and is counted)
    # — without this, a scan that threads its stacked batch through the
    # carry double-counts the whole batch
    reductions: Dict[int, int] = {}  # id(inst) -> bytes to subtract
    passthrough: set = set()         # (id(while inst), element k) covered
    for inst in instances:
        if inst.v.op != "while":
            continue
        elems: List[str] = list(inst.v.uses)
        if len(elems) == 1:
            opnd = cur.get(elems[0])
            if opnd is not None and opnd.v.op == "tuple":
                elems = list(opnd.v.uses)
        for k, u in enumerate(elems):
            src = cur.get(u)
            if src is None or src.v.param is None:
                continue
            if k < len(inst.v.results):
                b = tensor_bytes(*inst.v.results[k])
            elif src.v.results:
                b = tensor_bytes(*src.v.results[0])
            else:
                continue
            reductions[id(inst)] = reductions.get(id(inst), 0) + b
            passthrough.add((id(inst), k))

    # -- donated-alias exclusion: output j writing input i's buffer ------
    donated_bytes = 0
    out_ids = report.output_ids
    for out_idx, param_idx in sorted(report.donation.out_alias.items()):
        if param_idx < len(inputs):
            donated_bytes += tensor_bytes(*inputs[param_idx])
        if out_idx >= len(out_ids):
            continue
        token = out_ids[out_idx]
        base, sep, elem = token.partition("#")
        inst = cur.get(base)
        if inst is None:
            continue
        key = id(inst)
        if sep and elem.isdigit() and int(elem) < len(inst.v.results):
            # MLIR tuple-element ref: subtract exactly the carried element
            # (unless the pass-through rule above already zeroed it)
            if (key, int(elem)) in passthrough:
                continue
            reductions[key] = reductions.get(key, 0) + \
                tensor_bytes(*inst.v.results[int(elem)])
        elif inst.v.op == "get_tuple_element" and inst.v.uses:
            src = cur.get(inst.v.uses[0])
            if src is not None:
                k = inst.v.gte_index
                if k is not None and (id(src), k) in passthrough:
                    continue
                reductions[id(src)] = reductions.get(id(src), 0) + \
                    inst.v.bytes
        else:
            reductions[key] = reductions.get(key, 0) + inst.v.bytes

    # -- output bytes + keep outputs live to the end ---------------------
    output_bytes = 0
    for token in out_ids:
        base, sep, elem = token.partition("#")
        inst = cur.get(base)
        if inst is None:
            continue
        inst.t_end = n  # never expires inside the sweep
        inst.is_output = True
        if sep and elem.isdigit() and int(elem) < len(inst.v.results):
            output_bytes += tensor_bytes(*inst.v.results[int(elem)])
        else:
            output_bytes += inst.v.bytes

    # -- per-instance cost & category ------------------------------------
    by_def: Dict[int, _Inst] = {}
    expiring: Dict[int, List[_Inst]] = {}
    for inst in instances:
        by_def[inst.t_def] = inst
        if _zero_cost(inst.v):
            inst.cost = 0
        else:
            inst.cost = max(0, inst.v.bytes - reductions.get(id(inst), 0))
        inst.category = default_category
        expiring.setdefault(inst.t_end, []).append(inst)

    cat_live: _Counter = _Counter()
    for i, (dt, sh) in enumerate(inputs):
        cat_live[categories.get(i, "inputs")] += tensor_bytes(dt, sh)

    # -- the sweep --------------------------------------------------------
    memo: Dict[str, int] = {}
    subs = report.subcomputations
    live_temp = 0
    peak = pinned
    peak_idx = -1
    peak_line = 0
    peak_cats = dict(cat_live)
    timeline: List[Tuple[int, int, int]] = []
    temp_peak = 0
    for t, v in enumerate(values):
        callee_extra = 0
        if v.callees and v.op in RECURSE_OPS:
            callee_extra = max(
                _subcomp_peak(c, subs, memo, frozenset()) for c in v.callees)
        inst = by_def.get(t)
        released = False
        if inst is not None and v.op in ALIAS_OPS:
            # in-place: dying operands are freed BEFORE the result exists
            for d in expiring.get(t, ()):
                if d is not inst:
                    live_temp -= d.cost
                    cat_live[d.category] -= d.cost
            released = True
        if inst is not None:
            live_temp += inst.cost
            cat_live[inst.category] += inst.cost
        total = pinned + live_temp + callee_extra
        timeline.append((v.line, total, live_temp + callee_extra))
        temp_peak = max(temp_peak, live_temp + callee_extra)
        if total > peak:
            peak = total
            peak_idx = t
            peak_line = v.line
            peak_cats = dict(cat_live)
            if callee_extra:
                peak_cats[default_category] = \
                    peak_cats.get(default_category, 0) + callee_extra
        if not released:
            for d in expiring.get(t, ()):
                live_temp -= d.cost
                cat_live[d.category] -= d.cost

    buffers = [BufferLife(vid=i.v.vid, op=i.v.op, bytes=i.cost,
                          category=i.category, line=i.v.line,
                          t_def=i.t_def, t_end=min(i.t_end, n))
               for i in instances if i.cost > 0]

    mats: List[Materialization] = []
    if detect:
        mats = _detect_materializations(
            report, categories, buffers, n,
            gather_frac=gather_frac, upcast_min_bytes=upcast_min_bytes,
            long_lived_min_bytes=long_lived_min_bytes,
            long_lived_frac=long_lived_frac)

    peak_cats = {k: v for k, v in peak_cats.items() if v > 0}
    return MemoryReport(
        dialect=report.dialect, peak_bytes=peak,
        temp_peak_bytes=temp_peak, peak_index=peak_idx,
        peak_line=peak_line, timeline=timeline, buffers=buffers,
        by_category=peak_cats, input_bytes=pinned,
        output_bytes=output_bytes, donated_bytes=donated_bytes,
        materializations=mats, n_values=n)


def _detect_materializations(report: ProgramReport,
                             categories: Dict[int, str],
                             buffers: List[BufferLife], n: int, *,
                             gather_frac: float, upcast_min_bytes: int,
                             long_lived_min_bytes: int,
                             long_lived_frac: float
                             ) -> List[Materialization]:
    mats: List[Materialization] = []
    # KV gather-materialize: a gather result the size of a whole pool —
    # the decode path is reading the paged cache by materializing it
    kv_max = 0
    for i, (dt, sh) in enumerate(report.inputs):
        if categories.get(i) in _KV_CATEGORIES:
            kv_max = max(kv_max, tensor_bytes(dt, sh))
    if kv_max:
        for o in report.ops:
            if o.name not in ("gather", "dynamic_gather"):
                continue
            rb = tensor_bytes(o.dtype, o.shape)
            if rb >= gather_frac * kv_max:
                mats.append(Materialization(
                    "kv_gather_materialize", rb, o.line,
                    f"gather materializes {rb} B against a {kv_max} B "
                    "KV pool (the XLA gather-materialize the Pallas "
                    "decode kernel is meant to remove)"))
    # f32 upcast of bf16-stored tensors: the storage dtype's memory win
    # silently undone by a full-size convert copy
    for o in report.ops:
        if o.name != "convert" or o.dtype not in ("f32", "f64"):
            continue
        if "bf16" not in o.dtypes and "f16" not in o.dtypes:
            continue
        rb = tensor_bytes(o.dtype, o.shape)
        if rb >= upcast_min_bytes:
            src = "bf16" if "bf16" in o.dtypes else "f16"
            mats.append(Materialization(
                "f32_upcast", rb, o.line,
                f"{src}-stored tensor upcast into a {rb} B {o.dtype} "
                "copy"))
    # remat-defeating long-lived temps: a big non-input buffer held
    # across most of the program (forward→backward) — exactly what
    # jax.checkpoint was supposed to drop
    if n >= 16:
        for b in buffers:
            if b.bytes >= long_lived_min_bytes and \
                    b.span >= long_lived_frac * n:
                mats.append(Materialization(
                    "long_lived_temp", b.bytes, b.line,
                    f"%{b.vid} ({b.op}) holds {b.bytes} B across "
                    f"{b.span}/{n} instructions — a remat-defeating "
                    "live range"))
    return sorted(mats, key=lambda m: (m.line, m.kind))


def jax_expected_peak(ma) -> int:
    """The resident-bytes figure ``Compiled.memory_analysis()`` implies:
    arguments + outputs + temps − aliased (an aliased output reuses its
    donated argument's buffer). This is what :func:`memory_report`'s
    ``peak_bytes`` is validated against, within
    :data:`VALIDATION_TOLERANCE`."""
    return int(ma.argument_size_in_bytes + ma.output_size_in_bytes
               + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
