// Graph-build + autograd + executor + kvstore C ABI tier.
//
// Reference analogs: src/c_api/c_api_symbolic.cc (MXSymbolCreateAtomicSymbol
// / MXSymbolCompose), src/c_api/c_api_executor.cc (MXExecutorSimpleBindEx /
// MXExecutorForward / MXExecutorBackward), MXAutogradBackwardEx
// (c_api_ndarray.cc -> Imperative::Backward), src/kvstore/kvstore_local.h.
//
// Design: ONE reverse-mode machine — an imperative tape recorded by the op
// dispatch tier (internal.h hook) — serves both the `MXTPUAutograd*` surface
// and the executor (Forward = record-replay of the symbol graph, Backward =
// tape sweep). VJPs are *compositions of public ABI ops* (dot backward is
// two transposed dots, etc.), mirroring how the reference's backward passes
// are themselves registered operators. The native tier is a host f32/f64
// reference implementation; the jax/XLA path remains the performance tier.
#include "../include/mxtpu_c_api.h"
#include "internal.h"

#include <cmath>
#include <cstring>
#include <deque>
#include <map>
#include <set>
#include <string>
#include <vector>

namespace {

// -- small helpers over the public ABI --------------------------------------

struct Arr {
  MXTPUNDHandle h = nullptr;
};

int nd_shape(MXTPUNDHandle h, std::vector<int64_t>* shape) {
  int ndim = 0;
  const int64_t* s = nullptr;
  if (MXTPUNDArrayGetShape(h, &ndim, &s) != 0) return -1;
  shape->assign(s, s + ndim);
  return 0;
}

int64_t nd_size(MXTPUNDHandle h) {
  int64_t n = 0;
  MXTPUNDArraySize(h, &n);
  return n;
}

int nd_dtype(MXTPUNDHandle h) {
  int dt = kMXTPUFloat32;
  MXTPUNDArrayGetDType(h, &dt);
  return dt;
}

size_t nd_esize(MXTPUNDHandle h) {
  return nd_dtype(h) == kMXTPUFloat64 ? 8 : 4;
}

// element 0 as double (f32/f64 — the graph tier's dtypes)
double nd_scalar(MXTPUNDHandle h) {
  const void* p = nullptr;
  MXTPUNDArrayGetData(h, &p);
  if (nd_dtype(h) == kMXTPUFloat64) return *static_cast<const double*>(p);
  return *static_cast<const float*>(p);
}

MXTPUNDHandle nd_full_like(MXTPUNDHandle h, double value) {
  std::vector<int64_t> shape;
  if (nd_shape(h, &shape) != 0) return nullptr;
  size_t n = static_cast<size_t>(nd_size(h));
  int dt = nd_dtype(h);
  MXTPUNDHandle out = nullptr;
  if (dt == kMXTPUFloat64) {
    std::vector<double> buf(n, value);
    if (MXTPUNDArrayCreateFromBytes(buf.data(), shape.data(),
                                    static_cast<int>(shape.size()),
                                    kMXTPUFloat64, &out) != 0)
      return nullptr;
  } else {
    std::vector<float> buf(n, static_cast<float>(value));
    if (MXTPUNDArrayCreateFromBytes(buf.data(), shape.data(),
                                    static_cast<int>(shape.size()),
                                    kMXTPUFloat32, &out) != 0)
      return nullptr;
  }
  return out;
}

MXTPUNDHandle nd_copy(MXTPUNDHandle h) {
  std::vector<int64_t> shape;
  if (nd_shape(h, &shape) != 0) return nullptr;
  const void* p = nullptr;
  MXTPUNDArrayGetData(h, &p);
  MXTPUNDHandle out = nullptr;
  if (MXTPUNDArrayCreateFromBytes(p, shape.data(),
                                  static_cast<int>(shape.size()),
                                  nd_dtype(h), &out) != 0)
    return nullptr;
  return out;
}

// invoke a 1-output op; returns the new handle or nullptr (error already set)
MXTPUNDHandle inv1(const char* op, std::vector<MXTPUNDHandle> ins,
                   const char* params = "") {
  MXTPUNDHandle out[1] = {nullptr};
  int n_out = 1;
  if (MXTPUImperativeInvoke(op, ins.data(), static_cast<int>(ins.size()),
                            params, out, &n_out) != 0)
    return nullptr;
  return out[0];
}

// -- autograd tape -----------------------------------------------------------

struct TapeNode {
  std::string op;
  std::string params;
  std::vector<MXTPUNDHandle> inputs;
  std::vector<MXTPUNDHandle> outputs;
};

struct AutogradState {
  bool recording = false;
  std::vector<TapeNode> tape;
  std::set<MXTPUNDHandle> marked;
  std::map<MXTPUNDHandle, MXTPUNDHandle> grads;  // var -> grad (owned)
  std::vector<MXTPUNDHandle> temps;              // owned intermediates

  void clear_grads() {
    for (auto& kv : grads) MXTPUNDArrayFree(kv.second);
    grads.clear();
    for (auto h : temps) MXTPUNDArrayFree(h);
    temps.clear();
  }
  void clear_tape() { tape.clear(); }
};

thread_local AutogradState g_ag;

double param_num(const std::string& json, const char* key, double dflt) {
  // single-key lookup into the flat param JSON (numbers only)
  std::string pat = std::string("\"") + key + "\"";
  size_t p = json.find(pat);
  if (p == std::string::npos) return dflt;
  p = json.find(':', p);
  if (p == std::string::npos) return dflt;
  return std::strtod(json.c_str() + p + 1, nullptr);
}

bool param_flag(const std::string& json, const char* key) {
  std::string pat = std::string("\"") + key + "\"";
  size_t p = json.find(pat);
  if (p == std::string::npos) return false;
  p = json.find(':', p);
  if (p == std::string::npos) return false;
  size_t v = json.find_first_not_of(" \t", p + 1);
  return v != std::string::npos && json.compare(v, 4, "true") == 0;
}

// accumulate cotangent `g` (owned by caller's map logic) into cot[var]
int accumulate(std::map<MXTPUNDHandle, MXTPUNDHandle>* cot,
               MXTPUNDHandle var, MXTPUNDHandle g) {
  auto it = cot->find(var);
  if (it == cot->end()) {
    (*cot)[var] = g;
    return 0;
  }
  MXTPUNDHandle sum = inv1("add", {it->second, g});
  if (sum == nullptr) return -1;
  MXTPUNDArrayFree(it->second);
  MXTPUNDArrayFree(g);
  it->second = sum;
  return 0;
}

// VJP of one tape node: push input cotangents given output cotangent g.
// Returns 0/-1; new cotangents are accumulated into `cot` (ownership moves).
int vjp_node(const TapeNode& n, MXTPUNDHandle g,
             std::map<MXTPUNDHandle, MXTPUNDHandle>* cot) {
  const std::string& op = n.op;
  auto in = [&](size_t i) { return n.inputs[i]; };
  if (op == "dot") {
    // all four transpose layouts; derivation from C[i,j] index algebra:
    //   C = A·B    : dA = g·Bᵀ        dB = Aᵀ·g
    //   C = Aᵀ·B   : dA = B·gᵀ        dB = A·g
    //   C = A·Bᵀ   : dA = g·B         dB = gᵀ·A
    //   C = Aᵀ·Bᵀ  : dA = Bᵀ·gᵀ       dB = gᵀ·Aᵀ
    bool ta = param_flag(n.params, "transpose_a");
    bool tb = param_flag(n.params, "transpose_b");
    MXTPUNDHandle da, db;
    if (!ta && !tb) {
      da = inv1("dot", {g, in(1)}, "{\"transpose_b\": true}");
      db = inv1("dot", {in(0), g}, "{\"transpose_a\": true}");
    } else if (ta && !tb) {
      da = inv1("dot", {in(1), g}, "{\"transpose_b\": true}");
      db = inv1("dot", {in(0), g});
    } else if (!ta && tb) {
      da = inv1("dot", {g, in(1)});
      db = inv1("dot", {g, in(0)}, "{\"transpose_a\": true}");
    } else {
      da = inv1("dot", {in(1), g},
                "{\"transpose_a\": true, \"transpose_b\": true}");
      db = inv1("dot", {g, in(0)},
                "{\"transpose_a\": true, \"transpose_b\": true}");
    }
    if (da == nullptr || db == nullptr) return -1;
    if (accumulate(cot, in(0), da)) return -1;
    return accumulate(cot, in(1), db);
  }
  if (op == "add" || op == "broadcast_add") {
    std::vector<int64_t> sa, sb;
    nd_shape(in(0), &sa);
    nd_shape(in(1), &sb);
    MXTPUNDHandle da = nd_copy(g);
    if (da == nullptr || accumulate(cot, in(0), da)) return -1;
    if (sa == sb) {
      MXTPUNDHandle db = nd_copy(g);
      if (db == nullptr) return -1;
      return accumulate(cot, in(1), db);
    }
    // (M,N)+(N,): bias grad = column sums of g
    MXTPUNDHandle db = inv1("sum", {g}, "{\"axis\": 0}");
    if (db == nullptr) return -1;
    return accumulate(cot, in(1), db);
  }
  if (op == "subtract") {
    MXTPUNDHandle da = nd_copy(g);
    MXTPUNDHandle db = inv1("negative", {g});
    if (da == nullptr || db == nullptr) return -1;
    if (accumulate(cot, in(0), da)) return -1;
    return accumulate(cot, in(1), db);
  }
  if (op == "multiply") {
    MXTPUNDHandle da = inv1("multiply", {g, in(1)});
    MXTPUNDHandle db = inv1("multiply", {g, in(0)});
    if (da == nullptr || db == nullptr) return -1;
    if (accumulate(cot, in(0), da)) return -1;
    return accumulate(cot, in(1), db);
  }
  if (op == "relu") {
    MXTPUNDHandle zeros = nd_full_like(in(0), 0.0f);
    if (zeros == nullptr) return -1;
    MXTPUNDHandle mask = inv1("greater", {in(0), zeros});
    MXTPUNDArrayFree(zeros);
    if (mask == nullptr) return -1;
    MXTPUNDHandle da = inv1("multiply", {g, mask});
    MXTPUNDArrayFree(mask);
    if (da == nullptr) return -1;
    return accumulate(cot, in(0), da);
  }
  if (op == "exp") {
    MXTPUNDHandle da = inv1("multiply", {g, n.outputs[0]});
    if (da == nullptr) return -1;
    return accumulate(cot, in(0), da);
  }
  if (op == "log") {
    MXTPUNDHandle da = inv1("divide", {g, in(0)});
    if (da == nullptr) return -1;
    return accumulate(cot, in(0), da);
  }
  if (op == "negative") {
    MXTPUNDHandle da = inv1("negative", {g});
    if (da == nullptr) return -1;
    return accumulate(cot, in(0), da);
  }
  if (op == "_mul_scalar") {
    char buf[64];
    std::snprintf(buf, sizeof(buf), "{\"scalar\": %.17g}",
                  param_num(n.params, "scalar", 1.0));
    MXTPUNDHandle da = inv1("_mul_scalar", {g}, buf);
    if (da == nullptr) return -1;
    return accumulate(cot, in(0), da);
  }
  if (op == "sum") {
    double axis = param_num(n.params, "axis", -999.0);
    if (axis == -999.0) {  // full reduce: grad = broadcast of the scalar
      MXTPUNDHandle da = nd_full_like(in(0), nd_scalar(g));
      if (da == nullptr) return -1;
      return accumulate(cot, in(0), da);
    }
    if (axis == 0.0) {  // (M,N) -axis0-> (N,): grad = row-broadcast of g,
                        // composed as zeros_like(in) (M,N) + g (N,)
      MXTPUNDHandle zeros = nd_full_like(in(0), 0.0);
      if (zeros == nullptr) return -1;
      MXTPUNDHandle da = inv1("broadcast_add", {zeros, g});
      MXTPUNDArrayFree(zeros);
      if (da == nullptr) return -1;
      return accumulate(cot, in(0), da);
    }
    MXTPUSetLastError("autograd: sum vjp supports full reduce or axis=0");
    return -1;
  }
  MXTPUSetLastError(
      (std::string("autograd: no vjp registered for op '") + op + "'")
          .c_str());
  return -1;
}

int backward_from(MXTPUNDHandle head) {
  g_ag.clear_grads();
  std::map<MXTPUNDHandle, MXTPUNDHandle> cot;
  MXTPUNDHandle seed = nd_full_like(head, 1.0f);
  if (seed == nullptr) return -1;
  cot[head] = seed;
  bool was_recording = g_ag.recording;
  g_ag.recording = false;  // vjp-composition invokes must not re-record
  int rc = 0;
  for (auto it = g_ag.tape.rbegin(); it != g_ag.tape.rend(); ++it) {
    // every registered VJP is for a single-output op; a cotangent arriving
    // on a secondary output (multi-output bridge op) must fail loudly, not
    // be skipped — that would silently zero upstream grads
    for (size_t oi = 1; oi < it->outputs.size(); ++oi) {
      if (cot.count(it->outputs[oi])) {
        MXTPUSetLastError(
            (std::string("autograd: no multi-output vjp for op '") + it->op +
             "' (gradient reached output " + std::to_string(oi) + ")")
                .c_str());
        rc = -1;
        break;
      }
    }
    if (rc != 0) break;
    auto git = cot.find(it->outputs[0]);
    if (git == cot.end()) continue;  // node not on the path to head
    MXTPUNDHandle g = git->second;
    cot.erase(git);
    rc = vjp_node(*it, g, &cot);
    MXTPUNDArrayFree(g);
    if (rc != 0) break;
  }
  g_ag.recording = was_recording;
  if (rc != 0) {
    for (auto& kv : cot) MXTPUNDArrayFree(kv.second);
    return -1;
  }
  for (auto& kv : cot) {
    if (g_ag.marked.count(kv.first))
      g_ag.grads[kv.first] = kv.second;  // ownership to grads map
    else
      MXTPUNDArrayFree(kv.second);
  }
  return 0;
}

// -- symbol graph ------------------------------------------------------------

struct SymRec {
  std::string op;      // empty for variables
  std::string name;    // variable name / op instance name
  std::string params;  // flat JSON
  std::vector<SymRec*> inputs;
};

// -- executor ---------------------------------------------------------------

struct ExecRec {
  SymRec* root = nullptr;
  std::map<std::string, MXTPUNDHandle> args;   // client-owned arrays
  std::map<SymRec*, MXTPUNDHandle> values;     // owned forward values
  std::map<std::string, MXTPUNDHandle> grads;  // owned per-arg grads
  std::vector<TapeNode> tape;                  // recorded forward

  void clear_run() {
    for (auto& kv : values)
      MXTPUNDArrayFree(kv.second);
    values.clear();
    for (auto& kv : grads) MXTPUNDArrayFree(kv.second);
    grads.clear();
    tape.clear();
  }
};

int exec_eval(ExecRec* ex, SymRec* node, MXTPUNDHandle* out) {
  if (node->op.empty()) {
    auto it = ex->args.find(node->name);
    if (it == ex->args.end()) {
      MXTPUSetLastError(
          (std::string("executor: unbound variable '") + node->name + "'")
              .c_str());
      return -1;
    }
    *out = it->second;
    return 0;
  }
  auto vit = ex->values.find(node);
  if (vit != ex->values.end()) {
    *out = vit->second;
    return 0;
  }
  std::vector<MXTPUNDHandle> ins;
  for (SymRec* s : node->inputs) {
    MXTPUNDHandle h = nullptr;
    if (exec_eval(ex, s, &h) != 0) return -1;
    ins.push_back(h);
  }
  MXTPUNDHandle o = inv1(node->op.c_str(), ins, node->params.c_str());
  if (o == nullptr) return -1;
  ex->values[node] = o;
  *out = o;
  return 0;
}

// -- kvstore ----------------------------------------------------------------

struct KVRec {
  std::map<int, MXTPUNDHandle> store;  // owned
  std::map<int, MXTPUNDHandle> mom;    // owned momentum state (lazy-init)
  bool sgd = false;
  double lr = 0.01;
  double momentum = 0.0;

  ~KVRec() {
    for (auto& kv : store) MXTPUNDArrayFree(kv.second);
    for (auto& kv : mom) MXTPUNDArrayFree(kv.second);
  }
};

}  // namespace

namespace mxtpu {

bool autograd_is_recording() { return g_ag.recording; }

void autograd_record(const char* op_name, MXTPUNDHandle* inputs, int n_in,
                     const char* param_json, MXTPUNDHandle* outputs,
                     int n_out) {
  TapeNode n;
  n.op = op_name ? op_name : "";
  n.params = param_json ? param_json : "";
  n.inputs.assign(inputs, inputs + n_in);
  n.outputs.assign(outputs, outputs + n_out);
  g_ag.tape.push_back(std::move(n));
}

}  // namespace mxtpu

extern "C" {

// -- autograd ---------------------------------------------------------------

int MXTPUAutogradSetRecording(int recording, int* prev) {
  if (prev) *prev = g_ag.recording ? 1 : 0;
  g_ag.recording = recording != 0;
  if (recording) g_ag.clear_tape();
  return 0;
}

int MXTPUAutogradMarkVariables(int n, MXTPUNDHandle* vars) {
  for (int i = 0; i < n; ++i) g_ag.marked.insert(vars[i]);
  return 0;
}

int MXTPUAutogradBackward(MXTPUNDHandle head) {
  if (head == nullptr) {
    MXTPUSetLastError("AutogradBackward: null head");
    return -1;
  }
  return backward_from(head);
}

/* grad handle stays owned by the autograd state (valid until the next
 * backward); callers copy out what they need. */
int MXTPUAutogradGetGrad(MXTPUNDHandle var, MXTPUNDHandle* grad) {
  auto it = g_ag.grads.find(var);
  if (it == g_ag.grads.end()) {
    MXTPUSetLastError("AutogradGetGrad: no grad recorded for this handle "
                      "(not marked, or backward not run)");
    return -1;
  }
  *grad = it->second;
  return 0;
}

int MXTPUAutogradReset() {
  g_ag.clear_grads();
  g_ag.clear_tape();
  g_ag.marked.clear();
  return 0;
}

// -- symbol -----------------------------------------------------------------

int MXTPUSymbolCreateVariable(const char* name, MXTPUSymHandle* out) {
  if (name == nullptr || out == nullptr) {
    MXTPUSetLastError("SymbolCreateVariable: null arg");
    return -1;
  }
  auto* s = new SymRec();
  s->name = name;
  *out = s;
  return 0;
}

int MXTPUSymbolCreateAtomicSymbol(const char* op_name, const char* param_json,
                                  const char* name, MXTPUSymHandle* out) {
  if (op_name == nullptr || out == nullptr) {
    MXTPUSetLastError("SymbolCreateAtomicSymbol: null arg");
    return -1;
  }
  auto* s = new SymRec();
  s->op = op_name;
  s->params = param_json ? param_json : "";
  s->name = name ? name : op_name;
  *out = s;
  return 0;
}

/* Compose: attach inputs (reference MXSymbolCompose). Input symbols must
 * outlive this symbol and any executor bound to it. */
int MXTPUSymbolCompose(MXTPUSymHandle sym, MXTPUSymHandle* args, int n_args) {
  if (sym == nullptr) {
    MXTPUSetLastError("SymbolCompose: null symbol");
    return -1;
  }
  auto* s = static_cast<SymRec*>(sym);
  if (s->op.empty()) {
    MXTPUSetLastError("SymbolCompose: cannot compose a variable");
    return -1;
  }
  s->inputs.clear();
  for (int i = 0; i < n_args; ++i) {
    if (args[i] == nullptr) {
      MXTPUSetLastError("SymbolCompose: null input symbol");
      return -1;
    }
    s->inputs.push_back(static_cast<SymRec*>(args[i]));
  }
  return 0;
}

int MXTPUSymbolFree(MXTPUSymHandle sym) {
  delete static_cast<SymRec*>(sym);
  return 0;
}

// -- executor ---------------------------------------------------------------

/* Bind: arg_names/arrays pair variables to client-owned NDArrays (reference
 * MXExecutorSimpleBindEx with explicit args). Arrays must outlive the
 * executor; updates to their contents are seen by the next Forward. */
int MXTPUExecutorBind(MXTPUSymHandle sym, const char** arg_names,
                      MXTPUNDHandle* args, int n_args,
                      MXTPUExecHandle* out) {
  if (sym == nullptr || out == nullptr) {
    MXTPUSetLastError("ExecutorBind: null arg");
    return -1;
  }
  auto* ex = new ExecRec();
  ex->root = static_cast<SymRec*>(sym);
  for (int i = 0; i < n_args; ++i)
    ex->args[arg_names[i]] = args[i];
  *out = ex;
  return 0;
}

/* Forward: evaluates the graph (recording a tape for Backward); *out is
 * owned by the executor, valid until the next Forward/Free. */
int MXTPUExecutorForward(MXTPUExecHandle exec, MXTPUNDHandle* out) {
  if (exec == nullptr || out == nullptr) {
    MXTPUSetLastError("ExecutorForward: null arg");
    return -1;
  }
  auto* ex = static_cast<ExecRec*>(exec);
  ex->clear_run();
  // record through the shared autograd tape, then stash it per-executor;
  // the user's imperative tape is saved across this (SetRecording(1)
  // clears it), so Forward between record() and AutogradBackward is safe
  std::vector<TapeNode> saved_tape = std::move(g_ag.tape);
  int prev = 0;
  MXTPUAutogradSetRecording(1, &prev);
  MXTPUNDHandle o = nullptr;
  int rc = exec_eval(ex, ex->root, &o);
  ex->tape = std::move(g_ag.tape);
  g_ag.clear_tape();
  MXTPUAutogradSetRecording(prev, nullptr);
  g_ag.tape = std::move(saved_tape);
  if (rc != 0) return -1;
  *out = o;
  return 0;
}

/* Backward: seeds the root with ones and sweeps the recorded tape;
 * per-argument grads retrievable via MXTPUExecutorGetGrad. */
int MXTPUExecutorBackward(MXTPUExecHandle exec) {
  if (exec == nullptr) {
    MXTPUSetLastError("ExecutorBackward: null executor");
    return -1;
  }
  auto* ex = static_cast<ExecRec*>(exec);
  auto vit = ex->values.find(ex->root);
  if (ex->tape.empty() || vit == ex->values.end()) {
    MXTPUSetLastError("ExecutorBackward: run Forward first");
    return -1;
  }
  // borrow the autograd machinery against this executor's tape
  std::vector<TapeNode> saved = std::move(g_ag.tape);
  auto saved_marked = std::move(g_ag.marked);
  g_ag.tape = ex->tape;
  g_ag.marked.clear();
  for (auto& kv : ex->args) g_ag.marked.insert(kv.second);
  int rc = backward_from(vit->second);
  if (rc == 0) {
    for (auto& kv : ex->args) {
      auto git = g_ag.grads.find(kv.second);
      if (git != g_ag.grads.end()) {
        ex->grads[kv.first] = git->second;  // take ownership
        g_ag.grads.erase(git);
      }
    }
  }
  g_ag.clear_grads();
  g_ag.tape = std::move(saved);
  g_ag.marked = std::move(saved_marked);
  return rc;
}

/* Grad handle owned by the executor (valid until next Forward/Free). */
int MXTPUExecutorGetGrad(MXTPUExecHandle exec, const char* arg_name,
                         MXTPUNDHandle* grad) {
  if (exec == nullptr || arg_name == nullptr || grad == nullptr) {
    MXTPUSetLastError("ExecutorGetGrad: null arg");
    return -1;
  }
  auto* ex = static_cast<ExecRec*>(exec);
  auto it = ex->grads.find(arg_name);
  if (it == ex->grads.end()) {
    MXTPUSetLastError(
        (std::string("ExecutorGetGrad: no grad for '") + arg_name +
         "' (not an arg, or Backward not run)")
            .c_str());
    return -1;
  }
  *grad = it->second;
  return 0;
}

int MXTPUExecutorFree(MXTPUExecHandle exec) {
  auto* ex = static_cast<ExecRec*>(exec);
  if (ex) ex->clear_run();
  delete ex;
  return 0;
}

// -- kvstore ----------------------------------------------------------------

int MXTPUKVStoreCreate(const char* type, MXTPUKVHandle* out) {
  if (out == nullptr) {
    MXTPUSetLastError("KVStoreCreate: null out");
    return -1;
  }
  std::string t = type ? type : "local";
  if (t != "local" && t != "device") {
    MXTPUSetLastError("KVStoreCreate: native tier supports 'local'/'device' "
                      "(distributed kvstore lives in the jax runtime)");
    return -1;
  }
  *out = new KVRec();
  return 0;
}

/* {"optimizer": "sgd", "learning_rate": 0.1} — enables update-on-push
 * (reference update_on_kvstore semantics with the server-side Updater). */
int MXTPUKVStoreSetOptimizer(MXTPUKVHandle kv, const char* param_json) {
  if (kv == nullptr) {
    MXTPUSetLastError("KVStoreSetOptimizer: null kvstore");
    return -1;
  }
  auto* k = static_cast<KVRec*>(kv);
  std::string js = param_json ? param_json : "";
  if (js.find("sgd") == std::string::npos) {
    MXTPUSetLastError("KVStoreSetOptimizer: native tier supports sgd "
                      "(optionally with momentum) only");
    return -1;
  }
  k->sgd = true;
  k->lr = param_num(js, "learning_rate", 0.01);
  k->momentum = param_num(js, "momentum", 0.0);
  return 0;
}

int MXTPUKVStoreInit(MXTPUKVHandle kv, int key, MXTPUNDHandle val) {
  if (kv == nullptr || val == nullptr) {
    MXTPUSetLastError("KVStoreInit: null arg");
    return -1;
  }
  auto* k = static_cast<KVRec*>(kv);
  if (k->store.count(key)) {
    MXTPUSetLastError("KVStoreInit: key already initialized");
    return -1;
  }
  MXTPUNDHandle copy = nd_copy(val);
  if (copy == nullptr) return -1;
  k->store[key] = copy;
  return 0;
}

int MXTPUKVStorePush(MXTPUKVHandle kv, int key, MXTPUNDHandle grad) {
  if (kv == nullptr || grad == nullptr) {
    MXTPUSetLastError("KVStorePush: null arg");
    return -1;
  }
  auto* k = static_cast<KVRec*>(kv);
  auto it = k->store.find(key);
  if (it == k->store.end()) {
    MXTPUSetLastError("KVStorePush: key not initialized");
    return -1;
  }
  // kvstore-internal invokes must not land on the user's tape: the temps
  // are freed below, and dangling tape entries could misattribute grads
  // after allocator address reuse (same discipline as backward_from)
  bool was_recording = g_ag.recording;
  g_ag.recording = false;
  MXTPUNDHandle next = nullptr;
  if (k->sgd && k->momentum > 0.0) {
    // reference sgd_mom_update: m <- momentum*m - lr*grad; w <- w + m
    char mbuf[64], lbuf[64];
    std::snprintf(mbuf, sizeof(mbuf), "{\"scalar\": %.17g}", k->momentum);
    std::snprintf(lbuf, sizeof(lbuf), "{\"scalar\": %.17g}", -k->lr);
    bool had_m = k->mom.count(key) > 0;
    MXTPUNDHandle m = had_m ? k->mom[key] : nd_full_like(it->second, 0.0);
    MXTPUNDHandle m_scaled = m ? inv1("_mul_scalar", {m}, mbuf) : nullptr;
    MXTPUNDHandle g_step = inv1("_mul_scalar", {grad}, lbuf);
    MXTPUNDHandle new_m = (m_scaled && g_step)
                              ? inv1("add", {m_scaled, g_step}) : nullptr;
    if (m_scaled) MXTPUNDArrayFree(m_scaled);
    if (g_step) MXTPUNDArrayFree(g_step);
    if (m && !had_m) MXTPUNDArrayFree(m);  // fresh zero state: temp only
    if (new_m != nullptr) {
      next = inv1("add", {it->second, new_m});
      if (next != nullptr) {
        // commit the momentum state only once the weight update is in
        // hand — a failed push must leave state consistent for a retry
        if (had_m) MXTPUNDArrayFree(k->mom[key]);
        k->mom[key] = new_m;  // state persists across pushes
      } else {
        MXTPUNDArrayFree(new_m);
      }
    }
  } else if (k->sgd) {  // w <- w - lr * grad
    char buf[64];
    std::snprintf(buf, sizeof(buf), "{\"scalar\": %.17g}", -k->lr);
    MXTPUNDHandle step = inv1("_mul_scalar", {grad}, buf);
    next = step == nullptr ? nullptr : inv1("add", {it->second, step});
    if (step != nullptr) MXTPUNDArrayFree(step);
  } else {  // plain aggregation (reference local kvstore reduce)
    next = inv1("add", {it->second, grad});
  }
  g_ag.recording = was_recording;
  if (next == nullptr) return -1;
  MXTPUNDArrayFree(it->second);
  it->second = next;
  return 0;
}

/* Pull copies the stored value into the caller-provided array (shapes must
 * match), mirroring the reference's pull-into-preallocated-NDArray. */
int MXTPUKVStorePull(MXTPUKVHandle kv, int key, MXTPUNDHandle out) {
  if (kv == nullptr || out == nullptr) {
    MXTPUSetLastError("KVStorePull: null arg");
    return -1;
  }
  auto* k = static_cast<KVRec*>(kv);
  auto it = k->store.find(key);
  if (it == k->store.end()) {
    MXTPUSetLastError("KVStorePull: key not initialized");
    return -1;
  }
  if (nd_size(out) != nd_size(it->second) ||
      nd_dtype(out) != nd_dtype(it->second)) {
    MXTPUSetLastError("KVStorePull: destination size/dtype mismatch");
    return -1;
  }
  const void* src = nullptr;
  MXTPUNDArrayGetData(it->second, &src);
  const void* dst_c = nullptr;
  MXTPUNDArrayGetData(out, &dst_c);
  std::memcpy(const_cast<void*>(dst_c), src,
              static_cast<size_t>(nd_size(out)) * nd_esize(out));
  return 0;
}

int MXTPUKVStoreFree(MXTPUKVHandle kv) {
  delete static_cast<KVRec*>(kv);
  return 0;
}

}  // extern "C"
