"""Distributed / parallelism (SURVEY §2.3, §5.8).

The reference's three comm stacks (ps-lite ZMQ, NCCL, CUDA p2p comm trees)
collapse into *one* mechanism here: a ``jax.sharding.Mesh`` + sharding
annotations, with GSPMD emitting all collectives over ICI/DCN. This package
adds the parallelism the reference never had (TP, SP/CP ring attention,
GPipe-style PP, expert-parallel MoE) as first-class capabilities, per the
build contract.
"""
from .layout import AXES, Layout  # noqa: F401
from .mesh import MeshConfig, make_mesh, local_mesh, refit_config  # noqa: F401
from .sharding import (ShardingRules, named_sharding, reshard_tree,  # noqa: F401
                       shard_params)
from .train_step import TrainStep  # noqa: F401
from .distributed_trainer import DistributedTrainer, init as dist_init  # noqa: F401
from . import ring_attention  # noqa: F401
from .pipeline import pipeline_apply, stack_stage_params, stage_sharding  # noqa: F401
from .moe import moe_ffn, init_moe_params, moe_param_specs  # noqa: F401
from .blocks import PipelineStages, MoEFFN  # noqa: F401
