"""``mx.nd`` — imperative NDArray API over ``jax.Array``.

Reference: ``src/ndarray/ndarray.cc`` + ``python/mxnet/ndarray/`` — an async
tensor whose every mutation is an engine op with read/write var deps. On TPU
the dependency engine is deleted outright (SURVEY §1): ``jax.Array`` is
already an async future scheduled by XLA's dataflow, so ``wait_to_read`` is
``block_until_ready`` and "mutation" is functional rebinding of the
underlying buffer (``x[:] = v`` → ``x._data = x._data.at[...].set(v)``),
which preserves MXNet's user-visible aliasing behavior on the *handle* level
(NDArray identity) without shared-buffer mutation.

The op surface (``mx.nd.dot`` etc.) is code-generated from the central
registry, mirroring the reference's import-time codegen
(``python/mxnet/ndarray/register.py`` over ``MXSymbolListAtomicSymbolCreators``).
"""
from __future__ import annotations

import sys
import threading as _threading
import types

import jax
import jax.numpy as jnp
import numpy as _np

from .. import autograd as _ag
from .. import ops as _ops  # noqa: F401  (populates the registry)
from .. import random as _rng
from .. import registry as _registry
from ..base import MXNetError, dtype_np
from ..context import Context, current_context

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange", "waitall", "concat", "stack"]


_pyslice = slice  # the op codegen below registers an op named "slice"

# Live-handle registry for ``waitall`` (reference: ``MXNDArrayWaitAll`` —
# drain ALL outstanding engine work). With the engine deleted, outstanding
# work == not-yet-ready ``jax.Array`` buffers held by live NDArrays, so
# waitall blocks on every live handle's buffer.
import weakref as _weakref

_live_ndarrays: "_weakref.WeakSet[NDArray]" = _weakref.WeakSet()


def _wrap(raw, ctx=None):
    return NDArray(raw, ctx=ctx)


def _raw(x):
    return x._data if isinstance(x, NDArray) else x


class NDArray:
    """Tensor handle wrapping a ``jax.Array`` (or a tracer under jit)."""

    __slots__ = ("_data", "_ctx", "_tape", "_grad", "_grad_req", "__weakref__")
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if dtype is not None:
            data = jnp.asarray(data, dtype_np(dtype))
        elif not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = jnp.asarray(data)
        if ctx is not None and not isinstance(data, jax.core.Tracer):
            data = jax.device_put(data, Context(ctx).jax_device if not isinstance(ctx, Context) else ctx.jax_device)
        self._data = data
        self._ctx = ctx if isinstance(ctx, Context) else (Context(ctx) if ctx else None)
        self._tape = None
        self._grad = None
        self._grad_req = "null"
        if not isinstance(data, jax.core.Tracer):
            _live_ndarrays.add(self)

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype) if self._data.dtype.name != "bfloat16" else self._data.dtype

    @property
    def size(self):
        return int(_np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        dev = getattr(self._data, "device", None)
        if dev is None or isinstance(self._data, jax.core.Tracer):
            return current_context()
        plat = getattr(dev, "platform", "cpu")
        return Context("cpu" if plat == "cpu" else "gpu", getattr(dev, "id", 0))

    ctx = context

    @property
    def stype(self):
        return "default"  # sparse storage types are not carried on TPU (SURVEY §2.2)

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return self.transpose()

    # -- sync / host interop ------------------------------------------------
    def wait_to_read(self):
        jax.block_until_ready(self._data)
        return self

    wait_to_write = wait_to_read

    def asnumpy(self):
        return _np.asarray(jax.device_get(self._data))

    def asscalar(self):
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        return bool(self.asnumpy().reshape(()).item()) if self.size == 1 else self.size > 0

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of 0-d NDArray")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._data.__dlpack__(**kw)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def __repr__(self):
        try:
            body = str(self.asnumpy())
        except Exception:
            body = f"<traced {self.shape} {self._data.dtype}>"
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # -- autograd -----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        self._grad_req = grad_req
        self._grad = NDArray(jnp.zeros_like(self._data))

    def _empty_like(self):
        return NDArray(jnp.zeros_like(self._data))

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _ag.backward([self], [out_grad] if out_grad is not None else None,
                     retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    # -- conversion / copies ------------------------------------------------
    def astype(self, dtype, copy=True):
        return _invoke_name("cast", (self,), {"dtype": dtype})

    def copy(self):
        return NDArray(self._data + 0 if False else jnp.copy(self._data), ctx=self._ctx)

    def copyto(self, other):
        """Copy into ``other`` (NDArray) or onto a Context.

        Reference semantics (``CopyFromTo``, ``src/ndarray/ndarray.cc``):
        writes into ``other``'s buffer, requires matching shapes, casts to
        ``other``'s dtype. Here "writing into the buffer" is functional
        rebinding of ``other._data`` — the *handle* observes the new value
        (MXNet's user-visible contract), but handles that aliased the old
        buffer keep the old value. That divergence is deliberate: the
        functional model never shares mutable buffers between handles
        (module docstring), so reference-style view aliasing cannot occur in
        the first place.
        """
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        if other.shape != self.shape:
            raise ValueError(
                f"copyto: shape mismatch {self.shape} vs {other.shape}")
        other._data = jnp.asarray(self._data, other._data.dtype)
        return other

    def as_in_context(self, ctx):
        if isinstance(self._data, jax.core.Tracer):
            return self
        return NDArray(self._data, ctx=ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp

        return _sp.cast_storage(self, stype)

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, key):
        key = _raw_index(key)
        if _ag.is_recording():
            def _slice(x, key=key):
                return x[key]
            node = _ag.TapeNode(_slice, {}, [self], 1, "getitem")
            out = _wrap(_slice(self._data))
            out._tape = (node, 0)
            return out
        return _wrap(self._data[key])

    def __setitem__(self, key, value):
        value = _raw(value)
        if isinstance(key, _pyslice) and key == _pyslice(None):
            self._data = jnp.broadcast_to(jnp.asarray(value, self._data.dtype), self.shape)
        else:
            self._data = self._data.at[_raw_index(key)].set(jnp.asarray(value, self._data.dtype))

    # -- arithmetic (recorded through the registry) -------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray) or isinstance(other, (jax.Array, jax.core.Tracer, _np.ndarray)):
            o = other if isinstance(other, NDArray) else NDArray(other)
            a, b = (o, self) if reverse else (self, o)
            return _invoke_name(op, (a, b), {})
        return _invoke_name(scalar_op[1] if reverse and scalar_op[1] else scalar_op[0],
                            (self,), {"scalar": other})

    def __add__(self, o): return self._binop(o, "add", ("_plus_scalar", None))
    __radd__ = __add__
    def __sub__(self, o): return self._binop(o, "subtract", ("_minus_scalar", None))
    def __rsub__(self, o): return self._binop(o, "subtract", (None, "_rminus_scalar"), reverse=True) if isinstance(o, (NDArray, jax.Array, _np.ndarray)) else _invoke_name("_rminus_scalar", (self,), {"scalar": o})
    def __mul__(self, o): return self._binop(o, "multiply", ("_mul_scalar", None))
    __rmul__ = __mul__
    def __truediv__(self, o): return self._binop(o, "divide", ("_div_scalar", None))
    def __rtruediv__(self, o): return self._binop(o, "divide", (None, "_rdiv_scalar"), reverse=True) if isinstance(o, (NDArray, jax.Array, _np.ndarray)) else _invoke_name("_rdiv_scalar", (self,), {"scalar": o})
    def __mod__(self, o): return self._binop(o, "mod", ("_mod_scalar", None))
    def __pow__(self, o): return self._binop(o, "power", ("_power_scalar", None))
    def __rpow__(self, o): return _invoke_name("_rpower_scalar", (self,), {"scalar": o})
    def __matmul__(self, o): return _invoke_name("dot", (self, o if isinstance(o, NDArray) else NDArray(o)), {})
    def __neg__(self): return _invoke_name("negative", (self,), {})
    def __abs__(self): return _invoke_name("abs", (self,), {})

    def __iadd__(self, o):
        self._data = self._data + _raw(o)
        return self

    def __isub__(self, o):
        self._data = self._data - _raw(o)
        return self

    def __imul__(self, o):
        self._data = self._data * _raw(o)
        return self

    def __itruediv__(self, o):
        self._data = self._data / _raw(o)
        return self

    def __eq__(self, o): return _invoke_name("equal", (self, NDArray(o)), {}) if _is_arr(o) else _invoke_name("equal", (self, NDArray(jnp.asarray(o))), {})
    def __ne__(self, o): return _invoke_name("not_equal", (self, NDArray(jnp.asarray(_raw(o)))), {})
    def __gt__(self, o): return _invoke_name("greater", (self, NDArray(jnp.asarray(_raw(o)))), {})
    def __ge__(self, o): return _invoke_name("greater_equal", (self, NDArray(jnp.asarray(_raw(o)))), {})
    def __lt__(self, o): return _invoke_name("lesser", (self, NDArray(jnp.asarray(_raw(o)))), {})
    def __le__(self, o): return _invoke_name("lesser_equal", (self, NDArray(jnp.asarray(_raw(o)))), {})

    def __hash__(self):
        return id(self)

    # -- method versions of common ops --------------------------------------
    def reshape(self, *shape, **kw):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _invoke_name("reshape", (self,), {"shape": shape, **kw})

    def reshape_like(self, other):
        return _invoke_name("reshape_like", (self, other), {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke_name("transpose", (self,), {"axes": axes or None})

    def flatten(self): return _invoke_name("flatten", (self,), {})
    def expand_dims(self, axis): return _invoke_name("expand_dims", (self,), {"axis": axis})
    def squeeze(self, axis=None): return _invoke_name("squeeze", (self,), {"axis": axis})
    def sum(self, axis=None, keepdims=False): return _invoke_name("sum", (self,), {"axis": axis, "keepdims": keepdims})
    def mean(self, axis=None, keepdims=False): return _invoke_name("mean", (self,), {"axis": axis, "keepdims": keepdims})
    def max(self, axis=None, keepdims=False): return _invoke_name("max", (self,), {"axis": axis, "keepdims": keepdims})
    def min(self, axis=None, keepdims=False): return _invoke_name("min", (self,), {"axis": axis, "keepdims": keepdims})
    def prod(self, axis=None, keepdims=False): return _invoke_name("prod", (self,), {"axis": axis, "keepdims": keepdims})
    def argmax(self, axis=None): return _invoke_name("argmax", (self,), {"axis": axis})
    def argmin(self, axis=None): return _invoke_name("argmin", (self,), {"axis": axis})
    def norm(self, ord=2, axis=None, keepdims=False): return _invoke_name("norm", (self,), {"ord": ord, "axis": axis, "keepdims": keepdims})
    def dot(self, other, **kw): return _invoke_name("dot", (self, other), kw)
    def clip(self, a_min, a_max): return _invoke_name("clip", (self,), {"a_min": a_min, "a_max": a_max})
    def abs(self): return _invoke_name("abs", (self,), {})
    def sqrt(self): return _invoke_name("sqrt", (self,), {})
    def square(self): return _invoke_name("square", (self,), {})
    def exp(self): return _invoke_name("exp", (self,), {})
    def log(self): return _invoke_name("log", (self,), {})
    def tanh(self): return _invoke_name("tanh", (self,), {})
    def sigmoid(self): return _invoke_name("sigmoid", (self,), {})
    def relu(self): return _invoke_name("relu", (self,), {})
    def softmax(self, axis=-1): return _invoke_name("softmax", (self,), {"axis": axis})
    def log_softmax(self, axis=-1): return _invoke_name("log_softmax", (self,), {"axis": axis})
    def slice_axis(self, axis, begin, end): return _invoke_name("slice_axis", (self,), {"axis": axis, "begin": begin, "end": end})
    def take(self, indices, axis=0, mode="clip"): return _invoke_name("take", (self, indices), {"axis": axis, "mode": mode})
    def one_hot(self, depth, **kw): return _invoke_name("one_hot", (self,), {"depth": depth, **kw})
    def tile(self, reps): return _invoke_name("tile", (self,), {"reps": reps})
    def repeat(self, repeats, axis=None): return _invoke_name("repeat", (self,), {"repeats": repeats, "axis": axis})
    def broadcast_to(self, shape): return _invoke_name("broadcast_to", (self,), {"shape": shape})
    def broadcast_like(self, other): return _invoke_name("broadcast_like", (self, other), {})
    def swapaxes(self, dim1, dim2): return _invoke_name("swapaxes", (self,), {"dim1": dim1, "dim2": dim2})
    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _invoke_name("split", (self,), {"num_outputs": num_outputs, "axis": axis, "squeeze_axis": squeeze_axis})
    def zeros_like(self): return _invoke_name("zeros_like", (self,), {})
    def ones_like(self): return _invoke_name("ones_like", (self,), {})
    def sign(self): return _invoke_name("sign", (self,), {})
    def round(self): return _invoke_name("round", (self,), {})
    def topk(self, **kw): return _invoke_name("topk", (self,), kw)
    def sort(self, **kw): return _invoke_name("sort", (self,), kw)
    def argsort(self, **kw): return _invoke_name("argsort", (self,), kw)


def _is_arr(o):
    return isinstance(o, (NDArray, jax.Array, _np.ndarray))


def _raw_index(key):
    if isinstance(key, NDArray):
        return key._data if not jnp.issubdtype(key._data.dtype, jnp.floating) else key._data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_raw_index(k) for k in key)
    return key


# --------------------------------------------------------------------------
# op invocation (the analog of MXImperativeInvokeEx)
# --------------------------------------------------------------------------
_DENSIFY_WARNED: set = set()
_DENSIFY_LOCK = _threading.Lock()  # op dispatch can be multi-threaded


def invoke(opdef, args, kwargs):
    # storage-type dispatch (FInferStorageType analog): ops with a declared
    # sparse handler keep sparse inputs sparse; everything else densifies at
    # the op boundary (logical-tensor semantics) with a once-per-op warning
    if any(hasattr(a, "_to_dense_raw") for a in args):
        from .. import registry as _reg

        sfn = _reg.get_sparse(getattr(opdef, "name", ""))
        if sfn is not None:
            out = sfn(*args, **kwargs)
            if out is not NotImplemented:
                return out
        from .. import config as _config

        if _config.get("storage_fallback_warn"):
            import warnings

            name = getattr(opdef, "name", "?")
            with _DENSIFY_LOCK:
                first = name not in _DENSIFY_WARNED
                _DENSIFY_WARNED.add(name)  # once per op, like the reference
            if first:
                warnings.warn(
                    f"op {name!r}: sparse input densified at the op boundary "
                    "(storage type fallback). Use nd.sparse.{dot,add,retain} "
                    "for sparse-aware compute, or set "
                    "MXNET_STORAGE_FALLBACK_WARN=0 to silence.",
                    stacklevel=3)
        args = tuple(a.todense() if hasattr(a, "_to_dense_raw") else a
                     for a in args)
    arr_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    raw_args = [_raw(a) for a in args]
    # NDArray kwargs (masks etc.) are unwrapped but not taped — gradients flow
    # through positional args only, like the reference's input/attr split
    kwargs = {k: _raw(v) for k, v in kwargs.items()}
    if opdef.stochastic and kwargs.get("key") is None:
        kwargs["key"] = _rng.next_key()

    if _ag.is_recording() and arr_pos:
        consts = list(raw_args)

        def pure(*arrs, _consts=consts, _pos=arr_pos, _kw=kwargs):
            full = list(_consts)
            for p, r in zip(_pos, arrs):
                full[p] = r
            return opdef.fn(*full, **_kw)

        node = _ag.TapeNode(pure, {}, [args[i] for i in arr_pos], opdef.nout, opdef.name)
        out = pure(*[raw_args[i] for i in arr_pos])
        if isinstance(out, tuple):
            wrapped = []
            for i, o in enumerate(out):
                w = _wrap(o)
                w._tape = (node, i)
                wrapped.append(w)
            return tuple(wrapped)
        w = _wrap(out)
        w._tape = (node, 0)
        return w

    out = opdef.fn(*raw_args, **kwargs)
    if isinstance(out, tuple):
        return tuple(_wrap(o) for o in out)
    return _wrap(out)


def _invoke_name(name, args, kwargs):
    return invoke(_registry.get(name), args, kwargs)


def _make_op_func(name):
    opdef = _registry.get(name)

    def fn(*args, **kwargs):
        ctx = kwargs.pop("ctx", None)
        out = kwargs.pop("out", None)
        res = invoke(opdef, args, kwargs)
        if out is not None:
            out._data = res._data
            return out
        return res

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = opdef.doc
    return fn


# populate mx.nd.* from the registry (import-time codegen, like the reference)
_g = globals()
for _name in _registry.list_ops():
    if _name not in _g:
        _g[_name] = _make_op_func(_name)


def __getattr__(name):  # late-registered ops (e.g. contrib modules)
    try:
        return _make_op_func(name)
    except AttributeError:
        raise AttributeError(f"module 'mx.nd' has no attribute {name!r}") from None


def Custom(*args, op_type=None, **kwargs):
    """Invoke a registered user-defined operator (reference:
    ``mx.nd.Custom`` routed through ``src/operator/custom/custom.cc``)."""
    from ..operator import make_custom_fn
    from ..registry import OpDef

    if op_type is None:
        raise MXNetError("nd.Custom requires op_type=")
    fn, nout = make_custom_fn(op_type, kwargs)
    opdef = OpDef(name=f"Custom:{op_type}", fn=fn, nout=nout)
    return invoke(opdef, args, {})


# --------------------------------------------------------------------------
# creation functions
# --------------------------------------------------------------------------
def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        source_array = source_array._data
    np_dt = dtype_np(dtype) if dtype is not None else None
    if not isinstance(source_array, (jax.Array, jax.core.Tracer)):
        src_is_i64 = getattr(_np.asarray(source_array), "dtype", None) in (
            _np.dtype(_np.int64), _np.dtype(_np.uint64))
        if np_dt == _np.dtype(_np.int64) or (np_dt is None and src_is_i64):
            # x64 stance (base.as_index_array): validated narrow, never
            # jax's silent truncation — covers both explicit dtype="int64"
            # and numpy's default int64 inference
            from ..base import as_index_array

            source_array = as_index_array(source_array, "nd.array int64")
            np_dt = _np.dtype(_np.int32) if np_dt is not None else None
    a = jnp.asarray(source_array, np_dt)
    if a.dtype == jnp.float64:
        a = a.astype(jnp.float32)  # MXNet default_dtype is f32
    return NDArray(a, ctx=ctx)


def zeros(shape, ctx=None, dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.zeros(shape, dtype_np(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.ones(shape, dtype_np(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.full(shape, val, dtype_np(dtype)), ctx=ctx)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype_np(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return NDArray(jnp.linspace(start, stop, int(num), endpoint=endpoint, dtype=dtype_np(dtype)), ctx=ctx)


def zeros_like(a):
    return _invoke_name("zeros_like", (a,), {})


def ones_like(a):
    return _invoke_name("ones_like", (a,), {})


def waitall():
    """Block until every live NDArray's buffer is ready.

    Reference: ``MXNDArrayWaitAll`` (``src/c_api/c_api.cc``) drains the
    dependency engine. Here outstanding work is exactly the set of
    not-yet-ready ``jax.Array`` buffers reachable from live handles, so this
    is a true barrier for wall-clock timing (round-2 verdict, weak #8).
    """
    for arr in list(_live_ndarrays):
        data = arr._data
        if isinstance(data, jax.core.Tracer):
            continue
        try:
            jax.block_until_ready(data)
        except Exception:
            pass  # deleted/donated buffers don't count as outstanding work


def save(fname, data):
    from ..serialization import save_ndarrays

    save_ndarrays(fname, data)


def load(fname):
    from ..serialization import load_ndarrays

    return load_ndarrays(fname)


def from_dlpack(cap):
    return NDArray(jnp.from_dlpack(cap))


def to_dlpack_for_read(arr):
    return arr._data.__dlpack__()


to_dlpack_for_write = to_dlpack_for_read


# --------------------------------------------------------------------------
# mx.nd.random submodule
# --------------------------------------------------------------------------
random = types.ModuleType(__name__ + ".random")
random.uniform = _make_op_func("_random_uniform")
random.normal = _make_op_func("_random_normal")
random.gamma = _make_op_func("_random_gamma")
random.exponential = _make_op_func("_random_exponential")
random.poisson = _make_op_func("_random_poisson")
random.randint = _make_op_func("_random_randint")
random.multinomial = _make_op_func("_sample_multinomial")
random.shuffle = _make_op_func("shuffle")
random.seed = _rng.seed
sys.modules[random.__name__] = random

# contrib namespace: ops registered with _contrib_ prefix surface as nd.contrib.x
contrib = types.ModuleType(__name__ + ".contrib")


def _contrib_getattr(name):
    return _make_op_func("_contrib_" + name)


contrib.__getattr__ = _contrib_getattr
from ..control_flow import cond as _cf_cond  # noqa: E402
from ..control_flow import foreach as _cf_foreach  # noqa: E402
from ..control_flow import while_loop as _cf_while_loop  # noqa: E402

contrib.foreach = _cf_foreach
contrib.while_loop = _cf_while_loop
contrib.cond = _cf_cond
sys.modules[contrib.__name__] = contrib

# linalg namespace: mx.nd.linalg.gemm2 etc. resolve to the linalg_* ops
linalg = types.ModuleType(__name__ + ".linalg")
linalg.__getattr__ = lambda name: _make_op_func("linalg_" + name)
sys.modules[linalg.__name__] = linalg

from . import sparse  # noqa: E402  (row_sparse/csr storage — needs NDArray defined)
# reference exposes cast_storage at the nd top level too (tensor/cast_storage.cc)
cast_storage = sparse.cast_storage
