"""Compiled autoregressive inference (docs/INFERENCE.md).

Two pieces:

  - :class:`GenerationEngine` — a fixed family of jitted programs for
    token generation: bucketed-length *prefill* (one XLA program per prompt
    bucket) and a single-token *decode step* (one program, donated KV-cache
    carry, sampling + EOS masking compiled in). ``paged=True`` swaps the
    per-row contiguous cache for a global page pool with per-row int32
    page tables riding the compiled carry (admission bounded by free
    pages, not slots); ``draft_net=``/``speculate_k=`` adds draft-model
    speculative decoding on top (one compiled draft scan + one verify
    program per round, greedy output token-identical to plain decoding);
  - :class:`ContinuousBatcher` — slot-based continuous batching: queued
    requests are admitted into free rows of the static decode batch at step
    boundaries (page-bounded on a paged engine), so serving never changes
    a shape and never recompiles. Also the serving-resilience layer
    (docs/RESILIENCE.md "Serving resilience"): per-request deadlines and
    cancellation with immediate page reclaim, overload shedding, an
    admission aging guard against head starvation, accept-rate-governed
    fallback from speculative to plain decode, a dispatch watchdog, and
    retried ``gen.*`` fault sites — chaos-gated by ``make chaos-serve``.
"""
from .engine import GenerationEngine, SamplingConfig  # noqa: F401
from .batcher import ContinuousBatcher, GenRequest  # noqa: F401
from .prefix_cache import RadixPrefixCache  # noqa: F401

__all__ = ["GenerationEngine", "SamplingConfig", "ContinuousBatcher",
           "GenRequest", "RadixPrefixCache"]
