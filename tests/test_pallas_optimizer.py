"""Fused Adam/master-weight Pallas kernel vs the unfused XLA chain
(interpret mode on CPU)."""
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu import config as _config
from mxnet_tpu.ops import optimizer_ops as oo
from mxnet_tpu.ops import pallas_optimizer as po

_HP = dict(beta1=0.9, beta2=0.999, epsilon=1e-8)


def _mk(rs, shape, gdtype=jnp.float32):
    w = jnp.asarray(rs.randn(*shape), jnp.float32)
    g = jnp.asarray(rs.randn(*shape), gdtype)
    m = jnp.asarray(rs.randn(*shape) * 0.1, jnp.float32)
    v = jnp.asarray(np.abs(rs.randn(*shape)) * 0.01, jnp.float32)
    return w, g, m, v


@pytest.mark.parametrize("shape", [(7,), (33, 5), (300, 129), (2, 3, 64)])
@pytest.mark.parametrize("clip", [-1.0, 2.0])
def test_fused_adam_matches_unfused(shape, clip):
    """Any rank/size (operands are lane-padded internally), clip on/off."""
    rs = np.random.RandomState(0)
    w, g, m, v = _mk(rs, shape)
    lr_t, wd = jnp.float32(0.003), jnp.float32(0.01)
    ref = oo.adam_update(w, g, m, v, lr_t, _HP["beta1"], _HP["beta2"],
                         _HP["epsilon"], wd, 1.5, clip)
    out = po.adam_update_fused(w, g, m, v, lr_t, wd=wd, rescale_grad=1.5,
                               clip_gradient=clip, interpret=True, **_HP)
    assert len(out) == 3
    for a, b, name in zip(ref, out, ("w", "m", "v")):
        assert b.shape == shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                                   atol=1e-7, err_msg=name)


def test_fused_adam_bf16_grad():
    """bf16 gradients (the FSDP storage layout's wire dtype) upcast to f32
    inside the kernel exactly like ``_apply_wd``."""
    rs = np.random.RandomState(1)
    w, g, m, v = _mk(rs, (65, 17), gdtype=jnp.bfloat16)
    lr_t, wd = jnp.float32(0.001), jnp.float32(0.0)
    ref = oo.adam_update(w, g, m, v, lr_t, _HP["beta1"], _HP["beta2"],
                         _HP["epsilon"], wd, 1.0, -1.0)
    out = po.adam_update_fused(w, g, m, v, lr_t, wd=wd, interpret=True, **_HP)
    for a, b in zip(ref, out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                                   atol=1e-7)


def test_fused_adam_master_weight_one_pass():
    """out_dtype= emits the low-precision model copy as a 4th kernel output;
    it must equal the two-pass master-then-cast result bit-for-bit."""
    rs = np.random.RandomState(2)
    w, g, m, v = _mk(rs, (129, 33))
    lr_t, wd = jnp.float32(0.01), jnp.float32(0.02)
    new_w, new_m, new_v, low = po.adam_update_fused(
        w, g, m, v, lr_t, wd=wd, out_dtype=jnp.bfloat16, interpret=True, **_HP)
    assert low.dtype == jnp.bfloat16 and low.shape == w.shape
    np.testing.assert_array_equal(np.asarray(low, np.float32),
                                  np.asarray(new_w.astype(jnp.bfloat16),
                                             np.float32))


def test_fused_adam_multi_step_trajectory():
    """10 fused steps track 10 unfused steps (error stays at fp noise, no
    divergence drift)."""
    rs = np.random.RandomState(3)
    w1, _, m1, v1 = _mk(rs, (50, 30))
    w2, m2, v2 = w1, m1, v1
    for t in range(1, 11):
        g = jnp.asarray(rs.randn(50, 30), jnp.float32)
        lr_t = jnp.float32(0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t))
        w1, m1, v1 = oo.adam_update(w1, g, m1, v1, lr_t, 0.9, 0.999, 1e-8,
                                    0.01, 1.0, -1.0)
        w2, m2, v2 = po.adam_update_fused(w2, g, m2, v2, lr_t,
                                          wd=jnp.float32(0.01),
                                          interpret=True, **_HP)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), rtol=1e-5,
                               atol=1e-6)


def test_fused_adam_gating():
    """CPU backend never claims support (the mesh-compiled TrainStep path
    must keep its GSPMD-partitionable XLA chain); the opt-in knob + TPU
    mock flips it on, dtype rules still apply."""
    import unittest.mock as mock

    w = jnp.zeros((256,), jnp.float32)
    g, m = w, w
    assert not po.fused_adam_supported(w, g, m)
    _config.set("fused_adam", True)
    try:
        assert not po.fused_adam_supported(w, g, m)  # still CPU
        with mock.patch.object(po, "_on_tpu", return_value=True):
            assert po.fused_adam_supported(w, g, m)
            assert po.fused_adam_supported(w, g.astype(jnp.bfloat16), m)
            # f16 grads / non-f32 master: not in the kernel's contract
            assert not po.fused_adam_supported(w, g.astype(jnp.float16), m)
            assert not po.fused_adam_supported(
                w.astype(jnp.bfloat16), g, m)
    finally:
        _config.set("fused_adam", False)


def test_adam_update_raw_mp_integration():
    """Optimizer.update_multi_precision routes through update_raw_mp: the
    default two-pass path must produce the same master/low pair the fused
    kernel emits (tested here via the base-class composition on CPU)."""
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.optimizer import Adam

    rs = np.random.RandomState(4)
    opt = Adam(learning_rate=0.01, multi_precision=True)
    w_bf = NDArray(jnp.asarray(rs.randn(40, 20), jnp.bfloat16))
    grad = NDArray(jnp.asarray(rs.randn(40, 20), jnp.bfloat16))
    state = opt.create_state_multi_precision(0, w_bf)
    assert "master" in state
    new_state = opt.update_multi_precision(0, w_bf, grad, state)
    # stored weight is the cast of the new master
    np.testing.assert_array_equal(
        np.asarray(w_bf._data, np.float32),
        np.asarray(new_state["master"].astype(jnp.bfloat16), np.float32))
