"""Profiler (reference: ``src/profiler/`` + ``python/mxnet/profiler.py``).

The reference engine wraps every op with Chrome-trace events. On TPU the
instrumentation layer is ``jax.profiler`` (XPlane → TensorBoard/Perfetto);
this module keeps the MXNet control surface (``set_config`` /
``set_state('run'|'stop')`` / ``dump``) and the ``scope``/``annotate`` API
mapped onto ``jax.profiler`` traces + named annotations.
"""
from __future__ import annotations

import os
import time
from contextlib import contextmanager

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume", "scope", "Profiler"]

_state = {"running": False, "dir": "/tmp/mxnet_tpu_profile", "aggregate": {}}


def set_config(filename=None, profile_all=False, profile_symbolic=True,
               profile_imperative=True, profile_memory=True, profile_api=True,
               aggregate_stats=False, **kwargs):
    if filename:
        _state["dir"] = os.path.dirname(os.path.abspath(filename)) or "."
    _state["aggregate_stats"] = aggregate_stats


def set_state(state="stop", profile_process="worker"):
    if state == "run" and not _state["running"]:
        jax.profiler.start_trace(_state["dir"])
        _state["running"] = True
        _state["t0"] = time.time()
    elif state == "stop" and _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


def pause(profile_process="worker"):
    set_state("stop")


def resume(profile_process="worker"):
    set_state("run")


def dump(finished=True, profile_process="worker"):
    if _state["running"]:
        set_state("stop")
    return _state["dir"]


def dumps(reset=False):
    """Aggregate per-op stat table. With XLA fusion, per-op means per-compiled
    computation; detailed tables come from the xplane protos in the dump dir."""
    lines = ["Profile Statistics (see TensorBoard / Perfetto for op-level "
             f"detail; traces in {_state['dir']})"]
    for name, (count, total) in sorted(_state["aggregate"].items()):
        lines.append(f"{name}\t{count}\t{total * 1e3:.3f}ms")
    return "\n".join(lines)


@contextmanager
def scope(name="<unk>:"):
    with jax.profiler.TraceAnnotation(name):
        t0 = time.time()
        yield
        c, t = _state["aggregate"].get(name, (0, 0.0))
        _state["aggregate"][name] = (c + 1, t + time.time() - t0)


annotate = scope


class Profiler:
    """Context-manager convenience (not in the reference; thin sugar)."""

    def __init__(self, output_dir=None):
        if output_dir:
            set_config(filename=os.path.join(output_dir, "profile.json"))

    def __enter__(self):
        set_state("run")
        return self

    def __exit__(self, *exc):
        set_state("stop")
