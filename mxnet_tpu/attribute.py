"""AttrScope (reference: ``python/mxnet/attribute.py``).

In the reference, ``with mx.AttrScope(ctx_group='dev1'):`` annotates symbol
nodes for manual model parallelism (`group2ctx` binding). On TPU the analog
is a *sharding hint* scope consumed by ``mxnet_tpu.parallel`` — ops created
inside the scope carry a logical-axis annotation instead of a device id.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]


class AttrScope:
    _tls = threading.local()

    def __init__(self, **attrs):
        self._attrs = attrs

    def __enter__(self):
        stack = getattr(AttrScope._tls, "stack", None)
        if stack is None:
            stack = AttrScope._tls.stack = []
        merged = dict(stack[-1]) if stack else {}
        merged.update(self._attrs)
        stack.append(merged)
        return self

    def __exit__(self, *exc):
        AttrScope._tls.stack.pop()


def current_attrs() -> dict:
    stack = getattr(AttrScope._tls, "stack", None)
    return dict(stack[-1]) if stack else {}
