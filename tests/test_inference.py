"""Compiled autoregressive inference (ISSUE 4 acceptance):

  - cached prefill + decode logits ≡ a full re-forward at EVERY step (fp
    tolerance, GPT-2 small config, CPU), for GPT-2 and the Transformer
    decoder side;
  - per-row EOS done-masks: finished rows emit pad and stop advancing;
  - the continuous batcher admits queued requests FIFO into free slots at
    step boundaries and serves mixed-length traffic;
  - compiled-program count is exactly (prefill buckets used + 1 decode
    program) — no per-token recompiles, asserted through the
    ``gen_recompiles_total`` telemetry;
  - the sampling primitives (`temperature_sampling` / `top_k_sampling`)
    are key-deterministic and respect their support.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.inference import ContinuousBatcher, GenerationEngine, SamplingConfig
from mxnet_tpu.models import gpt2, transformer as tfm
from mxnet_tpu.observability import REGISTRY
from mxnet_tpu.ops import random_ops as rops

VOCAB, EOS, PAD = 97, 96, 0


def _gpt2(max_length=64, seed=0):
    mx.random.seed(seed)
    net = gpt2.GPT2Model(num_layers=2, units=64, num_heads=4,
                         max_length=max_length, vocab_size=VOCAB, dropout=0.0)
    net.initialize()
    _ = net(nd.array(np.zeros((1, 4)), dtype="int32"))
    return net


def _engine(net, **kw):
    kw.setdefault("batch_size", 3)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("eos_id", EOS)
    kw.setdefault("pad_id", PAD)
    return GenerationEngine(net, **kw)


def _gen_program_count():
    c = REGISTRY.get("gen_recompiles_total")
    return 0 if c is None else int(c.total())


def _prompt(n, seed, lo=1, hi=EOS):
    return list(np.random.RandomState(seed).randint(lo, hi, n))


# ---------------------------------------------------------------------------
# sampling primitives
# ---------------------------------------------------------------------------
class TestSamplingOps:
    def test_temperature_key_deterministic(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(5, 33), jnp.float32)
        k = jax.random.key(7)
        a = rops.temperature_sampling(logits, temperature=0.8, key=k)
        b = rops.temperature_sampling(logits, temperature=0.8, key=k)
        assert a.shape == (5,) and a.dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_temperature_zero_is_greedy(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(4, 11), jnp.float32)
        out = rops.temperature_sampling(logits, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.argmax(np.asarray(logits), -1))

    def test_top_k_support(self):
        # one dominant + (k-1) runner-up logits: samples must stay in top-k
        rs = np.random.RandomState(2)
        logits = jnp.asarray(rs.randn(64, 50), jnp.float32)
        k = 5
        out = np.asarray(rops.top_k_sampling(logits, k=k, temperature=2.0,
                                             key=jax.random.key(3)))
        topk = np.argsort(np.asarray(logits), axis=-1)[:, -k:]
        assert all(out[i] in topk[i] for i in range(out.shape[0]))

    def test_top_k_one_is_greedy(self):
        logits = jnp.asarray(np.random.RandomState(3).randn(6, 19), jnp.float32)
        out = rops.top_k_sampling(logits, k=1, key=jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.argmax(np.asarray(logits), -1))

    def test_registered_ops_draw_from_global_chain(self):
        mx.random.seed(5)
        a = nd.temperature_sampling(nd.ones((3, 9)), temperature=1.0)
        mx.random.seed(5)
        b = nd.temperature_sampling(nd.ones((3, 9)), temperature=1.0)
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


# ---------------------------------------------------------------------------
# cached decode ≡ full re-forward
# ---------------------------------------------------------------------------
class TestCachedDecodeEquivalence:
    def test_gpt2_every_step_matches_full_forward(self):
        net = _gpt2()
        eng = _engine(net, batch_size=2)
        prompts = [_prompt(5, 10), _prompt(12, 11)]
        gen_len = 8

        # cached path, capturing per-step logits
        eng.done[:] = True
        step_logits = []
        for i, p in enumerate(prompts):
            eng.prefill(p, slot=i)
        while len(step_logits) < gen_len - 1:
            _, _, logits = eng.decode_step()
            step_logits.append(np.array(logits))

        # naive path: greedy full re-forward from the same prompts
        naive = [list(p) for p in prompts]
        for r, p in enumerate(prompts):
            logits = net(nd.array(np.asarray([p]), dtype="int32")).asnumpy()
            naive[r].append(int(np.argmax(logits[0, -1])))
        for step in range(gen_len - 1):
            for r in range(len(prompts)):
                full = net(nd.array(np.asarray([naive[r]]), dtype="int32")).asnumpy()
                np.testing.assert_allclose(
                    step_logits[step][r], full[0, -1], rtol=1e-4, atol=1e-4,
                    err_msg=f"row {r} step {step}: cached logits != re-forward")
                naive[r].append(int(np.argmax(full[0, -1])))

    def test_gpt2_generate_matches_naive_greedy(self):
        net = _gpt2()
        eng = _engine(net, batch_size=2)
        prompts = [_prompt(5, 20), _prompt(12, 21)]
        outs = eng.generate(prompts, max_new_tokens=7)
        for p, got in zip(prompts, outs):
            seq = list(p)
            for _ in range(7):
                logits = net(nd.array(np.asarray([seq]), dtype="int32")).asnumpy()
                seq.append(int(np.argmax(logits[0, -1])))
            assert got == seq[len(p):]

    def test_transformer_decoder_cached_step(self):
        mx.random.seed(0)
        net = tfm.Transformer(num_layers=2, units=32, hidden_size=64,
                              num_heads=2, vocab_size=53, max_length=32,
                              dropout=0.0)
        net.initialize()
        src = nd.array(np.random.RandomState(0).randint(1, 53, (2, 6)),
                       dtype="int32")
        tgt = np.random.RandomState(1).randint(1, 53, (2, 5))
        full = net(src, nd.array(tgt, dtype="int32")).asnumpy()

        mem, mem_mask = net.encode(nd, src)
        cache = [(nd.NDArray(k), nd.NDArray(v))
                 for k, v in net.init_decode_cache(2, 32)]
        lg, cache = net.decode_step(
            nd.array(tgt[:, :3].copy(), dtype="int32"), mem, mem_mask,
            cache=cache, start_pos=nd.array(np.zeros(2), dtype="int32"))
        np.testing.assert_allclose(lg.asnumpy(), full[:, :3],
                                   rtol=1e-4, atol=1e-4)
        for t in (3, 4):
            lg, cache = net.decode_step(
                nd.array(tgt[:, t:t + 1].copy(), dtype="int32"), mem, mem_mask,
                cache=cache, start_pos=nd.array(np.full(2, t), dtype="int32"))
            np.testing.assert_allclose(lg.asnumpy()[:, 0], full[:, t],
                                       rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# EOS masking + engine state machine
# ---------------------------------------------------------------------------
class TestEosMasking:
    def test_done_rows_emit_pad_and_freeze(self):
        net = _gpt2()
        eng = _engine(net, batch_size=3)
        eng.prefill(_prompt(4, 30), slot=0)
        eng.prefill(_prompt(4, 31), slot=1)
        # mark row 1 done by hand (as the batcher does on completion)
        eng.release_slot(1)
        pos1_before = int(eng.positions[1])
        pos0_before = int(eng.positions[0])
        tok, done, _ = eng.decode_step()
        assert tok[1] == PAD and done[1]
        assert int(eng.positions[1]) == pos1_before  # frontier frozen
        assert int(eng.positions[0]) == pos0_before + 1  # active row advanced

    def test_eos_token_finishes_row(self):
        net = _gpt2()
        # learn what greedy decoding will emit, then declare THAT token EOS
        probe = _engine(net, batch_size=2, eos_id=None)
        first = probe.prefill(_prompt(6, 40), slot=0)
        probe_tok, _, _ = probe.decode_step()
        eos = int(probe_tok[0])
        eng2 = _engine(net, batch_size=2, eos_id=eos)
        t0 = eng2.prefill(_prompt(6, 40), slot=0)
        assert t0 == first
        if eng2.done[0]:  # prefill-sampled token was already the EOS
            assert first == eos
            eng2.done[0] = False  # exercise the decode-step mask anyway
        tok, done, _ = eng2.decode_step()
        assert int(tok[0]) == eos and bool(done[0])
        # next step: the finished row emits pad and stays done
        tok2, done2, _ = eng2.decode_step()
        assert int(tok2[0]) == PAD and bool(done2[0])

    def test_cache_end_forces_done(self):
        net = _gpt2(max_length=16)
        eng = GenerationEngine(net, batch_size=1, max_length=16,
                               prefill_buckets=(8,), eos_id=EOS)
        outs = eng.generate([_prompt(6, 50)], max_new_tokens=100)
        # 6-token prompt fills positions 0..5; decode inputs occupy 6..15,
        # so at most (16 - 6) decode steps run -> 1 prefill token + 10 more
        assert len(outs[0]) <= 16 - 6 + 1
        assert bool(eng.done[0])
        c = REGISTRY.get("gen_cache_overflow_total")
        assert c is not None and c.total() >= 1


# ---------------------------------------------------------------------------
# continuous batcher
# ---------------------------------------------------------------------------
class TestContinuousBatcher:
    def test_fifo_admission_into_free_slots(self):
        net = _gpt2()
        eng = _engine(net, batch_size=2)
        bat = ContinuousBatcher(eng)
        reqs = [bat.submit(_prompt(4, 60 + i), max_new_tokens=3 + i)
                for i in range(4)]
        # only 2 slots: requests 0,1 admitted first, 2,3 wait in FIFO order
        bat.step()
        assert reqs[0].slot == 0 and reqs[1].slot == 1
        assert reqs[2].slot is None and bat.pending == 2
        bat.run_until_idle(max_steps=100)
        assert all(r.done for r in reqs)
        assert [len(r.result()) for r in reqs] == [3, 4, 5, 6]
        # later submissions were admitted into freed slots, FIFO
        assert reqs[2].first_token_t <= reqs[3].first_token_t

    def test_batched_results_match_solo_generation(self):
        net = _gpt2()
        prompts = [_prompt(4, 70), _prompt(11, 71), _prompt(7, 72)]
        solo = GenerationEngine(net, batch_size=1, prefill_buckets=(8, 16),
                                eos_id=EOS)
        want = [solo.generate([p], max_new_tokens=5)[0] for p in prompts]
        eng = _engine(net, batch_size=2)
        bat = ContinuousBatcher(eng)
        reqs = [bat.submit(p, max_new_tokens=5) for p in prompts]
        bat.run_until_idle(max_steps=100)
        assert [r.result() for r in reqs] == want

    def test_serving_metrics_recorded(self):
        net = _gpt2()
        eng = _engine(net, batch_size=2)
        bat = ContinuousBatcher(eng)
        ttft_before = (REGISTRY.get("ttft_seconds").total_count()
                       if REGISTRY.get("ttft_seconds") else 0)
        reqs = [bat.submit(_prompt(5, 80 + i), max_new_tokens=4)
                for i in range(3)]
        bat.run_until_idle(max_steps=100)
        assert REGISTRY.get("ttft_seconds").total_count() - ttft_before == 3
        assert REGISTRY.get("decode_tokens_per_s").total_count() >= 3
        assert REGISTRY.get("gen_queue_depth").value() == 0
        assert REGISTRY.get("gen_requests_total").total() >= 3
        assert all(r.ttft is not None and r.ttft >= 0 for r in reqs)

    def test_oversize_prompt_rejected_at_submit(self):
        net = _gpt2()
        eng = _engine(net, batch_size=2)  # buckets (8, 16)
        bat = ContinuousBatcher(eng)
        with pytest.raises(ValueError):
            bat.submit(_prompt(17, 90), max_new_tokens=2)
        # empty prompts are rejected at submit too (admitting one would
        # crash mid-step and leak the slot)
        with pytest.raises(ValueError):
            bat.submit([], max_new_tokens=2)


# ---------------------------------------------------------------------------
# compiled-program count: prefill buckets + 1, no per-token recompiles
# ---------------------------------------------------------------------------
class TestCompiledProgramCount:
    def test_bucket_plus_one_and_stable(self):
        net = _gpt2()
        eng = _engine(net, batch_size=3)  # buckets (8, 16)
        before = _gen_program_count()
        prompts = [_prompt(5, 100), _prompt(12, 101), _prompt(3, 102)]
        eng.generate(prompts, max_new_tokens=9)
        used_buckets = {eng.bucket_for(len(p)) for p in prompts}
        assert eng.compiled_programs == len(used_buckets) + 1
        assert _gen_program_count() - before == len(used_buckets) + 1
        # more traffic, same shapes -> zero new programs
        eng.generate([_prompt(7, 103), _prompt(15, 104)], max_new_tokens=11)
        bat = ContinuousBatcher(eng)
        for i in range(5):
            bat.submit(_prompt(2 + i, 110 + i), max_new_tokens=6)
        bat.run_until_idle(max_steps=200)
        assert eng.compiled_programs == len(used_buckets) + 1
        assert _gen_program_count() - before == len(used_buckets) + 1

    def test_counter_reasons(self):
        net = _gpt2()
        c = REGISTRY.get("gen_recompiles_total")
        pre_prefill = c.value(reason="prefill_bucket") if c else 0
        pre_decode = c.value(reason="decode") if c else 0
        eng = _engine(net, batch_size=2)
        eng.generate([_prompt(4, 120)], max_new_tokens=3)
        c = REGISTRY.get("gen_recompiles_total")
        assert c.value(reason="prefill_bucket") - pre_prefill == 1
        assert c.value(reason="decode") - pre_decode == 1


# ---------------------------------------------------------------------------
# Module.predict: device futures, one materialization
# ---------------------------------------------------------------------------
class TestModulePredict:
    def test_predict_concatenates_batches(self):
        from mxnet_tpu import sym
        from mxnet_tpu.io import NDArrayIter

        x = sym.var("data")
        w = sym.var("fc_weight")
        b = sym.var("fc_bias")
        out = sym.FullyConnected(x, w, b, num_hidden=5)
        mod = mx.mod.Module(out, data_names=("data",), label_names=())
        mod.bind(data_shapes=[("data", (4, 3))], for_training=False)
        mod.init_params()
        data = np.random.RandomState(0).rand(8, 3).astype(np.float32)
        it = NDArrayIter(data, None, batch_size=4)
        pred = mod.predict(it)
        w_np = mod._arg_params["fc_weight"].asnumpy()
        b_np = mod._arg_params["fc_bias"].asnumpy()
        assert pred.shape == (8, 5)
        np.testing.assert_allclose(pred.asnumpy(), data @ w_np.T + b_np,
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# bf16 KV cache (ISSUE 5 satellite): cache_dtype='bfloat16' halves decode
# cache HBM; greedy decoding must be token-identical to the fp32 cache on
# the tiny GPT-2 config
# ---------------------------------------------------------------------------
class TestBf16KVCache:
    def test_cache_dtype_threads_to_buffers(self):
        net = _gpt2()
        eng = _engine(net, cache_dtype="bfloat16")
        for k_buf, v_buf in eng.cache:
            assert k_buf.dtype == jnp.bfloat16 and v_buf.dtype == jnp.bfloat16

    def test_greedy_tokens_identical_to_fp32_cache(self):
        net = _gpt2(seed=3)
        prompts = [_prompt(5, 31), _prompt(9, 32), _prompt(3, 33)]
        ref = _engine(net, cache_dtype="float32").generate(
            prompts, max_new_tokens=12)
        bf16 = _engine(net, cache_dtype="bfloat16").generate(
            prompts, max_new_tokens=12)
        assert bf16 == ref
