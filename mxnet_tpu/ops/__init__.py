"""Operator library: pure jax functions registered under MXNet op names.

Replaces ``src/operator/`` (~150k LoC of C++/CUDA kernels in the reference)
with jnp/lax compositions that XLA fuses and tiles onto the MXU — plus Pallas
kernels for the attention hot path (``mxnet_tpu.ops.attention``). Import
order: every submodule populates :mod:`mxnet_tpu.registry` at import time.
"""
from . import core  # noqa: F401
from . import nn  # noqa: F401
from . import attention  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import pallas_softmax_xent  # noqa: F401
from . import random_ops  # noqa: F401
from . import contrib_vision  # noqa: F401
from . import linalg  # noqa: F401
from . import extra  # noqa: F401
