"""Loss blocks (reference: ``python/mxnet/gluon/loss.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
           "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss", "HuberLoss",
           "HingeLoss", "CosineEmbeddingLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.square(label.reshape(pred.shape) - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.abs(label.reshape(pred.shape) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            # log-sum-exp stable bce on logits
            max_val = F.maximum(-pred, 0.0 * pred)
            loss = pred - pred * label + max_val + F.log(F.exp(-max_val) + F.exp(-pred - max_val))
            if pos_weight is not None:
                loss = loss + (pos_weight - 1) * label * (
                    max_val + F.log(F.exp(-max_val) + F.exp(-pred - max_val)))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label + F.log(1 - pred + eps) * (1 - label))
            else:
                loss = -(F.log(pred + eps) * label * pos_weight
                         + F.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference semantics: sparse labels by default, optional dense
    (one-hot/soft) labels, from_logits, axis."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=False)
        else:
            label = label.reshape(pred.shape)
            loss = -(pred * label).sum(axis=self._axis)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        if loss.ndim <= 1:
            return loss
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.abs(label.reshape(pred.shape) - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.relu(self._margin - pred * label.reshape(pred.shape))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.reshape((loss.shape[0], -1)).mean(axis=1)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        sim = (input1 * input2).sum(axis=1) / (
            F.sqrt(F.square(input1).sum(axis=1)) * F.sqrt(F.square(input2).sum(axis=1)) + 1e-12)
        label = label.reshape(sim.shape)
        loss = F.where(label == 1, 1 - sim, F.relu(sim - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)
