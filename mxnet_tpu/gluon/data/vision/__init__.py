"""Vision datasets + transforms (reference: ``python/mxnet/gluon/data/vision/``)."""
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,  # noqa: F401
                       ImageRecordDataset, ImageFolderDataset)
from . import transforms  # noqa: F401
