#!/usr/bin/env python
"""Compiled autoregressive generation + continuous-batching demo
(docs/INFERENCE.md).

Builds a small GPT-2, stands up the generation engine (bucketed prefill +
one donated decode step), and serves a burst of mixed-length requests
through the slot-based continuous batcher while printing per-request
TTFT / throughput. Runs in seconds on CPU:

  python examples/generate_gpt2.py
  python examples/generate_gpt2.py --model gpt2_117m --batch-size 8
  python examples/generate_gpt2.py --paged --num-pages 24
  python examples/generate_gpt2.py --paged --speculate 4

``--paged`` swaps the dense per-slot cache for the page-pool cache
(admission bounded by free pages; pages-in-use printed per run) and
``--speculate k`` adds self-drafting speculative decoding on top (accept
rate printed; greedy tokens stay identical).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.inference import ContinuousBatcher, GenerationEngine, SamplingConfig
from mxnet_tpu.models import gpt2
from mxnet_tpu.observability import REGISTRY


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2_tiny", choices=list(gpt2.gpt2_configs))
    ap.add_argument("--vocab", type=int, default=2048,
                    help="trimmed vocab so the demo stays CPU-friendly")
    ap.add_argument("--batch-size", type=int, default=4,
                    help="decode slots (static batch rows)")
    ap.add_argument("--max-length", type=int, default=256)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--sampling", default="greedy",
                    choices=["greedy", "temperature", "top_k"])
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache: global page pool + per-row page "
                         "tables (docs/INFERENCE.md 'Paged cache')")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="pool capacity in pages (default: dense-equivalent)")
    ap.add_argument("--speculate", type=int, default=0, metavar="K",
                    help="self-drafting speculative decode, K tokens/round "
                         "(implies --paged, forces greedy)")
    args = ap.parse_args()

    mx.random.seed(0)
    net = gpt2.get_gpt2(args.model, dropout=0.0, vocab_size=args.vocab,
                        max_length=args.max_length)
    net.initialize()
    _ = net(nd.array(np.zeros((1, 4)), dtype="int32"))  # materialize params

    paged = args.paged or args.speculate > 0
    sampling = ("greedy" if args.speculate else
                SamplingConfig(method=args.sampling,
                               temperature=args.temperature))
    eng = GenerationEngine(
        net, batch_size=args.batch_size, max_length=args.max_length,
        prefill_buckets=(16, 32, 64), eos_id=None, pad_id=0,
        sampling=sampling, paged=paged, page_size=args.page_size,
        num_pages=args.num_pages,
        draft_net=net if args.speculate else None,
        speculate_k=args.speculate)
    bat = ContinuousBatcher(eng)

    rs = np.random.RandomState(1)
    reqs = [bat.submit(list(rs.randint(1, args.vocab, rs.randint(4, 48))),
                       max_new_tokens=args.max_new_tokens)
            for _ in range(args.requests)]
    peak_pages = 0
    while bat.step():
        peak_pages = max(peak_pages, eng.pages_in_use)

    for r in reqs:
        toks = r.result()
        print(f"req {r.id}: prompt={len(r.prompt):3d} tok  "
              f"ttft={1e3 * r.ttft:7.1f} ms  generated={len(toks):3d}  "
              f"[{', '.join(map(str, toks[:8]))}{', ...' if len(toks) > 8 else ''}]")
    programs = REGISTRY.get("gen_recompiles_total")
    kind = ("prefill buckets used + 1 draft + 1 verify" if eng.speculative
            else "prefill buckets used + 1 decode")
    print(f"\ncompiled programs: {eng.compiled_programs} ({kind}) — "
          f"{int(programs.total()) if programs else 0} counted by telemetry")
    if paged:
        print(f"pages: peak {peak_pages}/{eng.num_pages} in use "
              f"(page_size {eng.page_size}, now {eng.pages_in_use} held)")
    if eng.speculative:
        rate = REGISTRY.get("gen_spec_accept_rate")
        acc = REGISTRY.get("gen_spec_accepted_tokens_total")
        drf = REGISTRY.get("gen_spec_drafted_tokens_total")
        overall = (acc.total() / drf.total()) if acc and drf else float("nan")
        last = rate.value() if rate is not None else float("nan")
        print(f"speculative k={eng.speculate_k}: accept rate "
              f"{overall:.2f} overall ({last:.2f} last round)")


if __name__ == "__main__":
    main()
