"""Structured JSONL event log — one writer per process, rotation, stable
schema.

Every record is one JSON object per line with a fixed envelope::

    {"ts": <unix seconds>, "run": "<run id>", "host": <process index>,
     "step": <monotonic step>, "event": "<name>", ...payload...}

``run`` is shared by every host of one training run (derived from time+pid
on host 0 semantics are fine for single-controller runs; multi-host runs
pass an explicit run id). ``step`` is whatever the step loop last declared
via :func:`set_step` unless the emitter overrides it, so asynchronous
emitters (DataLoader workers, checkpoint IO) land on the training step they
belong to and can be correlated with the XPlane trace rows annotated by
``obs.span``.

Rotation: when the active file exceeds ``rotate_bytes`` the writer
gzip-compresses it into ``<path>.<seq>.gz`` (monotonically increasing
``seq`` — lowest is oldest) and reopens fresh. Total retained rotated
bytes are capped by the ``events_keep_bytes`` knob
(``MXNET_TPU_EVENTS_KEEP_BYTES``): the oldest segments are deleted until
the cap fits, and with the default ``0`` exactly one rotated segment is
kept — the pre-cap disk bound. :func:`read_events` reads rotated
segments (gzipped or the legacy plain ``.1``) plus the live file in
order, transparently.
"""
from __future__ import annotations

import gzip
import json
import os
import re
import threading
import time
from typing import Iterator, List, Optional

__all__ = ["EventLog", "LOG", "emit", "set_step", "configure", "close",
           "read_events", "current_step", "rotated_segments",
           "latest_rotated"]


def _segment_seq(base: str, path: str) -> Optional[int]:
    m = re.fullmatch(re.escape(os.path.basename(base))
                     + r"\.(\d+)(?:\.gz)?", os.path.basename(path))
    return int(m.group(1)) if m else None


def rotated_segments(path: str) -> List[str]:
    """Rotated predecessors of the live file at ``path``, oldest first
    (``<path>.N[.gz]`` ordered by N; the legacy single ``.1`` sorts the
    same way). When a segment briefly exists both plain and compressed
    (the background compressor replaced the ``.gz`` but has not removed
    the plain file yet) the ``.gz`` wins — it is complete by then."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    by_seq: dict = {}
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        seq = _segment_seq(base, name)
        if seq is None:
            continue
        cur = by_seq.get(seq)
        if cur is None or name.endswith(".gz"):
            by_seq[seq] = name
    return [os.path.join(d, name)
            for _seq, name in sorted(by_seq.items())]


def latest_rotated(path: str) -> Optional[str]:
    segs = rotated_segments(path)
    return segs[-1] if segs else None


def segment_seq(path: str, segment: str) -> int:
    """Rotation index of one of ``path``'s rotated segments (0 when
    ``segment`` is not one)."""
    return _segment_seq(path, segment) or 0


def _open_text(path: str):
    """Text handle over a (possibly gzipped) JSONL segment."""
    if path.endswith(".gz"):
        return gzip.open(path, "rt", errors="replace")
    return open(path, "r", errors="replace")


_host_index_cache = None


def _host_index() -> int:
    # cached: emit() stamps every record with the host index, and
    # jax.process_index() costs tens of microseconds per call — the bulk
    # of the per-event budget (a process's index never changes once the
    # distributed runtime is up; before that it is 0 either way)
    global _host_index_cache
    if _host_index_cache is None:
        try:
            import jax

            _host_index_cache = int(jax.process_index())
        except Exception:
            return 0
    return _host_index_cache


class EventLog:
    def __init__(self):
        self._fh = None
        self._path: Optional[str] = None
        self._run_id: Optional[str] = None
        self._rotate_bytes = 64 * 1024 * 1024
        self._keep_bytes = 0  # 0 = keep exactly one rotated segment
        self._size = 0
        self._seq = 1  # next rotation index (resumed from disk on configure)
        self._step = 0
        self._lock = threading.Lock()
        # in-flight background compress/sweep workers (joined on close so
        # a clean shutdown leaves only .gz segments behind)
        self._rot_threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def configure(self, path: str, run_id: Optional[str] = None,
                  rotate_bytes: Optional[int] = None,
                  keep_bytes: Optional[int] = None) -> "EventLog":
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._path = path
            self._fh = open(path, "a", buffering=1)  # line-buffered
            # size tracked in-process: a tell() per emit is a syscall the
            # per-event budget can't afford
            self._size = self._fh.tell()
            self._run_id = run_id or f"{int(time.time())}-{os.getpid()}"
            if rotate_bytes is not None:
                self._rotate_bytes = int(rotate_bytes)
            if keep_bytes is not None:
                self._keep_bytes = int(keep_bytes)
            # resume the rotation sequence past whatever a previous
            # process (same path) already wrote
            segs = rotated_segments(path)
            last = _segment_seq(path, segs[-1]) if segs else 0
            self._seq = (last or 0) + 1
        return self

    @property
    def configured(self) -> bool:
        return self._fh is not None

    @property
    def path(self) -> Optional[str]:
        return self._path

    @property
    def run_id(self) -> Optional[str]:
        return self._run_id

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            threads, self._rot_threads = self._rot_threads, []
        for t in threads:  # outside the lock: workers never take it
            t.join(timeout=30.0)

    # -- write path ----------------------------------------------------------
    def set_step(self, step: int) -> None:
        self._step = int(step)

    def current_step(self) -> int:
        return self._step

    def emit(self, event: str, **fields) -> bool:
        """Write one record; returns False (and is a near-no-op) when the
        log was never configured — call sites don't need their own guard."""
        if self._fh is None:
            return False
        step = fields.pop("step", None)
        rec = {"ts": round(time.time(), 6), "run": self._run_id,
               "host": _host_index(),
               "step": self._step if step is None else int(step),
               "event": event}
        rec.update(fields)
        line = json.dumps(rec, default=_json_fallback)
        with self._lock:
            if self._fh is None:
                return False
            try:
                self._fh.write(line + "\n")
                self._size += len(line) + 1
                self._maybe_rotate()
            except (OSError, ValueError):
                # telemetry must NEVER fail the train loop: on a dead disk/
                # deleted dir, drop the log and keep training (metrics — in
                # memory — survive)
                try:
                    self._fh.close()
                except Exception:
                    pass
                self._fh = None
                import logging

                logging.getLogger("mxnet_tpu.observability").warning(
                    "event log %s unwritable; disabling event emission",
                    self._path)
                return False
        return True

    def _maybe_rotate(self) -> None:
        if self._size < self._rotate_bytes:
            return
        try:
            self._fh.close()
            rot = f"{self._path}.{self._seq}"
            os.replace(self._path, rot)  # O(1) — this is all emit() pays
            self._seq += 1
            # gzip + retention sweep run OFF the emit lock on a daemon
            # thread: compressing a 64 MB segment inline would stall the
            # training step that happened to cross the threshold (and
            # every other emitting thread behind the lock). The plain
            # numbered segment stays readable until the .gz replaces it.
            t = threading.Thread(target=self._compress_and_sweep,
                                 args=(rot,), daemon=True,
                                 name="events-rotate")
            self._rot_threads.append(t)
            t.start()
        finally:
            # reopen even if the rotation failed (truncation beats a
            # closed handle); a reopen failure propagates to emit()'s
            # guard above
            self._fh = open(self._path, "a", buffering=1)
            self._size = self._fh.tell()

    def _compress_and_sweep(self, rot: str) -> None:
        try:
            with open(rot, "rb") as src, \
                    gzip.open(rot + ".gz.tmp", "wb") as dst:
                while True:
                    chunk = src.read(1 << 20)
                    if not chunk:
                        break
                    dst.write(chunk)
            os.replace(rot + ".gz.tmp", rot + ".gz")
            os.remove(rot)
        except OSError:
            pass  # the plain segment stays readable; retry never needed
        try:
            self._sweep_retention()
        except OSError:
            pass

    def _sweep_retention(self) -> None:
        """Delete oldest rotated segments until the retained total fits
        ``keep_bytes`` (0 = keep exactly one segment, the historical
        bound). The newest segment always survives — the fleet
        snapshotter recovers post-rotation bytes from it."""
        segs = rotated_segments(self._path)
        if not segs:
            return
        if self._keep_bytes <= 0:
            doomed = segs[:-1]
        else:
            sizes = {}
            for p in segs:
                try:
                    sizes[p] = os.path.getsize(p)
                except OSError:
                    sizes[p] = 0
            total = sum(sizes.values())
            doomed = []
            for p in segs[:-1]:
                if total <= self._keep_bytes:
                    break
                doomed.append(p)
                total -= sizes[p]
        for p in doomed:
            try:
                os.remove(p)
            except OSError:
                pass


def _json_fallback(o):
    try:
        return float(o)  # jax/numpy scalars
    except Exception:
        return str(o)


def read_events(path: str) -> List[dict]:
    """Read every record from ``path`` (its rotated predecessors first,
    oldest to newest — gzipped ``.N.gz`` segments and the legacy plain
    ``.1`` both read transparently). ``path`` may also be a directory, in
    which case every ``events*.jsonl[.gz]`` file under it is read
    (multi-host runs write one file per host), or a single ``.gz``
    segment."""
    if os.path.isdir(path):
        files: List[str] = []
        names = sorted(os.listdir(path))
        # rotated segments first (oldest records), ordered per base file
        # by NUMERIC seq — lexically, .10.gz would sort before .2.gz
        rotated = []
        for name in names:
            seq = _segment_seq(name.split(".jsonl")[0] + ".jsonl", name)
            if name.startswith("events") and seq is not None:
                rotated.append((name.split(".jsonl")[0], seq, name))
        files.extend(os.path.join(path, name)
                     for _base, _seq, name in sorted(rotated))
        for name in names:
            if name.startswith("events") and (name.endswith(".jsonl")
                                              or name.endswith(".jsonl.gz")):
                files.append(os.path.join(path, name))
    elif path.endswith(".gz"):
        files = [path]
    else:
        files = rotated_segments(path) + [path]
    out: List[dict] = []
    for p in files:
        try:
            with _open_text(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn final line after a crash
        except (OSError, EOFError):
            continue  # vanished file / torn gzip trailer after a crash
    return out


def iter_events(path: str) -> Iterator[dict]:
    yield from read_events(path)


#: the process-wide default event log
LOG = EventLog()

emit = LOG.emit
set_step = LOG.set_step
current_step = LOG.current_step
configure = LOG.configure
close = LOG.close
