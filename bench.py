"""Benchmark: BERT-large pretraining throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline (BASELINE.md): reference-era GluonNLP BERT-large pretraining was
~60-80 seq/s per V100 (fp16, seq 128); vs_baseline uses the 70 seq/s
midpoint. The full training step (fwd+bwd+Adam update, bf16 compute /
f32 master math in the optimizer) runs as one donated jit program.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _tpu_ready(retries=4, delay=10):
    """The axon tunnel is lease-based and transiently flaky — retry init."""
    import jax

    for i in range(retries):
        try:
            devs = jax.devices()
            return devs[0].platform != "cpu"
        except RuntimeError as e:
            if i == retries - 1:
                print(f"TPU backend unavailable after {retries} tries: {e}",
                      file=sys.stderr)
                return False
            time.sleep(delay)
    return False


def build_step(model_name, batch, seq, masked, vocab=30522, dtype="bfloat16"):
    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.models import bert

    mx.random.seed(0)
    net = bert.get_bert(model_name, pretrain_head=True, vocab_size=vocab,
                        max_length=seq, dropout=0.1)
    net.initialize()
    rs = np.random.RandomState(0)
    ids = nd.array(rs.randint(0, vocab, (batch, seq)), dtype="int32")
    types = nd.zeros((batch, seq), dtype="int32")
    valid = nd.full((batch,), seq, dtype="int32")
    pos = nd.array(rs.randint(0, seq, (batch, masked)), dtype="int32")
    labels = nd.array(rs.randint(0, vocab, (batch, masked)), dtype="int32")
    weights = nd.ones((batch, masked))
    nsp_labels = nd.array(rs.randint(0, 2, (batch,)), dtype="int32")
    _ = net(ids, types, valid, pos)  # deferred init (f32)
    if dtype == "bfloat16":
        net.cast("bfloat16")

    def loss_fn(out, labels, weights, nsp_labels):
        mlm, nsp = out
        return bert.pretrain_loss(mlm.astype("float32"), nsp.astype("float32"),
                                  labels, weights, nsp_labels)

    from mxnet_tpu.parallel import TrainStep

    ts = TrainStep(net, loss_fn, optimizer.Adam(learning_rate=1e-4), mesh=None,
                   n_model_inputs=4)
    args = (ids, types, valid, pos, labels, weights, nsp_labels)
    return ts, args


def bert_flops(batch, seq, masked, num_layers, units, hidden, vocab):
    """Training FLOPs (fwd + bwd ~= 3x fwd matmul FLOPs) per step."""
    per_token_layer = (
        4 * units * units * 2          # qkv + out proj
        + 2 * units * hidden * 2       # ffn in/out
        + 2 * seq * units * 2          # attention scores + context
    )
    fwd = batch * seq * per_token_layer * num_layers
    head = batch * masked * units * vocab * 2
    return 3 * (fwd + head)


def main():
    on_tpu = _tpu_ready()
    # bench config: BERT-large, seq 128 (phase-1 pretraining shape); batch 64
    # is the measured MFU knee on one v5e chip (16->0.31, 32->0.35, 64->0.42,
    # 128->0.39) — the OOM fallback below halves it if a smaller chip balks
    name, batch, seq, masked = ("bert_large", 64, 128, 20) if on_tpu else (
        "bert_mini", 4, 64, 8)
    tried = []
    ts = None
    while True:
        try:
            ts, args = build_step(name, batch, seq, masked)
            import jax

            # warmup: absorb BOTH compiles (first call, and the donated-buffer
            # relayout recompile the axon backend does on call #2), then sync
            # hard via a host read of the loss
            for _ in range(3):
                loss = ts(*args)
                float(np.asarray(jax.device_get(loss)))
            break
        except Exception as e:  # OOM or transient: halve batch once or twice
            tried.append(str(e)[:100])
            if batch <= 2:
                print(json.dumps({"metric": "bert_large_samples_per_sec_chip",
                                  "value": 0.0, "unit": "seq/s",
                                  "vs_baseline": 0.0, "error": tried}), flush=True)
                return
            batch //= 2

    import jax

    # median of 3 timed windows; each window drains the device pipeline with a
    # host read of its final loss (the param donation chain makes that final
    # value depend on every step in the window)
    steps = 10 if on_tpu else 3
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = ts(*args)
        float(np.asarray(jax.device_get(loss)))
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[1]
    sps = steps * batch / dt

    from mxnet_tpu.models.bert import bert_configs

    cfg = bert_configs[name]
    flops = bert_flops(batch, seq, masked, cfg["num_layers"], cfg["units"],
                       cfg["hidden_size"], 30522) * steps
    peak = 197e12  # TPU v5e bf16 dense peak
    mfu = flops / dt / peak if on_tpu else 0.0

    print(json.dumps({
        "metric": "bert_large_samples_per_sec_chip" if name == "bert_large"
        else f"{name}_samples_per_sec",
        "value": round(sps, 2),
        "unit": "seq/s",
        "vs_baseline": round(sps / 70.0, 3),
        "batch": batch, "seq": seq, "steps": steps,
        "loss": float(np.asarray(jax.device_get(loss))),
        "mfu_est": round(mfu, 4),
        "platform": "tpu" if on_tpu else "cpu",
    }), flush=True)


if __name__ == "__main__":
    main()
