"""Golden-program memory gate (ISSUE 12, docs/ANALYSIS.md "Memory"):
`make memcheck` as a test — the committed mem_* goldens match the current
programs, an injected >5% peak regression fails the build, the paged families
stay gather-free under the hard assert_gather_free() invariant
(ISSUE 18), and the --update-golden rebless workflow round-trips.

Runs tools/memcheck.py in-process (importlib) so each case can pick one
cheap program family and capture the JSON verdict without a subprocess
per family.
"""
import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def memcheck():
    spec = importlib.util.spec_from_file_location(
        "memcheck_mod", os.path.join(REPO, "tools", "memcheck.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _verdict(capsys):
    out = capsys.readouterr().out
    row, _ = json.JSONDecoder().raw_decode(out, out.index("{"))
    return row, out


def test_gate_matches_committed_goldens(memcheck, capsys):
    """ISSUE 12 acceptance: the committed goldens describe the current
    programs — peak residency within tolerance, donation intact, no new
    materialization classes."""
    rc = memcheck.main(["--family", "step_fsdp", "--skip-validate"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    fam = row["families"]["step_fsdp"]
    assert fam["carry_donation"] == 1.0
    assert fam["peak_bytes"] > 0
    assert fam["materializations"] == {}
    # the fsdp step's carry categories are per-device shards
    assert set(fam["by_category"]) >= {"params", "opt_state",
                                       "activations", "batch"}


def test_injected_peak_regression_fails_gate(memcheck, capsys):
    """ISSUE 12 acceptance: a synthetic +20% peak (the --inject test
    hook) must fail the build as a >5% residency regression."""
    rc = memcheck.main(["--family", "step_dp8", "--inject-peak-regression",
                        "--skip-validate"])
    _, out = _verdict(capsys)
    assert rc == 1
    assert "peak residency regressed" in out


def test_paged_gather_free_is_asserted_not_just_blessed(memcheck, capsys):
    """ISSUE 18: the paged decode reads the page table inside the Pallas
    kernel, so the family is gather-FREE — and not merely because the
    golden says so: assert_gather_free() hard-fails on any
    kv_gather_materialize in the paged families, even during a rebless."""
    rc = memcheck.main(["--family", "decode_paged", "--skip-validate"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    fam = row["families"]["decode_paged"]
    assert fam["materializations"].get("kv_gather_materialize", 0) == 0
    assert fam["by_category"]["kv_pages"] > 0
    assert fam["carry_donation"] == 1.0
    # failure path: a reappearing gather fails regardless of the goldens
    fails = []
    memcheck.assert_gather_free(
        "verify_spec", {"materializations": {"kv_gather_materialize": 2}},
        fails)
    assert fails and "kv_gather_materialize" in fails[0]
    # ...and only the paged families carry the invariant
    fails = []
    memcheck.assert_gather_free(
        "decode", {"materializations": {"kv_gather_materialize": 2}}, fails)
    assert not fails


def test_validation_cross_checks_memory_analysis(memcheck, capsys):
    """The estimator self-check: liveness peak vs memory_analysis() on
    the mesh-less step and decode programs, within the documented
    tolerance, reported in the gate output."""
    rc = memcheck.main(["--family", "decode"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    progs = row["validation"]["programs"]
    tol = row["validation"]["tolerance"]
    assert set(progs) == {"step", "decode"}
    for name, p in progs.items():
        assert abs(p["rel_err"]) <= tol, (name, p)


def test_inject_cannot_combine_with_update_golden(memcheck, capsys):
    """The failure-path hook must never bless inflated peaks into the
    committed goldens."""
    with pytest.raises(SystemExit) as exc:
        memcheck.main(["--update-golden", "--inject-peak-regression"])
    assert exc.value.code == 2
    assert "cannot be combined" in capsys.readouterr().err


def test_update_golden_rebless_roundtrip(memcheck, capsys, monkeypatch,
                                         tmp_path):
    """--update-golden writes a fresh golden the plain gate then passes
    against; with no golden at all the gate fails with the rebless
    instruction instead of crashing."""
    monkeypatch.setattr(memcheck, "GOLDEN_DIR", str(tmp_path))
    rc = memcheck.main(["--family", "decode", "--skip-validate"])
    _, out = _verdict(capsys)
    assert rc == 1 and "no committed golden" in out
    assert "--update-golden" in out
    rc = memcheck.main(["--family", "decode", "--update-golden"])
    assert rc == 0
    golden = json.loads((tmp_path / "mem_decode.json").read_text())
    assert golden["carry_donation"] == 1.0
    assert golden["by_category"]["kv_cache"] > 0
    rc = memcheck.main(["--family", "decode", "--skip-validate"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
