"""GPT-2 (driver config #5: 345M, multi-host data parallel).

Decoder-only transformer with causal flash attention. Sizes follow the
published GPT-2 family; 345M == ``gpt2_medium``. Pre-LN blocks (as GPT-2).
Parameter names carry the TP sharding markers (qkv_/proj_/ffn1_/ffn2_).
"""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import initializer as init

__all__ = ["GPT2Model", "get_gpt2", "gpt2_configs", "lm_loss"]


def _chunk_positions(F, t, start_pos=None):
    """Position ids for a t-token chunk: ``arange(t)`` for a full forward,
    per-row ``start_pos + arange(t)`` for a cached chunk (rows admitted by
    the batcher at different times sit at different sequence positions)."""
    ar = F.arange(0, t, dtype="int32")
    if start_pos is None:
        return ar
    return start_pos.reshape((-1, 1)).astype("int32") + ar.reshape((1, -1))

gpt2_configs = {
    "gpt2_tiny": dict(num_layers=2, units=128, num_heads=2, max_length=512,
                      vocab_size=50257),
    "gpt2_117m": dict(num_layers=12, units=768, num_heads=12, max_length=1024,
                      vocab_size=50257),
    "gpt2_345m": dict(num_layers=24, units=1024, num_heads=16, max_length=1024,
                      vocab_size=50257),
    "gpt2_774m": dict(num_layers=36, units=1280, num_heads=20, max_length=1024,
                      vocab_size=50257),
}


class GPT2Block(HybridBlock):
    # one pre-LN decoder block = one rematerialization unit under
    # ``net.hybridize(remat=...)``: long-context training recomputes the
    # block's activations (attention scores included) during backward
    # instead of saving them (docs/PERFORMANCE.md "Mixed precision")
    _remat_unit = True

    def __init__(self, units, num_heads, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._heads = num_heads
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.qkv = nn.Dense(3 * units, flatten=False, prefix="qkv_",
                                weight_initializer=init.Normal(0.02))
            self.proj = nn.Dense(units, flatten=False, prefix="proj_",
                                 weight_initializer=init.Normal(0.02))
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            self.ffn1 = nn.Dense(4 * units, flatten=False, prefix="ffn1_",
                                 weight_initializer=init.Normal(0.02))
            self.ffn2 = nn.Dense(units, flatten=False, prefix="ffn2_",
                                 weight_initializer=init.Normal(0.02))
            self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, cache=None, start_pos=None, page_table=None):
        b, t, c = x.shape
        h = self._heads
        y = self.ln1(x)
        qkv = self.qkv(y).reshape((b, t, 3, h, c // h)).transpose((2, 0, 3, 1, 4))
        if cache is None:
            att = F.multi_head_attention(qkv[0], qkv[1], qkv[2], causal=True)
        else:
            # autoregressive path (docs/INFERENCE.md): only the t new tokens
            # flow through; K/V history lives in the static-shape cache —
            # contiguous (B,H,Tmax,Ch) buffers, or page pools indirected
            # through the per-row page_table (paged cache)
            att, k_buf, v_buf = F.multi_head_attention(
                qkv[0], qkv[1], qkv[2], cache=cache, position=start_pos,
                page_table=page_table)
        att = att.transpose((0, 2, 1, 3)).reshape((b, t, c))
        x = x + self.drop(self.proj(att))
        y = self.ffn2(F.Activation(self.ffn1(self.ln2(x)), act_type="tanh_gelu"))
        out = x + self.drop(y)
        return out if cache is None else (out, (k_buf, v_buf))


class GPT2Model(HybridBlock):
    def __init__(self, num_layers=12, units=768, num_heads=12, max_length=1024,
                 vocab_size=50257, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_layers = num_layers
        self._num_heads = num_heads
        self._max_length = max_length
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units, prefix="word_embed_",
                                           weight_initializer=init.Normal(0.02))
            self.position_embed = nn.Embedding(max_length, units,
                                               prefix="position_embed_",
                                               weight_initializer=init.Normal(0.01))
            self.drop = nn.Dropout(dropout)
            self.blocks = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                self.blocks.add(GPT2Block(units, num_heads, dropout,
                                          prefix=f"layer{i}_"))
            self.ln_f = nn.LayerNorm(in_channels=units, prefix="lnf_")

    def init_cache(self, batch_size, max_length=None, dtype="float32"):
        """Allocate per-layer ``(k_buf, v_buf)`` static decode buffers of
        shape (B, H, Tmax, Ch) — the carry of the compiled decode step
        (``mxnet_tpu.inference.GenerationEngine``)."""
        from ..ops.attention import alloc_kv_cache

        return alloc_kv_cache(batch_size, self._num_heads,
                              max_length or self._max_length,
                              self._units // self._num_heads,
                              self._num_layers, dtype=dtype)

    def init_paged_cache(self, num_pages, page_size, dtype="float32"):
        """Allocate per-layer ``(k_pool, v_pool)`` page pools of shape
        (num_pages + 1, H, page_size, Ch) — the paged decode carry; page 0
        is the reserved trash page (docs/INFERENCE.md "Paged cache")."""
        from ..ops.attention import alloc_paged_kv_cache

        return alloc_paged_kv_cache(num_pages, self._num_heads, page_size,
                                    self._units // self._num_heads,
                                    self._num_layers, dtype=dtype)

    def hybrid_forward(self, F, token_ids, cache=None, start_pos=None,
                       page_table=None):
        b, t = token_ids.shape
        pos = _chunk_positions(F, t, start_pos)
        x = self.drop(self.word_embed(token_ids) + self.position_embed(pos))
        new_cache = []
        for i, blk in enumerate(self.blocks):
            if cache is None:
                x = blk(x)
            else:
                x, layer_cache = blk(x, cache=cache[i], start_pos=start_pos,
                                     page_table=page_table)
                new_cache.append(layer_cache)
        x = self.ln_f(x)
        # weight-tied LM head (GPT-2 ties input/output embeddings)
        logits = F.dot(x.reshape((b * t, self._units)),
                       self.word_embed.weight.data(), transpose_b=True)
        logits = logits.reshape((b, t, -1))
        return logits if cache is None else (logits, new_cache)


def get_gpt2(model_name="gpt2_345m", dropout=0.1, **overrides):
    cfg = dict(gpt2_configs[model_name])
    cfg.update(overrides)
    return GPT2Model(dropout=dropout, **cfg)


def lm_loss(logits, labels):
    """Next-token cross entropy; labels = input shifted by caller."""
    from .. import ndarray as nd

    b, t, v = logits.shape
    logp = nd.log_softmax(logits, axis=-1)
    ll = nd.pick(logp.reshape((b * t, v)), labels.reshape((b * t,)), axis=-1)
    return -ll.mean()
