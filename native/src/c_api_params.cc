// .params (dmlc 0x112 NDArray-list) serialization for the C ABI.
//
// Reference analog: MXNDArraySave / MXNDArrayLoad (src/c_api/c_api.cc) over
// NDArray::Save/Load (src/ndarray/ndarray.cc). The wire format here matches
// mxnet_tpu/serialization.py byte-for-byte for dense V2 blocks:
//   u64 magic 0x112 | u64 reserved | u64 count
//   per array: u32 0xF993FAC9 | u32 ndim | i64*ndim | i32 devtype=1
//              | i32 devid=0 | i32 dtype_flag | raw C-order bytes
//   u64 n_names | per name: u64 len | bytes
// The MXTPU dtype enum (mxtpu_c_api.h) IS the MXNet type flag for 0..6, so
// no translation table is needed.
#include "../include/mxtpu_c_api.h"

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint64_t kListMagic = 0x112;
constexpr uint32_t kV2Magic = 0xF993FAC9;

size_t esize(int dtype) {
  switch (dtype) {
    case kMXTPUFloat32: return 4;
    case kMXTPUFloat64: return 8;
    case kMXTPUFloat16: return 2;
    case kMXTPUUint8: return 1;
    case kMXTPUInt32: return 4;
    case kMXTPUInt8: return 1;
    case kMXTPUInt64: return 8;
    default: return 0;
  }
}

// Load's returned name pointers stay valid until the next Load on this
// thread (reference MXAPIThreadLocalEntry ownership).
struct LoadTLS {
  std::vector<std::string> names;
  std::vector<const char*> name_ptrs;
};
thread_local LoadTLS g_load;

bool wr(std::FILE* f, const void* p, size_t n) {
  return std::fwrite(p, 1, n, f) == n;
}

bool rd(std::FILE* f, void* p, size_t n) {
  return std::fread(p, 1, n, f) == n;
}

template <typename T>
bool wr1(std::FILE* f, T v) { return wr(f, &v, sizeof(T)); }

template <typename T>
bool rd1(std::FILE* f, T* v) { return rd(f, v, sizeof(T)); }

}  // namespace

extern "C" {

int MXTPUNDArraySave(const char* fname, int n, MXTPUNDHandle* arrays,
                     const char** names) {
  if (fname == nullptr || (n > 0 && arrays == nullptr)) {
    MXTPUSetLastError("NDArraySave: null arg");
    return -1;
  }
  std::FILE* f = std::fopen(fname, "wb");
  if (f == nullptr) {
    MXTPUSetLastError("NDArraySave: cannot open file for writing");
    return -1;
  }
  bool ok = wr1<uint64_t>(f, kListMagic) && wr1<uint64_t>(f, 0) &&
            wr1<uint64_t>(f, static_cast<uint64_t>(n));
  for (int i = 0; ok && i < n; ++i) {
    int ndim = 0;
    const int64_t* shape = nullptr;
    int dtype = 0;
    int64_t size = 0;
    const void* data = nullptr;
    if (MXTPUNDArrayGetShape(arrays[i], &ndim, &shape) != 0 ||
        MXTPUNDArrayGetDType(arrays[i], &dtype) != 0 ||
        MXTPUNDArraySize(arrays[i], &size) != 0 ||
        MXTPUNDArrayGetData(arrays[i], &data) != 0) {
      std::fclose(f);
      return -1;  // error already set
    }
    size_t es = esize(dtype);
    if (es == 0) {
      std::fclose(f);
      MXTPUSetLastError("NDArraySave: unsupported dtype");
      return -1;
    }
    ok = ok && wr1<uint32_t>(f, kV2Magic) &&
         wr1<uint32_t>(f, static_cast<uint32_t>(ndim));
    for (int d = 0; ok && d < ndim; ++d) ok = wr1<int64_t>(f, shape[d]);
    ok = ok && wr1<int32_t>(f, 1) && wr1<int32_t>(f, 0) &&  // ctx: cpu(0)
         wr1<int32_t>(f, dtype) &&
         wr(f, data, static_cast<size_t>(size) * es);
  }
  int n_names = (names != nullptr) ? n : 0;
  ok = ok && wr1<uint64_t>(f, static_cast<uint64_t>(n_names));
  for (int i = 0; ok && i < n_names; ++i) {
    size_t len = names[i] ? std::strlen(names[i]) : 0;
    ok = wr1<uint64_t>(f, static_cast<uint64_t>(len)) &&
         (len == 0 || wr(f, names[i], len));
  }
  std::fclose(f);
  if (!ok) {
    MXTPUSetLastError("NDArraySave: short write");
    return -1;
  }
  return 0;
}

int MXTPUNDArrayLoad(const char* fname, int* out_n,
                     MXTPUNDHandle** out_arrays, int* out_n_names,
                     const char*** out_names) {
  if (fname == nullptr || out_n == nullptr || out_arrays == nullptr) {
    MXTPUSetLastError("NDArrayLoad: null arg");
    return -1;
  }
  std::FILE* f = std::fopen(fname, "rb");
  if (f == nullptr) {
    MXTPUSetLastError("NDArrayLoad: cannot open file");
    return -1;
  }
  static thread_local std::vector<MXTPUNDHandle> handles;
  std::vector<MXTPUNDHandle> created;
  auto fail = [&](const char* msg) {
    for (auto h : created) MXTPUNDArrayFree(h);
    std::fclose(f);
    MXTPUSetLastError(msg);
    return -1;
  };
  // file size bounds every later allocation: a corrupt shape can at most
  // claim the bytes the file actually has, so no exception ever crosses
  // the extern "C" boundary from a giant vector resize
  std::fseek(f, 0, SEEK_END);
  long fsize_l = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (fsize_l < 0) return fail("NDArrayLoad: cannot stat file");
  uint64_t fsize = static_cast<uint64_t>(fsize_l);
  uint64_t magic = 0, reserved = 0, count = 0;
  if (!rd1(f, &magic) || magic != kListMagic)
    return fail("NDArrayLoad: not a .params file (bad list magic)");
  if (!rd1(f, &reserved) || !rd1(f, &count) || count > (1u << 24))
    return fail("NDArrayLoad: corrupt header");
  for (uint64_t i = 0; i < count; ++i) {
    uint32_t m = 0, ndim = 0;
    if (!rd1(f, &m)) return fail("NDArrayLoad: truncated block");
    if (m != kV2Magic)
      return fail("NDArrayLoad: non-dense or unknown array block (the "
                  "native tier reads dense V2 blocks only)");
    if (!rd1(f, &ndim) || ndim > 32) return fail("NDArrayLoad: bad ndim");
    std::vector<int64_t> shape(ndim);
    uint64_t nelem = 1;
    for (uint32_t d = 0; d < ndim; ++d) {
      if (!rd1(f, &shape[d]) || shape[d] < 0 ||
          static_cast<uint64_t>(shape[d]) > fsize)
        return fail("NDArrayLoad: bad shape");
      nelem *= static_cast<uint64_t>(shape[d]);
      if (nelem > fsize)  // more elements than file bytes: corrupt
        return fail("NDArrayLoad: shape exceeds file size");
    }
    int32_t devtype = 0, devid = 0, dtype = 0;
    if (!rd1(f, &devtype) || !rd1(f, &devid) || !rd1(f, &dtype))
      return fail("NDArrayLoad: truncated context/dtype");
    size_t es = esize(dtype);
    if (es == 0) return fail("NDArrayLoad: unsupported dtype flag");
    if (nelem * es > fsize)
      return fail("NDArrayLoad: tensor bytes exceed file size");
    std::vector<uint8_t> buf(static_cast<size_t>(nelem) * es);
    if (!buf.empty() && !rd(f, buf.data(), buf.size()))
      return fail("NDArrayLoad: truncated tensor data");
    MXTPUNDHandle h = nullptr;
    if (MXTPUNDArrayCreateFromBytes(buf.data(), shape.data(),
                                    static_cast<int>(ndim), dtype, &h) != 0) {
      for (auto hh : created) MXTPUNDArrayFree(hh);
      std::fclose(f);
      return -1;
    }
    created.push_back(h);
  }
  // the name-count field is unconditional in the wire format (both save
  // paths always write it) — a missing or oversized count is corruption,
  // not an unnamed list; silently dropping names would make a name-keyed
  // consumer restore the wrong weights
  uint64_t n_names = 0;
  g_load.names.clear();
  g_load.name_ptrs.clear();
  if (!rd1(f, &n_names))
    return fail("NDArrayLoad: truncated name section");
  if (n_names > count)
    return fail("NDArrayLoad: corrupt name count");
  for (uint64_t i = 0; i < n_names; ++i) {
    uint64_t len = 0;
    if (!rd1(f, &len) || len > (1u << 20))
      return fail("NDArrayLoad: bad name length");
    std::string s(len, '\0');
    if (len && !rd(f, &s[0], len))
      return fail("NDArrayLoad: truncated name");
    g_load.names.push_back(std::move(s));
  }
  std::fclose(f);
  for (auto& s : g_load.names) g_load.name_ptrs.push_back(s.c_str());
  handles = std::move(created);
  *out_n = static_cast<int>(handles.size());
  *out_arrays = handles.data();
  if (out_n_names) *out_n_names = static_cast<int>(g_load.name_ptrs.size());
  if (out_names) *out_names = g_load.name_ptrs.data();
  return 0;
}

}  // extern "C"
