"""Parallelism on the 8-device virtual CPU mesh (SURVEY §4 fixture #5):
GSPMD train step with dp/tp shardings, ring attention vs dense oracle,
KVStore facade semantics."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (MeshConfig, ShardingRules, TrainStep, make_mesh,
                                ring_attention)
from mxnet_tpu.parallel.sharding import DEFAULT_BERT_RULES


def test_mesh_construction():
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    mesh2 = make_mesh(MeshConfig.auto(8, tp=2))
    assert mesh2.shape["dp"] == 4


def test_sharding_rules_tp_patterns():
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    spec = DEFAULT_BERT_RULES.spec_for("bert0_enc_layer3_attn_qkv_weight", (384, 128), mesh)
    assert spec == P("tp", None)
    spec = DEFAULT_BERT_RULES.spec_for("bert0_enc_layer3_attn_proj_weight", (128, 128), mesh)
    assert spec == P(None, "tp")
    spec = DEFAULT_BERT_RULES.spec_for("bert0_embed_ln_gamma", (128,), mesh)
    assert spec == P()


def test_sharding_rules_fits_edge_cases():
    """ISSUE 8 satellite: _fits is a total predicate — uneven axis
    divisibility, rank-shorter-than-spec, tuple-axis products, and a spec
    naming an axis the mesh lacks all answer False (spec_for then falls
    back), never raise."""
    from mxnet_tpu.parallel.sharding import ShardingRules, _fits

    mesh = make_mesh(MeshConfig(dp=2, fsdp=4))
    # uneven divisibility: 6 % fsdp(4) != 0
    assert not _fits(("fsdp", None), (6, 4), mesh)
    assert _fits(("fsdp", None), (8, 4), mesh)
    # a spec naming a missing mesh axis answers False, not KeyError
    assert not _fits(("nope", None), (8, 4), mesh)
    # tuple entries multiply the axis sizes
    assert _fits((("dp", "fsdp"), None), (8, 4), mesh)
    assert not _fits((("dp", "fsdp"),), (12,), mesh)    # 12 % 8
    assert not _fits((("dp", "ghost"),), (8,), mesh)    # missing in tuple
    # spec longer than the rank only constrains the dims that exist
    assert _fits(("dp", "fsdp", "tp"), (2,), mesh)
    # None entries constrain nothing
    assert _fits((None, None), (7, 13), mesh)

    # spec_for: a rule with a typo'd axis falls back to REPLICATED (the
    # contract checker + JH006 report it; tracing must not crash)
    rules = ShardingRules(rules=[("weight", ("ghost", None))])
    assert rules.spec_for("dense0_weight", (8, 4), mesh) == P()
    # ...while the declared intent keeps the raw (broken) spec
    assert tuple(rules.declared_spec_for(
        "dense0_weight", (8, 4), mesh)) == ("ghost", None)
    # rank shorter than the rule's spec: truncated, not an IndexError
    r2 = ShardingRules(rules=[("bias", (None, "fsdp"))])
    assert r2.spec_for("dense0_bias", (8,), mesh) == P(None)
    # the fsdp fallback is skipped entirely on a mesh without that axis
    # (make_mesh always carries all six axes; a hand-built Mesh may not)
    import jax
    import numpy as np
    from jax.sharding import Mesh

    r3 = ShardingRules(fsdp_axis="fsdp", min_fsdp_size=1)
    dp_only = Mesh(np.array(jax.devices()[:8]), ("dp",))
    assert r3.spec_for("w", (8, 8), dp_only) == P()
    # and picks the largest divisible dim when the axis exists
    assert r3.spec_for("w", (6, 8), mesh) == P(None, "fsdp")
    # no divisible dim at all: replicated, not a crash
    assert r3.spec_for("w", (7, 13), mesh) == P()


def test_train_step_dp_matches_single_device():
    """DP over the mesh must produce the same params as single-device."""
    def build():
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        _ = net(nd.ones((8, 8)))
        return net

    X = np.random.RandomState(0).rand(16, 8).astype(np.float32)
    Y = np.random.RandomState(1).randint(0, 4, 16)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_of(out, label):
        return loss_fn(out, label)

    from mxnet_tpu import optimizer as opt

    net1 = build()
    ts1 = TrainStep(net1, loss_of, opt.SGD(learning_rate=0.1), mesh=None)
    net2 = build()
    mesh = make_mesh(MeshConfig(dp=8))
    ts2 = TrainStep(net2, loss_of, opt.SGD(learning_rate=0.1), mesh=mesh)

    for _ in range(3):
        l1 = ts1(nd.array(X), nd.array(Y))
        l2 = ts2(nd.array(X), nd.array(Y))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    # prefixes differ between builds (global name counters); compare by order
    # with a NATURAL sort (conftest.natkey) — lexicographic breaks when
    # counters cross a digit boundary (dense10 < dense9)
    from conftest import natkey

    for (k1, v1), (k2, v2) in zip(sorted(ts1.params.items(), key=natkey),
                                  sorted(ts2.params.items(), key=natkey)):
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                                   rtol=1e-4, atol=1e-6, err_msg=f"{k1} vs {k2}")


@pytest.mark.slow
def test_train_step_tp_bert_tiny():
    """TP-sharded BERT step must run and produce finite loss with params
    actually sharded across tp."""
    from mxnet_tpu.models import bert

    mx.random.seed(0)
    net = bert.get_bert("bert_tiny", pretrain_head=False, vocab_size=512)
    net.initialize()
    B, T = 8, 16
    ids = nd.array(np.random.randint(0, 512, (B, T)), dtype="int32")
    _ = net(ids)

    mesh = make_mesh(MeshConfig(dp=4, tp=2))

    def loss_of(out):
        seq, pooled = out
        return (seq * seq).mean() + (pooled * pooled).mean()

    from mxnet_tpu import optimizer as opt

    ts = TrainStep(net, lambda out: loss_of(out), opt.Adam(learning_rate=1e-3),
                   mesh=mesh, rules=DEFAULT_BERT_RULES)
    qkv_names = [k for k in ts.params if "qkv_weight" in k]
    assert qkv_names
    sh = ts.params[qkv_names[0]].sharding
    assert "tp" in str(sh.spec), f"qkv weight not tp-sharded: {sh.spec}"
    loss = ts(ids)
    assert np.isfinite(float(loss))
    loss2 = ts(ids)
    assert float(loss2) < float(loss)  # deterministic batch: loss must drop
    ts.sync()  # write back to gluon params without error


@pytest.mark.slow
def test_ring_attention_matches_dense():
    mesh = make_mesh(MeshConfig(sp=8))
    B, H, T, D = 2, 2, 64, 16
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)

    def dense(q, k, v, causal):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    with mesh:
        out = ring_attention.ring_attention(q, k, v, mesh, axis="sp", causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense(q, k, v, False)),
                               rtol=1e-4, atol=1e-5)

    with mesh:
        out_c = ring_attention.ring_attention(q, k, v, mesh, axis="sp", causal=True)
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(dense(q, k, v, True)),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_grad_finite():
    mesh = make_mesh(MeshConfig(sp=4))
    B, H, T, D = 1, 2, 32, 8
    q = jnp.ones((B, H, T, D), jnp.float32) * 0.1

    def f(q):
        return ring_attention.ring_attention(q, q, q, mesh, axis="sp", causal=True).sum()

    g = jax.grad(f)(q)
    assert np.isfinite(np.asarray(g)).all()


def test_kvstore_local_push_pull():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones((2, 3)))
    kv.push(3, nd.full((2, 3), 4.0))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 4.0))
    # multi-value push aggregates (the reference's multi-device reduce)
    kv.push(3, [nd.ones((2, 3)), nd.ones((2, 3))])
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 2.0))


def test_kvstore_optimizer_on_store():
    kv = mx.kv.create("device")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv.init("w", nd.ones((4,)))
    kv.push("w", nd.ones((4,)))  # grad=1 -> w -= 0.5
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), 0.5))


def test_distributed_trainer_single_process():
    from mxnet_tpu.parallel import DistributedTrainer, dist_init

    dist_init()
    net = nn.Dense(2, in_units=3)
    net.initialize()
    tr = DistributedTrainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    x = nd.ones((4, 3))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    tr.step(4)  # must not raise


@pytest.mark.slow
def test_pipeline_parallel_parity():
    """GPipe over a pp=8 mesh == sequential stage application, fwd and grad."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import local_mesh, pipeline_apply, stack_stage_params

    mesh = local_mesh(8, pp=8)
    d = 8
    rs = np.random.RandomState(0)
    stages = [{"w": jnp.asarray(rs.normal(0, 0.3, (d, d)), jnp.float32)}
              for _ in range(8)]
    stacked = stack_stage_params(stages)

    def stage(p, a):
        return jnp.tanh(a @ p["w"])

    x = jnp.asarray(rs.normal(size=(8, d)), jnp.float32)
    got = pipeline_apply(stage, stacked, x, mesh, num_microbatches=4)
    ref = x
    for p in stages:
        ref = jnp.tanh(ref @ p["w"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    g_pp = jax.grad(lambda ps, a: jnp.sum(
        pipeline_apply(stage, ps, a, mesh, num_microbatches=4) ** 2))(stacked, x)
    g_ref = jax.grad(lambda ps, a: jnp.sum(
        __import__("functools").reduce(lambda h, p: jnp.tanh(h @ p["w"]), ps, a) ** 2))(
        stages, x)
    np.testing.assert_allclose(np.asarray(g_pp["w"]),
                               np.asarray(stack_stage_params(g_ref)["w"]),
                               rtol=5e-4, atol=5e-5)


@pytest.mark.slow
def test_moe_expert_parallel_parity():
    """ep=8 all_to_all MoE == dense top-1 routing reference (no drops)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import init_moe_params, local_mesh, moe_ffn

    mesh = local_mesh(8, ep=8)
    E, d, h = 8, 16, 32
    params = init_moe_params(jax.random.key(0), d, h, E)
    x = jax.random.normal(jax.random.key(1), (8, 6, d))
    out, aux = moe_ffn(x, params, mesh, capacity_factor=8.0)

    xt = x.reshape(-1, d)
    probs = jax.nn.softmax(xt @ params["gate"], axis=-1)
    eidx = jnp.argmax(probs, axis=-1)
    prob = jnp.take_along_axis(probs, eidx[:, None], axis=-1)[:, 0]
    hmid = jax.nn.gelu(jnp.einsum("nd,ndh->nh", xt, params["w1"][eidx]))
    ref = (prob[:, None] * jnp.einsum("nh,nhd->nd", hmid, params["w2"][eidx])
           ).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
    assert float(aux) > 0  # load-balance loss is live

    g = jax.grad(lambda p: jnp.sum(moe_ffn(x, p, mesh, capacity_factor=8.0)[0] ** 2))(params)
    for k, v in g.items():
        arr = np.asarray(v)
        assert np.isfinite(arr).all() and np.abs(arr).sum() > 0, k


@pytest.mark.slow
def test_moe_capacity_drops_tokens_gracefully():
    """Tight capacity drops overflow tokens to zero output, no crash/nan."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import init_moe_params, local_mesh, moe_ffn

    mesh = local_mesh(8, ep=8)
    params = init_moe_params(jax.random.key(0), 8, 16, 8)
    x = jax.random.normal(jax.random.key(2), (8, 16, 8))
    out, aux = moe_ffn(x, params, mesh, capacity_factor=0.25)
    assert np.isfinite(np.asarray(out)).all()
    # with drops, some token rows must be exactly zero
    zero_rows = np.all(np.asarray(out).reshape(-1, 8) == 0, axis=-1)
    assert zero_rows.any()


def test_train_step_two_batch_arities():
    """A second call with a different batch arity must get its own compiled
    program, not silently reuse the first (round-2 verdict weak #6)."""
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    net = nn.Dense(4, in_units=8)
    net.initialize()

    def loss2(out, label):
        return ((out - label) ** 2).mean()

    ts = TrainStep(net, lambda out, *labels: loss2(out, labels[0]),
                   optimizer.SGD(learning_rate=0.1), mesh=None)
    x = nd.ones((2, 8))
    y = nd.zeros((2, 4))
    l1 = float(np.asarray(ts(x, y)))
    assert np.isfinite(l1)

    # 3-ary call: loss_fn ignores the extra array but the jit signature differs
    w = nd.ones((2, 4))
    l2 = ts(x, y, w)
    assert len(ts._compiled) == 2
    assert np.isfinite(np.asarray(l2)).all()
    # alternate back — cached program for arity 2 still usable
    l3 = ts(x, y)
    assert np.isfinite(np.asarray(l3)).all()


def test_kvstore_async_accumulates_sync_replaces():
    """dist_async pushes ACCUMULATE into the store between pulls (reference
    KVStoreDistServer sync_mode_==false); sync stores replace (round-2
    verdict weak #7 — the semantics are now explicit and tested)."""
    from mxnet_tpu import kvstore as kv_mod

    async_kv = kv_mod.create("local")
    async_kv.type = "dist_async"  # single-process: exercise the merge rule
    async_kv.init("w", nd.ones((2,)))
    async_kv.push("w", nd.ones((2,)))
    async_kv.push("w", nd.ones((2,)))
    out = nd.zeros((2,))
    async_kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [3.0, 3.0])  # 1 + 1 + 1

    sync_kv = kv_mod.create("local")
    sync_kv.init("w", nd.ones((2,)))
    sync_kv.push("w", nd.full((2,), 5.0))
    sync_kv.push("w", nd.full((2,), 7.0))
    sync_kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [7.0, 7.0])  # last push wins


def test_kvstore_async_with_updater_owns_merge():
    """With set_updater, the updater (not raw accumulate) merges each push —
    matching the reference's optimizer-on-server path."""
    from mxnet_tpu import kvstore as kv_mod

    kv = kv_mod.create("local")
    kv.type = "dist_async"
    kv.init("w", nd.full((2,), 10.0))

    # simple SGD updater via the supported callable form
    def upd(key, grad, stored):
        stored._data = (stored._data - 0.1 * grad._data)

    kv._set_updater(upd)
    kv.push("w", nd.ones((2,)))
    kv.push("w", nd.ones((2,)))
    out = nd.zeros((2,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [9.8, 9.8], rtol=1e-6)


def test_train_step_honors_param_lr_mult():
    """Per-parameter lr_mult/wd_mult (reference Optimizer._get_lr semantics)
    must reach the compiled update: lr_mult=0 freezes a parameter."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, use_bias=False))
        net.add(nn.Dense(2, use_bias=False))
    net.initialize()
    x = nd.array(np.random.RandomState(0).rand(4, 3).astype(np.float32))
    y = nd.array(np.random.RandomState(1).rand(4, 2).astype(np.float32))
    _ = net(x)
    frozen = net[0].weight
    frozen.lr_mult = 0.0

    def loss_fn(out, y):
        import jax.numpy as jnp

        o = out._data if hasattr(out, "_data") else out
        yv = y._data if hasattr(y, "_data") else y
        return jnp.mean((o - yv) ** 2)

    ts = TrainStep(net, loss_fn, optimizer.SGD(learning_rate=0.5),
                   mesh=None, n_model_inputs=1)
    before = {k: np.asarray(v) for k, v in ts.params.items()}
    for _ in range(3):
        ts(x, y)
    after = {k: np.asarray(v) for k, v in ts.params.items()}
    np.testing.assert_array_equal(before[frozen.name], after[frozen.name])
    moved = [k for k in before
             if k != frozen.name and not np.array_equal(before[k], after[k])]
    assert moved, "the unfrozen parameter should have moved"


def test_train_step_honors_optimizer_set_lr_mult():
    """opt.set_lr_mult (the reference's name-keyed channel) must also reach
    the compiled step, matching the imperative Trainer."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    mx.random.seed(1)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, use_bias=False))
    net.initialize()
    x = nd.array(np.random.RandomState(0).rand(2, 3).astype(np.float32))
    y = nd.array(np.random.RandomState(1).rand(2, 4).astype(np.float32))
    _ = net(x)
    wname = net[0].weight.name

    def loss_fn(out, y):
        import jax.numpy as jnp

        o = out._data if hasattr(out, "_data") else out
        yv = y._data if hasattr(y, "_data") else y
        return jnp.mean((o - yv) ** 2)

    opt = optimizer.SGD(learning_rate=0.5)
    opt.set_lr_mult({wname: 0.0})
    ts = TrainStep(net, loss_fn, opt, mesh=None, n_model_inputs=1)
    before = np.asarray(ts.params[wname])
    ts(x, y)
    np.testing.assert_array_equal(before, np.asarray(ts.params[wname]))


def test_train_step_set_lr_mult_after_first_step_recompiles():
    """set_lr_mult AFTER the first compiled step must not be silently
    frozen: the multipliers are part of the jit cache key (round-3
    advisor finding), so a later freeze takes effect imperatively."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    mx.random.seed(1)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, use_bias=False))
    net.initialize()
    x = nd.array(np.random.RandomState(0).rand(2, 3).astype(np.float32))
    y = nd.array(np.random.RandomState(1).rand(2, 4).astype(np.float32))
    _ = net(x)
    wname = net[0].weight.name

    def loss_fn(out, y):
        import jax.numpy as jnp

        o = out._data if hasattr(out, "_data") else out
        yv = y._data if hasattr(y, "_data") else y
        return jnp.mean((o - yv) ** 2)

    opt = optimizer.SGD(learning_rate=0.5)
    ts = TrainStep(net, loss_fn, opt, mesh=None, n_model_inputs=1)
    before = np.asarray(ts.params[wname])
    ts(x, y)
    after_step1 = np.asarray(ts.params[wname])
    assert not np.array_equal(before, after_step1)  # actually trained
    opt.set_lr_mult({wname: 0.0})
    ts(x, y)
    np.testing.assert_array_equal(after_step1, np.asarray(ts.params[wname]))
