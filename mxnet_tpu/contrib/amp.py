"""Automatic mixed precision (reference: ``python/mxnet/contrib/amp/amp.py``).

The reference rewrites graphs with ``amp_cast`` using fp16 white/black op
lists and dynamically scales the loss. On TPU the target dtype is
**bfloat16**, which shares float32's exponent range — so loss scaling is
mathematically unnecessary and ``scale_loss`` becomes an identity (kept as a
context manager for script compat, and fully functional if ``dtype='float16'``
is forced). ``init()`` flips the global policy; ``init_trainer`` attaches the
scaler; ``convert_model``/Block casting maps to ``net.cast``.

Op lists survive conceptually: matmul/conv-class ops run in bf16, reductions
and normalizations accumulate f32 (the ops in ``mxnet_tpu.ops`` already do
f32 accumulation internally — see ``_reduce``/``layer_norm``/``batch_norm``).

Two layers now coexist (docs/MIGRATING.md "amp.init → compiled-policy
mapping"):

  - the host-side surface above, for imperative ``Trainer.step`` loops;
  - the **compiled policy** (:class:`Policy` / :func:`resolve_policy`),
    threaded into ``parallel.TrainStep(amp=...)``: casts live inside the
    jitted program against fp32 master weights, and float16's dynamic loss
    scaling runs entirely in-graph. ``amp="auto"`` (the TrainStep default)
    inherits the ``init()`` dtype, so existing ``amp.init()`` scripts get
    the compiled policy for free.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading

import jax.numpy as jnp

__all__ = ["init", "init_trainer", "scale_loss", "convert_model", "LossScaler",
           "amp_dtype", "Policy", "resolve_policy"]

# PROCESS-global, deliberately not threading.local: amp.init() flips a
# compile-affecting policy for the whole program, and both DataLoader
# prefetch threads (ops reading compute_dtype) and TrainSteps built on
# worker threads (resolve_policy("auto")) must see it — a thread-local
# here silently degraded those to f32
_STATE = {"dtype": None}
# deliberately process-global, not thread-local: worker-thread TrainSteps
# and loader threads must see amp.init(). Guard the transitions (JH005).
_STATE_LOCK = threading.Lock()


def amp_dtype():
    return _STATE["dtype"]


def compute_dtype():
    """jnp dtype matmul-class ops should COMPUTE in, or None when AMP is off.
    Consumed by FullyConnected / Convolution / attention (``ops/nn.py``,
    ``ops/attention.py``): inputs are cast to this dtype for the dot and
    accumulated in f32 (``preferred_element_type``) — the TPU collapse of the
    reference's fp16 op white/black lists (``lists/symbol_fp16.py``), where
    only the MXU-bound ops change precision and everything else stays f32."""
    d = amp_dtype()
    if d is None:
        return None
    return jnp.bfloat16 if d == "bfloat16" else jnp.float16


def cast_inputs(*arrays):
    """Cast f32 arrays to the active AMP compute dtype (identity w/o AMP).
    Non-f32 arrays (ints, already-cast bf16 params) pass through untouched."""
    cd = compute_dtype()
    if cd is None:
        return arrays
    return tuple(a.astype(cd) if a is not None and a.dtype == jnp.float32 else a
                 for a in arrays)


@dataclasses.dataclass(frozen=True)
class Policy:
    """Compiled-in mixed-precision policy (docs/PERFORMANCE.md "Mixed
    precision").

    Unlike the host-side ``init()``/``LossScaler`` compatibility surface,
    a Policy is threaded INTO the jitted training program
    (``parallel.TrainStep(amp=...)``): float32 parameters and model inputs
    are cast to ``compute_dtype`` inside the traced loss, so XLA fuses the
    casts away and every matmul-class op lowers to a low-precision dot,
    while the *stored* parameters — the fp32 master weights — and the
    optimizer update stay float32. For ``float16`` the dynamic loss scale
    rides the compiled carry (scale / good-step counter / skipped total):
    overflow detection is a compiled ``jnp.isfinite`` all-reduce feeding a
    ``lax.cond`` skip-update, with no per-step host sync — replacing the
    host-side ``LossScaler.has_overflow`` per-param loop, and compatible
    with the k-step ``lax.scan`` window.
    """

    compute_dtype: str = "bfloat16"   # 'bfloat16' | 'float16'
    loss_scale: float = 2.0 ** 16     # initial dynamic scale (float16 only)
    scale_factor: float = 2.0
    scale_window: int = 2000

    def __post_init__(self):
        if self.compute_dtype not in ("bfloat16", "float16"):
            raise ValueError(f"Policy compute_dtype must be 'bfloat16' or "
                             f"'float16', got {self.compute_dtype!r}")

    @property
    def jnp_compute_dtype(self):
        return jnp.bfloat16 if self.compute_dtype == "bfloat16" else jnp.float16

    @property
    def dynamic_scaling(self) -> bool:
        """bf16 shares f32's exponent range — only float16 needs scaling."""
        return self.compute_dtype == "float16"


def resolve_policy(amp):
    """Normalize a TrainStep ``amp=`` argument to a Policy (or None).

    ``"auto"`` inherits the process-global ``amp.init()`` dtype (None when
    AMP was never initialised) — the compiled-policy mapping of the
    reference's global flag. ``None``/``False`` disable; a dtype string or
    an explicit Policy pass through.
    """
    if amp is None or amp is False:
        return None
    if isinstance(amp, Policy):
        return amp
    if amp == "auto":
        d = amp_dtype()
        return None if d is None else Policy(compute_dtype=d)
    if isinstance(amp, str):
        return Policy(compute_dtype=amp)
    raise TypeError(f"amp= must be 'auto', None, a dtype string, or a "
                    f"Policy, got {type(amp)}")


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP globally. On TPU target_dtype defaults to bfloat16."""
    assert target_dtype in ("bfloat16", "float16")
    with _STATE_LOCK:
        _STATE["dtype"] = target_dtype
    # invalidate jit programs traced under the previous policy — otherwise a
    # hybridized net keeps replaying its f32 dots and AMP silently no-ops
    from ..gluon import block as _block

    _block.bump_global_cache_epoch()


# the op-class lists behind the policy (reference: amp/lists/symbol_fp16.py
# FP16_FUNCS / FP16_FP32_FUNCS / FP32_FUNCS). On TPU the low-precision set
# is exactly the MXU-bound ops; reductions/normalizations accumulate f32.
_LP16_OPS = ["FullyConnected", "Convolution", "Deconvolution", "dot",
             "batch_dot", "linalg_gemm", "linalg_gemm2",
             "interleaved_matmul_selfatt_qk",
             "interleaved_matmul_selfatt_valatt", "multi_head_attention"]
_F32_OPS = ["softmax", "log_softmax", "SoftmaxOutput", "LayerNorm",
            "BatchNorm", "RMSNorm", "InstanceNorm", "L2Normalization",
            "norm", "sum", "mean", "exp", "log", "erf", "gammaln"]
_WIDEST_OPS = ["add", "subtract", "multiply", "divide", "maximum", "minimum",
               "concat", "where"]


def list_lp16_ops(target_dtype="bfloat16"):
    """Ops computed in the low-precision dtype under AMP (reference:
    ``amp.list_fp16_ops``)."""
    return list(_LP16_OPS)


list_fp16_ops = list_lp16_ops


def list_fp32_ops(target_dtype="bfloat16"):
    """Ops pinned to f32 compute/accumulation under AMP."""
    return list(_F32_OPS)


def list_widest_type_cast_ops(target_dtype="bfloat16"):
    """Ops that follow the widest input dtype (reference:
    ``list_widest_type_cast``)."""
    return list(_WIDEST_OPS)


def _reset():
    """Disable AMP (test hook)."""
    with _STATE_LOCK:
        _STATE["dtype"] = None
    # invalidate jit caches traced under a different amp policy
    from ..gluon import block as _block

    _block.bump_global_cache_epoch()


class LossScaler:
    """Dynamic loss scaling (only meaningful for float16)."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0, scale_window=2000):
        # enabled is latched at creation: the scaler stays active (overflow
        # checks keep running, the scale can grow back) even if the scale
        # later bottoms out at 1.0
        self.enabled = amp_dtype() == "float16"
        self.loss_scale = init_scale if self.enabled else 1.0
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        for p in params:
            if p._nd is None or p.data()._grad is None:
                continue
            if not bool(jnp.isfinite(p.grad()._data).all()):
                return True
        return False

    def update_scale(self, skip):
        if skip:
            self.loss_scale = max(1.0, self.loss_scale / self._factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0


def init_trainer(trainer):
    trainer._amp_loss_scaler = LossScaler()
    trainer._amp_original_scale = trainer._scale
    # float16 weights need f32 master math (reference: AMP forces
    # multi_precision optimizers); harmless when weights are f32/bf16.
    # States created before the flip keep working: the self-describing
    # {"master", "base"} layout lets update_multi_precision adopt a plain
    # state as the base (momentum preserved) and re-derive the master.
    if amp_dtype() == "float16":
        trainer._optimizer.multi_precision = True


@contextlib.contextmanager
def scale_loss(loss, trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        yield loss
        return
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale
    trainer._scale = trainer._amp_original_scale


def unscale(trainer):
    pass  # grads rescaled through trainer._scale


def convert_model(net, target_dtype="bfloat16"):
    """Cast a Gluon block's parameters for mixed-precision compute.
    BatchNorm stats/gamma/beta stay f32 (see BatchNorm.cast)."""
    net.cast(target_dtype)
    return net
