#!/usr/bin/env python
"""Program-audit gate (``make audit``; docs/ANALYSIS.md, ISSUE 6).

Runs the structural HLO auditor over the framework's two donated-carry
program families on CPU and FAILS unless the structural contracts hold:

  1. **bf16 purity** — the bf16-policy TrainStep's lowered program (single
     step AND the fused k-step window) contains bf16 dots and ZERO f64
     ops (an f64 promotion leak silently halves MXU throughput);
  2. **donation coverage** — 100% of the TrainStep carry (params + opt
     state, window included) and of the decode engine's KV-cache carry is
     aliased input->output in the compiled executable (a lost alias means
     a full buffer copy every step);
  3. **recompile causes** — a recompile triggered by a batch-shape change
     is *logged* with cause ``"shape"`` and an ``arg: old -> new`` detail
     in the observability event log, not just counted.

Everything here reads :class:`mxnet_tpu.analysis.ProgramReport` /
``TrainStep.audit()`` / ``GenerationEngine.audit()`` — the same API the
test suite uses, exercised as a standalone pre-merge gate.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def train_step_section(fails):
    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    x = nd.ones((8, 16))
    _ = net(x)
    ts = TrainStep(net, lambda out, *l: ((out - l[0]) ** 2).mean(),
                   optimizer.Adam(learning_rate=1e-3), amp="bfloat16")
    batch = (x, nd.zeros((8, 8)))

    out = {}
    for name, audit in (("step", ts.audit(*batch)),
                        ("window", ts.audit(*batch, window=3))):
        dots = audit.lowered.dot_dtypes()
        f64 = audit.lowered.ops_with_dtype("f64")
        cov = audit.carry_donation()
        out[name] = {"dots": dots, "f64_ops": len(f64),
                     "carry_n": len(audit.carry_indices),
                     "donation_coverage": cov}
        if dots.get("bf16", 0) < 2:
            fails.append(f"{name}: bf16-policy program has no bf16 dots "
                         f"({dots})")
        if f64:
            fails.append(f"{name}: {len(f64)} f64 ops leaked into the "
                         f"compiled bf16 program: {f64[:3]}")
        if cov < 1.0:
            fails.append(f"{name}: carry donation {cov:.0%} < 100% — "
                         f"missing flat inputs {audit.carry_missing()}")
    return out


def decode_engine_section(fails):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.inference import GenerationEngine
    from mxnet_tpu.models import gpt2

    mx.random.seed(0)
    net = gpt2.get_gpt2("gpt2_tiny", dropout=0.0, num_layers=2, units=32,
                        num_heads=2, max_length=64, vocab_size=64)
    net.initialize()
    _ = net(nd.array(np.zeros((1, 4), np.int32)))
    eng = GenerationEngine(net, batch_size=2, max_length=64,
                           prefill_buckets=(8, 16))
    paged = GenerationEngine(net, batch_size=2, max_length=64,
                             prefill_buckets=(8, 16), paged=True,
                             page_size=16)
    spec = GenerationEngine(net, batch_size=2, max_length=64,
                            prefill_buckets=(8, 16), paged=True,
                            page_size=16, draft_net=net, speculate_k=4)
    out = {}
    audits = (("decode", eng.audit()),
              ("prefill", eng.audit(bucket=8)),
              ("paged_decode", paged.audit()),
              ("paged_prefill", paged.audit(bucket=8)),
              ("spec_draft", spec.audit()),
              ("spec_verify", spec.audit(program="verify")),
              ("spec_prefill", spec.audit(bucket=8)))
    for name, audit in audits:
        cov = audit.carry_donation()
        out[name] = {"carry_n": len(audit.carry_indices),
                     "donation_coverage": cov,
                     "host_transfers": [o.name for o in
                                        audit.compiled.host_transfers()]}
        if cov < 1.0:
            fails.append(f"{name}: KV-cache carry donation {cov:.0%} < "
                         f"100% — missing {audit.carry_missing()}")
        if out[name]["host_transfers"]:
            fails.append(f"{name}: host-transfer ops in the serving "
                         f"program: {out[name]['host_transfers']}")
    return out


def recompile_cause_section(fails):
    """A shape-change recompile must land in the event log with cause
    "shape" and a detail naming the changed argument."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, observability as obs, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    with tempfile.TemporaryDirectory() as tmp:
        obs.enable(tmp)
        try:
            mx.random.seed(0)
            net = nn.Dense(4, in_units=3)
            net.initialize()
            _ = net(nd.ones((2, 3)))
            ts = TrainStep(net, lambda out, y: ((out - y) ** 2).mean(),
                           optimizer.SGD(learning_rate=0.1))
            ts(nd.ones((2, 3)), nd.ones((2, 4)))
            ts(nd.ones((6, 3)), nd.ones((6, 4)))   # the shape change
            obs.shutdown()
            recs = [e for e in obs.read_events(tmp)
                    if e["event"] == "recompile"]
        finally:
            obs.disable()
    shape_evs = [e for e in recs if e.get("reason") == "shape"]
    out = {"recompile_events": len(recs),
           "shape_events": [{k: e.get(k) for k in
                             ("reason", "cause", "detail")}
                            for e in shape_evs]}
    if not shape_evs:
        fails.append(f"no recompile event with reason='shape' (got "
                     f"{[e.get('reason') for e in recs]})")
    elif not (shape_evs[0].get("cause") == "shape"
              and "->" in shape_evs[0].get("detail", "")):
        fails.append(f"shape recompile not explained: {shape_evs[0]}")
    return out


def main():
    fails: list = []
    row = {
        "gate": "audit",
        "train_step": train_step_section(fails),
        "decode_engine": decode_engine_section(fails),
        "recompile_cause": recompile_cause_section(fails),
    }
    row["ok"] = not fails
    if fails:
        row["failures"] = fails
    print(json.dumps(row, indent=1))
    if fails:
        for msg in fails:
            print(f"FAIL: {msg}")
        return 1
    ts = row["train_step"]
    print(f"OK: bf16 step/window carry donation 100% "
          f"({ts['step']['carry_n']}+{ts['window']['carry_n']} buffers), "
          f"0 f64 ops, decode/paged/speculative cache donation 100% with "
          f"zero host transfers, shape recompile explained in the event log")
    return 0


if __name__ == "__main__":
    sys.exit(main())
