"""Compiled KV-cache generation engine (docs/INFERENCE.md).

The training insight of ``TrainStep.run`` — one donated jit program instead
of a per-step dispatch storm — applied to decoding. A naive sampling loop
re-forwards the whole growing sequence every token: O(N·L²) attention
recompute plus a fresh dispatch (or, hybridized, a fresh *compile* per
growing shape). This engine runs exactly two compiled program families:

  - **prefill** — the prompt, padded to a static bucket length, runs one
    cached causal forward that writes the prompt's K/V into one row of the
    static decode cache and samples the first new token. One XLA program
    per bucket length, batch-1 row insert (``lax.dynamic_update_slice`` at
    the slot index), so admitting a request never touches the other rows.
  - **decode** — one token for every row of the static batch: cache update
    via per-row ``dynamic_update_slice``, attention against the full
    buffers, sampling (greedy / temperature / top-k) and per-row EOS
    done-masking all compiled in. The cache is a donated carry, so XLA
    updates it in place.

Nothing in the serving loop changes a shape, so the compiled-program count
is exactly ``len(buckets used) + 1`` — counted through the observability
registry (``gen_recompiles_total{reason="prefill_bucket"|"decode"}``), the
same discipline as ``train_recompiles_total``.
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..gluon.block import _HybridTrace
from ..ndarray import NDArray
from ..ops import random_ops as _rops

__all__ = ["GenerationEngine", "SamplingConfig"]


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Decode-time sampling, folded into the compiled programs as constants
    (changing it makes a new engine / new programs, counted as recompiles).
    """

    method: str = "greedy"  # greedy | temperature | top_k
    temperature: float = 1.0
    top_k: int = 40
    seed: int = 0

    def __post_init__(self):
        if self.method not in ("greedy", "temperature", "top_k"):
            raise ValueError(f"unknown sampling method {self.method!r}")

    @property
    def stochastic(self) -> bool:
        return self.method != "greedy" and self.temperature > 0


def _default_buckets(max_length: int) -> Tuple[int, ...]:
    out, b = [], 16
    while b < max_length:
        out.append(b)
        b *= 2
    return tuple(out) or (max_length - 1,)


class GenerationEngine:
    """Compiled autoregressive generation over a static decode batch.

    Parameters
    ----------
    net : GPT2Model (or any block whose ``hybrid_forward`` threads
        ``cache=``/``start_pos=`` and that provides ``init_cache``).
        Must be initialized; dropout should be 0 for exact equivalence
        (evaluation mode disables it regardless).
    batch_size : rows of the static decode batch (= serving slots).
    max_length : KV-cache length per row (default: the net's max_length).
    prefill_buckets : ascending prompt-length buckets; each bucket used
        costs one prefill compile. Default: powers of two from 16.
    eos_id : token that finishes a row (compiled into the done-mask);
        None = rows only finish by max_new_tokens.
    pad_id : token emitted by finished rows and used for prompt padding.
    sampling : SamplingConfig (or method string), compiled in.
    """

    def __init__(self, net, batch_size: int = 4, max_length: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 sampling=None, cache_dtype: str = "float32"):
        self.net = net
        self.batch_size = int(batch_size)
        self.max_length = int(max_length or net._max_length)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.pad_id = int(pad_id)
        if sampling is None:
            sampling = SamplingConfig()
        elif isinstance(sampling, str):
            sampling = SamplingConfig(method=sampling)
        self.sampling = sampling
        buckets = tuple(sorted(prefill_buckets or
                               _default_buckets(self.max_length)))
        if not buckets or buckets[-1] >= self.max_length:
            raise ValueError(f"prefill buckets {buckets} must be non-empty "
                             f"and < max_length={self.max_length}")
        self.prefill_buckets = buckets

        self._plist = [p for _, p in sorted(net.collect_params().items())]
        for p in self._plist:
            if p._nd is None:
                raise ValueError(f"parameter {p.name} not initialized; run "
                                 "one forward pass first")
        #: device state: per-layer (k_buf, v_buf), the donated decode carry
        self.cache = net.init_cache(self.batch_size, self.max_length,
                                    dtype=cache_dtype)
        # host state (tiny (B,) vectors shipped to the device each step —
        # keeping them host-side makes slot admission trivial)
        self.positions = np.zeros(self.batch_size, np.int32)
        self.done = np.ones(self.batch_size, bool)  # empty slots are "done"
        self.last_tokens = np.full(self.batch_size, self.pad_id, np.int32)

        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=(1,),
                                    static_argnums=())
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=(1,))
        # lowered-program fingerprints seen (cf. TrainStep._note_recompile):
        # a miss means XLA compiles a new executable. Reasons are fixed by
        # contract ("prefill_bucket"/"decode") — the guard supplies the
        # event plumbing and the program count (docs/ANALYSIS.md).
        from ..analysis import RecompileGuard

        self._recompile_guard = RecompileGuard(
            "gen_recompiles_total",
            "generation program lowerings (cache misses)")
        self._key = None  # lazily created PRNG key for stochastic sampling
        self._fixed_key = None

    # -- program accounting --------------------------------------------------
    @property
    def compiled_programs(self) -> int:
        """How many XLA executables this engine has lowered (prefill buckets
        actually used + the decode step)."""
        return len(self._recompile_guard)

    def _note_program(self, sig, reason):
        from ..analysis import Fingerprint

        self._recompile_guard.observe(Fingerprint.of((), sig=sig),
                                      reason=reason, group=reason,
                                      sig=list(map(str, sig)))

    # -- sampling (compiled into both programs) ------------------------------
    def _sample(self, logits2d, key):
        cfg = self.sampling
        if cfg.method == "greedy":
            return jnp.argmax(logits2d, axis=-1).astype(jnp.int32)
        if cfg.method == "temperature":
            return _rops.temperature_sampling(
                logits2d, temperature=cfg.temperature, key=key)
        return _rops.top_k_sampling(logits2d, k=cfg.top_k,
                                    temperature=cfg.temperature, key=key)

    def _next_key(self):
        if not self.sampling.stochastic:
            if self._fixed_key is None:
                self._fixed_key = jax.random.key(self.sampling.seed)
            return self._fixed_key
        if self._key is None:
            self._key = jax.random.key(self.sampling.seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def _params(self):
        return tuple(p._nd._data for p in self._plist)

    # -- pure programs -------------------------------------------------------
    def _prefill_fn(self, params, cache, tokens, slot, length, key):
        """(params, cache, (1, Lb) tokens, slot, real length, key) ->
        (cache', first sampled token, last-prompt-position logits)."""
        row_cache = [tuple(jax.lax.dynamic_slice_in_dim(b, slot, 1, axis=0)
                           for b in layer) for layer in cache]
        start = jnp.zeros((1,), jnp.int32)
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_rows = self.net(
                NDArray(tokens),
                cache=[(NDArray(k), NDArray(v)) for k, v in row_cache],
                start_pos=NDArray(start))
        logits = logits._data  # (1, Lb, vocab)
        new_cache = [
            tuple(jax.lax.dynamic_update_slice_in_dim(full, row._data, slot,
                                                      axis=0)
                  for full, row in zip(layer, rows))
            for layer, rows in zip(cache, new_rows)]
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                            keepdims=False)[0]  # (vocab,)
        tok = self._sample(last[None, :], key)[0].astype(jnp.int32)
        return new_cache, tok, last

    def _decode_fn(self, params, cache, tokens, positions, done, key):
        """One token for every row: (cache', next tokens, done', logits).
        Finished rows emit ``pad_id`` and keep their cache frontier."""
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_cache = self.net(
                NDArray(tokens.reshape(self.batch_size, 1)),
                cache=[(NDArray(k), NDArray(v)) for k, v in cache],
                start_pos=NDArray(positions))
        logits = logits._data[:, 0]  # (B, vocab)
        sampled = self._sample(logits, key)
        next_tok = jnp.where(done, jnp.int32(self.pad_id), sampled)
        if self.eos_id is not None:
            done = done | (sampled == self.eos_id)
        new_cache = [tuple(b._data for b in layer) for layer in new_cache]
        return new_cache, next_tok.astype(jnp.int32), done, logits

    # -- host API ------------------------------------------------------------
    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if b >= length:
                return b
        raise ValueError(f"prompt length {length} exceeds largest prefill "
                         f"bucket {self.prefill_buckets[-1]}")

    def prefill(self, prompt, slot: int) -> int:
        """Admit a prompt into row ``slot``: write its K/V into the cache,
        sample the first new token (returned as a host int — this sync is
        the time-to-first-token point). Never touches other rows."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        length = prompt.size
        if not 0 < length:
            raise ValueError("empty prompt")
        if not 0 <= slot < self.batch_size:
            raise ValueError(f"slot {slot} out of range")
        bucket = self.bucket_for(length)
        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, :length] = prompt
        t0 = time.perf_counter()
        self._note_program(("prefill", bucket), "prefill_bucket")
        cache, tok, last = self._prefill_jit(
            self._params(), self.cache, jnp.asarray(padded),
            jnp.asarray(slot, jnp.int32), jnp.asarray(length, jnp.int32),
            self._next_key())
        self.cache = cache
        tok = int(tok)  # host sync: the first token is ready here
        self.positions[slot] = length
        self.last_tokens[slot] = tok
        self.done[slot] = (self.eos_id is not None and tok == self.eos_id)
        if _obs.enabled():
            _obs.histogram("gen_prefill_seconds", "prompt prefill wall clock",
                           unit="s").observe(time.perf_counter() - t0,
                                             bucket=bucket)
        self._last_logits = last
        return tok

    def decode_step(self):
        """One compiled step over the whole batch. Returns
        ``(next_tokens (B,) np.int32, done (B,) np.bool_, logits (B, V)
        device array)``. Rows that were already done emit ``pad_id``."""
        t0 = time.perf_counter()
        active_in = ~self.done
        self._note_program(("decode", self.batch_size), "decode")
        cache, tok, done, logits = self._decode_jit(
            self._params(), self.cache, jnp.asarray(self.last_tokens),
            jnp.asarray(self.positions), jnp.asarray(self.done),
            self._next_key())
        self.cache = cache
        # np.array (copy): zero-copy views of jax buffers are read-only,
        # and this host state is mutated by release_slot/prefill
        tok = np.array(tok)
        done = np.array(done)
        # rows active going into the step consumed one cache index
        self.positions = self.positions + active_in.astype(np.int32)
        # a row whose frontier hit the buffer end cannot take another token
        full = active_in & (self.positions >= self.max_length)
        if full.any():
            done = done | full
            _obs.counter("gen_cache_overflow_total",
                         "rows force-finished at the KV-cache end").inc(
                             int(full.sum()))
        self.done = done
        self.last_tokens = tok
        if _obs.enabled():
            dt = time.perf_counter() - t0
            _obs.histogram("gen_decode_step_seconds",
                           "one compiled decode step wall clock",
                           unit="s").observe(dt)
            # slot utilization of this step: fraction of the static batch
            # that decoded real tokens (the fleet report's serving rollup)
            _obs.gauge("gen_slot_utilization",
                       "fraction of decode slots active this step").set(
                           float(active_in.sum()) / self.batch_size)
        return tok, done, logits

    def audit(self, bucket: Optional[int] = None, compile: bool = True):
        """Structural :class:`~mxnet_tpu.analysis.ProgramAudit` of a
        serving program (docs/ANALYSIS.md). Default: the decode step —
        ``carry_indices`` are the flat positions of the KV-cache buffers
        (the donated carry), so ``audit().carry_donation() == 1.0`` is the
        in-place-cache-update check. With ``bucket=`` the prefill program
        for that bucket length is audited instead (same donated cache)."""
        from .. import analysis as _analysis

        params = self._params()
        n_params = len(jax.tree_util.tree_leaves(params))
        n_cache = len(jax.tree_util.tree_leaves(self.cache))
        # constant dummy key: lower() never runs the program, and drawing
        # from _next_key() would advance the stochastic-sampling stream —
        # an audit() between decode steps must not change the tokens
        key = jax.random.key(0)
        if bucket is None:
            lowered = self._decode_jit.lower(
                params, self.cache, jnp.asarray(self.last_tokens),
                jnp.asarray(self.positions), jnp.asarray(self.done), key)
        else:
            bucket = self.bucket_for(bucket)
            tokens = jnp.full((1, bucket), self.pad_id, jnp.int32)
            lowered = self._prefill_jit.lower(
                params, self.cache, tokens, jnp.asarray(0, jnp.int32),
                jnp.asarray(bucket, jnp.int32), key)
        # flat arg order: params leaves, then the cache leaves (argnum 1,
        # the donated carry)
        lowered_rep = _analysis.audit_lowered(lowered)
        compiled_rep = (_analysis.audit_compiled(lowered.compile())
                        if compile else None)
        # serving programs run mesh-less today, so the comm report is the
        # "no collectives crept into the decode path" check — any priced
        # collective here is a regression tools/shardcheck.py catches
        comm = _analysis.comm_report(
            compiled_rep if compiled_rep is not None else lowered_rep)
        return _analysis.ProgramAudit(
            lowered=lowered_rep, compiled=compiled_rep,
            carry_indices=tuple(range(n_params, n_params + n_cache)),
            comm=comm)

    def release_slot(self, slot: int) -> None:
        """Mark a row free (emits pad, frontier frozen) — the next prefill
        into this slot overwrites it."""
        self.done[slot] = True
        self.last_tokens[slot] = self.pad_id

    # -- convenience: whole-batch generation ---------------------------------
    def generate(self, prompts, max_new_tokens: int = 32) -> List[List[int]]:
        """Generate up to ``max_new_tokens`` for each prompt (≤ batch_size
        prompts, one slot each). Returns the generated token lists (prompt
        excluded); rows stop at EOS, max_new_tokens, or a full cache."""
        if len(prompts) > self.batch_size:
            raise ValueError(f"{len(prompts)} prompts > batch_size="
                             f"{self.batch_size}; use ContinuousBatcher")
        self.done[:] = True  # park unused rows
        outs: List[List[int]] = []
        for i, p in enumerate(prompts):
            tok = self.prefill(p, slot=i)
            outs.append([tok])
        while True:
            active = [i for i in range(len(prompts))
                      if not self.done[i] and len(outs[i]) < max_new_tokens]
            if not active:
                break
            tok, done, _ = self.decode_step()
            for i in active:
                outs[i].append(int(tok[i]))
                if len(outs[i]) >= max_new_tokens and not self.done[i]:
                    self.release_slot(i)  # cap reached: stop advancing
        return outs
