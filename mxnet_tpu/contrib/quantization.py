"""INT8 post-training quantization (reference:
``python/mxnet/contrib/quantization.py`` + ``src/operator/quantization/``).

The reference inserts quantize/dequantize ops and calibrates scales via
min-max or KL(entropy) over a calibration set. The TPU design keeps the same
calibration logic (it's backend-agnostic math) and offers two execution
modes:

  - *simulated* (``quantize_net``): int8-grid values stored dequantized in
    the model dtype — accuracy study without touching execution;
  - *real int8* (``quantized_fully_connected`` / ``quantized_conv`` registry
    ops + ``convert_to_int8``): ``lax.dot_general`` on int8 operands with
    int32 accumulation — the MXU's native int8 path (reference:
    ``quantized_fully_connected.cc``, ``quantized_conv.cc``), with f32
    requant scales applied to the int32 accumulator.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..registry import register

__all__ = ["quantize_array", "dequantize_array", "calib_minmax", "calib_entropy",
           "quantize_net", "quantized_fully_connected", "quantized_conv",
           "convert_to_int8", "QuantizedDense"]


def quantize_array(x, scale=None, axis=None):
    """f32 -> (int8, scale). Per-channel when axis is given."""
    xf = x.astype(jnp.float32)
    if scale is None:
        amax = jnp.max(jnp.abs(xf), axis=None if axis is None else tuple(
            i for i in range(x.ndim) if i != axis), keepdims=axis is not None)
        scale = amax / 127.0 + 1e-12
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_array(q, scale, dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def calib_minmax(samples):
    """Min-max calibration: scale from the absolute max over samples."""
    amax = max(float(np.abs(np.asarray(s)).max()) for s in samples)
    return amax / 127.0 + 1e-12


def calib_entropy(samples, num_bins=2048, num_quantized_bins=255):
    """KL-divergence (entropy) calibration, reference algorithm shape."""
    data = np.abs(np.concatenate([np.asarray(s).ravel() for s in samples]))
    amax = data.max() + 1e-12
    hist, edges = np.histogram(data, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    for i in range(num_quantized_bins // 2, num_bins + 1, num_bins // 64 or 1):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()  # clip outliers into last bin
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = max(1, i // num_quantized_bins)
        q = np.zeros_like(p)
        for j in range(0, i, factor):
            chunk = p[j:j + factor]
            nz = (chunk > 0).sum()
            if nz:
                q[j:j + factor] = np.where(chunk > 0, chunk.sum() / nz, 0)
        pn, qn = p / p.sum(), q / max(q.sum(), 1e-12)
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(pn[mask] / np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, t
    return best_t / 127.0


# --------------------------------------------------------------------------
# real int8 execution (reference: src/operator/quantization/
# quantized_fully_connected.cc / quantized_conv.cc — cuDNN int8 there,
# MXU int8 dot with s32 accumulation here)
# --------------------------------------------------------------------------
@register("_contrib_quantized_fully_connected", aliases=("quantized_fully_connected",))
def quantized_fully_connected(dataq, weightq, bias=None, data_scale=1.0,
                              weight_scale=1.0, num_hidden=None, no_bias=False,
                              flatten=True, out_dtype="float32"):
    """int8 GEMM: ``s8 x s8 -> s32`` accumulate, then one f32 requant-scale.

    ``weight_scale`` may be per-output-channel (shape ``(num_hidden,)`` or
    ``(num_hidden, 1)``). Output is dequantized f32/bf16 — on TPU keeping the
    boundary in float and the FLOPs in int8 is the whole win; there is no
    int8 "requantize to next layer" chain like the cuDNN path needed.
    """
    if flatten and dataq.ndim > 2:
        dataq = dataq.reshape(dataq.shape[0], -1)
    acc = lax.dot_general(dataq, weightq, (((dataq.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    ws = jnp.asarray(weight_scale, jnp.float32).reshape(-1)
    out = acc.astype(jnp.float32) * (jnp.asarray(data_scale, jnp.float32) * ws)
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype)


@register("_contrib_quantized_conv", aliases=("quantized_conv",))
def quantized_conv(dataq, weightq, bias=None, kernel=None, stride=(1, 1),
                   pad=(0, 0), dilate=(1, 1), num_filter=None, num_group=1,
                   no_bias=False, data_scale=1.0, weight_scale=1.0,
                   out_dtype="float32"):
    """int8 convolution with s32 accumulation (NCHW, like ``Convolution``)."""
    def _pair(v):
        return tuple(int(x) for x in v) if isinstance(v, (tuple, list)) else (int(v),) * 2

    stride, dilate, pad = _pair(stride), _pair(dilate), _pair(pad)
    acc = lax.conv_general_dilated(
        dataq, weightq, window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dilate, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=int(num_group),
        preferred_element_type=jnp.int32)
    ws = jnp.asarray(weight_scale, jnp.float32).reshape(1, -1, 1, 1)
    out = acc.astype(jnp.float32) * (jnp.asarray(data_scale, jnp.float32) * ws)
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32).reshape(1, -1, 1, 1)
    return out.astype(out_dtype)


class QuantizedDense:
    """Inference-only replacement for ``gluon.nn.Dense`` holding int8 weights
    (produced by :func:`convert_to_int8`). Activations are quantized with the
    calibrated static scale when available, else dynamically per batch."""

    def __init__(self, wq, w_scale, bias=None, activation=None, act_scale=None):
        self._wq = wq
        self._ws = jnp.ravel(jnp.asarray(w_scale, jnp.float32))
        self._bias = bias
        self._act = activation
        self._act_scale = act_scale

    def __call__(self, x):
        from ..ndarray import NDArray

        data = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        orig_dtype = data.dtype
        xf = data.astype(jnp.float32)
        a_scale = (jnp.asarray(self._act_scale, jnp.float32)
                   if self._act_scale is not None
                   else jnp.max(jnp.abs(xf)) / 127.0 + 1e-12)
        xq = jnp.clip(jnp.round(xf / a_scale), -127, 127).astype(jnp.int8)
        out = quantized_fully_connected(
            xq, self._wq,
            bias=self._bias._data if isinstance(self._bias, NDArray)
            else self._bias,
            data_scale=a_scale, weight_scale=self._ws)
        if self._act == "relu":
            out = jnp.maximum(out, 0)
        elif self._act == "tanh":
            out = jnp.tanh(out)
        return NDArray(out.astype(orig_dtype))


def convert_to_int8(net, calib_data=None, exclude_patterns=("embed",)):
    """Swap every ``Dense`` child of a Gluon block tree for a
    :class:`QuantizedDense` with real int8 weights. Returns the (mutated)
    net and {layer_name: weight_scale}. With ``calib_data`` (list of input
    batches), activation scales are calibrated min-max by running the f32 net
    once with capture hooks; otherwise activations quantize dynamically."""
    from ..gluon import nn as _gnn

    # run eagerly from here on: stale jit programs would bypass the calib
    # hooks (and keep executing f32 after conversion), and tracing through a
    # hook's float() would crash on a tracer
    for blk in [net] + [c for _, c in _walk_blocks(net)]:
        if hasattr(blk, "_jit_cache"):
            blk._jit_cache.clear()
        if hasattr(blk, "_active"):
            blk._active = False

    act_stats = {}
    if calib_data is not None:
        hooked = []

        def _capture(blk, name):
            orig = blk.forward

            def fwd(x, *a, **k):
                act_stats.setdefault(name, 0.0)
                act_stats[name] = max(act_stats[name],
                                      float(jnp.max(jnp.abs(x._data))))
                return orig(x, *a, **k)

            blk.forward = fwd
            hooked.append((blk, orig))

        for name, child in _walk_blocks(net):
            if isinstance(child, _gnn.Dense):
                _capture(child, name)
        for batch in calib_data:
            net(batch)
        for blk, orig in hooked:
            blk.forward = orig

    scales = {}
    for parent, key, child, name in _walk_children(net):
        if not isinstance(child, _gnn.Dense):
            continue
        if any(s in name for s in exclude_patterns) or child.weight._nd is None:
            continue
        wq, ws = quantize_array(child.weight.data()._data, axis=0)
        bias = child.bias.data() if child.bias is not None and child.bias._nd is not None else None
        a_scale = (act_stats[name] / 127.0 + 1e-12) if name in act_stats else None
        qd = QuantizedDense(wq, ws, bias=bias,
                            activation=getattr(child, "_act", None),
                            act_scale=a_scale)
        parent._children[key] = qd
        scales[name] = np.asarray(ws)
    return net, scales


def _walk_blocks(net, prefix=""):
    for _parent, _key, child, name in _walk_children(net, prefix):
        yield name, child


def _walk_children(net, prefix=""):
    for key, child in list(getattr(net, "_children", {}).items()):
        name = f"{prefix}{key}"
        yield net, key, child, name
        yield from _walk_children(child, prefix=name + ".")


def quantize_net(net, calib_data=None, calib_mode="naive", quantized_dtype="int8",
                 exclude_patterns=("bias", "gamma", "beta", "running", "embed")):
    """Quantize a Gluon block's weight parameters in place (simulated int8:
    stored dequantized-bf16 with int8-grid values; scales returned)."""
    scales = {}
    for name, p in net.collect_params().items():
        if p._nd is None or any(s in name for s in exclude_patterns):
            continue
        if p.data().ndim < 2:
            continue
        q, scale = quantize_array(p.data()._data, axis=0)
        p._nd._data = dequantize_array(q, scale, dtype=p.data()._data.dtype)
        scales[name] = np.asarray(scale)
    return net, scales
