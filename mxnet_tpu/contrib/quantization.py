"""INT8 post-training quantization (reference:
``python/mxnet/contrib/quantization.py`` + ``src/operator/quantization/``).

The reference inserts quantize/dequantize ops and calibrates scales via
min-max or KL(entropy) over a calibration set. The TPU design keeps the same
calibration logic (it's backend-agnostic math) and applies *simulated*
quantization: int8 weights with per-channel scales, dequantized into the bf16
matmul — which is how XLA consumes int8 on TPU without custom kernels. A
Pallas native-int8 matmul is the later optimization.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["quantize_array", "dequantize_array", "calib_minmax", "calib_entropy",
           "quantize_net"]


def quantize_array(x, scale=None, axis=None):
    """f32 -> (int8, scale). Per-channel when axis is given."""
    xf = x.astype(jnp.float32)
    if scale is None:
        amax = jnp.max(jnp.abs(xf), axis=None if axis is None else tuple(
            i for i in range(x.ndim) if i != axis), keepdims=axis is not None)
        scale = amax / 127.0 + 1e-12
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_array(q, scale, dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def calib_minmax(samples):
    """Min-max calibration: scale from the absolute max over samples."""
    amax = max(float(np.abs(np.asarray(s)).max()) for s in samples)
    return amax / 127.0 + 1e-12


def calib_entropy(samples, num_bins=2048, num_quantized_bins=255):
    """KL-divergence (entropy) calibration, reference algorithm shape."""
    data = np.abs(np.concatenate([np.asarray(s).ravel() for s in samples]))
    amax = data.max() + 1e-12
    hist, edges = np.histogram(data, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    for i in range(num_quantized_bins // 2, num_bins + 1, num_bins // 64 or 1):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()  # clip outliers into last bin
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = max(1, i // num_quantized_bins)
        q = np.zeros_like(p)
        for j in range(0, i, factor):
            chunk = p[j:j + factor]
            nz = (chunk > 0).sum()
            if nz:
                q[j:j + factor] = np.where(chunk > 0, chunk.sum() / nz, 0)
        pn, qn = p / p.sum(), q / max(q.sum(), 1e-12)
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(pn[mask] / np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, t
    return best_t / 127.0


def quantize_net(net, calib_data=None, calib_mode="naive", quantized_dtype="int8",
                 exclude_patterns=("bias", "gamma", "beta", "running", "embed")):
    """Quantize a Gluon block's weight parameters in place (simulated int8:
    stored dequantized-bf16 with int8-grid values; scales returned)."""
    scales = {}
    for name, p in net.collect_params().items():
        if p._nd is None or any(s in name for s in exclude_patterns):
            continue
        if p.data().ndim < 2:
            continue
        q, scale = quantize_array(p.data()._data, axis=0)
        p._nd._data = dequantize_array(q, scale, dtype=p.data()._data.dtype)
        scales[name] = np.asarray(scale)
    return net, scales
