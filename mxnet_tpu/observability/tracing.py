"""End-to-end request tracing + SLO attainment ledger
(docs/OBSERVABILITY.md "Request tracing & SLO ledger").

One *trace* is the life of one router request, identified by the
router's request id. Each process that touches the request emits
*spans* — named ``[t0, t1]`` intervals on its own clock — into a
per-process append-only JSONL file inside the shared fleet directory
(the same transport contract as every other fleet artifact: per-replica
files under ``telemetry-h{rid}/``, router files under ``router/``,
readers skip torn lines). Traces join **by trace id at aggregation**,
never via shared memory, so the in-process drill and a real
multi-process fleet read identically.

Span vocabulary (docs/OBSERVABILITY.md has the full table):

  ``router.backlog``   waiting in the router for a replica (one per
                       residency — a redistributed request gets another)
  ``router.place``     zero-width placement marker (replica, attempt #)
  ``router.attempt``   placed on a replica until harvested / pulled back
  ``redistribution``   zero-width pull-back marker (cause, hop #)
  ``replica.queue``    waiting in the batcher's admission queue
  ``prefill``          admission dispatch -> first sampled token
  ``decode``           first token -> local finish (child ``decode.round``
                       spans per dispatch, speculation rounds labelled
                       with accept counts)

The router-level spans **telescope**: every boundary (submit, place,
pull-back, finish) closes one span and opens the next at the same
timestamp, so ``sum(router.backlog) + sum(router.attempt)`` equals the
end-to-end latency *exactly, on any clock* — including the drills' fake
clocks where a dispatch takes zero fake seconds. That is also what makes
a trace spanning a **killed** replica gap-free: the router's attempt
span covers the dead replica's residency even when that replica's own
span file never got flushed. Replica-side spans are *detail* nested
inside an attempt; they share the router's timebase only when the
processes share a clock (true in drills; in production they attribute
durations, not absolute alignment).

Tail-based sampling: the keep/drop decision happens at trace *end*,
when the outcome is known. Always kept: anomalous outcomes (deadline /
shed / cancelled / page_exhausted / cache_full, or any redistribution),
traces whose deadline margin dips below ``trace_margin_floor``, and the
slowest ``trace_slow_pct`` percentile (bounded reservoir of recent
durations). The healthy rest is sampled at ``trace_sample`` by a
**deterministic hash** of (seed, trace id) — router and replicas agree
on the healthy subset without coordinating. SLO "end" verdict records
are written for **every** terminal request regardless of the sampling
decision (one line each — the ledger must measure the population, not
the sample); sampling governs only whether the buffered spans flush.

The SLO ledger folds the end records into per-priority-class
deadline-margin distributions, an attainment fraction, and multi-window
burn rates (``burn = (1 - attainment_in_window) / (1 - slo_target)``;
burn > 1 means the class is spending error budget faster than it
accrues). ``FleetAggregator`` carries it into ``FleetReport``;
``tools/fleetreport.py`` and ``tools/tracereport.py`` render it.

Hot-path cost when tracing is off: every emission site reads one
attribute (``tracer is None``) — the same one-read gate contract as
:func:`mxnet_tpu.observability.enabled`. The emitting methods here are
registered in ``analysis/astlint.py`` ``EXTRA_HOT_PATHS`` so the lint
tier holds them to hot-path rules (no wall clock, no global RNG).
"""
from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from . import metrics as _metrics

__all__ = ["Tracer", "TailSampler", "maybe_tracer", "read_span_records",
           "collect_records", "assemble", "check_trace", "trace_phases",
           "slo_ledger", "ANOMALY_OUTCOMES", "SERVED_OUTCOMES",
           "ROUTER_LEVEL_SPANS"]

#: outcomes the tail sampler always keeps — each one is a request the
#: operator may need to explain
ANOMALY_OUTCOMES = frozenset({"deadline", "shed", "cancelled",
                              "page_exhausted", "cache_full",
                              "redistributed"})

#: outcomes that count as *served* for SLO attainment (together with a
#: non-negative deadline margin)
SERVED_OUTCOMES = frozenset({"eos", "length"})

#: outcomes excluded from the SLO denominator: the client abandoned the
#: work, the fleet did not fail it
SLO_EXEMPT_OUTCOMES = frozenset({"cancelled"})

#: the telescoping span names whose durations must sum to the
#: end-to-end latency (everything else is nested detail)
ROUTER_LEVEL_SPANS = ("router.backlog", "router.attempt")

_HASH_DENOM = float(1 << 64)


def _hash_unit(seed: int, trace_id: str) -> float:
    """Deterministic uniform-[0,1) from (seed, trace id) — stable across
    processes and runs, so every tracer in the fleet makes the same
    healthy-sampling call for the same trace."""
    h = hashlib.blake2b(f"{seed}:{trace_id}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big") / _HASH_DENOM


class TailSampler:
    """Keep/drop decision at trace end (see module docstring).

    ``decide`` returns ``(keep, reason)``; reasons are
    ``outcome:<reason>`` / ``redistributed`` / ``margin`` / ``slow`` /
    ``sampled`` / ``dropped``. The slow-percentile rule compares against
    a bounded reservoir of the last ``history`` end-to-end durations and
    stays silent until ``min_history`` of them exist (a cold reservoir
    would flag everything)."""

    def __init__(self, sample: Optional[float] = None,
                 seed: Optional[int] = None,
                 slow_pct: Optional[float] = None,
                 margin_floor: Optional[float] = None,
                 history: int = 256, min_history: int = 16):
        from .. import config

        self.sample = float(sample if sample is not None
                            else config.get("trace_sample"))
        self.seed = int(seed if seed is not None
                        else config.get("trace_seed"))
        self.slow_pct = float(slow_pct if slow_pct is not None
                              else config.get("trace_slow_pct"))
        self.margin_floor = float(margin_floor if margin_floor is not None
                                  else config.get("trace_margin_floor"))
        if not 0.0 <= self.sample <= 1.0:
            raise ValueError("trace_sample must be in [0, 1]")
        if not 0.0 < self.slow_pct <= 100.0:
            raise ValueError("trace_slow_pct must be in (0, 100]")
        self.min_history = int(min_history)
        self._recent: deque = deque(maxlen=int(history))

    def _slow_threshold(self) -> Optional[float]:
        if len(self._recent) < self.min_history:
            return None
        vals = sorted(self._recent)
        idx = max(0, -(-len(vals) * int(self.slow_pct) // 100) - 1)
        return vals[idx]

    def decide(self, trace_id: str, outcome: str,  # lint: disable=JH001,JH002 -- host floats/branches, never traced
               e2e: Optional[float] = None,
               margin: Optional[float] = None,
               redistributed: bool = False) -> Tuple[bool, str]:
        if outcome in ANOMALY_OUTCOMES:
            return True, f"outcome:{outcome}"
        if redistributed:
            return True, "redistributed"
        if (margin is not None and self.margin_floor > 0
                and margin < self.margin_floor):
            return True, "margin"
        thresh = self._slow_threshold() if e2e is not None else None
        if e2e is not None:
            self._recent.append(float(e2e))
        if thresh is not None and e2e >= thresh:
            return True, "slow"
        if self.sample >= 1.0 \
                or _hash_unit(self.seed, trace_id) < self.sample:
            return True, "sampled"
        return False, "dropped"


class Tracer:
    """Buffer spans per trace; flush (or drop) them when the trace ends
    locally. One Tracer per emitting process-role:

      - the router's (``owner=True``) writes the authoritative ``end``
        verdict record the SLO ledger folds;
      - a replica's (``owner=False``) writes ``local_end`` records —
        flush bookkeeping and debugging detail, never ledger material
        (a request touching two replicas must not count twice).

    ``capture_cb(trace_id, margin)`` fires when a finishing trace's
    deadline margin dips below the sampler's ``margin_floor`` — the
    serving replica hooks the PR 14 ``prof-request`` trigger there.

    All writes are best-effort append-JSONL (a torn final line is the
    crash signature; every reader skips it). Never raises into the
    serving loop."""

    def __init__(self, path: str, source: str,
                 sampler: Optional[TailSampler] = None,
                 clock=None, owner: bool = False, capture_cb=None):
        self.path = os.path.abspath(path)
        self.source = str(source)
        self.sampler = sampler or TailSampler()
        self.owner = bool(owner)
        self.capture_cb = capture_cb
        self._clock = clock or time.time
        self._buf: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()
        self._fh = None
        self.kept = 0
        self.dropped = 0

    # -- emission (hot path when tracing is ON) ------------------------------
    def span(self, trace_id: str, name: str, t0: float, t1: float,  # lint: disable=JH001,JH002 -- host floats/branches, never traced
             **attrs) -> None:
        rec = {"kind": "span", "trace": str(trace_id), "name": name,
               "t0": round(float(t0), 6), "t1": round(float(t1), 6),
               "src": self.source}
        if attrs:
            rec.update(attrs)
        with self._lock:
            self._buf.setdefault(rec["trace"], []).append(rec)

    def finish(self, trace_id: str, outcome: str, t0: float, t1: float,  # lint: disable=JH001,JH002 -- host floats/branches, never traced
               cls: Optional[str] = None, deadline: Optional[float] = None,
               hops: int = 0, **attrs) -> bool:
        """Close a trace locally: run the tail sampler, flush or drop the
        buffered spans, and append the verdict record (``end`` for the
        owner, ``local_end`` otherwise). Returns the keep decision."""
        tid = str(trace_id)
        e2e = max(0.0, float(t1) - float(t0))
        margin = None if deadline is None else float(deadline) - float(t1)
        keep, why = self.sampler.decide(tid, outcome, e2e=e2e,
                                        margin=margin,
                                        redistributed=hops > 0)
        rec = {"kind": "end" if self.owner else "local_end", "trace": tid,
               "outcome": outcome, "cls": cls,
               "t0": round(float(t0), 6), "t1": round(float(t1), 6),
               "e2e": round(e2e, 6),
               "deadline": None if deadline is None
               else round(float(deadline), 6),
               "margin": None if margin is None else round(margin, 6),
               "hops": int(hops), "keep": keep, "why": why,
               "src": self.source}
        if attrs:
            rec.update(attrs)
        with self._lock:
            spans = self._buf.pop(tid, [])
            if keep:
                self.kept += 1
                self._write(spans + [rec])
            else:
                self.dropped += 1
                self._write([rec])
        _metrics.REGISTRY.counter(
            "trace_traces_total",
            "locally ended traces, by tail-sampling decision").inc(
                decision="kept" if keep else "dropped")
        if (self.capture_cb is not None and margin is not None
                and self.sampler.margin_floor > 0
                and margin < self.sampler.margin_floor):
            try:
                self.capture_cb(tid, margin)
            except Exception:  # advisory: never fail the serving loop
                pass
        return keep

    def discard(self, trace_id: str) -> None:
        """Drop a trace's buffered spans without any verdict record
        (e.g. a handle the client threw away before terminal state)."""
        with self._lock:
            self._buf.pop(str(trace_id), None)

    # -- IO ------------------------------------------------------------------
    def _write(self, records: List[dict]) -> None:
        """Append records as JSONL in one write + flush (caller holds the
        lock). A crash mid-write leaves at most one torn final line —
        exactly what every fleet-dir reader already tolerates."""
        if not records:
            return
        try:
            if self._fh is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write("".join(json.dumps(r, sort_keys=True) + "\n"
                                   for r in records))
            self._fh.flush()
        except (OSError, ValueError):
            pass  # telemetry must never fail serving

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def maybe_tracer(path: str, source: str, owner: bool = False,
                 clock=None, capture_cb=None) -> Optional[Tracer]:
    """The config-gated constructor the serving tier calls: None unless
    the ``trace`` knob (``MXNET_TPU_TRACE``) is on — so a disabled fleet
    pays exactly one ``tracer is None`` read per emission site."""
    from .. import config

    if not config.get("trace"):
        return None
    return Tracer(path, source, sampler=TailSampler(), clock=clock,
                  owner=owner, capture_cb=capture_cb)


# -- reading / assembly (aggregation side, never hot) ------------------------

def read_span_records(path: str) -> List[dict]:
    """Parse one span JSONL file, skipping torn/garbage lines (the
    crash-mid-write signature) like every other fleet-dir reader."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn line: skip, keep reading
                if isinstance(rec, dict) and "trace" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


def collect_records(fleet_dir: str) -> List[dict]:
    """Every span/end record in a fleet dir: the router's
    ``router/spans-g*.jsonl`` plus each replica's
    ``telemetry-h*/spans-g*.jsonl``."""
    fleet_dir = os.path.abspath(fleet_dir)
    paths = sorted(
        glob.glob(os.path.join(fleet_dir, "router", "spans-g*.jsonl"))
        + glob.glob(os.path.join(fleet_dir, "telemetry-h*",
                                 "spans-g*.jsonl")))
    out: List[dict] = []
    for p in paths:
        out.extend(read_span_records(p))
    return out


def assemble(records: Iterable[dict]) -> Dict[str, dict]:
    """Join records by trace id:
    ``{trace: {spans, end, local_ends}}`` with spans sorted by
    ``(t0, t1)``. A trace with spans but no owner ``end`` record is an
    *orphan* — either still in flight or (the red path the drill
    injects) a span that lost its request."""
    traces: Dict[str, dict] = {}
    for rec in records:
        t = traces.setdefault(str(rec.get("trace")),
                              {"spans": [], "end": None, "local_ends": []})
        kind = rec.get("kind")
        if kind == "span":
            t["spans"].append(rec)
        elif kind == "end":
            # two owner ends for one trace id should not happen; keep
            # the later one (restarted router re-ran the request)
            if t["end"] is None or rec.get("t1", 0) >= t["end"].get("t1", 0):
                t["end"] = rec
        elif kind == "local_end":
            t["local_ends"].append(rec)
    for t in traces.values():
        t["spans"].sort(key=lambda s: (s.get("t0", 0.0), s.get("t1", 0.0)))
    return traces


def trace_phases(trace: dict) -> Dict[str, float]:
    """Total duration per span name (seconds). Router-level names are
    the telescoping partition of the end-to-end latency; the rest is
    nested detail."""
    phases: Dict[str, float] = {}
    for s in trace["spans"]:
        d = max(0.0, float(s.get("t1", 0.0)) - float(s.get("t0", 0.0)))
        phases[s["name"]] = phases.get(s["name"], 0.0) + d
    return phases


def check_trace(trace: dict, tol: float = 0.05,
                abs_tol: float = 1e-6) -> dict:
    """Reconcile one assembled trace against its ``end`` record.

    Checks (each failed check appends to ``problems``):

      - an ``end`` record exists (otherwise the trace is an orphan);
      - the router-level spans cover ``[submit, finish]`` contiguously —
        first starts at submit, each next starts where the previous
        ended, last ends at finish (gap/overlap > ``abs_tol`` flags);
      - their durations sum to the end-to-end latency within ``tol``
        (relative) — the acceptance gate's 5%.

    Returns ``{ok, problems, e2e, phase_sum, rel_err, phases, hops}``."""
    problems: List[str] = []
    end = trace.get("end")
    phases = trace_phases(trace)
    levels = [s for s in trace["spans"] if s["name"] in ROUTER_LEVEL_SPANS]
    hops = sum(1 for s in trace["spans"] if s["name"] == "redistribution")
    if end is None:
        return {"ok": False, "problems": ["orphan: no end record"],
                "e2e": None, "phase_sum": None, "rel_err": None,
                "phases": phases, "hops": hops}
    e2e = float(end.get("e2e") or 0.0)
    phase_sum = sum(max(0.0, float(s["t1"]) - float(s["t0"]))
                    for s in levels)
    if not levels:
        problems.append("no router-level spans")
    else:
        if abs(float(levels[0]["t0"]) - float(end["t0"])) > abs_tol:
            problems.append(
                f"first span starts {levels[0]['t0']} != submit {end['t0']}")
        if abs(float(levels[-1]["t1"]) - float(end["t1"])) > abs_tol:
            problems.append(
                f"last span ends {levels[-1]['t1']} != finish {end['t1']}")
        for a, b in zip(levels, levels[1:]):
            if abs(float(b["t0"]) - float(a["t1"])) > abs_tol:
                problems.append(
                    f"gap/overlap between {a['name']}@{a['t1']} and "
                    f"{b['name']}@{b['t0']}")
    rel_err = 0.0
    if e2e > abs_tol:
        rel_err = abs(phase_sum - e2e) / e2e
    elif abs(phase_sum - e2e) > abs_tol:
        rel_err = 1.0
    if rel_err > tol:
        problems.append(f"phase sum {phase_sum:.6f}s vs e2e {e2e:.6f}s "
                        f"({rel_err:.1%} > {tol:.0%})")
    if int(end.get("hops") or 0) != hops:
        problems.append(f"end record claims {end.get('hops')} hops, "
                        f"{hops} redistribution spans present")
    return {"ok": not problems, "problems": problems, "e2e": e2e,
            "phase_sum": phase_sum, "rel_err": rel_err, "phases": phases,
            "hops": hops}


# -- SLO ledger ---------------------------------------------------------------

def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, -(-len(sorted_vals) * int(q * 100) // 100) - 1))
    return sorted_vals[idx]


def parse_windows(spec: Optional[str] = None) -> List[float]:
    """``trace_slo_windows`` knob -> window seconds (bad entries
    skipped; empty spec falls back to the config default)."""
    from .. import config

    if spec is None:
        spec = config.get("trace_slo_windows")
    out: List[float] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            continue
        if w > 0:
            out.append(w)
    return out


def slo_ledger(ends: Iterable[dict], windows: Optional[List[float]] = None,
               target: Optional[float] = None,
               now: Optional[float] = None) -> dict:
    """Fold owner ``end`` records into the SLO ledger (see module
    docstring). ``now`` anchors the burn-rate windows; it defaults to
    the newest finish timestamp in the records (the aggregator is
    usually looking at a finished run, not wall-clock now).

    Per class: ``count`` (terminal requests), ``eligible`` (minus
    client cancellations), ``attained``, ``attainment``, ``margin``
    percentiles over deadline-carrying requests, ``burn`` per window,
    plus outcome and hop tallies."""
    from .. import config

    ends = [e for e in ends if e.get("kind") == "end"]
    if target is None:
        target = float(config.get("trace_slo_target"))
    if windows is None:
        windows = parse_windows()
    if now is None:
        now = max((float(e.get("t1") or 0.0) for e in ends), default=0.0)
    budget = max(1e-9, 1.0 - target)

    def attained(e) -> bool:
        m = e.get("margin")
        return (e.get("outcome") in SERVED_OUTCOMES
                and (m is None or float(m) >= 0.0))

    classes: Dict[str, List[dict]] = {}
    for e in ends:
        classes.setdefault(str(e.get("cls") or "default"), []).append(e)

    def fold(records: List[dict]) -> dict:
        eligible = [e for e in records
                    if e.get("outcome") not in SLO_EXEMPT_OUTCOMES]
        ok = sum(1 for e in eligible if attained(e))
        margins = sorted(float(e["margin"]) for e in eligible
                         if e.get("margin") is not None)
        outcomes: Dict[str, int] = {}
        for e in records:
            o = str(e.get("outcome"))
            outcomes[o] = outcomes.get(o, 0) + 1
        burn: Dict[str, Optional[float]] = {}
        for w in windows:
            inw = [e for e in eligible
                   if float(e.get("t1") or 0.0) >= now - w]
            if not inw:
                burn[f"{w:g}s"] = None
                continue
            bad = sum(1 for e in inw if not attained(e))
            burn[f"{w:g}s"] = round((bad / len(inw)) / budget, 4)
        return {
            "count": len(records), "eligible": len(eligible),
            "attained": ok,
            "attainment": round(ok / len(eligible), 4) if eligible else None,
            "margin": {"min": margins[0] if margins else None,
                       "p50": _pct(margins, 0.50),
                       "p95": _pct(margins, 0.95)},
            "redistributed": sum(1 for e in records
                                 if int(e.get("hops") or 0) > 0),
            "outcomes": outcomes, "burn": burn,
        }

    return {
        "target": target, "windows": [f"{w:g}s" for w in windows],
        "now": round(float(now), 6),
        "classes": {c: fold(rs) for c, rs in sorted(classes.items())},
        "total": fold(ends),
    } if ends else {}
