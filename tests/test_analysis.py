"""Static-analysis subsystem (ISSUES 6 + 8, docs/ANALYSIS.md): the HLO
auditor (ProgramReport parsing over both text dialects, donation coverage,
program fingerprints + recompile causes), the sharding-and-communication
layer (ShardingInfo parsing, the declared-vs-compiled contract checker,
the comm cost model + accidental-reshard detector), and the AST jit-hazard
linter (rule engine, suppressions, and the package-is-clean regression
that backs ``make lint``).
"""
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis, nd, optimizer as opt
from mxnet_tpu import observability as obs
from mxnet_tpu.analysis import astlint
from mxnet_tpu.analysis.hlo_audit import Fingerprint, fingerprint_diff
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import TrainStep

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mxnet_tpu")


# -- ProgramReport parsing ---------------------------------------------------
def _bf16_cond_program():
    def f(p, x):
        y = (p["w"].astype(jnp.bfloat16) @ x.astype(jnp.bfloat16)).astype(
            jnp.float32)
        z = jax.lax.cond(y.sum() > 0, lambda v: v + 1, lambda v: v - 1, y)
        return {"w": p["w"] - 0.1 * z.sum()}, z.sum()

    return jax.jit(f, donate_argnums=(0,)).lower(
        {"w": jnp.ones((4, 8))}, jnp.ones((8, 2)))


def test_stablehlo_report_census_dots_and_donation():
    rep = analysis.audit_lowered(_bf16_cond_program())
    assert rep.dialect == "stablehlo"
    assert rep.dot_dtypes() == {"bf16": 1}
    assert rep.count("case") == 1          # the lax.cond branch
    assert rep.has("dot_general") and not rep.has("nonexistent_op")
    assert not rep.ops_with_dtype("f64")   # no f64 promotion leak
    assert "bf16" in rep.dtype_census() and "f32" in rep.dtype_census()
    # donation: arg0 (the donated dict leaf) aliased, arg1 (batch) not
    assert rep.donation.aliased == {0: "may-alias"}
    assert rep.donation.n_inputs == 2
    assert rep.donation.coverage([0]) == 1.0
    assert rep.donation.coverage([0, 1]) == 0.5
    assert rep.donation.missing([0, 1]) == [1]
    assert rep.inputs[0] == ("f32", (4, 8))
    assert not rep.host_transfers()


def test_hlo_report_compiled_dialect_and_alias_header():
    low = _bf16_cond_program()
    rep = analysis.audit_compiled(low.compile())
    assert rep.dialect == "hlo"
    # nested-brace input_output_alias header parses (the regex trap)
    assert rep.donation.aliased == {0: "may-alias"}
    assert rep.count("fusion") >= 1 or rep.count("dot") >= 1


def test_report_collectives_replica_groups():
    """GSPMD-inserted collectives with both replica-group spellings."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=8))

    def g(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P())).sum() + x.mean()

    jg = jax.jit(g, in_shardings=NamedSharding(mesh, P("dp")),
                 out_shardings=NamedSharding(mesh, P()))
    xs = jax.device_put(jnp.ones((8, 4)), NamedSharding(mesh, P("dp")))
    rep = analysis.audit_compiled(jg.lower(xs).compile())
    counts = rep.collective_counts()
    assert counts.get("all_reduce", 0) >= 1
    for c in rep.collectives:
        assert c.groups is not None and c.group_size == 8, \
            (c.name, c.raw_groups)
    assert len(rep.replica_group_specs()) == 1


def test_stablehlo_donation_survives_sharding_attrs():
    """Arg attrs like ``mhlo.sharding = "{replicated}"`` hold a ``}``
    inside a quoted value — the lowered-dialect alias scan must not stop
    there and drop tf.aliasing_output (the compile=False audit path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=8))

    def f(p, x):
        return p + x.sum()

    lowered = jax.jit(f, donate_argnums=(0,),
                      in_shardings=(NamedSharding(mesh, P()),
                                    NamedSharding(mesh, P("dp"))),
                      out_shardings=NamedSharding(mesh, P())).lower(
        jnp.ones((4,)), jnp.ones((8, 4)))
    rep = analysis.audit_lowered(lowered)
    assert "mhlo.sharding" in lowered.as_text()  # the trap is present
    assert rep.donation.aliased == {0: "may-alias"}
    assert rep.donation.coverage([0]) == 1.0


def test_async_collective_pair_counts_once():
    """all-reduce-start/-done is ONE collective (TPU/GPU backends emit the
    async pair — with a TUPLE result type on the start op — and combined
    gradient all-reduces are variadic; the -done op carries no
    replica_groups and must not dilute the spanning check)."""
    text = textwrap.dedent("""\
        HloModule m

        ENTRY %main (p0: f32[4], p1: f32[2]) -> f32[4] {
          %p0 = f32[4]{0} parameter(0)
          %p1 = f32[2]{0} parameter(1)
          %ars = (f32[4]{0}, u32[], u32[]) all-reduce-start(f32[4]{0} %p0), replica_groups={{0,1,2,3}}, to_apply=%add
          %ard = f32[4]{0} all-reduce-done((f32[4]{0}, u32[], u32[]) %ars)
          %var = (f32[4]{0}, f32[2]{0}) all-reduce(f32[4]{0} %ard, f32[2]{0} %p1), replica_groups={{0,1,2,3}}, to_apply=%add
          %inf = ((f32[4]{0}), token[]) infeed(token[] %tok)
          ROOT %r = f32[4]{0} add(f32[4]{0} %ard, f32[4]{0} %ard)
        }
        """)
    rep = analysis.audit_text(text)
    # the start/done pair counts once; the variadic (tuple-result)
    # all-reduce is seen too
    assert rep.collective_counts() == {"all_reduce": 2}
    for ar in rep.collectives_named("all_reduce"):
        assert ar.groups == ((0, 1, 2, 3),) and ar.group_size == 4
    assert not rep.has("all_reduce_done")
    # tuple-result host transfers are not invisible to the serving gate
    assert [o.name for o in rep.host_transfers()] == ["infeed"]


def test_audit_text_synthetic_hlo_inventories():
    """Explicit-list replica groups, custom-call targets and host-transfer
    ops — exercised on synthetic HLO so every branch of the parser is
    pinned without needing a TPU-only lowering."""
    text = textwrap.dedent("""\
        HloModule m, input_output_alias={ {0}: (1, {}, must-alias) }

        ENTRY %main (p0: f32[4], p1: f32[4]) -> f32[4] {
          %p0 = f32[4]{0} parameter(0)
          %p1 = f32[4]{0} parameter(1)
          %ar = f32[4]{0} all-reduce(f32[4]{0} %p0), replica_groups={{0,1},{2,3}}, to_apply=%add
          %cc = f32[4]{0} custom-call(f32[4]{0} %ar), custom_call_target="my_kernel"
          %of = token[] outfeed(f32[4]{0} %cc)
          ROOT %r = f32[4]{0} add(f32[4]{0} %cc, f32[4]{0} %p1)
        }
        """)
    rep = analysis.audit_text(text)
    assert rep.dialect == "hlo"
    assert rep.donation.aliased == {1: "must-alias"}
    (ar,) = rep.collectives_named("all-reduce")
    assert ar.groups == ((0, 1), (2, 3)) and ar.group_size == 2
    assert rep.custom_calls == ["my_kernel"]
    assert [o.name for o in rep.host_transfers()] == ["outfeed"]
    assert rep.has_tensor((4,), dtype="f32")
    assert not rep.has_tensor((5,))


# -- fingerprints & recompile causes -----------------------------------------
def test_fingerprint_diff_distinct_causes():
    """ISSUE 6 satellite: shape-change vs dtype-change vs static-arg-change
    each produce a DISTINCT cause, with a detail naming the change."""
    base = Fingerprint.of([jnp.ones((2, 3)), jnp.ones((2, 4))], lr=0.1)
    shape = Fingerprint.of([jnp.ones((6, 3)), jnp.ones((2, 4))], lr=0.1)
    dtype = Fingerprint.of([jnp.ones((2, 3), jnp.bfloat16),
                            jnp.ones((2, 4))], lr=0.1)
    static = Fingerprint.of([jnp.ones((2, 3)), jnp.ones((2, 4))], lr=0.5)
    arity = Fingerprint.of([jnp.ones((2, 3))], lr=0.1)

    assert fingerprint_diff(base, shape) == ("shape", "arg0: [2, 3] -> [6, 3]")
    cause, detail = fingerprint_diff(base, dtype)
    assert cause == "dtype" and "float32 -> bfloat16" in detail
    cause, detail = fingerprint_diff(base, static)
    assert cause == "static" and "lr" in detail
    assert fingerprint_diff(base, arity)[0] == "arity"
    assert fingerprint_diff(base, base) == ("identical", "")


def test_recompile_guard_counts_and_explains(tmp_path):
    obs.enable(str(tmp_path))
    try:
        guard = analysis.RecompileGuard(
            "analysis_test_recompiles_total",
            label_map={"static": "hyperparams"})
        f1 = Fingerprint.of([jnp.ones((2, 3))], k=1)
        f2 = Fingerprint.of([jnp.ones((6, 3))], k=1)
        f3 = Fingerprint.of([jnp.ones((6, 3))], k=2)
        assert guard.observe(f1) == "first"
        assert guard.observe(f1) is None          # seen: no double count
        assert guard.observe(f2) == "shape"
        assert guard.observe(f3) == "hyperparams"  # label_map applied
        assert guard.observe(f1, reason="forced") is None  # f1 already seen
        assert len(guard) == 3
        c = obs.REGISTRY.get("analysis_test_recompiles_total")
        assert c.value(reason="first") == 1
        assert c.value(reason="shape") == 1
        assert c.value(reason="hyperparams") == 1
        obs.shutdown()
        recs = [e for e in obs.read_events(str(tmp_path))
                if e["event"] == "recompile"]
        assert len(recs) == 3
        shape_ev = next(e for e in recs if e["reason"] == "shape")
        assert shape_ev["cause"] == "shape"
        assert "arg0" in shape_ev["detail"]        # explained, not counted
        assert shape_ev["shapes"] == [[6, 3]]
    finally:
        obs.disable()
        obs.REGISTRY.reset("analysis_test_recompiles_total")


def test_recompile_guard_groups_diff_separately(tmp_path):
    """Program families never cross-diff: the first step program after a
    window run is cause 'first', NOT a phantom shape change vs the
    window's stacked-batch fingerprint."""
    obs.enable(str(tmp_path))
    try:
        guard = analysis.RecompileGuard("analysis_test_group_recompiles")
        window_fp = Fingerprint.of([jnp.ones((4, 8, 16))], key="w")
        step_fp = Fingerprint.of([jnp.ones((8, 16))], key="s")
        assert guard.observe(window_fp, reason="window",
                             group="window") == "window"
        assert guard.observe(step_fp, group="step") == "first"
        assert len(guard) == 2
        # within a family the diff still explains
        step2 = Fingerprint.of([jnp.ones((2, 16))], key="s")
        assert guard.observe(step2, group="step") == "shape"
    finally:
        obs.disable()
        obs.REGISTRY.reset("analysis_test_group_recompiles")


def test_train_step_recompile_causes_shape_dtype_hyperparams(tmp_path):
    """The live TrainStep path: a batch-shape change, a label-dtype change
    and an lr-multiplier edit each land in the event log with their own
    cause (acceptance: the shape recompile is *logged* with cause
    "shape")."""
    obs.enable(str(tmp_path))
    try:
        mx.random.seed(0)
        net = nn.Dense(4, in_units=3)
        net.initialize()
        _ = net(nd.ones((2, 3)))
        sgd = opt.SGD(learning_rate=0.1)
        ts = TrainStep(net, lambda out, y: ((out - y) ** 2).mean(), sgd)
        rc = obs.counter("train_recompiles_total")
        base = {k: rc.value(reason=k)
                for k in ("first", "shape", "dtype", "hyperparams")}
        ts(nd.ones((2, 3)), nd.ones((2, 4)))                  # first
        ts(nd.ones((6, 3)), nd.ones((6, 4)))                  # shape
        ts(nd.ones((6, 3)), nd.ones((6, 4), dtype="int32"))   # dtype
        w = net.weight.name
        sgd.set_lr_mult({w: 0.5})
        ts(nd.ones((6, 3)), nd.ones((6, 4), dtype="int32"))   # hyperparams
        assert rc.value(reason="first") == base["first"] + 1
        assert rc.value(reason="shape") == base["shape"] + 1
        assert rc.value(reason="dtype") == base["dtype"] + 1
        assert rc.value(reason="hyperparams") == base["hyperparams"] + 1
        obs.shutdown()
        recs = [e for e in obs.read_events(str(tmp_path))
                if e["event"] == "recompile"]
        by_reason = {e["reason"]: e for e in recs}
        assert by_reason["shape"]["cause"] == "shape"
        assert "[2, 3] -> [6, 3]" in by_reason["shape"]["detail"]
        assert "float32 -> int32" in by_reason["dtype"]["detail"]
    finally:
        obs.disable()


# -- audit(): donation coverage ----------------------------------------------
def _tiny_mlp_step(amp=None, optimizer=None):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.ones((4, 6))
    _ = net(x)
    ts = TrainStep(net, lambda out, *l: ((out - l[0]) ** 2).mean(),
                   optimizer or opt.Adam(learning_rate=1e-3), amp=amp)
    return ts, (x, nd.zeros((4, 4)))


def test_train_step_audit_step_carry_fully_donated():
    ts, batch = _tiny_mlp_step(amp="bfloat16")
    audit = ts.audit(*batch)
    # 4 params + 8 adam slots ride the donated carry
    assert len(audit.carry_indices) == 12
    assert audit.carry_donation() == 1.0, audit.carry_missing()
    # acceptance: zero f64 ops in the compiled bf16 program's lowering
    assert not audit.lowered.ops_with_dtype("f64")
    assert audit.lowered.dot_dtypes().get("bf16", 0) >= 2
    assert audit.summary()["carry"]["donation_coverage"] == 1.0


def test_train_step_audit_window_carry_fully_donated():
    """ISSUE 6 satellite: 100% donation coverage for the k-step window
    carry (params + opt state through the lax.scan program)."""
    ts, batch = _tiny_mlp_step()
    audit = ts.audit(*batch, window=3)
    assert audit.lowered.count("while") >= 1   # the scan compiled in
    assert audit.carry_donation() == 1.0, audit.carry_missing()


@pytest.mark.slow
def test_generation_engine_audit_cache_carry_fully_donated():
    """ISSUE 6 satellite: 100% donation coverage for the decode-engine
    KV-cache carry (and the prefill program's cache donation)."""
    from mxnet_tpu.inference import GenerationEngine
    from mxnet_tpu.models import gpt2

    mx.random.seed(0)
    net = gpt2.get_gpt2("gpt2_tiny", dropout=0.0, num_layers=2, units=32,
                        num_heads=2, max_length=64, vocab_size=64)
    net.initialize()
    _ = net(nd.array(np.zeros((1, 4), np.int32)))
    eng = GenerationEngine(net, batch_size=2, max_length=64,
                           prefill_buckets=(8, 16))
    audit = eng.audit()
    assert len(audit.carry_indices) == 4       # 2 layers x (k_buf, v_buf)
    assert audit.carry_donation() == 1.0, audit.carry_missing()
    assert eng.audit(bucket=8).carry_donation() == 1.0


def test_audit_does_not_consume_training_rng():
    """lower()/audit() must not draw from the live key stream — an audit
    mid-run would otherwise perturb every later step's dropout keys and
    break fixed-seed reproducibility."""
    from mxnet_tpu import random as mxrandom

    ts, batch = _tiny_mlp_step()
    mx.random.seed(42)
    ref = np.asarray(jax.random.key_data(mxrandom.next_key()))
    mx.random.seed(42)
    ts.audit(*batch, compile=False)
    ts.audit(*batch, window=2, compile=False)
    got = np.asarray(jax.random.key_data(mxrandom.next_key()))
    assert (ref == got).all(), "audit() advanced the global key stream"


# -- sharding annotations (ISSUE 8) ------------------------------------------
def test_parse_sharding_spellings():
    """Every GSPMD annotation form normalizes into ShardingInfo — both the
    compiled ``sharding={...}`` body and the lowered quoted-attr value."""
    p = analysis.parse_sharding
    assert p("{replicated}").is_replicated
    assert p('"{replicated}"').kind == "replicated"   # lowered quoting
    s = p("{devices=[4,1]<=[4]}")
    assert s.kind == "tiled" and s.tile_dims == (4, 1)
    assert not s.is_replicated
    assert s.describe() == "sharded devices=[4, 1]"
    # subgroup replication: the trailing tile dim partitions nothing
    s = p("{devices=[4,1,2]<=[2,4]T(1,0) last_tile_dim_replicate}")
    assert s.tile_dims == (4, 1) and s.replicate_last
    assert p("{maximal device=0}").is_replicated     # one device holds all
    assert p("{manual}").kind == "manual"
    assert p("{devices=[1,1]<=[1]}").is_replicated   # all-ones tiling
    # tuple shardings (per-element layouts) are not a single-tensor form
    t = p("{{replicated}, {devices=[2]<=[2]}}")
    assert t.kind == "unknown" and t.raw


def test_hlo_parameter_shardings_parsed():
    """Compiled-dialect parameter shardings land in arg_shardings, with
    the balanced-brace scan surviving nested/annotated bodies."""
    text = textwrap.dedent("""\
        HloModule m

        ENTRY %main (p0: f32[8,8], p1: f32[4], p2: f32[2,2]) -> f32[8,8] {
          %p0 = f32[8,8]{1,0} parameter(0), sharding={devices=[4,1]<=[8] last_tile_dim_replicate}
          %p1 = f32[4]{0} parameter(1), sharding={replicated}
          %p2 = f32[2,2]{1,0} parameter(2)
          ROOT %r = f32[8,8]{1,0} add(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
        }
        """)
    rep = analysis.audit_text(text)
    assert rep.arg_sharding(0).tile_dims == (4,)
    assert rep.arg_sharding(1).is_replicated
    assert rep.arg_sharding(2) is None       # unannotated -> None
    assert rep.sharded_inputs() == [0]
    assert rep.summary()["sharded_inputs"] == 1


def test_stablehlo_arg_and_op_shardings_parsed():
    """Lowered-dialect mhlo.sharding attrs: per-arg annotations on a live
    mesh lowering parse into arg_shardings (and per-op attrs onto Op)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=8))

    def f(p, x):
        return p * x.sum()

    lowered = jax.jit(
        f, in_shardings=(NamedSharding(mesh, P()),
                         NamedSharding(mesh, P("dp"))),
        out_shardings=NamedSharding(mesh, P())).lower(
            jnp.ones((4,)), jnp.ones((8, 4)))
    rep = analysis.audit_lowered(lowered)
    assert "mhlo.sharding" in lowered.as_text()
    assert rep.arg_sharding(0) is not None
    assert rep.arg_sharding(0).is_replicated
    assert rep.arg_sharding(1) is not None
    assert not rep.arg_sharding(1).is_replicated
    assert rep.arg_sharding(1).tile_dims[0] == 8
    assert rep.sharded_inputs() == [1]


def test_replica_groups_transposed_iota():
    """The V2 iota form GSPMD emits for a NON-trailing mesh axis:
    ``[4,2]<=[2,4]T(1,0)`` groups device ids column-major — the dp-axis
    groups of a dp=2 x fsdp=4 mesh, not 4 consecutive pairs."""
    from mxnet_tpu.analysis.hlo_audit import _parse_groups

    assert _parse_groups("[4,2]<=[2,4]T(1,0)") == \
        ((0, 4), (1, 5), (2, 6), (3, 7))
    assert _parse_groups("[2,4]<=[8]") == ((0, 1, 2, 3), (4, 5, 6, 7))
    # malformed forms stay unparsed (raw preserved), never throw
    assert _parse_groups("[2,4]<=[9]") is None
    assert _parse_groups("[2,2,2]<=[8]") is None


# -- communication cost model (ISSUE 8) ---------------------------------------
_COMM_HLO = textwrap.dedent("""\
    HloModule m

    ENTRY %main (p0: f32[100], p1: f32[2,8], p2: f32[4,8]) -> f32[100] {
      %p0 = f32[100]{0} parameter(0)
      %p1 = f32[2,8]{1,0} parameter(1)
      %p2 = f32[4,8]{1,0} parameter(2)
      %ar = f32[100]{0} all-reduce(f32[100]{0} %p0), replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add
      %ag = f32[8,8]{1,0} all-gather(f32[2,8]{1,0} %p1), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
      %rs = f32[1,8]{1,0} reduce-scatter(f32[4,8]{1,0} %p2), replica_groups={{0,1,2,3}}, to_apply=%add
      ROOT %r = f32[100]{0} add(f32[100]{0} %ar, f32[100]{0} %ar)
    }
    """)


def test_comm_report_prices_collectives():
    """The documented cost convention: all-reduce 2x tensor bytes,
    all-gather shard x group span, reduce-scatter 1x the input."""
    rep = analysis.audit_text(_COMM_HLO)
    comm = analysis.comm_report(rep)          # no mesh: all axes "?"
    by = {c.kind: c for c in comm.costs}
    assert by["all_reduce"].payload_bytes == 400      # 100 x f32
    assert by["all_reduce"].bytes == 800              # 2x factor
    # (2,8) shard x span 4 == the full (8,8) gathered tensor
    assert by["all_gather"].payload_bytes == 256
    assert by["all_gather"].bytes == 256
    assert by["reduce_scatter"].bytes == 128          # the (4,8) input
    assert by["reduce_scatter"].payload_bytes == 128
    assert comm.total_bytes() == 800 + 256 + 128
    assert comm.by_axis() == {"?": comm.total_bytes()}
    assert comm.by_kind()["all_reduce"] == 800
    assert comm.kind_counts() == {"all_reduce": 1, "all_gather": 1,
                                  "reduce_scatter": 1}
    assert bool(comm)
    assert comm.summary()["n_collectives"] == 3


def test_stablehlo_collective_payload_ignores_group_table():
    """The lowered dialect's ``replica_groups = dense<..> : tensor<NxMxi64>``
    attribute carries its own tensor type — payload sizing must price the
    operands, never the group table; the region form (types on the closing
    line) prices 0 rather than garbage."""
    text = textwrap.dedent("""\
        module @m {
          func.func public @main(%arg0: tensor<2x8xf32>) -> tensor<8x8xf32> {
            %0 = "stablehlo.all_gather"(%arg0) {all_gather_dim = 0 : i64, replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>} : (tensor<2x8xf32>) -> tensor<8x8xf32>
            %1 = "stablehlo.all_reduce"(%0) <{channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>, replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, use_global_device_ids}> ({
            ^bb0(%a: tensor<f32>, %b: tensor<f32>):
              "stablehlo.return"(%a) : (tensor<f32>) -> ()
            }) : (tensor<8x8xf32>) -> tensor<8x8xf32>
            return %1 : tensor<8x8xf32>
          }
        }
        """)
    rep = analysis.audit_text(text)
    ag, ar = rep.collectives
    assert ag.name == "all_gather" and ag.group_size == 4
    assert ag.operand_info == (("f32", (2, 8)),)
    assert "i64" not in ag.dtypes                 # the table is not a tensor
    comm = analysis.comm_report(rep)
    by = {c.kind: c for c in comm.costs}
    assert by["all_gather"].payload_bytes == 256  # (2,8) f32 shard x 4
    # region form: groups still parse, payload best-effort 0 — NOT the
    # 32-byte i64 table priced as an all-reduce
    assert ar.groups == ((0, 1, 2, 3, 4, 5, 6, 7),)
    assert by["all_reduce"].payload_bytes == 0


def test_comm_report_axis_attribution():
    """Replica groups resolve onto mesh axes: groups whose device
    coordinates vary along dp land under "dp", groups varying along fsdp
    under "fsdp" — so per-axis byte budgets are structural."""
    from mxnet_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, fsdp=4))
    text = textwrap.dedent("""\
        HloModule m

        ENTRY %main (p0: f32[16], p1: f32[16]) -> f32[16] {
          %p0 = f32[16]{0} parameter(0)
          %p1 = f32[16]{0} parameter(1)
          %a = f32[16]{0} all-reduce(f32[16]{0} %p0), replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%add
          %b = f32[16]{0} all-reduce(f32[16]{0} %p1), replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add
          ROOT %r = f32[16]{0} add(f32[16]{0} %a, f32[16]{0} %b)
        }
        """)
    comm = analysis.comm_report(analysis.audit_text(text), mesh)
    assert [c.axes for c in comm.costs] == [("dp",), ("fsdp",)]
    assert comm.by_axis() == {"dp": 128, "fsdp": 128}   # 2 x 64 bytes each


def test_accidental_reshard_detector():
    """An all-gather whose full result matches a declared-sharded tensor's
    global shape is flagged — unless it is an intended compute gather."""
    from jax.sharding import PartitionSpec as P

    rep = analysis.audit_text(_COMM_HLO)
    declared = {"w": P("fsdp", None), "b": P(None)}
    shapes = {"w": (8, 8), "b": (100,)}
    flagged = analysis.detect_accidental_reshards(rep, declared, shapes)
    assert len(flagged) == 1 and flagged[0].param == "w"
    assert "fully materializes" in str(flagged[0])
    assert flagged[0].bytes == 256
    # the intended ZeRO compute gathers are exempt
    assert analysis.detect_accidental_reshards(
        rep, declared, shapes, intended={"w"}) == []
    # a replicated declaration is never a reshard (nothing to preserve)
    assert analysis.detect_accidental_reshards(
        rep, {"b": P(None)}, {"b": (8, 8)}) == []
    # shape shared between an intended and a non-intended tensor is
    # ambiguous: skipped, so the intended gather never flags its twin
    twin = {"w": P("fsdp", None), "w2": P("tp", None)}
    tshapes = {"w": (8, 8), "w2": (8, 8)}
    assert analysis.detect_accidental_reshards(
        rep, twin, tshapes, intended={"w"}) == []
    # with a mesh the gather's OPERAND must be the declared shard shape:
    # P('fsdp', None) on fsdp=4 shards (8,8) into (2,8) — matches the
    # program's gather, still flagged...
    from mxnet_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, fsdp=4))
    hit = analysis.detect_accidental_reshards(
        rep, declared, shapes, mesh=mesh)
    assert [r.param for r in hit] == ["w"]
    # ...but a declaration whose shard shape is (4,8) does NOT own this
    # gather (a same-result-shape coincidence, e.g. an activation)
    assert analysis.detect_accidental_reshards(
        rep, {"w": P("dp", None)}, shapes, mesh=mesh) == []


# -- sharding contract checker (ISSUE 8) --------------------------------------
def test_expected_tiles():
    from jax.sharding import PartitionSpec as P

    shape = {"dp": 2, "fsdp": 4, "tp": 1}
    assert analysis.expected_tiles(P("fsdp", None), 2, shape) == (4, 1)
    assert analysis.expected_tiles(P(None, ("dp", "fsdp")), 2, shape) == \
        (1, 8)
    # spec shorter than rank pads with 1s; size-1 axes partition nothing
    assert analysis.expected_tiles(P("tp"), 3, shape) == (1, 1, 1)
    # an axis the mesh does not have: un-realizable intent
    assert analysis.expected_tiles(P("ghost"), 1, shape) is None


def test_check_contract_synthetic():
    """Declared-vs-compiled diffs over a synthetic compiled program: a
    matching tiled layout passes, a replicated-where-declared-sharded
    param is reported in the ``declared → compiled`` rendering."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, fsdp=4))
    text = textwrap.dedent("""\
        HloModule m

        ENTRY %main (p0: f32[8,8], p1: f32[4]) -> f32[8,8] {
          %p0 = f32[8,8]{1,0} parameter(0), sharding={devices=[4,1,2]<=[2,4]T(1,0) last_tile_dim_replicate}
          %p1 = f32[4]{0} parameter(1)
          ROOT %r = f32[8,8]{1,0} add(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
        }
        """)
    rep = analysis.audit_text(text)
    shapes = {"w": (8, 8), "b": (4,)}
    order = {"w": 0, "b": 1}
    # intent matches the compiled layout: no violations
    ok = analysis.check_contract(
        rep, {"w": P("fsdp", None), "b": P(None)}, shapes, order, mesh)
    assert ok == []
    # w declared on dp (2 shards) but compiled with 4; b fine
    vs = analysis.check_contract(
        rep, {"w": P("dp", None), "b": P(None)}, shapes, order, mesh)
    assert len(vs) == 1
    assert str(vs[0]) == \
        "w: declared P('dp', None) → compiled sharded devices=[4, 1]"
    # b declared sharded but compiled without any annotation (replicated)
    vs = analysis.check_contract(
        rep, {"b": P("fsdp")}, shapes, {"b": 1}, mesh)
    assert str(vs[0]) == "b: declared P('fsdp') → compiled replicated"
    # declaring a size-1 axis legitimately compiles replicated: no report
    assert analysis.check_contract(
        rep, {"b": P("tp")}, shapes, {"b": 1}, mesh) == []
    # an axis the mesh lacks is ALWAYS a violation, even vs replicated
    vs = analysis.check_contract(
        rep, {"b": P("ghost")}, shapes, {"b": 1}, mesh)
    assert len(vs) == 1 and "P('ghost')" in vs[0].declared


def test_train_step_audit_fsdp_contract_and_comm():
    """ISSUE 8 acceptance: on a 4-device fsdp mesh the audit reports ZERO
    sharding-contract violations, a non-empty CommReport with the ZeRO
    traffic attributed to mesh axes, and no accidental reshards."""
    from mxnet_tpu.parallel import MeshConfig, ShardingRules, make_mesh

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    x = nd.ones((8, 16))
    _ = net(x)
    mesh = make_mesh(MeshConfig(fsdp=4))
    rules = ShardingRules(fsdp_axis="fsdp", min_fsdp_size=1)
    ts = TrainStep(net, lambda out, *l: ((out - l[0]) ** 2).mean(),
                   opt.Adam(learning_rate=1e-3), mesh=mesh, rules=rules)
    audit = ts.audit(x, nd.zeros((8, 8)))
    assert audit.contract == [], [str(v) for v in audit.contract]
    comm = audit.comm
    assert comm is not None and bool(comm), "empty CommReport on a mesh"
    assert comm.reshards == [], [str(r) for r in comm.reshards]
    # the ZeRO pattern: compute all-gathers + grad reductions, every
    # priced byte attributed to a real mesh axis (nothing under "?")
    assert comm.kind_counts().get("all_gather", 0) >= 1
    assert comm.kind_counts().get("all_reduce", 0) >= 1
    assert "fsdp" in comm.by_axis() and "?" not in comm.by_axis()
    assert audit.summary()["comm"]["total_bytes"] == comm.total_bytes()
    assert audit.summary()["contract"] == []


def test_train_step_audit_catches_misspecced_rule():
    """ISSUE 8 acceptance: a deliberately mis-specced rule (typo'd axis
    name — spec_for silently falls back to replicated) is caught with the
    ``declared → compiled`` diff."""
    from mxnet_tpu.parallel import MeshConfig, ShardingRules, make_mesh

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    x = nd.ones((8, 16))
    _ = net(x)
    mesh = make_mesh(MeshConfig(fsdp=4))
    bad = ShardingRules(rules=[("weight", ("fsdq", None))])   # typo'd axis
    ts = TrainStep(net, lambda out, *l: ((out - l[0]) ** 2).mean(),
                   opt.Adam(learning_rate=1e-3), mesh=mesh, rules=bad)
    audit = ts.audit(x, nd.zeros((8, 8)))
    msgs = [str(v) for v in audit.contract]
    assert len(msgs) == 2, msgs                    # both dense weights
    for m in msgs:
        assert "declared P('fsdq', None) → compiled replicated" in m
    # the rules= override audits an alternative declaration against the
    # SAME compiled program (what shardcheck uses for what-if checks)
    good = ShardingRules(fsdp_axis="fsdp", min_fsdp_size=1)
    ts2 = TrainStep(net, lambda out, *l: ((out - l[0]) ** 2).mean(),
                    opt.Adam(learning_rate=1e-3), mesh=mesh, rules=good)
    vs = ts2.audit(x, nd.zeros((8, 8)), rules=bad).contract
    # every param diffs: the weights' typo'd intent vs the compiled fsdp
    # layout, and the biases' implied-replicated intent vs their compiled
    # fsdp-fallback sharding
    weight_vs = [v for v in vs if "weight" in v.param]
    assert weight_vs and all(
        "declared P('fsdq', None) → compiled sharded" in str(v)
        for v in weight_vs)


# -- astlint: rules ----------------------------------------------------------
HOT_SRC = textwrap.dedent("""\
    import time
    import numpy as np
    import jax

    def make_step():
        def step(params, batch):
            if params > 0:                    # JH002
                pass
            x = float(batch)                  # JH001
            v = np.asarray(batch)             # JH001
            y = batch.item()                  # JH001
            t = time.time()                   # JH003
            return params
        fn = step
        return jax.jit(fn, donate_argnums=(0,))
    """)


def _rules(violations):
    return sorted(v.rule for v in violations)


def test_lint_hot_path_rules_fire_through_alias():
    vs = astlint.lint_source(HOT_SRC, "mxnet_tpu/x.py")
    assert _rules(vs) == ["JH001", "JH001", "JH001", "JH002", "JH003"]
    lines = {v.rule + ":" + str(v.line) for v in vs}
    assert "JH002:7" in lines and "JH003:12" in lines


def test_lint_structural_idioms_not_flagged():
    """`x is None` and `name in container` are static under tracing; casts
    of static op params are trace-time specialization — none may fire."""
    src = textwrap.dedent("""\
        import jax

        def make(topk):
            def step(params, state):
                if params is not None:        # structural: ok
                    pass
                for name in state:
                    if name not in state:     # structural: ok
                        pass
                k = int(topk)                 # static param: ok
                return params
            return jax.jit(step)
        """)
    assert astlint.lint_source(src, "mxnet_tpu/x.py") == []


def test_lint_decorated_and_method_hot_paths():
    src = textwrap.dedent("""\
        import numpy as np
        import jax

        @jax.jit
        def decorated(x):
            return np.asarray(x)              # JH001

        class Engine:
            def __init__(self):
                self._fn = jax.jit(self._decode)

            def _decode(self, x):
                return x.item()               # JH001 (method via self.)
        """)
    assert _rules(astlint.lint_source(src, "m.py")) == ["JH001", "JH001"]


def test_lint_mutable_defaults_and_global_mutation():
    src = textwrap.dedent("""\
        import threading

        _REG = {}
        _lock = threading.Lock()

        def bad(x=[], y={}):                  # JH004 x2
            return x

        def put(k, v):
            _REG[k] = v                       # JH005

        def put_locked(k, v):
            with _lock:
                _REG[k] = v                   # ok

        def rhs_mutation(site):
            h = _REG.setdefault(site, [])     # JH005: mutates via RHS
            return h

        def aug(k):
            _REG[k] += 1                      # JH005: read-modify-write

        def local_only(k, v):
            reg = {}
            reg[k] = v                        # ok: not module-global
            return reg

        def deferred(k, v):
            with _lock:
                def cb():
                    _REG[k] = v               # JH005: cb runs later,
                return cb                     # NOT under the lock
        """)
    assert _rules(astlint.lint_source(src, "m.py")) == \
        ["JH004", "JH004", "JH005", "JH005", "JH005", "JH005"]


def test_lint_nondeterminism_in_op_modules():
    src = textwrap.dedent("""\
        import numpy as np

        def my_op(x):
            noise = np.random.normal(size=x.shape)     # JH003
            rs = np.random.RandomState(0)              # ok: explicit seed
            return x + noise + rs.normal(size=x.shape)
        """)
    vs = astlint.lint_source(src, "mxnet_tpu/ops/myop.py")
    assert _rules(vs) == ["JH003"]
    # same source outside op scope and outside hot paths: clean
    assert astlint.lint_source(src, "mxnet_tpu/io/loader.py") == []


def test_lint_suppressions_inline_above_def_and_file():
    src = textwrap.dedent("""\
        import numpy as np
        import jax

        def make():
            def step(p):
                a = np.asarray(p)  # lint: disable=JH001
                # lint: disable=JH001
                b = np.asarray(p)
                c = np.asarray(p)               # still flagged
                return a, b, c
            return jax.jit(step)

        def make2():
            def step2(p):  # lint: disable=all
                return np.asarray(p)
            return jax.jit(step2)
        """)
    vs = astlint.lint_source(src, "m.py")
    assert len(vs) == 1 and vs[0].line == 9
    assert astlint.lint_source(
        "# lint: disable-file=JH004\ndef f(x=[]):\n    return x\n",
        "m.py") == []


def test_lint_suppression_in_string_literal_is_inert():
    """A docstring that merely QUOTES the suppression syntax (as the rule
    catalog and astlint's own module docstring do) must not activate it —
    only real comment tokens count."""
    src = textwrap.dedent('''\
        """Docs quoting the syntax: # lint: disable-file=JH004"""

        def f(x=[]):
            return x
        ''')
    assert _rules(astlint.lint_source(src, "m.py")) == ["JH004"]


def test_lint_registered_extra_hot_paths():
    """EXTRA_HOT_PATHS reaches helpers called from jitted closures — the
    registered TrainStep._loss_of is hot even with no jit call in sight."""
    src = textwrap.dedent("""\
        class TrainStep:
            def _loss_of(self, params, batch, key):
                return float(batch)           # JH001 via registration
        """)
    vs = astlint.lint_source(src, "mxnet_tpu/parallel/train_step.py")
    assert _rules(vs) == ["JH001"]
    assert astlint.lint_source(src, "mxnet_tpu/parallel/other.py") == []


def test_lint_unknown_mesh_axis_jh006():
    """ISSUE 8 satellite: axis-name literals outside the MeshConfig
    vocabulary at PartitionSpec/named_sharding call sites — a typo'd axis
    silently replicates the tensor."""
    src = textwrap.dedent("""\
        from jax.sharding import PartitionSpec as P

        def specs(mesh):
            a = P("fsdq", None)               # JH006: typo'd axis
            b = P("dp", "fsdp")               # ok
            c = P(("dp", "fsdpp"))            # JH006: inside a tuple entry
            d = named_sharding(mesh, "tpp")   # JH006 (mesh arg skipped)
            e = PartitionSpec(None, "ep")     # ok
            f = P(axis)                       # ok: not a literal
            return a, b, c, d, e, f
        """)
    vs = astlint.lint_source(src, "mxnet_tpu/x.py")
    assert _rules(vs) == ["JH006", "JH006", "JH006"]
    assert sorted(v.line for v in vs) == [4, 6, 7]
    assert "fsdq" in [v for v in vs if v.line == 4][0].message
    # inline-suppressible like JH001-JH005
    sup = 'P("fsdq")  # lint: disable=JH006\n'
    assert astlint.lint_source(sup, "mxnet_tpu/x.py") == []
    # the vocabulary pins to parallel.layout.AXES (the declarative spec
    # owns it; parallel.mesh re-exports) — update both together
    from mxnet_tpu.parallel.layout import AXES

    assert astlint._MESH_AXES == frozenset(AXES)


def test_lint_traced_constant_capture_jh007():
    """ISSUE 12 satellite: a jitted/scanned closure reading a name bound
    to a host np.ndarray (module global or enclosing-function local) —
    the trace bakes it into the program as a constant. Shadowing and
    inline suppression are respected."""
    src = textwrap.dedent("""\
        import jax
        import numpy as np

        TABLE = np.arange(1000).reshape(10, 100)

        def build():
            scale = np.ones((64,))
            def step(x):
                return x @ TABLE + scale      # JH007 x2
            return jax.jit(step)

        def cold(x):
            return x @ TABLE                  # ok: not a hot path

        def shadowed():
            def step(x, TABLE):
                return x @ TABLE              # ok: parameter shadows
            return jax.jit(step)
        """)
    vs = astlint.lint_source(src, "mxnet_tpu/x.py")
    assert _rules(vs) == ["JH007", "JH007"]
    assert {"TABLE", "scale"} == {v.message.split("'")[1] for v in vs}
    sup = src.replace("return x @ TABLE + scale",
                      "return x @ TABLE + scale  # lint: disable=JH007")
    assert astlint.lint_source(sup, "mxnet_tpu/x.py") == []
    # jnp arrays are device values, not baked host constants
    ok = textwrap.dedent("""\
        import jax
        import jax.numpy as jnp

        TABLE = jnp.arange(1000)

        def f(x):
            return x + TABLE
        g = jax.jit(f)
        """)
    assert astlint.lint_source(ok, "mxnet_tpu/x.py") == []
    # the build-then-transfer idiom: a later rebinding to a non-host
    # expression clears the hazard (module level AND function level)
    rebound = textwrap.dedent("""\
        import jax
        import jax.numpy as jnp
        import numpy as np

        X = np.arange(100000)
        X = jnp.asarray(X)

        def build():
            y = np.ones((64,))
            y = jnp.asarray(y)
            def step(v):
                return v + X + y
            return jax.jit(step)
        """)
    assert astlint.lint_source(rebound, "mxnet_tpu/x.py") == []


def test_lint_sync_per_dispatch_jh008():
    """ISSUE 13 satellite: a driver loop that dispatches a compiled
    callable and immediately materializes its result blocks the host
    every step — async dispatch pipelining is gone. Recognized compiled
    callees: jax.jit(...) assignment targets (name or attribute) and the
    *_jit naming convention; materializers: block_until_ready/.item()/
    float()/np.asarray/device_get. Deferred materialization after the
    loop is the fix and stays clean; inline suppression is honored."""
    src = textwrap.dedent("""\
        import jax
        import numpy as np

        step = jax.jit(lambda x: x + 1)

        def drive(xs):
            out = []
            for x in xs:
                y = step(x)
                out.append(float(y))           # JH008
            return out

        def drive_direct(xs):
            while xs:
                step(xs.pop()).block_until_ready()   # JH008
            return 1

        class Engine:
            def __init__(self):
                self._decode_jit = jax.jit(lambda x: x)

            def loop(self, xs):
                for x in xs:
                    r = self._decode_jit(x)
                    np.asarray(r)              # JH008
        """)
    vs = astlint.lint_source(src, "mxnet_tpu/driver.py")
    assert _rules(vs) == ["JH008", "JH008", "JH008"]
    assert "defeating async dispatch" in vs[0].message
    # the fix: keep device futures, materialize ONCE after the loop
    ok = textwrap.dedent("""\
        import jax

        step = jax.jit(lambda x: x + 1)

        def drive(xs):
            futs = [ ]
            for x in xs:
                futs.append(step(x))
            last = futs[-1]
            last.block_until_ready()
            return [float(f) for f in futs]

        def plain(xs):
            for x in xs:
                y = helper(x)     # not a compiled callee
                float(y)
        """)
    assert astlint.lint_source(ok, "mxnet_tpu/driver.py") == []
    # inside a jitted hot path the rule stays quiet (that's JH001's turf)
    hot = textwrap.dedent("""\
        import jax

        inner = jax.jit(lambda x: x)

        def traced(xs):
            for x in xs:
                y = inner(x)
            return y
        g = jax.jit(traced)
        """)
    assert "JH008" not in _rules(astlint.lint_source(
        hot, "mxnet_tpu/driver.py"))
    sup = src.replace("out.append(float(y))           # JH008",
                      "out.append(float(y))  # lint: disable=JH008")
    assert _rules(astlint.lint_source(sup, "mxnet_tpu/driver.py")) == \
        ["JH008", "JH008"]


def test_lint_changed_diffs_merge_base(tmp_path):
    """ISSUE 8 satellite: --changed diffs against the merge-base of main,
    so a pre-commit run late in a branch still sees the files committed
    earlier ON that branch (the old vs-HEAD diff saw only the dirty
    tree)."""
    import importlib.util
    import subprocess

    spec = importlib.util.spec_from_file_location(
        "lintcli", os.path.join(os.path.dirname(PKG_DIR), "tools",
                                "lint.py"))
    lintcli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lintcli)

    repo = tmp_path / "r"
    (repo / "mxnet_tpu").mkdir(parents=True)

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True, text=True)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    git("checkout", "-q", "-b", "main")
    (repo / "mxnet_tpu" / "old.py").write_text("def f(x=()):\n    return x\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    git("checkout", "-q", "-b", "feature")
    (repo / "mxnet_tpu" / "committed.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "branch work")
    (repo / "mxnet_tpu" / "untracked.py").write_text("y = 2\n")
    (repo / "elsewhere.py").write_text("z = 3\n")   # outside linted trees

    names = {os.path.basename(f)
             for f in lintcli._changed_files(repo=str(repo))}
    # the branch's committed file IS seen (the fix), untracked still is,
    # the unchanged seed file and out-of-tree files are not
    assert names == {"committed.py", "untracked.py"}
    # on main itself the merge-base degrades to HEAD: nothing changed
    (repo / "mxnet_tpu" / "untracked.py").unlink()
    git("checkout", "-q", "main")
    assert lintcli._changed_files(repo=str(repo)) == []


def test_package_is_lint_clean():
    """The `make lint` contract, as a regression test: the package carries
    no unsuppressed jit hazards. Any new violation fails here AND in CI."""
    vs = astlint.lint_paths([PKG_DIR])
    assert vs == [], "\n".join(str(v) for v in vs)


def test_lint_cli_smoke(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    tools = os.path.join(os.path.dirname(PKG_DIR), "tools", "lint.py")
    r = subprocess.run([sys.executable, tools, str(bad)],
                       capture_output=True, text=True)
    assert r.returncode == 1 and "JH004" in r.stdout
    good = tmp_path / "good.py"
    good.write_text("def f(x=()):\n    return x\n")
    r = subprocess.run([sys.executable, tools, str(good)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run([sys.executable, tools, "--list-rules"],
                       capture_output=True, text=True)
    assert r.returncode == 0 and "JH005" in r.stdout
