"""Pallas flash attention for TPU.

The marquee custom kernel (SURVEY §5.7): replaces the reference's O(L^2)
fused attention (``src/operator/contrib/transformer.cu``) with an online-
softmax blocked kernel — O(L) memory, MXU-tiled q/k blocks, f32 accumulation.

Forward is a Pallas kernel (grid = (batch*heads, q_blocks, k_blocks), with
m/l/acc scratch carried across the sequential innermost k dimension).
Backward recomputes attention through the XLA einsum path via ``custom_vjp``
— correct and fusion-friendly at BERT/GPT block sizes; a dedicated backward
kernel is a later optimisation.

On non-TPU backends the kernel runs in interpret mode (tests) or callers fall
back to the einsum path via ``flash_supported``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .pallas_common import HAS_PLTPU as _HAS_PLTPU
from .pallas_common import LANES as _LANES
from .pallas_common import on_tpu as _on_tpu
from .pallas_common import pltpu


_FLASH_MIN_SEQ = 4096  # below this XLA's fused einsum attention is faster on
# TPU (round-1 session measured seq 2048 flash 8.4ms vs einsum 6.4ms on v5e;
# UNREPRODUCED since — no driver artifact has recorded a TPU run, treat as a
# design heuristic, not a verified number); flash's win is O(L) memory — the
# [b,h,t,t] score tensor the einsum path materializes stops fitting HBM
# around tq*tk ≥ 4k², exactly where the kernel takes over


def flash_supported(q, k, v, mask=None) -> bool:
    """Kernel eligibility: TPU backend, no arbitrary mask, tile-able lengths,
    and long enough that O(L) memory beats XLA's fused einsum."""
    if mask is not None or not _HAS_PLTPU or not _on_tpu():
        return False
    b, h, tq, d = q.shape
    tk = k.shape[2]
    # the kernel's BlockSpecs put d on the lane dimension; Mosaic wants
    # 128-multiple lane tiles, so sub-128 head dims are zero-padded to 128
    # inside _flash_fwd (zeros in the contraction dim leave scores exact,
    # padded v columns are sliced off). d % 64 == 0 bounds the pad waste at
    # 2x and admits BERT/GPT's d=64 heads (round-2 verdict weak #4)
    return (tq % 128 == 0 and tk % 128 == 0 and d % 64 == 0
            and max(tq, tk) >= _FLASH_MIN_SEQ
            and q.dtype in (jnp.float32, jnp.bfloat16))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, causal,
                bq, bk, scale, off):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _body():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k = k_ref[0].astype(jnp.float32)  # (bk, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            # bottom-right-aligned causal mask: row r attends to cols
            # <= r + (tk - tq), matching _ref_attention/_chunked_attention
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows + off >= cols, s, -jnp.inf)
        m_prev = m_ref[:, :1]  # (bq, 1), replicated over lanes
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows (m_new == -inf) against nan exp
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = corr * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip fully-masked k blocks above the (offset) diagonal: the block
        # has live entries iff its max row + off reaches its min col
        @pl.when(qi * bq + bq - 1 + off >= ki * bk)
        def _():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, block_q=128, block_k=128, interpret=False):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = 1.0 / (d ** 0.5)  # true head dim, even when lanes are padded
    d_orig = d
    if d % _LANES:
        # lane-pad the head dim to a full 128 tile: zero columns contribute
        # nothing to q·kᵀ, and the padded v columns come out as zeros in the
        # output, sliced off below. XLA fuses the pads/slice; cost is the
        # idle lane fraction of the two block matmuls.
        d_pad = ((d + _LANES - 1) // _LANES) * _LANES
        pad = [(0, 0)] * 3 + [(0, d_pad - d)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        d = d_pad
    bq, bk = min(block_q, tq), min(block_k, tk)
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    grid = (b * h, tq // bq, tk // bk)
    kernel = functools.partial(_fwd_kernel, causal=causal, bq=bq, bk=bk,
                               scale=scale, off=tk - tq)
    scratch = [
        pltpu.VMEM((bq, _LANES), jnp.float32),
        pltpu.VMEM((bq, _LANES), jnp.float32),
        pltpu.VMEM((bq, d), jnp.float32),
    ] if _HAS_PLTPU else [
        pl.MemorySpace.ANY  # pragma: no cover
    ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if _HAS_PLTPU and not interpret else None,
    )(qr, kr, vr)
    out = out.reshape(b, h, tq, d)
    return out[..., :d_orig] if d_orig != d else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, causal):
    return _flash_fwd(q, k, v, causal)


def _ref_attention(q, k, v, causal):
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    s = jnp.einsum("bhqc,bhkc->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(cm, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkc->bhqc", p, v)


def _chunked_attention(q, k, v, causal, chunk=1024):
    """Memory-efficient attention (Rabe & Staats): online softmax over KV
    chunks via ``lax.scan`` with a rematerialized chunk body — O(tq·chunk)
    live memory instead of the einsum path's O(tq·tk). Numerically identical
    to softmax attention; used as the backward of the Pallas forward so the
    whole train step stays O(L) in sequence length."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    chunk = min(chunk, tk)
    if tk % chunk:
        raise ValueError(f"tk={tk} not divisible by chunk={chunk}")
    scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32) * scale
    rows = lax.broadcasted_iota(jnp.int32, (tq, chunk), 0)

    @jax.checkpoint
    def body(carry, i):
        m, l, acc = carry
        ks = lax.dynamic_slice_in_dim(k, i * chunk, chunk, 2).astype(jnp.float32)
        vs = lax.dynamic_slice_in_dim(v, i * chunk, chunk, 2).astype(jnp.float32)
        s = jnp.einsum("bhqc,bhkc->bhqk", qf, ks,
                       preferred_element_type=jnp.float32)
        if causal:
            cols = i * chunk + lax.broadcasted_iota(jnp.int32, (tq, chunk), 1)
            s = jnp.where((rows + (tk - tq) >= cols)[None, None], s, -jnp.inf)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum("bhqk,bhkc->bhqc", p, vs,
                                          preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, tq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tq, 1), jnp.float32)
    a0 = jnp.zeros((b, h, tq, d), jnp.float32)
    (m, l, acc), _ = lax.scan(body, (m0, l0, a0), jnp.arange(tk // chunk))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)


def _flash_vjp_fwd(q, k, v, causal):
    return _flash_fwd(q, k, v, causal), (q, k, v)


def _flash_vjp_bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _chunked_attention(q, k, v, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, mask=None, causal=False, interpret=None):
    """Blocked flash attention over (B, H, T, Ch). ``mask`` unsupported here —
    callers gate via :func:`flash_supported`."""
    if mask is not None:
        raise ValueError("flash_attention kernel does not take arbitrary masks; "
                         "use multi_head_attention which falls back to the einsum path")
    if interpret is None:
        interpret = not _on_tpu()
    if interpret:
        return _flash_fwd(q, k, v, causal, interpret=True)
    return _flash(q, k, v, bool(causal))
