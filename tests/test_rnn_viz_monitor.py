"""Legacy mx.rnn cells, mx.viz, mx.monitor (reference:
tests/python/unittest/test_rnn.py, test_viz.py, monitor usage in fit)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.base import MXNetError


def _bind_and_run(out_sym, feed):
    ex = out_sym.bind(args={k: nd.array(v) for k, v in feed.items()})
    return ex.forward()[0].asnumpy()


def test_lstm_cell_unroll_matches_manual():
    """Unrolled symbolic LSTM == step-by-step numpy recurrence."""
    H, C_in, B, T = 4, 3, 2, 3
    rs = np.random.RandomState(0)
    wi = rs.normal(0, 0.2, (4 * H, C_in)).astype(np.float32)
    wh = rs.normal(0, 0.2, (4 * H, H)).astype(np.float32)
    bi = rs.normal(0, 0.1, (4 * H,)).astype(np.float32)
    bh = np.zeros(4 * H, np.float32)
    x = rs.normal(size=(B, T, C_in)).astype(np.float32)

    cell = mx.rnn.LSTMCell(num_hidden=H, prefix="l0_", forget_bias=0.0)
    outs, _ = cell.unroll(T, sym.var("data"), layout="NTC", merge_outputs=True)
    got = _bind_and_run(outs, {"data": x, "l0_i2h_weight": wi, "l0_i2h_bias": bi,
                               "l0_h2h_weight": wh, "l0_h2h_bias": bh})

    def sigmoid(v):
        return 1 / (1 + np.exp(-v))

    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    expect = []
    for t in range(T):
        g = x[:, t] @ wi.T + bi + h @ wh.T + bh
        i, f, gg, o = g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H], g[:, 3 * H:]
        c = sigmoid(f) * c + sigmoid(i) * np.tanh(gg)
        h = sigmoid(o) * np.tanh(c)
        expect.append(h)
    np.testing.assert_allclose(got, np.stack(expect, axis=1), rtol=1e-4, atol=1e-5)


def test_gru_and_sequential_cells_shapes():
    seq = mx.rnn.SequentialRNNCell()
    seq.add(mx.rnn.GRUCell(5, prefix="g0_"))
    seq.add(mx.rnn.RNNCell(7, prefix="r0_"))
    outs, states = seq.unroll(4, sym.var("data"), merge_outputs=True)
    args = outs.list_arguments()
    feed = {"data": np.random.rand(2, 4, 3).astype(np.float32)}
    rs = np.random.RandomState(1)
    shapes = {"g0_i2h_weight": (15, 3), "g0_i2h_bias": (15,),
              "g0_h2h_weight": (15, 5), "g0_h2h_bias": (15,),
              "r0_i2h_weight": (7, 5), "r0_i2h_bias": (7,),
              "r0_h2h_weight": (7, 7), "r0_h2h_bias": (7,)}
    for k, s in shapes.items():
        assert k in args, k
        feed[k] = rs.normal(0, 0.1, s).astype(np.float32)
    got = _bind_and_run(outs, feed)
    assert got.shape == (2, 4, 7)


def test_bidirectional_cell():
    bi = mx.rnn.BidirectionalCell(mx.rnn.RNNCell(4, prefix="fw_"),
                                  mx.rnn.RNNCell(4, prefix="bw_"))
    outs, _ = bi.unroll(3, sym.var("data"), merge_outputs=True)
    rs = np.random.RandomState(2)
    feed = {"data": rs.normal(size=(2, 3, 5)).astype(np.float32)}
    for p in ("fw_", "bw_"):
        feed[p + "i2h_weight"] = rs.normal(0, 0.1, (4, 5)).astype(np.float32)
        feed[p + "i2h_bias"] = np.zeros(4, np.float32)
        feed[p + "h2h_weight"] = rs.normal(0, 0.1, (4, 4)).astype(np.float32)
        feed[p + "h2h_bias"] = np.zeros(4, np.float32)
    got = _bind_and_run(outs, feed)
    assert got.shape == (2, 3, 8)
    with pytest.raises(MXNetError):
        bi(sym.var("x"), [])


def test_viz_print_summary_and_dot(capsys):
    a = sym.var("data")
    w = sym.var("fc_weight")
    b = sym.var("fc_bias")
    out = sym.softmax(sym.FullyConnected(a, w, b, num_hidden=10))
    total = mx.viz.print_summary(out, shape={"data": (1, 20)})
    printed = capsys.readouterr().out
    assert "Total params" in printed
    assert total == 20 * 10 + 10
    dot = mx.viz.plot_network(out)
    assert dot.startswith("digraph") and "FullyConnected" in dot


def test_monitor_collects_param_stats():
    from mxnet_tpu.gluon import nn

    net = nn.Dense(3, in_units=2)
    net.initialize()
    mon = mx.Monitor(interval=2, sort=True).install(net)
    seen = []
    for step in range(4):
        mon.tic()
        seen.extend(mon.toc())
    names = {n for _, n, _ in seen}
    assert any("weight" in n for n in names)
    # interval=2 -> activated on steps 0 and 2 only
    steps = {s for s, _, _ in seen}
    assert len(steps) == 2


def test_bidirectional_begin_state_forwarded():
    """begin_state must reach both sub-cells (stateful/truncated-BPTT)."""
    bi = mx.rnn.BidirectionalCell(mx.rnn.RNNCell(3, prefix="fw_"),
                                  mx.rnn.RNNCell(3, prefix="bw_"))
    data = sym.var("data")
    states = [sym.var("fw_h0"), sym.var("bw_h0")]
    outs, _ = bi.unroll(2, data, begin_state=states, merge_outputs=True)
    args = outs.list_arguments()
    assert "fw_h0" in args and "bw_h0" in args  # states are live graph inputs
