"""Hardware validation + timing for the Pallas kernels (flash attention,
fused LayerNorm, paged decode-attention, fused Adam, fused softmax-xent)
against their XLA-composition fallbacks.

Run on a machine with a real TPU visible (the axon tunnel). Each case runs in
its own subprocess so an OOM (the einsum path's O(L^2) scores buffer at long
seq — exactly the failure mode flash exists to remove) can't poison the HBM
of later cases. Prints one JSON line per case plus a summary to stderr.

The axon tunnel adds a large fixed cost (~65ms observed interactively in
round 3; no committed artifact row — treat the figure as order-of-magnitude)
to every host readback, so each timing runs ``reps`` dependent iterations per
dispatch chain and syncs ONCE at the end; reported times are per-iteration
with that fixed cost amortized.

Usage:  python tools/kernelbench.py [--reps 15] [--fwd-only]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ATTN_CASES = [
    # (b, h, seq, d) — b*h shrinks as seq grows to keep qkv+grads resident
    (4, 8, 1024, 64), (4, 8, 2048, 64), (4, 8, 4096, 64), (1, 8, 8192, 64),
    (4, 8, 1024, 128), (4, 8, 2048, 128), (2, 8, 4096, 128), (1, 8, 8192, 128),
]
LN_CASES = [(8192, 1024), (32768, 1024), (8192, 4096)]

# paged decode attention: (b, h, ch, page_size, n_pages) — serving-shaped
# single-query rows; the A/B is kernel vs the XLA pool[table] gather
PAGED_CASES = [(8, 8, 128, 16, 64), (32, 8, 128, 16, 64), (8, 8, 128, 16, 256)]
# fused Adam: parameter element counts (one tensor per case; the mp variant
# also emits the bf16 model copy in the same pass)
ADAM_CASES = [(1 << 20,), (1 << 24,)]
# fused softmax-xent: (rows, classes) — LM-head shapes
XENT_CASES = [(8192, 32768), (16384, 50304)]

# conv layout A/B (round-3 verdict ask #7): NCHW dimension_numbers as the op
# is written vs explicit NHWC — settles whether XLA layout assignment makes
# the Python-level layout immaterial on TPU. (B, C, H, W, O, k)
CONV_CASES = [(32, 512, 28, 28, 512, 3), (64, 3, 224, 224, 64, 7)]

if os.environ.get("KERNELBENCH_TINY") == "1":
    # benchall --dryrun-cpu: same code paths, CPU-survivable shapes (the
    # flash kernels run in interpret mode off-TPU, where seq 8192 would
    # take hours on one core)
    ATTN_CASES = [(1, 2, 256, 64)]
    LN_CASES = [(512, 256)]
    CONV_CASES = [(2, 8, 14, 14, 8, 3)]
    PAGED_CASES = [(2, 2, 32, 8, 4)]
    ADAM_CASES = [(1 << 12,)]
    XENT_CASES = [(64, 256)]


def _chain(fn, args, reps):
    import jax
    import jax.numpy as jnp

    # feed a scalar of the previous output back into the first arg so the
    # chain is sequentially dependent (no CSE collapsing reps into one call)
    def body(carry, _):
        first = args[0] + carry
        out = fn(first, *args[1:])
        leaf = jax.tree_util.tree_leaves(out)[0]
        return (leaf.reshape(-1)[0] * 1e-9).astype(args[0].dtype), ()

    carry, _ = jax.lax.scan(body, jnp.zeros((), args[0].dtype), None,
                            length=reps)
    return carry


def _timeit(fn, args, reps):
    """Median-of-3 per-iteration seconds with one host sync per window."""
    import jax
    import numpy as np

    chained = jax.jit(lambda *a: _chain(fn, a, reps))
    np.asarray(jax.device_get(chained(*args)))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        # timing harness: one blocking fetch per reps-step window —
        # lint: disable=JH008 -- the per-iteration sync IS the measurement
        np.asarray(jax.device_get(chained(*args)))
        times.append((time.perf_counter() - t0) / reps)
    return sorted(times)[1]


def run_attn_case(b, h, seq, d, causal, reps, fwd_only):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops import flash_attention as fa

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)
    case = {"kind": "attn", "b": b, "h": h, "d": d, "seq": seq,
            "causal": causal}
    # correctness on-chip. Oracle: einsum reference where its O(L^2) scores
    # buffer fits; the chunked path (numerically exact online softmax, pure
    # XLA, independently tested against einsum at short seq) beyond that.
    oracle = (fa._ref_attention if b * h * seq * seq * 4 < 2e9
              else fa._chunked_attention)
    case["oracle"] = oracle.__name__
    ref = oracle(q, k, v, causal)
    out = fa.flash_attention(q, k, v, causal=causal, interpret=_INTERP)
    err = float(jnp.max(jnp.abs(
        out.astype(jnp.float32) - ref.astype(jnp.float32))))
    case["max_err"] = round(err, 5)
    case["correct"] = err < 0.05
    del ref, out

    def flash_f(q):
        return fa.flash_attention(q, k, v, causal=causal, interpret=_INTERP)

    def einsum_f(q):
        return fa._ref_attention(q, k, v, causal)

    def chunked_f(q):
        return fa._chunked_attention(q, k, v, causal)

    def with_grad(f):
        def g(q):
            return jax.grad(lambda q: jnp.sum(f(q).astype(jnp.float32)))(q)
        return g

    for label, f in (("flash", flash_f), ("einsum", einsum_f),
                     ("chunked", chunked_f)):
        try:
            t = _timeit(f if fwd_only else with_grad(f), (q,), reps)
            case[f"{label}_ms"] = round(t * 1e3, 3)
        except Exception as e:  # OOM etc. — that result IS informative
            case[f"{label}_error"] = repr(e)[:120]
    if "flash_ms" in case and "einsum_ms" in case:
        case["flash_vs_einsum"] = round(case["einsum_ms"] / case["flash_ms"], 2)
    return case


def run_ln_case(n, d, reps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import config as _config
    from mxnet_tpu.ops import pallas_layernorm as pln

    _config.set("fused_layernorm", True)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, d), jnp.bfloat16)
    g = jnp.ones((d,), jnp.bfloat16)
    b = jnp.zeros((d,), jnp.bfloat16)
    case = {"kind": "ln", "n": n, "d": d}

    def composed(x):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, -1, keepdims=True)
        var = jnp.var(xf, -1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
        return (y * g.astype(jnp.float32) + b.astype(jnp.float32)
                ).astype(x.dtype)

    out = pln.layer_norm_fused(x, g, b, interpret=_INTERP)
    ref = composed(x)
    err = float(jnp.max(jnp.abs(
        out.astype(jnp.float32) - ref.astype(jnp.float32))))
    case["max_err"] = round(err, 5)
    case["correct"] = err < 0.05
    del out, ref

    def fused(x):
        return pln.layer_norm_fused(x, g, b, interpret=_INTERP)

    for label, f in (("fused", fused), ("xla", composed)):
        try:
            case[f"{label}_ms"] = round(_timeit(f, (x,), reps) * 1e3, 3)
        except Exception as e:
            case[f"{label}_error"] = repr(e)[:120]
    if "fused_ms" in case and "xla_ms" in case:
        case["fused_vs_xla"] = round(case["xla_ms"] / case["fused_ms"], 2)
    return case


def run_conv_case(b, c, h, w, o, k, reps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(0)
    pad = k // 2
    x_nchw = jnp.asarray(rng.randn(b, c, h, w), jnp.bfloat16)
    w_oihw = jnp.asarray(rng.randn(o, c, k, k) * 0.05, jnp.bfloat16)
    x_nhwc = jnp.transpose(x_nchw, (0, 2, 3, 1))
    w_hwio = jnp.transpose(w_oihw, (2, 3, 1, 0))
    case = {"kind": "conv_layout", "b": b, "c": c, "hw": h, "o": o, "k": k}

    def conv_nchw(x):
        return jax.lax.conv_general_dilated(
            x, w_oihw, (1, 1), [(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def conv_nhwc(x):
        return jax.lax.conv_general_dilated(
            x, w_hwio, (1, 1), [(pad, pad), (pad, pad)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    a = jnp.transpose(conv_nchw(x_nchw), (0, 2, 3, 1)).astype(jnp.float32)
    bb = conv_nhwc(x_nhwc).astype(jnp.float32)
    err = float(jnp.max(jnp.abs(a - bb)))
    case["max_err"] = round(err, 4)
    case["correct"] = err < 1.0  # bf16 conv tolerance at these sizes
    del a, bb
    case["nchw_ms"] = round(_timeit(conv_nchw, (x_nchw,), reps) * 1e3, 3)
    case["nhwc_ms"] = round(_timeit(conv_nhwc, (x_nhwc,), reps) * 1e3, 3)
    case["nchw_vs_nhwc"] = round(case["nchw_ms"] / case["nhwc_ms"], 3)
    return case


def run_paged_case(b, h, ch, ps, n_pages, reps):
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops import pallas_paged_attention as ppa

    rng = np.random.RandomState(0)
    pool_pages = b * n_pages
    k_pool = jnp.asarray(rng.randn(pool_pages + 1, h, ps, ch), jnp.bfloat16)
    v_pool = jnp.asarray(rng.randn(pool_pages + 1, h, ps, ch), jnp.bfloat16)
    table = jnp.asarray(rng.randint(1, pool_pages + 1, (b, n_pages)), jnp.int32)
    position = jnp.asarray(rng.randint(0, n_pages * ps - 1, (b,)), jnp.int32)
    # f32 activations over a bf16 pool: the engine's decode layout, and the
    # combination the bit-identity contract covers (mixed-dtype dots promote
    # to f32; all-bf16 dots pick up backend-dependent accumulation).
    q = jnp.asarray(rng.randn(b, h, 1, ch), jnp.float32)
    kn = jnp.asarray(rng.randn(b, h, 1, ch), jnp.float32)
    vn = jnp.asarray(rng.randn(b, h, 1, ch), jnp.float32)
    case = {"kind": "paged_attn", "b": b, "h": h, "ch": ch, "ps": ps,
            "n_pages": n_pages}

    def gather_ref(q):
        from mxnet_tpu import config as _config
        from mxnet_tpu.ops import attention as att

        _config.set("paged_attention_kernel", False)
        try:
            return att._paged_cached_mha(q, kn, vn, k_pool, v_pool,
                                         table, position)[0]
        finally:
            _config.set("paged_attention_kernel", True)

    def kernel(q):
        return ppa.paged_attention(q, kn, vn, k_pool, v_pool, table,
                                   position, interpret=_INTERP)[0]

    ref, out = gather_ref(q), kernel(q)
    err = float(jnp.max(jnp.abs(
        out.astype(jnp.float32) - ref.astype(jnp.float32))))
    case["max_err"] = round(err, 5)
    case["correct"] = err == 0.0  # the paged contract is BIT identity
    del ref, out
    for label, f in (("kernel", kernel), ("gather", gather_ref)):
        try:
            case[f"{label}_ms"] = round(_timeit(f, (q,), reps) * 1e3, 3)
        except Exception as e:
            case[f"{label}_error"] = repr(e)[:120]
    if "kernel_ms" in case and "gather_ms" in case:
        case["kernel_vs_gather"] = round(case["gather_ms"] / case["kernel_ms"], 2)
    return case


def run_adam_case(n, reps):
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops import optimizer_ops as oo
    from mxnet_tpu.ops import pallas_optimizer as po

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(n), jnp.float32)
    g = jnp.asarray(rng.randn(n), jnp.bfloat16)
    m = jnp.asarray(rng.randn(n) * 0.1, jnp.float32)
    v = jnp.asarray(np.abs(rng.randn(n)) * 0.01, jnp.float32)
    lr_t, wd = jnp.float32(1e-3), jnp.float32(1e-2)
    case = {"kind": "fused_adam", "n": n}

    def unfused(w):
        nw, nm, nv = oo.adam_update(w, g, m, v, lr_t, 0.9, 0.999, 1e-8,
                                    wd, 1.0, -1.0)
        return nw, nm, nv, nw.astype(jnp.bfloat16)  # the mp two-pass cast

    def fused(w):
        return po.adam_update_fused(w, g, m, v, lr_t, beta1=0.9, beta2=0.999,
                                    epsilon=1e-8, wd=wd,
                                    out_dtype=jnp.bfloat16, interpret=_INTERP)

    ref, out = unfused(w), fused(w)
    err = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - b.astype(jnp.float32))))
              for a, b in zip(ref, out))
    case["max_err"] = round(err, 6)
    case["correct"] = err < 1e-5
    del ref, out
    for label, f in (("fused", fused), ("xla", unfused)):
        try:
            case[f"{label}_ms"] = round(_timeit(f, (w,), reps) * 1e3, 3)
        except Exception as e:
            case[f"{label}_error"] = repr(e)[:120]
    if "fused_ms" in case and "xla_ms" in case:
        case["fused_vs_xla"] = round(case["xla_ms"] / case["fused_ms"], 2)
    return case


def run_xent_case(n, c, reps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops import pallas_softmax_xent as px

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, c), jnp.bfloat16)
    lbl = jnp.asarray(rng.randint(0, c, (n,)), jnp.int32)
    co = jnp.ones((n,), jnp.float32)
    case = {"kind": "softmax_xent", "n": n, "c": c}

    def composed(x):
        lp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(lp, lbl[:, None], axis=-1)[:, 0]

    def fused(x):
        return px.softmax_cross_entropy_fused(x, lbl, interpret=_INTERP)

    ref, out = composed(x), fused(x)
    err = float(jnp.max(jnp.abs(ref - out)))
    case["max_err"] = round(err, 5)
    case["correct"] = err < 0.05
    del ref, out

    def with_grad(f):
        return jax.grad(lambda x: jnp.sum(f(x).astype(jnp.float32) * co))

    for label, f in (("fused", fused), ("xla", composed)):
        try:
            case[f"{label}_ms"] = round(
                _timeit(with_grad(f), (x,), reps) * 1e3, 3)
        except Exception as e:
            case[f"{label}_error"] = repr(e)[:120]
    if "fused_ms" in case and "xla_ms" in case:
        case["fused_vs_xla"] = round(case["xla_ms"] / case["fused_ms"], 2)
    return case


_INTERP = os.environ.get("KERNELBENCH_TINY") == "1"  # CPU dryrun: pallas
# kernels only run in interpret mode off-TPU


def run_one(argv):
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon sitecustomize pins the platform at jax-config level; honor
        # an explicit CPU request (smoke runs) the same way bench.py does
        import jax

        jax.config.update("jax_platforms", "cpu")
    spec = json.loads(argv[argv.index("--one") + 1])
    try:
        if spec["kind"] == "attn":
            case = run_attn_case(spec["b"], spec["h"], spec["seq"], spec["d"],
                                 spec["causal"], spec["reps"], spec["fwd_only"])
        elif spec["kind"] == "conv_layout":
            case = run_conv_case(spec["b"], spec["c"], spec["hw"], spec["hw"],
                                 spec["o"], spec["k"], spec["reps"])
        elif spec["kind"] == "paged_attn":
            case = run_paged_case(spec["b"], spec["h"], spec["ch"],
                                  spec["ps"], spec["n_pages"], spec["reps"])
        elif spec["kind"] == "fused_adam":
            case = run_adam_case(spec["n"], spec["reps"])
        elif spec["kind"] == "softmax_xent":
            case = run_xent_case(spec["n"], spec["c"], spec["reps"])
        else:
            case = run_ln_case(spec["n"], spec["d"], spec["reps"])
    except Exception as e:
        case = dict(spec, error=repr(e)[:200])
    print("CASE " + json.dumps(case), flush=True)


def main():
    if "--one" in sys.argv:
        run_one(sys.argv)
        return
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=15)
    ap.add_argument("--fwd-only", action="store_true")
    ap.add_argument("--skip-ln", action="store_true")
    ap.add_argument("--skip-attn", action="store_true")
    ap.add_argument("--timeout", type=int, default=600)
    args = ap.parse_args()

    specs = []
    if not args.skip_attn:
        for b, h, seq, d in ATTN_CASES:
            for causal in (False, True):
                specs.append({"kind": "attn", "b": b, "h": h, "seq": seq,
                              "d": d, "causal": causal, "reps": args.reps,
                              "fwd_only": args.fwd_only})
    if not args.skip_ln:
        specs += [{"kind": "ln", "n": n, "d": d, "reps": args.reps}
                  for n, d in LN_CASES]
    specs += [{"kind": "conv_layout", "b": b, "c": c, "hw": h, "o": o,
               "k": k, "reps": args.reps}
              for b, c, h, w, o, k in CONV_CASES]
    specs += [{"kind": "paged_attn", "b": b, "h": h, "ch": ch, "ps": ps,
               "n_pages": np_, "reps": args.reps}
              for b, h, ch, ps, np_ in PAGED_CASES]
    specs += [{"kind": "fused_adam", "n": n, "reps": args.reps}
              for (n,) in ADAM_CASES]
    specs += [{"kind": "softmax_xent", "n": n, "c": c, "reps": args.reps}
              for n, c in XENT_CASES]

    def _run_spec(spec):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one",
             json.dumps(spec)],
            capture_output=True, text=True, timeout=args.timeout)
        lines = [ln for ln in (r.stdout or "").splitlines()
                 if ln.startswith("CASE ")]
        return (json.loads(lines[-1][5:]) if lines
                else dict(spec, error=f"child rc={r.returncode}: "
                          + (r.stderr or "")[-200:]))

    def _transient(case):
        # both case-level "error" AND per-timing-label errors count:
        # KERNELBENCH_r03 seq=4096 lost its flash timing to a
        # 'flash_error: "read body: response body closed ..."' remote-compile
        # RPC drop while the rest of the case succeeded — that's an infra
        # failure, not a kernel result, and deserves one retry too
        errs = [str(v) for k, v in case.items()
                if k == "error" or k.endswith("_error")]
        pats = ("remote_compile", "DEADLINE", "UNAVAILABLE", "Socket closed",
                "read body", "response body closed")
        return next((e for e in errs if any(p in e for p in pats)), None)

    n_bad = 0
    for spec in specs:
        try:
            case = _run_spec(spec)
            first_err = _transient(case)
            if first_err is not None:
                # transient tunnel/compile-service failure: retry once after
                # a pause instead of recording an infra error as a result
                # (round-3 verdict weak #3; ISSUE 5 extends to per-label)
                time.sleep(20)
                retry = _run_spec(spec)
                retry["retried_after"] = first_err[:120]
                case = retry
        except subprocess.TimeoutExpired:
            case = dict(spec, error=f"timeout {args.timeout}s")
        case.pop("reps", None)
        case.pop("fwd_only", None)
        if not case.get("correct", False):
            n_bad += 1
        print(json.dumps(case), flush=True)
    print(f"# {len(specs)} cases, {n_bad} failed-or-errored", file=sys.stderr)


if __name__ == "__main__":
    main()
