"""Compiled KV-cache generation engine (docs/INFERENCE.md).

The training insight of ``TrainStep.run`` — one donated jit program instead
of a per-step dispatch storm — applied to decoding. A naive sampling loop
re-forwards the whole growing sequence every token: O(N·L²) attention
recompute plus a fresh dispatch (or, hybridized, a fresh *compile* per
growing shape). This engine runs a fixed family of compiled programs:

  - **prefill** — the prompt, padded to a static bucket length, runs one
    cached causal forward that writes the prompt's K/V into one row of the
    decode cache and samples the first new token. One XLA program per
    bucket length; admitting a request never touches the other rows.
  - **decode** — one token for every row of the static batch: cache update,
    attention against the full history, sampling (greedy / temperature /
    top-k) and per-row EOS done-masking all compiled in. The cache is a
    donated carry, so XLA updates it in place.

Two serving-scale extensions ride the same no-shape-change discipline:

  - **paged cache** (``paged=True``) — instead of per-row contiguous
    (B, H, Tmax, Ch) buffers, K/V live in a global pool of fixed-size
    pages; each row owns an int32 *page table* riding the compiled carry.
    Admission is bounded by free pages, not slots, so a batch of short
    sequences no longer pays ``Tmax − actual_len`` dead memory per row.
    Pages are reclaimed on ``release_slot``/EOS; a released row's table is
    cleared in-program and its (masked) writes redirect to a reserved
    trash page, so reallocated pages can never be corrupted.
  - **speculative decoding** (``draft_net=`` + ``speculate_k=``) — a small
    draft model proposes k tokens through its own paged cache in ONE
    compiled ``lax.scan`` program, and one target-model *verify* program
    scores all k+1 positions at once: accepted prefixes advance the page
    table in-place, rejected tails simply don't advance the write frontier
    (stale entries stay masked and are overwritten next round). Greedy
    output is token-identical to the non-speculative path; each round costs
    2 dispatches for up to k+1 tokens.

A third serving-scale extension builds on the paged allocator
(docs/INFERENCE.md "Prefix sharing"):

  - **prefix sharing** (``prefix_cache=True``) — the host allocator keeps
    per-page *refcounts*, so a page can back several rows at once.
    ``fork_slot`` clones a row by bumping refcounts (zero pool bytes
    moved); the first write into a shared page triggers a page-granular
    compiled *copy-on-write* program. A radix tree over token-id prefixes
    (:class:`~mxnet_tpu.inference.prefix_cache.RadixPrefixCache`) maps
    prompt heads to cached page runs: prefill adopts the longest cached
    prefix (refcount bump, zero recompute) and runs only the suffix
    through the bucketed prefill programs — the same per-bucket program
    family, with the start offset a traced argument. Under free-page
    pressure, refcount-1 (cache-only) entries are LRU-evicted. Released
    forks decrement refcounts and only refcount-0 pages return to the
    free list, preserving the trash-page-safe reclaim contract.

Speculative decoding composes with stochastic sampling through
*rejection sampling*: the draft scan samples from its own distribution q
(recording q per drafted token), and the verify program accepts token x
with probability ``min(1, p(x)/q(x))`` against the target distribution p,
resampling the first rejection from the normalized residual
``max(p - q, 0)`` — the emitted tokens are distributed exactly as plain
sampled decode.

Nothing in the serving loop changes a shape, so the compiled-program count
is exactly ``len(buckets used) + 1`` (+1 verify when speculating, +1 the
first copy-on-write dispatch) — counted through the observability registry
(``gen_recompiles_total{reason="prefill_bucket"|"decode"|"verify"|
"cow_copy"}``), the same discipline as ``train_recompiles_total``.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..gluon.block import _HybridTrace
from ..ndarray import NDArray
from ..ops import random_ops as _rops
from ..resilience import faults as _faults
from ..resilience import retry as _retry
from .prefix_cache import RadixPrefixCache

__all__ = ["GenerationEngine", "SamplingConfig"]


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Decode-time sampling, folded into the compiled programs as constants
    (changing it makes a new engine / new programs, counted as recompiles).
    """

    method: str = "greedy"  # greedy | temperature | top_k
    temperature: float = 1.0
    top_k: int = 40
    seed: int = 0

    def __post_init__(self):
        if self.method not in ("greedy", "temperature", "top_k"):
            raise ValueError(f"unknown sampling method {self.method!r}")

    @property
    def stochastic(self) -> bool:
        return self.method != "greedy" and self.temperature > 0


def _default_buckets(max_length: int) -> Tuple[int, ...]:
    out, b = [], 16
    while b < max_length:
        out.append(b)
        b *= 2
    return tuple(out) or (max_length - 1,)


class GenerationEngine:
    """Compiled autoregressive generation over a static decode batch.

    Parameters
    ----------
    net : GPT2Model (or any block whose ``hybrid_forward`` threads
        ``cache=``/``start_pos=`` (and, for paged mode, ``page_table=``)
        and that provides ``init_cache``/``init_paged_cache``).
        Must be initialized; dropout should be 0 for exact equivalence
        (evaluation mode disables it regardless).
    batch_size : rows of the static decode batch (= serving slots).
    max_length : per-row sequence capacity (default: the net's max_length).
    prefill_buckets : ascending prompt-length buckets; each bucket used
        costs one prefill compile. Default: powers of two from 16.
    eos_id : token that finishes a row (compiled into the done-mask);
        None = rows only finish by max_new_tokens.
    pad_id : token emitted by finished rows and used for prompt padding.
    sampling : SamplingConfig (or method string), compiled in.
    paged : store K/V in a global page pool instead of per-row contiguous
        buffers (docs/INFERENCE.md "Paged cache").
    page_size : tokens per page (paged mode).
    num_pages : pool capacity in pages, excluding the reserved trash page.
        Default: ``batch_size * ceil(max_length / page_size)`` (the
        dense-equivalent capacity — size it DOWN to oversubscribe slots).
    draft_net : small initialized model drafting ``speculate_k`` tokens per
        round through its own paged cache (requires ``paged=True``; pass
        ``net`` itself to self-draft). Greedy sampling verifies by exact
        prefix match; stochastic sampling verifies by rejection sampling
        (distribution-identical to plain sampled decode).
    speculate_k : draft window length per speculative round.
    prefix_cache : index computed prefixes in a radix tree so later
        prompts sharing them skip recompute (requires ``paged=True``;
        docs/INFERENCE.md "Prefix sharing").
    layout : optional :class:`~mxnet_tpu.parallel.Layout` — the same
        declarative spec that drives training places the serving weights:
        each parameter is laid out per the layout's rules on the layout's
        mesh before any program compiles. Serving programs themselves stay
        single-program (no pp/ep dispatch loop yet); a layout whose total
        is 1 (or None) keeps today's replicated placement.
    """

    def __init__(self, net, batch_size: int = 4, max_length: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 sampling=None, cache_dtype: str = "float32",
                 paged: bool = False, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 draft_net=None, speculate_k: int = 0,
                 prefix_cache: bool = False, layout=None):
        self.net = net
        self.batch_size = int(batch_size)
        self.max_length = int(max_length or net._max_length)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.pad_id = int(pad_id)
        if sampling is None:
            sampling = SamplingConfig()
        elif isinstance(sampling, str):
            sampling = SamplingConfig(method=sampling)
        self.sampling = sampling
        buckets = tuple(sorted(prefill_buckets or
                               _default_buckets(self.max_length)))
        if not buckets or buckets[-1] >= self.max_length:
            raise ValueError(f"prefill buckets {buckets} must be non-empty "
                             f"and < max_length={self.max_length}")
        self.prefill_buckets = buckets

        self._plist = [p for _, p in sorted(net.collect_params().items())]
        for p in self._plist:
            if p._nd is None:
                raise ValueError(f"parameter {p.name} not initialized; run "
                                 "one forward pass first")

        #: declarative parallelism spec (docs/PARALLELISM.md). Weight
        #: placement only: the layout's rules decide each parameter's
        #: sharding on the layout's mesh, so the spec that trained a model
        #: is the spec that serves it — no separate serving placement code.
        self.layout = layout
        if layout is not None and layout.total > 1:
            from jax.sharding import NamedSharding

            mesh = layout.mesh()
            for p in self._plist:
                d = p._nd._data
                p._nd._data = jax.device_put(
                    d, NamedSharding(mesh,
                                     layout.spec_for(p.name, d.shape, mesh)))

        # -- paged / speculative configuration --------------------------------
        self.paged = bool(paged)
        self.page_size = int(page_size)
        self.speculate_k = int(speculate_k)
        self.draft_net = draft_net
        if (self.speculate_k > 0) != (draft_net is not None):
            raise ValueError("speculative decoding needs BOTH draft_net= "
                             "and speculate_k >= 1")
        if draft_net is not None and not self.paged:
            raise ValueError("speculative decoding rides the paged cache; "
                             "pass paged=True")
        if (self.speculate_k and self.sampling.method != "greedy"
                and not self.sampling.stochastic):
            # temperature=0 stochastic methods degenerate to argmax but
            # the rejection-sampling residual would be ill-defined
            raise ValueError("speculative decoding needs greedy sampling "
                             "or a stochastic config (temperature > 0): "
                             "stochastic rounds verify by rejection "
                             "sampling, greedy by exact prefix match")
        if prefix_cache and not self.paged:
            raise ValueError("prefix_cache=True rides the paged allocator; "
                             "pass paged=True")

        if self.paged:
            if self.page_size < 1:
                raise ValueError("page_size must be >= 1")
            #: page-table width: page slots per row (slot s = positions
            #: s*ps .. (s+1)*ps - 1)
            self._n_row_pages = -(-self.max_length // self.page_size)
            # explicit `is None` check: a computed num_pages that underflows
            # to 0 must hit the error below, not the dense-equivalent default
            self.num_pages = int(self.batch_size * self._n_row_pages
                                 if num_pages is None else num_pages)
            if self.num_pages < 1:
                raise ValueError("num_pages must be >= 1")
            #: device carry: per-row page tables (0 = unallocated/trash)
            self.page_table = jnp.zeros(
                (self.batch_size, self._n_row_pages), jnp.int32)
            #: device carry: per-layer (k_pool, v_pool) page pools
            self.pools = net.init_paged_cache(self.num_pages, self.page_size,
                                              dtype=cache_dtype)
            self.cache = None  # dense-only state
            # host allocator (authoritative; the device table mirrors it
            # through compiled update vectors shipped with each program)
            self._free_pages: deque = deque(range(1, self.num_pages + 1))
            self._row_pages: List[List[int]] = \
                [[] for _ in range(self.batch_size)]
            self._pending_clear: set = set()
            #: pages the batcher's aging guard holds back from decode-time
            #: growth for a parked queue head (docs/RESILIENCE.md)
            self._reserved_pages = 0
            #: rows force-finished because the pool ran dry (the batcher
            #: reports these as finish_reason="page_exhausted")
            self.page_exhausted = np.zeros(self.batch_size, bool)
            # worst-case NEW pages per row per dispatch (window k spans at
            # most k//ps + 2 page slots from an arbitrary start offset)
            self._upd_width = self.speculate_k // self.page_size + 2
            #: per-page refcounts (index 0 = trash page, never counted):
            #: a page may back several rows / the prefix cache at once;
            #: only refcount-0 pages return to the free list
            self._page_rc = np.zeros(self.num_pages + 1, np.int32)
            #: copy-on-write copies per compiled dispatch (chunked)
            self._cow_width = self.batch_size
            self._cow_jit = None  # lazily lowered page-copy program
            #: per-slot prefill logits (device (V,) arrays) — fork_slot's
            #: resample_first draws an independent first token from them
            self._prefill_logits = {}
            self.prefix_cache = (RadixPrefixCache(self.page_size)
                                 if prefix_cache else None)
            self._page_gauges()
        else:
            #: device state: per-layer (k_buf, v_buf), the donated carry
            self.cache = net.init_cache(self.batch_size, self.max_length,
                                        dtype=cache_dtype)
            self.prefix_cache = None

        if draft_net is not None:
            self._draft_plist = [p for _, p in
                                 sorted(draft_net.collect_params().items())]
            for p in self._draft_plist:
                if p._nd is None:
                    raise ValueError(f"draft parameter {p.name} not "
                                     "initialized; run one forward first")
            if draft_net._max_length < self.max_length:
                raise ValueError(f"draft_net.max_length "
                                 f"{draft_net._max_length} < engine "
                                 f"max_length {self.max_length}")
            self.draft_pools = draft_net.init_paged_cache(
                self.num_pages, self.page_size, dtype=cache_dtype)

        #: accept stats of the most recent speculative round (read by the
        #: batcher's degradation governor)
        self.last_round_drafted = 0
        self.last_round_accepted = 0
        self._plain_decode_jit = None  # lazy spec-engine fallback program
        #: RetryPolicy for the in-round gen.verify retry (None = config
        #: defaults); ContinuousBatcher installs its own policy here so
        #: one knob governs every serving retry
        self.retry_policy = None

        # host state (tiny (B,) vectors shipped to the device each step —
        # keeping them host-side makes slot admission trivial)
        self.positions = np.zeros(self.batch_size, np.int32)
        self.done = np.ones(self.batch_size, bool)  # empty slots are "done"
        self.last_tokens = np.full(self.batch_size, self.pad_id, np.int32)

        # keep_unused (paged families): flat input positions must be stable
        # for audit()'s carry_indices even when a program has dead params
        # (e.g. the spec prefill discards the draft's logits, killing its
        # final-LN inputs). The dense pair keeps the default — its programs
        # use every input and its shardcheck goldens predate this knob.
        if not self.paged:
            self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=(1,))
            self._decode_jit = jax.jit(self._decode_fn, donate_argnums=(1,))
        elif self.speculative:
            self._prefill_jit = jax.jit(self._spec_prefill_fn,
                                        donate_argnums=(2,),
                                        keep_unused=True)
            # stochastic sampling swaps the greedy prefix-match round for
            # the rejection-sampling pair (sampled draft scan records q;
            # verify accepts with min(1, p/q) and resamples residuals)
            draft_fn = (self._draft_sample_fn if self.sampling.stochastic
                        else self._draft_fn)
            verify_fn = (self._verify_sample_fn if self.sampling.stochastic
                         else self._verify_fn)
            self._draft_jit = jax.jit(draft_fn, donate_argnums=(1,),
                                      keep_unused=True)
            self._verify_jit = jax.jit(verify_fn, donate_argnums=(1,),
                                       keep_unused=True)
        else:
            self._prefill_jit = jax.jit(self._paged_prefill_fn,
                                        donate_argnums=(1,),
                                        keep_unused=True)
            self._decode_jit = jax.jit(self._paged_decode_fn,
                                       donate_argnums=(1,),
                                       keep_unused=True)
        # lowered-program fingerprints seen (cf. TrainStep._note_recompile):
        # a miss means XLA compiles a new executable. Reasons are fixed by
        # contract ("prefill_bucket"/"decode"/"verify") — the guard supplies
        # the event plumbing and the program count (docs/ANALYSIS.md).
        from ..analysis import RecompileGuard

        self._recompile_guard = RecompileGuard(
            "gen_recompiles_total",
            "generation program lowerings (cache misses)")
        self._key = None  # lazily created PRNG key for stochastic sampling
        self._fixed_key = None

    # -- program accounting --------------------------------------------------
    @property
    def compiled_programs(self) -> int:
        """How many XLA executables this engine has lowered (prefill buckets
        actually used + the decode step [+ the verify step])."""
        return len(self._recompile_guard)

    @property
    def speculative(self) -> bool:
        return self.speculate_k > 0

    def _note_program(self, sig, reason):
        from ..analysis import Fingerprint

        self._recompile_guard.observe(Fingerprint.of((), sig=sig),
                                      reason=reason, group=reason,
                                      sig=list(map(str, sig)))

    # -- page accounting (paged mode) ----------------------------------------
    @property
    def free_pages(self) -> int:
        """Unallocated pages in the pool (paged mode)."""
        return len(self._free_pages) if self.paged else 0

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free_pages) if self.paged else 0

    def pages_for(self, length: int) -> int:
        """Pages a ``length``-token sequence occupies."""
        return -(-int(length) // self.page_size)

    def suffix_for(self, prompt) -> int:
        """Tokens a prefill would actually compute for ``prompt`` after
        prefix adoption (the full length without a prefix cache). Probes
        the radix tree without touching its LRU clock — admission sizing
        is not traffic."""
        n = len(prompt)
        if not self.paged or self.prefix_cache is None or n == 0:
            return n
        _, mtok = self.prefix_cache.lookup(list(prompt), touch=False)
        return n - min(mtok, n - 1)

    def pages_needed(self, prompt) -> int:
        """NEW pages admitting ``prompt`` must supply after prefix reuse
        (paged mode): adopted full pages are refcount bumps, not
        allocations — the admission/shed watermarks must charge only
        these, or fully-cached prompts would shed on a busy pool."""
        if not self.paged:
            return 0
        n = len(prompt)
        adopted_full = (n - self.suffix_for(prompt)) // self.page_size
        return self.pages_for(n) - adopted_full

    def can_admit(self, prompt) -> bool:
        """Whether a prefill of ``prompt`` has a bucket to run in: the
        suffix after prefix adoption must fit a prefill bucket and the
        prompt must fit the row. Session-resume prompts longer than the
        largest bucket are admissible exactly when their cached history
        shrinks the suffix into one."""
        n = len(prompt)
        if n == 0 or (self.paged and n >= self.max_length):
            return False
        try:
            self.bucket_for(self.suffix_for(prompt))
        except ValueError:
            return False
        return True

    @property
    def available_pages(self) -> int:
        """Free pages plus prefix-cache pages evictable under pressure —
        the admission headroom (``free_pages`` alone undercounts once the
        cache holds refcount-1 pages the allocator can LRU-reclaim)."""
        if not self.paged:
            return 0
        n = len(self._free_pages)
        if self.prefix_cache is not None:
            n += self.prefix_cache.collectable(
                lambda pid: self._page_rc[pid] == 1)
        return n

    @property
    def reserved_pages(self) -> int:
        """Free pages currently held back for a parked queue head."""
        return self._reserved_pages if self.paged else 0

    def reserve_pages(self, n: int) -> None:
        """Hold ``n`` free pages back from decode-time growth (the
        batcher's aging guard: a queue head deferred too long on
        ``free_pages`` gets freed pages *reserved* instead of watching
        running rows' ``_grow_pages`` consume them forever). Reserved
        pages are still visible to :meth:`prefill` — the head's admission
        is exactly what they are being saved for. ``n=0`` releases the
        reservation. Rows that cannot cover their next write because of a
        reservation are evicted through the ordinary page-exhaustion path
        (explicit ``page_exhausted`` finish, never a hang)."""
        if not self.paged:
            return
        self._reserved_pages = max(0, int(n))
        _obs.gauge("gen_pages_reserved",
                   "free pages held back for a parked queue head").set(
                       self._reserved_pages)

    def _page_gauges(self):
        free = len(self._free_pages)
        _obs.gauge("gen_pages_free",
                   "free pages in the paged KV pool").set(free)
        _obs.gauge("gen_pages_in_use",
                   "allocated pages in the paged KV pool").set(
                       self.num_pages - free)
        _obs.gauge("gen_page_refcount_max",
                   "highest per-page refcount (sharing depth)").set(
                       int(self._page_rc.max()) if self.num_pages else 0)

    def _unref_pages(self, pages) -> int:
        """Drop one reference from each page; refcount-0 pages return to
        the free list (the trash-page-safe reclaim contract: a page still
        backing another row or the prefix cache stays allocated)."""
        freed = 0
        for pid in pages:
            self._page_rc[pid] -= 1
            if self._page_rc[pid] <= 0:
                self._page_rc[pid] = 0
                self._free_pages.append(pid)
                freed += 1
        return freed

    def _reclaim_row(self, slot: int) -> int:
        pages = self._row_pages[slot]
        if not pages:
            return 0
        self._row_pages[slot] = []
        freed = self._unref_pages(pages)
        if freed:
            _obs.counter("gen_pages_reclaimed_total",
                         "pages returned to the free pool").inc(freed)
        self._page_gauges()
        return freed

    def _avail(self) -> int:
        # pages past the reservation are off-limits to growth: they are
        # being accumulated for a parked queue head (reserve_pages)
        return len(self._free_pages) - self._reserved_pages

    def _evict_prefix(self, n: int, protect=()) -> int:
        """Free up to ``n`` pages by LRU-evicting cache-only (refcount-1)
        prefix-cache entries. Pages still shared with a live row are
        refused by the predicate."""
        if self.prefix_cache is None:
            return 0
        evicted = self.prefix_cache.evict(
            n, lambda pid: self._page_rc[pid] == 1, protect=protect)
        if evicted:
            self._unref_pages(evicted)
            _obs.counter("gen_prefix_evictions_total",
                         "prefix-cache pages evicted under free-page "
                         "pressure").inc(len(evicted))
            self._page_gauges()
        return len(evicted)

    def _take_page(self) -> int:
        """One page off the free list (refcount 1), LRU-evicting prefix
        cache entries under pressure. Returns 0 (the trash page id —
        never allocated) when nothing can be freed."""
        if self._avail() <= 0 and not self._evict_prefix(1):
            return 0
        pid = self._free_pages.popleft()
        self._page_rc[pid] = 1
        return pid

    def _grow_pages(self, window: int):
        """Allocate pages so every active row's table covers positions
        ``p .. min(p + window, max_length - 1)``; rows that cannot even
        cover their next write are force-finished (evicted) with
        ``gen_page_evictions_total``. Shared (refcount > 1) pages inside
        the write window get a private copy first — the copy-on-write
        point: the compiled copy program runs before the decode dispatch,
        so a forked row's writes can never mutate a page another row or
        the prefix cache still reads. Returns the (B, U) update vectors
        the compiled program scatters into the page-table carry."""
        ps = self.page_size
        upd_slots = np.zeros((self.batch_size, self._upd_width), np.int32)
        upd_pages = np.zeros((self.batch_size, self._upd_width), np.int32)
        allocated = 0
        copies = []  # (row, slot, src, dst) for the compiled copy program

        def _evict_row(row):
            self.done[row] = True
            self.page_exhausted[row] = True
            _obs.counter(
                "gen_page_evictions_total",
                "rows force-finished on page exhaustion").inc(
                    reason="exhausted")

        for row in range(self.batch_size):
            if self.done[row]:
                continue
            p = int(self.positions[row])
            need = min(p + window, self.max_length - 1) // ps + 1
            # copy-on-write: every existing page slot the window writes
            # into must be private before the next program dispatches
            short = False
            for s in range(p // ps, min(need, len(self._row_pages[row]))):
                pid = self._row_pages[row][s]
                if self._page_rc[pid] <= 1:
                    continue
                new = self._take_page()
                if not new:
                    short = True
                    break
                allocated += 1
                copies.append((row, s, pid, new))
                self._page_rc[pid] -= 1
                self._row_pages[row][s] = new
            if short:
                # a shared page it cannot copy = a write it cannot make
                _evict_row(row)
                continue
            u = 0
            while len(self._row_pages[row]) < need:
                pid = self._take_page()
                if not pid:
                    if len(self._row_pages[row]) * ps <= p:
                        # cannot write the next token: evict the row
                        _evict_row(row)
                    break
                upd_slots[row, u] = len(self._row_pages[row])
                upd_pages[row, u] = pid
                self._row_pages[row].append(pid)
                u += 1
                allocated += 1
        if allocated:
            _obs.counter("gen_page_allocs_total",
                         "pages taken from the free pool").inc(
                             allocated, site="decode")
            self._page_gauges()
        self._dispatch_cow(copies)
        return upd_slots, upd_pages

    def _dispatch_cow(self, copies) -> None:
        """Run the page-granular copy-on-write program: each (row, slot,
        src, dst) entry copies pool page ``src`` into the private ``dst``
        (every layer; target AND draft pools on a speculative engine) and
        repoints the row's page-table entry — all in-program on the
        donated carry, BEFORE the step program that writes. Entries are
        chunked to a fixed width so the copy program never relowers."""
        if not copies:
            return
        if self._cow_jit is None:
            self._cow_jit = jax.jit(self._cow_copy_fn, donate_argnums=(0,),
                                    keep_unused=True)
        W = self._cow_width
        for i in range(0, len(copies), W):
            chunk = copies[i:i + W]
            rows = np.zeros(W, np.int32)
            slots = np.zeros(W, np.int32)
            src = np.zeros(W, np.int32)  # dst=0 pads: trash-page no-ops
            dst = np.zeros(W, np.int32)
            for j, (r, s, sp, dp) in enumerate(chunk):
                rows[j], slots[j], src[j], dst[j] = r, s, sp, dp
            self._note_program(("cow", W), "cow_copy")
            if self.speculative:
                carry = (self.page_table, self.pools, self.draft_pools)
                carry = self._cow_jit(carry, jnp.asarray(rows),
                                      jnp.asarray(slots), jnp.asarray(src),
                                      jnp.asarray(dst))
                self.page_table, self.pools, self.draft_pools = carry
            else:
                carry = self._cow_jit((self.page_table, self.pools),
                                      jnp.asarray(rows), jnp.asarray(slots),
                                      jnp.asarray(src), jnp.asarray(dst))
                self.page_table, self.pools = carry
        _obs.counter("gen_cow_copies_total",
                     "copy-on-write page copies").inc(len(copies))

    def _take_clear_mask(self):
        """Rows released since the last dispatch: their device page-table
        rows are zeroed in-program BEFORE any write, so writes of a
        released row can never land in a page the allocator has already
        handed to someone else (they go to the trash page instead)."""
        clear = np.zeros(self.batch_size, bool)
        for s in self._pending_clear:
            clear[s] = True
        self._pending_clear.clear()
        return clear

    # -- sampling (compiled into both programs) ------------------------------
    def _sample(self, logits2d, key):
        cfg = self.sampling
        if cfg.method == "greedy":
            return jnp.argmax(logits2d, axis=-1).astype(jnp.int32)
        if cfg.method == "temperature":
            return _rops.temperature_sampling(
                logits2d, temperature=cfg.temperature, key=key)
        return _rops.top_k_sampling(logits2d, k=cfg.top_k,
                                    temperature=cfg.temperature, key=key)

    def _sample_logits(self, logits):
        """The EXACT logit transform the stochastic samplers draw through
        (ops/random_ops.py): optional top-k masking, then temperature
        scaling. ``softmax`` of the result is the sampling distribution —
        the p and q of the rejection-sampling verify must match it
        bit-for-bit or acceptance tests would drift off the plain-decode
        distribution."""
        cfg = self.sampling
        if cfg.method == "top_k":
            k, vocab = int(cfg.top_k), logits.shape[-1]
            if 0 < k < vocab:
                kth = jax.lax.top_k(logits, k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
        return logits.astype(jnp.float32) / float(cfg.temperature)

    def _next_key(self):
        if not self.sampling.stochastic:
            if self._fixed_key is None:
                self._fixed_key = jax.random.key(self.sampling.seed)
            return self._fixed_key
        if self._key is None:
            self._key = jax.random.key(self.sampling.seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def _params(self):
        return tuple(p._nd._data for p in self._plist)

    def _last_vocab(self) -> int:
        """Logits width of the target model (the tied word embedding's
        input dim) — shape info for audit()'s stochastic-verify dummy."""
        return int(self.net.word_embed._input_dim)

    def _draft_params(self):
        return tuple(p._nd._data for p in self._draft_plist)

    def _cache_nd(self, pools):
        return [(NDArray(k), NDArray(v)) for k, v in pools]

    # -- pure programs (dense) -----------------------------------------------
    def _prefill_fn(self, params, cache, tokens, slot, length, key):
        """(params, cache, (1, Lb) tokens, slot, real length, key) ->
        (cache', first sampled token, last-prompt-position logits)."""
        row_cache = [tuple(jax.lax.dynamic_slice_in_dim(b, slot, 1, axis=0)
                           for b in layer) for layer in cache]
        start = jnp.zeros((1,), jnp.int32)
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_rows = self.net(
                NDArray(tokens),
                cache=[(NDArray(k), NDArray(v)) for k, v in row_cache],
                start_pos=NDArray(start))
        logits = logits._data  # (1, Lb, vocab)
        new_cache = [
            tuple(jax.lax.dynamic_update_slice_in_dim(full, row._data, slot,
                                                      axis=0)
                  for full, row in zip(layer, rows))
            for layer, rows in zip(cache, new_rows)]
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                            keepdims=False)[0]  # (vocab,)
        tok = self._sample(last[None, :], key)[0].astype(jnp.int32)
        return new_cache, tok, last

    def _decode_fn(self, params, cache, tokens, positions, done, key):
        """One token for every row: (cache', next tokens, done', logits).
        Finished rows emit ``pad_id`` and keep their cache frontier."""
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_cache = self.net(
                NDArray(tokens.reshape(self.batch_size, 1)),
                cache=[(NDArray(k), NDArray(v)) for k, v in cache],
                start_pos=NDArray(positions))
        logits = logits._data[:, 0]  # (B, vocab)
        sampled = self._sample(logits, key)
        next_tok = jnp.where(done, jnp.int32(self.pad_id), sampled)
        if self.eos_id is not None:
            done = done | (sampled == self.eos_id)
        new_cache = [tuple(b._data for b in layer) for layer in new_cache]
        return new_cache, next_tok.astype(jnp.int32), done, logits

    # -- pure programs (paged) -----------------------------------------------
    def _apply_table_updates(self, table, upd_slots, upd_pages, clear):
        """Scatter the host allocator's decisions into the page-table carry:
        install newly allocated pages ((B, U) slot/page vectors, page 0 =
        no-op), then zero the rows of released slots."""
        bidx = jnp.arange(self.batch_size, dtype=jnp.int32)[:, None]
        cur = table[bidx, upd_slots]
        table = table.at[bidx, upd_slots].set(
            jnp.where(upd_pages > 0, upd_pages, cur))
        return jnp.where(clear[:, None], 0, table)

    def _paged_prefill_fn(self, params, carry, tokens, slot, length,
                          new_row, start, key):
        """Paged admission: install the row's freshly allocated page table,
        run the cached causal forward through the pools (scatter writes land
        only in this row's pages + trash), sample the TTFT token. ``start``
        ((1,) int32, traced) is the adopted-prefix length: a prefix-cache
        hit runs only the suffix through this same per-bucket program
        (cold prefill passes 0 — no extra lowering)."""
        table, pools = carry
        table = jax.lax.dynamic_update_slice(table, new_row[None, :],
                                             (slot, 0))
        row_table = jax.lax.dynamic_slice(table, (slot, 0),
                                          (1, self._n_row_pages))
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_pools = self.net(
                NDArray(tokens), cache=self._cache_nd(pools),
                start_pos=NDArray(start), page_table=NDArray(row_table))
        logits = logits._data  # (1, Lb, vocab)
        new_pools = [tuple(b._data for b in layer) for layer in new_pools]
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                            keepdims=False)[0]
        tok = self._sample(last[None, :], key)[0].astype(jnp.int32)
        return (table, new_pools), tok, last

    def _spec_prefill_fn(self, params, dparams, carry, tokens, slot, length,
                         new_row, start, key):
        """Speculative admission: one program writes the prompt's K/V into
        BOTH the target and the draft page pools (shared page table)."""
        table, pools, dpools = carry
        table = jax.lax.dynamic_update_slice(table, new_row[None, :],
                                             (slot, 0))
        row_table = jax.lax.dynamic_slice(table, (slot, 0),
                                          (1, self._n_row_pages))
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_pools = self.net(
                NDArray(tokens), cache=self._cache_nd(pools),
                start_pos=NDArray(start), page_table=NDArray(row_table))
        with _HybridTrace(self._draft_plist, list(dparams), False, key):
            _, new_dpools = self.draft_net(
                NDArray(tokens), cache=self._cache_nd(dpools),
                start_pos=NDArray(start), page_table=NDArray(row_table))
        logits = logits._data
        new_pools = [tuple(b._data for b in layer) for layer in new_pools]
        new_dpools = [tuple(b._data for b in layer) for layer in new_dpools]
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                            keepdims=False)[0]
        tok = self._sample(last[None, :], key)[0].astype(jnp.int32)
        return (table, new_pools, new_dpools), tok, last

    def _paged_decode_fn(self, params, carry, tokens, positions, done,
                         upd_slots, upd_pages, clear, key):
        """The paged decode step: apply page-table updates, then exactly the
        dense decode semantics with pool-indirect storage."""
        table, pools = carry
        table = self._apply_table_updates(table, upd_slots, upd_pages, clear)
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_pools = self.net(
                NDArray(tokens.reshape(self.batch_size, 1)),
                cache=self._cache_nd(pools), start_pos=NDArray(positions),
                page_table=NDArray(table))
        logits = logits._data[:, 0]
        sampled = self._sample(logits, key)
        next_tok = jnp.where(done, jnp.int32(self.pad_id), sampled)
        if self.eos_id is not None:
            done = done | (sampled == self.eos_id)
        new_pools = [tuple(b._data for b in layer) for layer in new_pools]
        return (table, new_pools), next_tok.astype(jnp.int32), done, logits

    def _draft_fn(self, dparams, carry, tokens, positions, done,
                  upd_slots, upd_pages, clear, key):
        """Draft k tokens greedily through the draft model's paged cache —
        the whole loop is ONE ``lax.scan`` program (one dispatch per
        speculative round, not k). The scan runs k+1 steps: step i consumes
        token i (t0, d1, …) writing its K/V at position p+i, so the LAST
        drafted token's entry lands at p+k too — on a full accept the
        frontier advances past it, and a skipped write there would leave a
        permanent zero-K/V hole below the draft frontier. The k+1-th
        sampled token is discarded."""
        table, pools = carry
        table = self._apply_table_updates(table, upd_slots, upd_pages, clear)

        def step(c, i):
            pools_c, tok = c
            with _HybridTrace(self._draft_plist, list(dparams), False, key):
                logits, new_pools = self.draft_net(
                    NDArray(tok.reshape(self.batch_size, 1)),
                    cache=self._cache_nd(pools_c),
                    start_pos=NDArray(positions + i),
                    page_table=NDArray(table))
            new_pools = [tuple(b._data for b in layer)
                         for layer in new_pools]
            nxt = jnp.argmax(logits._data[:, 0], axis=-1).astype(jnp.int32)
            return (new_pools, nxt), nxt

        (pools, _), drafted = jax.lax.scan(
            step, (pools, tokens),
            jnp.arange(self.speculate_k + 1, dtype=jnp.int32))
        return (table, pools), drafted[:self.speculate_k].T  # (B, k)

    def _verify_fn(self, params, carry, tokens, drafted, positions, done,
                   room, key):
        """One target forward scores all k+1 positions: the longest drafted
        prefix the target's own greedy choices agree with is accepted, plus
        the target's correction token. Emission stops at the first EOS and
        at ``room`` (remaining page-covered capacity); rejected tails just
        don't advance the frontier — their K/V entries stay masked and are
        overwritten next round. Returns (carry', (B, k+1) emitted tokens
        padded with pad_id, per-row emit counts, done', accept counts)."""
        table, pools = carry
        k = self.speculate_k
        x = jnp.concatenate([tokens[:, None], drafted], axis=1)  # (B, k+1)
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_pools = self.net(
                NDArray(x), cache=self._cache_nd(pools),
                start_pos=NDArray(positions), page_table=NDArray(table))
        logits = logits._data  # (B, k+1, vocab)
        new_pools = [tuple(b._data for b in layer) for layer in new_pools]
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # greedy next
        match = (drafted == g[:, :k]).astype(jnp.int32)
        acc = jnp.cumprod(match, axis=1).sum(axis=1)  # accepted drafts
        m = acc + 1  # + the target's correction/bonus token
        if self.eos_id is not None:
            is_eos = g == self.eos_id
            first = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
            m = jnp.minimum(m, jnp.where(is_eos.any(axis=1), first + 1,
                                         k + 1))
        m = jnp.minimum(m, jnp.maximum(room, 0))
        m = jnp.where(done, 0, m)
        emit = jnp.arange(k + 1, dtype=jnp.int32)[None, :] < m[:, None]
        out = jnp.where(emit, g, jnp.int32(self.pad_id))
        if self.eos_id is not None:
            done = done | (emit & (g == self.eos_id)).any(axis=1)
        return (table, new_pools), out, m, done, acc

    def _cow_copy_fn(self, carry, rows, slots, src, dst):
        """The copy-on-write program: page-granular pool copies on the
        donated carry. For each entry, pool page ``src`` is copied into
        the freshly allocated ``dst`` in every layer (target and draft
        pools share page tables, so a speculative engine copies both) and
        the owning row's page-table slot is repointed. Padding entries
        carry ``dst == 0``: their copy lands in the trash page (garbage
        by contract) and the table is left untouched."""
        if self.speculative:
            table, pools, dpools = carry
        else:
            (table, pools), dpools = carry, None

        def copy(ps):
            return [tuple(b.at[dst].set(b[src]) for b in layer)
                    for layer in ps]

        pools = copy(pools)
        if dpools is not None:
            dpools = copy(dpools)
        cur = table[rows, slots]
        table = table.at[rows, slots].set(jnp.where(dst > 0, dst, cur))
        return ((table, pools, dpools) if self.speculative
                else (table, pools))

    def _draft_sample_fn(self, dparams, carry, tokens, positions, done,
                         upd_slots, upd_pages, clear, key):
        """Stochastic draft scan (rejection-sampling speculation): the
        same k+1-step structure as :meth:`_draft_fn`, but each next token
        is SAMPLED from the draft's own decoding distribution q (the
        identical top-k/temperature transform plain decode compiles in),
        and q itself is recorded per drafted token — the verify program's
        ``min(1, p/q)`` accept test needs it. Returns ``(carry',
        (B, k) drafted tokens, (B, k, V) q distributions)``."""
        table, pools = carry
        table = self._apply_table_updates(table, upd_slots, upd_pages, clear)

        def step(c, i):
            pools_c, tok = c
            with _HybridTrace(self._draft_plist, list(dparams), False, key):
                logits, new_pools = self.draft_net(
                    NDArray(tok.reshape(self.batch_size, 1)),
                    cache=self._cache_nd(pools_c),
                    start_pos=NDArray(positions + i),
                    page_table=NDArray(table))
            new_pools = [tuple(b._data for b in layer)
                         for layer in new_pools]
            lg = self._sample_logits(logits._data[:, 0])  # (B, V)
            q = jax.nn.softmax(lg, axis=-1)
            nxt = jax.random.categorical(
                jax.random.fold_in(key, i), lg, axis=-1).astype(jnp.int32)
            return (new_pools, nxt), (nxt, q)

        (pools, _), (drafted, qdist) = jax.lax.scan(
            step, (pools, tokens),
            jnp.arange(self.speculate_k + 1, dtype=jnp.int32))
        k = self.speculate_k
        # drafted: (k+1, B) -> (B, k); qdist: (k+1, B, V) -> (B, k, V)
        return (table, pools), drafted[:k].T, jnp.moveaxis(qdist[:k], 0, 1)

    def _verify_sample_fn(self, params, carry, tokens, drafted, qdist,
                          positions, done, room, key):
        """Rejection-sampling verify: one target forward scores all k+1
        positions; drafted token x_i is accepted with probability
        ``min(1, p_i(x_i)/q_i(x_i))`` (uniform draw), the first rejection
        is resampled from the normalized residual ``max(p_i - q_i, 0)``,
        and a full accept earns a bonus token drawn from p_k — the
        standard speculative-sampling rule, so the emitted tokens are
        distributed EXACTLY as plain sampled decode (gated statistically
        in tests). EOS/room/done clamps mirror the greedy verify."""
        table, pools = carry
        k = self.speculate_k
        B = self.batch_size
        x = jnp.concatenate([tokens[:, None], drafted], axis=1)  # (B, k+1)
        with _HybridTrace(self._plist, list(params), False, key):
            logits, new_pools = self.net(
                NDArray(x), cache=self._cache_nd(pools),
                start_pos=NDArray(positions), page_table=NDArray(table))
        logits = logits._data  # (B, k+1, vocab)
        new_pools = [tuple(b._data for b in layer) for layer in new_pools]
        p = jax.nn.softmax(self._sample_logits(logits), axis=-1)
        bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
        iidx = jnp.arange(k, dtype=jnp.int32)[None, :]
        p_tok = p[:, :k][bidx, iidx, drafted]  # (B, k) target prob of draft
        q_tok = qdist[bidx, iidx, drafted]     # (B, k) draft prob of draft
        ukey, rkey = jax.random.split(jax.random.fold_in(key, 7))
        u = jax.random.uniform(ukey, (B, k), jnp.float32)
        # u < p/q  <=>  u*q < p (q(x) > 0 a.s.: x was sampled from q)
        accept = (u * q_tok < p_tok).astype(jnp.int32)
        acc = jnp.cumprod(accept, axis=1).sum(axis=1)  # accepted drafts
        # the token at out-index `acc`: residual resample on a rejection,
        # the bonus draw from p_k on a full accept. All k+1 candidate
        # distributions are sampled at once, then gathered at acc.
        resid = jnp.maximum(p[:, :k] - qdist, 0.0)  # (B, k, V)
        rs = resid.sum(axis=-1, keepdims=True)
        # p == q exactly -> empty residual: any draw from p is unbiased
        resid = jnp.where(rs > 0, resid / jnp.maximum(rs, 1e-30), p[:, :k])
        cand = jnp.concatenate([resid, p[:, k:]], axis=1)  # (B, k+1, V)
        corr = jax.random.categorical(
            rkey, jnp.log(jnp.maximum(cand, 1e-38)), axis=-1).astype(
                jnp.int32)  # (B, k+1)
        correction = corr[jnp.arange(B, dtype=jnp.int32), acc]
        pos_idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        padded = jnp.concatenate(
            [drafted, jnp.zeros((B, 1), jnp.int32)], axis=1)
        g = jnp.where(pos_idx < acc[:, None], padded,
                      jnp.where(pos_idx == acc[:, None], correction[:, None],
                                jnp.int32(self.pad_id)))
        m = acc + 1
        if self.eos_id is not None:
            is_eos = (g == self.eos_id) & (pos_idx <= acc[:, None])
            first = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
            m = jnp.minimum(m, jnp.where(is_eos.any(axis=1), first + 1,
                                         k + 1))
        m = jnp.minimum(m, jnp.maximum(room, 0))
        m = jnp.where(done, 0, m)
        emit = pos_idx < m[:, None]
        out = jnp.where(emit, g, jnp.int32(self.pad_id))
        if self.eos_id is not None:
            done = done | (emit & (out == self.eos_id)).any(axis=1)
        return (table, new_pools), out, m, done, acc

    # -- host API ------------------------------------------------------------
    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if b >= length:
                return b
        raise ValueError(f"prompt length {length} exceeds largest prefill "
                         f"bucket {self.prefill_buckets[-1]}")

    def prefill(self, prompt, slot: int) -> int:
        """Admit a prompt into row ``slot``: write its K/V into the cache,
        sample the first new token (returned as a host int — this sync is
        the time-to-first-token point). Never touches other rows. In paged
        mode, allocates ``pages_for(len(prompt))`` pages up front and raises
        RuntimeError if the pool cannot cover them (the batcher checks
        ``free_pages`` before admitting)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        length = prompt.size
        if not 0 < length:
            raise ValueError("empty prompt")
        if not 0 <= slot < self.batch_size:
            raise ValueError(f"slot {slot} out of range")
        # fault site BEFORE any allocator mutation: a retried admission
        # (ContinuousBatcher wraps prefill in retry_call) must replay
        # against untouched page/clear state
        _faults.fire("gen.prefill")
        t0 = time.perf_counter()
        if self.paged:
            if length >= self.max_length:
                raise ValueError(f"prompt length {length} >= max_length="
                                 f"{self.max_length}")
            ps = self.page_size
            total = self.pages_for(length)
            # prefix adoption: walk the radix cache for the longest cached
            # page run, keeping >= 1 suffix token so this prefill still
            # produces the last-prompt-position logits (the TTFT sample)
            adopt: List[int] = []
            tail_src = 0
            start = 0
            if self.prefix_cache is not None:
                cpages, mtok = self.prefix_cache.lookup(prompt.tolist())
                start = min(mtok, length - 1)
                adopt = cpages[:start // ps]
                if start % ps:
                    # adoption ends inside a cached page: CoW-copy it into
                    # a private page — stale positions past `start` stay
                    # frontier-masked until the suffix overwrites them
                    tail_src = cpages[start // ps]
            suffix = length - start
            bucket = self.bucket_for(suffix)
            need = total - len(adopt)
            # capacity check BEFORE any allocator mutation: a failed
            # admission must leave the slot's pending table-clear (and its
            # reclaimable pages) untouched, or a released row's stale
            # device table could keep pointing at pages later handed to
            # someone else (its masked writes would corrupt them). Pages
            # being adopted are off-limits to the eviction headroom.
            protect = set(adopt)
            if tail_src:
                protect.add(tail_src)
            own = sum(1 for pid in self._row_pages[slot]
                      if self._page_rc[pid] == 1 and pid not in protect)
            headroom = len(self._free_pages) + own
            if headroom < need and self.prefix_cache is not None:
                headroom += self.prefix_cache.collectable(
                    lambda pid: self._page_rc[pid] == 1, protect=protect)
            if headroom < need:
                raise RuntimeError(
                    f"insufficient free pages for a {length}-token prompt "
                    f"({need} needed, {len(self._free_pages)} free); release "
                    "slots or raise num_pages")
            self._reclaim_row(slot)  # previous occupant's pages, if any
            self._pending_clear.discard(slot)  # the new row replaces it
            self.page_exhausted[slot] = False
            short = need - len(self._free_pages)
            if short > 0:
                self._evict_prefix(short, protect=protect)
            for pid in adopt:  # adopted prefix: refcount bump, no compute
                self._page_rc[pid] += 1
            fresh = []
            for _ in range(need):
                pid = self._free_pages.popleft()
                self._page_rc[pid] = 1
                fresh.append(pid)
            pages = adopt + fresh
            self._row_pages[slot] = list(pages)
            if need:
                _obs.counter("gen_page_allocs_total",
                             "pages taken from the free pool").inc(
                                 need, site="prefill")
            if start:
                _obs.counter("gen_prefix_hits_total",
                             "prefills that adopted a cached prefix").inc()
                _obs.counter("gen_prefix_hit_tokens",
                             "prompt tokens served from the prefix "
                             "cache").inc(int(start))
            self._page_gauges()
            if tail_src:
                # the copy must land before the prefill dispatch writes
                # the suffix into the same page
                self._dispatch_cow([(slot, len(adopt), tail_src, fresh[0])])
            padded = np.full((1, bucket), self.pad_id, np.int32)
            padded[0, :suffix] = prompt[start:]
            new_row = np.zeros(self._n_row_pages, np.int32)
            new_row[:total] = pages
            self._note_program(("prefill", bucket), "prefill_bucket")
            start_v = jnp.full((1,), start, jnp.int32)
            if self.speculative:
                carry = (self.page_table, self.pools, self.draft_pools)
                carry, tok, last = self._prefill_jit(
                    self._params(), self._draft_params(), carry,
                    jnp.asarray(padded), jnp.asarray(slot, jnp.int32),
                    jnp.asarray(suffix, jnp.int32), jnp.asarray(new_row),
                    start_v, self._next_key())
                self.page_table, self.pools, self.draft_pools = carry
            else:
                carry, tok, last = self._prefill_jit(
                    self._params(), (self.page_table, self.pools),
                    jnp.asarray(padded), jnp.asarray(slot, jnp.int32),
                    jnp.asarray(suffix, jnp.int32), jnp.asarray(new_row),
                    start_v, self._next_key())
                self.page_table, self.pools = carry
        else:
            bucket = self.bucket_for(length)
            padded = np.full((1, bucket), self.pad_id, np.int32)
            padded[0, :length] = prompt
            self._note_program(("prefill", bucket), "prefill_bucket")
            cache, tok, last = self._prefill_jit(
                self._params(), self.cache, jnp.asarray(padded),
                jnp.asarray(slot, jnp.int32), jnp.asarray(length, jnp.int32),
                self._next_key())
            self.cache = cache
        tok = int(tok)  # host sync: the first token is ready here
        self.positions[slot] = length
        self.last_tokens[slot] = tok
        self.done[slot] = (self.eos_id is not None and tok == self.eos_id)
        if self.paged:
            self._prefill_logits[slot] = last
            if self.prefix_cache is not None:
                # index this prompt's full pages so later prompts sharing
                # the prefix adopt them (newly indexed pages gain a cache
                # reference; already-cached prefixes are kept as-is)
                for pid in self.prefix_cache.insert(prompt.tolist(),
                                                    self._row_pages[slot]):
                    self._page_rc[pid] += 1
                self._page_gauges()
        if _obs.enabled():
            _obs.histogram("gen_prefill_seconds", "prompt prefill wall clock",
                           unit="s").observe(time.perf_counter() - t0,
                                             bucket=bucket)
        self._last_logits = last
        return tok

    def decode_step(self):
        """One compiled step over the whole batch. Returns
        ``(next_tokens (B,) np.int32, done (B,) np.bool_, logits (B, V)
        device array)``. Rows that were already done emit ``pad_id``."""
        if self.speculative:
            raise RuntimeError("speculative engine decodes in rounds; "
                               "use spec_step() (or plain_step() for the "
                               "degrade-to-plain fallback)")
        return self._plain_decode_step()

    def plain_step(self):
        """One plain (non-speculative) decode step on ANY engine — the
        degrade-to-safe path of a speculative engine when the accept rate
        collapses (docs/RESILIENCE.md "Serving resilience"): one dispatch
        per token through the same paged pools, greedy-token-identical to
        the speculative rounds. The draft model's cache is NOT written
        during fallback, so rows decoded here have draft-cache holes after
        a re-arm — an accept-rate cost only, never a correctness one."""
        return self._plain_decode_step()

    def _plain_decode_step(self):
        _faults.fire("gen.decode")
        t0 = time.perf_counter()
        if self.paged:
            upd_slots, upd_pages = self._grow_pages(0)
            clear = self._take_clear_mask()
            active_in = ~self.done  # exhaustion may have finished rows
            if self.speculative:
                # the spec engine compiled draft+verify, not a single-token
                # decode: lower the fallback program lazily on first use
                # (counted like every other program lowering)
                if getattr(self, "_plain_decode_jit", None) is None:
                    self._plain_decode_jit = jax.jit(
                        self._paged_decode_fn, donate_argnums=(1,),
                        keep_unused=True)
                decode_jit = self._plain_decode_jit
            else:
                decode_jit = self._decode_jit
            self._note_program(("decode", self.batch_size, "paged"), "decode")
            carry, tok, done, logits = decode_jit(
                self._params(), (self.page_table, self.pools),
                jnp.asarray(self.last_tokens), jnp.asarray(self.positions),
                jnp.asarray(self.done), jnp.asarray(upd_slots),
                jnp.asarray(upd_pages), jnp.asarray(clear), self._next_key())
            self.page_table, self.pools = carry
        else:
            active_in = ~self.done
            self._note_program(("decode", self.batch_size), "decode")
            cache, tok, done, logits = self._decode_jit(
                self._params(), self.cache, jnp.asarray(self.last_tokens),
                jnp.asarray(self.positions), jnp.asarray(self.done),
                self._next_key())
            self.cache = cache
        # np.array (copy): zero-copy views of jax buffers are read-only,
        # and this host state is mutated by release_slot/prefill
        tok = np.array(tok)
        done = np.array(done)
        # rows active going into the step consumed one cache index
        self.positions = self.positions + active_in.astype(np.int32)
        # a row whose frontier hit the buffer end cannot take another token
        full = active_in & (self.positions >= self.max_length)
        if full.any():
            done = done | full
            _obs.counter("gen_cache_overflow_total",
                         "rows force-finished at the KV-cache end").inc(
                             int(full.sum()))
        self.done = done
        self.last_tokens = tok
        if _obs.enabled():
            dt = time.perf_counter() - t0
            _obs.histogram("gen_decode_step_seconds",
                           "one compiled decode step wall clock",
                           unit="s").observe(dt)
            # slot utilization of this step: fraction of the static batch
            # that decoded real tokens (the fleet report's serving rollup)
            _obs.gauge("gen_slot_utilization",
                       "fraction of decode slots active this step").set(
                           float(active_in.sum()) / self.batch_size)
        return tok, done, logits

    def spec_step(self):
        """One speculative round: ONE draft dispatch (k tokens through the
        draft cache, compiled scan) + ONE verify dispatch (target scores all
        k+1 positions). Returns ``(tokens (B, k+1) np.int32 padded with
        pad_id, counts (B,) np.int32 emitted per row, done (B,)
        np.bool_)``. Greedy output is token-identical to decode_step
        driven to the same length."""
        if not self.speculative:
            raise RuntimeError("spec_step() needs draft_net=/speculate_k=")
        _faults.fire("gen.decode")  # before any allocator mutation: the
        # batcher's retry_call replays the whole round cleanly
        k = self.speculate_k
        t0 = time.perf_counter()
        upd_slots, upd_pages = self._grow_pages(k)
        clear = self._take_clear_mask()
        active_in = ~self.done  # exhaustion may have finished rows
        # committed entries may only land in page-covered positions: the
        # verify program clamps per-row emission to this window
        room = np.zeros(self.batch_size, np.int32)
        for row in range(self.batch_size):
            covered = len(self._row_pages[row]) * self.page_size
            room[row] = min(covered, self.max_length) \
                - int(self.positions[row])
        key = self._next_key()
        self._note_program(("draft", self.batch_size, k), "decode")
        stochastic = self.sampling.stochastic
        qdist = None
        if stochastic:
            # rejection-sampling round: the draft records its sampling
            # distribution q per drafted token, device-resident into verify
            (table, dpools), drafted, qdist = self._draft_jit(
                self._draft_params(), (self.page_table, self.draft_pools),
                jnp.asarray(self.last_tokens), jnp.asarray(self.positions),
                jnp.asarray(self.done), jnp.asarray(upd_slots),
                jnp.asarray(upd_pages), jnp.asarray(clear), key)
        else:
            (table, dpools), drafted = self._draft_jit(
                self._draft_params(), (self.page_table, self.draft_pools),
                jnp.asarray(self.last_tokens), jnp.asarray(self.positions),
                jnp.asarray(self.done), jnp.asarray(upd_slots),
                jnp.asarray(upd_pages), jnp.asarray(clear), key)
        # commit the draft half's carry BEFORE the verify dispatch: the
        # old page_table buffer was donated to the draft program, and the
        # gen.verify fault site below must leave the engine re-entrant (a
        # retried spec_step re-runs the draft from the same positions —
        # deterministic overwrites of the same cache entries)
        self.page_table, self.draft_pools = table, dpools
        self._note_program(("verify", self.batch_size, k), "verify")

        def _dispatch_verify():
            _faults.fire("gen.verify")
            if stochastic:
                return self._verify_jit(
                    self._params(), (self.page_table, self.pools),
                    jnp.asarray(self.last_tokens), drafted, qdist,
                    jnp.asarray(self.positions), jnp.asarray(self.done),
                    jnp.asarray(room), key)
            return self._verify_jit(
                self._params(), (self.page_table, self.pools),
                jnp.asarray(self.last_tokens), drafted,
                jnp.asarray(self.positions), jnp.asarray(self.done),
                jnp.asarray(room), key)

        (table, pools), out, m, done, acc = _retry.retry_call(
            _dispatch_verify, site="gen.verify", policy=self.retry_policy)
        self.page_table, self.pools = table, pools
        out = np.array(out)
        m = np.array(m)
        done = np.array(done)
        acc = np.array(acc)
        self.positions = self.positions + m.astype(np.int32)
        took = m > 0
        last = out[np.arange(self.batch_size), np.maximum(m - 1, 0)]
        self.last_tokens = np.where(took, last,
                                    self.last_tokens).astype(np.int32)
        full = active_in & (self.positions >= self.max_length)
        if full.any():
            done = done | full
            _obs.counter("gen_cache_overflow_total",
                         "rows force-finished at the KV-cache end").inc(
                             int(full.sum()))
        self.done = done
        n_active = int(active_in.sum())
        _obs.counter("gen_spec_rounds_total",
                     "speculative draft+verify rounds").inc()
        # per-round accept stats for the degradation governor
        # (resilience.serving.SpeculationGovernor reads them after each
        # round the batcher dispatches)
        self.last_round_drafted = k * n_active
        self.last_round_accepted = int(acc[active_in].sum()) if n_active \
            else 0
        if n_active:
            accepted = int(acc[active_in].sum())
            _obs.counter("gen_spec_drafted_tokens_total",
                         "draft tokens proposed").inc(k * n_active)
            _obs.counter("gen_spec_accepted_tokens_total",
                         "draft tokens the target accepted").inc(accepted)
            _obs.counter("gen_spec_emitted_tokens_total",
                         "tokens emitted by speculative rounds").inc(
                             int(m.sum()))
            _obs.gauge("gen_spec_accept_rate",
                       "accepted/drafted ratio of the last round").set(
                           accepted / float(k * n_active))
        if _obs.enabled():
            _obs.histogram("gen_spec_round_seconds",
                           "one draft+verify round wall clock",
                           unit="s").observe(time.perf_counter() - t0)
            _obs.gauge("gen_slot_utilization",
                       "fraction of decode slots active this step").set(
                           float(active_in.sum()) / self.batch_size)
        return out, m, done

    def audit(self, bucket: Optional[int] = None, compile: bool = True,
              program: str = "decode"):
        """Structural :class:`~mxnet_tpu.analysis.ProgramAudit` of a
        serving program (docs/ANALYSIS.md). Default: the decode step —
        ``carry_indices`` are the flat positions of the cache buffers (the
        donated carry: KV buffers, or page table + pools in paged mode), so
        ``audit().carry_donation() == 1.0`` is the in-place-cache-update
        check. With ``bucket=`` the prefill program for that bucket length
        is audited instead (same donated cache). On a speculative engine,
        ``program="decode"`` audits the draft program (its decode-family
        program) and ``program="verify"`` the verify pass. On any paged
        engine ``program="cow"`` audits the copy-on-write page-copy
        program (prefix sharing / forks): carry-only inputs, 100%
        donation, zero collectives.

        ``audit(...).memory`` is the buffer-liveness residency estimate:
        cache bytes appear under the ``kv_pages`` (paged) / ``kv_cache``
        (dense) category, model weights under ``params``, and the
        program's own temporaries under ``activations`` /
        ``draft_temp`` / ``verify_temp`` — including the
        ``kv_gather_materialize`` detector for the paged decode's XLA
        gather of the pool (docs/ANALYSIS.md). ``audit(...).schedule``
        is the static schedule model (critical-path latency, overlap,
        MFU bound — serving programs are collective-free by contract, so
        its exposed-comm census must stay empty)."""
        from .. import analysis as _analysis

        params = self._params()
        n_pre = len(jax.tree_util.tree_leaves(params))
        # constant dummy key: lower() never runs the program, and drawing
        # from _next_key() would advance the stochastic-sampling stream —
        # an audit() between decode steps must not change the tokens
        key = jax.random.key(0)
        toks = jnp.asarray(self.last_tokens)
        pos = jnp.asarray(self.positions)
        done = jnp.asarray(self.done)
        if not self.paged:
            carry = self.cache
            if bucket is None:
                lowered = self._decode_jit.lower(params, carry, toks, pos,
                                                 done, key)
            else:
                bucket = self.bucket_for(bucket)
                tokens = jnp.full((1, bucket), self.pad_id, jnp.int32)
                lowered = self._prefill_jit.lower(
                    params, carry, tokens, jnp.asarray(0, jnp.int32),
                    jnp.asarray(bucket, jnp.int32), key)
        else:
            upd_s = jnp.zeros((self.batch_size, self._upd_width), jnp.int32)
            upd_p = jnp.zeros((self.batch_size, self._upd_width), jnp.int32)
            clear = jnp.zeros((self.batch_size,), bool)
            if bucket is not None:
                bucket = self.bucket_for(bucket)
                tokens = jnp.full((1, bucket), self.pad_id, jnp.int32)
                new_row = jnp.zeros((self._n_row_pages,), jnp.int32)
                start0 = jnp.zeros((1,), jnp.int32)
                if self.speculative:
                    dparams = self._draft_params()
                    n_pre += len(jax.tree_util.tree_leaves(dparams))
                    carry = (self.page_table, self.pools, self.draft_pools)
                    lowered = self._prefill_jit.lower(
                        params, dparams, carry, tokens,
                        jnp.asarray(0, jnp.int32),
                        jnp.asarray(bucket, jnp.int32), new_row, start0,
                        key)
                else:
                    carry = (self.page_table, self.pools)
                    lowered = self._prefill_jit.lower(
                        params, carry, tokens, jnp.asarray(0, jnp.int32),
                        jnp.asarray(bucket, jnp.int32), new_row, start0,
                        key)
            elif program == "cow":
                # the copy-on-write page-copy program: no params at all —
                # the donated carry's leaves lead the flat input order
                if self._cow_jit is None:
                    self._cow_jit = jax.jit(self._cow_copy_fn,
                                            donate_argnums=(0,),
                                            keep_unused=True)
                n_pre = 0
                vec = jnp.zeros((self._cow_width,), jnp.int32)
                if self.speculative:
                    carry = (self.page_table, self.pools, self.draft_pools)
                else:
                    carry = (self.page_table, self.pools)
                lowered = self._cow_jit.lower(carry, vec, vec, vec, vec)
            elif program == "verify":
                if not self.speculative:
                    raise ValueError("program='verify' needs a speculative "
                                     "engine (draft_net=/speculate_k=)")
                carry = (self.page_table, self.pools)
                drafted = jnp.zeros((self.batch_size, self.speculate_k),
                                    jnp.int32)
                room = jnp.zeros((self.batch_size,), jnp.int32)
                if self.sampling.stochastic:
                    vocab = self._last_vocab()
                    qd = jnp.zeros((self.batch_size, self.speculate_k,
                                    vocab), jnp.float32)
                    lowered = self._verify_jit.lower(params, carry, toks,
                                                     drafted, qd, pos,
                                                     done, room, key)
                else:
                    lowered = self._verify_jit.lower(params, carry, toks,
                                                     drafted, pos, done,
                                                     room, key)
            elif self.speculative:
                dparams = self._draft_params()
                n_pre = len(jax.tree_util.tree_leaves(dparams))
                carry = (self.page_table, self.draft_pools)
                lowered = self._draft_jit.lower(dparams, carry, toks, pos,
                                                done, upd_s, upd_p, clear,
                                                key)
            else:
                carry = (self.page_table, self.pools)
                lowered = self._decode_jit.lower(params, carry, toks, pos,
                                                 done, upd_s, upd_p, clear,
                                                 key)
        n_carry = len(jax.tree_util.tree_leaves(carry))
        # flat arg order: (params [+ draft params]) leaves, then the cache
        # leaves (the donated carry)
        lowered_rep = _analysis.audit_lowered(lowered)
        compiled_rep = (_analysis.audit_compiled(lowered.compile())
                        if compile else None)
        # serving programs run mesh-less today, so the comm report is the
        # "no collectives crept into the decode path" check — any priced
        # collective here is a regression tools/shardcheck.py catches
        rep = compiled_rep if compiled_rep is not None else lowered_rep
        comm = _analysis.comm_report(rep)
        # residency estimate with serving categories: the donated cache
        # carry is "kv_pages" (page table + pools) in paged mode and
        # "kv_cache" (per-layer K/V buffers) in dense mode, so genbench's
        # "equal cache memory" claim reads auditor-attributed bytes; the
        # draft/verify programs tag their temporaries distinctly
        kv_cat = "kv_pages" if self.paged else "kv_cache"
        mem_cats = {i: "params" for i in range(n_pre)}
        mem_cats.update({i: kv_cat
                         for i in range(n_pre, n_pre + n_carry)})
        for i in range(n_pre + n_carry, len(rep.inputs)):
            mem_cats[i] = "io"
        if program == "verify":
            default_cat = "verify_temp"
        elif self.speculative and bucket is None and program != "cow":
            default_cat = "draft_temp"
        else:
            default_cat = "activations"
        memory = _analysis.memory_report(rep, categories=mem_cats,
                                         default_category=default_cat)
        # static schedule model over the same (scheduled) report: serving
        # programs are mesh-less today so comm time is zero by contract —
        # the critical path and MFU bound still price the decode step
        schedule = _analysis.schedule_report(rep, comm=comm)
        return _analysis.ProgramAudit(
            lowered=lowered_rep, compiled=compiled_rep,
            carry_indices=tuple(range(n_pre, n_pre + n_carry)),
            comm=comm, memory=memory, schedule=schedule)

    def profile(self, prompt=None, steps: int = 8, warmup: int = 2,
                trace_dir: Optional[str] = None, calibrate: bool = True,
                band: float = 3.0):
        """Trace ``steps`` REAL decode steps (speculative rounds on a
        speculative engine) and return the
        :class:`~mxnet_tpu.observability.profiling.Capture` — the
        measured per-op timeline of the serving hot loop, hot-op ranking
        and measured step time (docs/OBSERVABILITY.md "Measured
        profiling"). The dispatch goes through the engine's own
        ``_decode_jit``/``_draft_jit`` caches, so the traced program IS
        the program continuous batching dispatches. ``prompt`` (default
        a short synthetic one) is prefilled into slot 0 first, outside
        the traced window, so the decode has a live row to extend; the
        slot is released afterwards.

        With ``calibrate=True`` the capture carries per-op-class
        predicted/measured ratios against :meth:`audit`'s schedule model
        of the same decode program."""
        from ..observability import profiling as _profiling

        if prompt is None:
            prompt = list(range(1, 1 + min(4, self.prefill_buckets[0])))
        self.prefill(prompt, slot=0)
        fn = self.spec_step if self.speculative else self.decode_step
        try:
            cap = _profiling.capture(fn, steps=steps, warmup=warmup,
                                     trace_dir=trace_dir)
        finally:
            self.release_slot(0)
        if calibrate:
            cap.schedule = self.audit().schedule
            cap.calibration = _profiling.calibrate(cap.schedule, cap.report,
                                                   band=band)
        return cap

    def fork_slot(self, src: int, dst: int,
                  resample_first: bool = False) -> int:
        """Copy-on-write fork: row ``dst`` becomes a live clone of row
        ``src`` sharing every page — a refcount bump per page, zero pool
        bytes moved. Divergence is lazy: the first write either row makes
        into a shared page triggers the page-granular copy program
        (:meth:`_grow_pages`), so N forks of a P-page prompt cost P pages
        total plus each fork's private suffix.

        ``resample_first=True`` draws an independent first token from the
        source row's prefill logits (N-way parallel sampling: fork right
        after :meth:`prefill`, before any decode step — later forks would
        re-sample a stale position). Returns ``dst``'s current last token.
        """
        if not self.paged:
            raise RuntimeError("fork_slot needs a paged engine")
        if src == dst or not (0 <= src < self.batch_size
                              and 0 <= dst < self.batch_size):
            raise ValueError(f"bad fork {src} -> {dst}")
        if self.done[src] or not self._row_pages[src]:
            raise RuntimeError(f"cannot fork finished/empty row {src}")
        self._reclaim_row(dst)  # previous occupant's pages, if any
        self._pending_clear.discard(dst)
        self.page_exhausted[dst] = False
        pages = list(self._row_pages[src])
        for pid in pages:
            self._page_rc[pid] += 1
        self._row_pages[dst] = pages
        row = np.zeros(self._n_row_pages, np.int32)
        row[:len(pages)] = pages
        # eager device-table install: forks happen at admission
        # boundaries, not in the per-token hot loop
        self.page_table = self.page_table.at[dst].set(jnp.asarray(row))
        self.positions[dst] = self.positions[src]
        tok = int(self.last_tokens[src])
        if resample_first:
            logits = self._prefill_logits.get(src)
            if logits is None:
                raise RuntimeError(f"row {src} has no prefill logits to "
                                   "resample from")
            tok = int(self._sample(logits[None, :], self._next_key())[0])
            self._prefill_logits[dst] = logits
        self.last_tokens[dst] = tok
        self.done[dst] = (self.eos_id is not None and tok == self.eos_id)
        self._page_gauges()
        _obs.counter("gen_forks_total", "copy-on-write row forks").inc()
        return tok

    def cache_sequence(self, slot: int, tokens) -> int:
        """Index a live row's computed pages under ``tokens`` (the
        sequence the row holds K/V for: prompt + generated output) in the
        radix prefix cache — the multi-turn session-resume hook: the
        batcher calls this right before releasing a finished row, and the
        next turn's prompt (history + new text) adopts the whole history
        as a prefix hit. Only positions the row has actually written
        (``positions[slot]``) and only full pages are indexed. Returns
        the number of tokens now served from cache for this sequence."""
        if not self.paged or self.prefix_cache is None:
            return 0
        n = min(len(tokens), int(self.positions[slot]))
        if n < self.page_size:
            return 0
        for pid in self.prefix_cache.insert(list(tokens)[:n],
                                            self._row_pages[slot]):
            self._page_rc[pid] += 1
        self._page_gauges()
        return (n // self.page_size) * self.page_size

    def release_slot(self, slot: int) -> None:
        """Mark a row free (emits pad, frontier frozen) — the next prefill
        into this slot overwrites it. In paged mode, the row's references
        are dropped and only refcount-0 pages return to the free pool
        (pages still backing a fork or the prefix cache stay allocated);
        the row's device page-table row is cleared before the next
        compiled step writes anything."""
        self.done[slot] = True
        self.last_tokens[slot] = self.pad_id
        if self.paged:
            self._reclaim_row(slot)
            self._pending_clear.add(slot)
            self._prefill_logits.pop(slot, None)

    # -- convenience: whole-batch generation ---------------------------------
    def generate(self, prompts, max_new_tokens: int = 32) -> List[List[int]]:
        """Generate up to ``max_new_tokens`` for each prompt (≤ batch_size
        prompts, one slot each). Returns the generated token lists (prompt
        excluded); rows stop at EOS, max_new_tokens, or a full cache."""
        if len(prompts) > self.batch_size:
            raise ValueError(f"{len(prompts)} prompts > batch_size="
                             f"{self.batch_size}; use ContinuousBatcher")
        if self.paged:
            for s in range(self.batch_size):  # park rows + reclaim pages
                self.release_slot(s)
        else:
            self.done[:] = True  # park unused rows
        outs: List[List[int]] = []
        for i, p in enumerate(prompts):
            tok = self.prefill(p, slot=i)
            outs.append([tok])
        while True:
            active = [i for i in range(len(prompts))
                      if not self.done[i] and len(outs[i]) < max_new_tokens]
            if not active:
                break
            if self.speculative:
                toks, counts, _ = self.spec_step()
                for i in active:
                    for j in range(int(counts[i])):
                        if len(outs[i]) >= max_new_tokens:
                            break
                        outs[i].append(int(toks[i, j]))
                    if len(outs[i]) >= max_new_tokens and not self.done[i]:
                        self.release_slot(i)  # cap reached: stop advancing
            else:
                tok, done, _ = self.decode_step()
                for i in active:
                    if (self.paged and done[i]
                            and bool(self.page_exhausted[i])):
                        # evicted BEFORE the dispatch (pool ran dry): the
                        # row emitted pad this step, not a token
                        continue
                    outs[i].append(int(tok[i]))
                    if len(outs[i]) >= max_new_tokens and not self.done[i]:
                        self.release_slot(i)  # cap reached: stop advancing
        return outs
