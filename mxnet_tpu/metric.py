"""Eval metric registry (reference: ``python/mxnet/metric.py``).

Same ``update(labels, preds)`` batch protocol and registry surface. Metric
accumulators stay device-resident (jax scalars) and only sync to host on
``.get()`` — the reference already had this design point (SURVEY §5.5) and it
matters even more on TPU where a per-batch host sync stalls the pipeline.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE", "RMSE",
           "CrossEntropy", "Perplexity", "Loss", "PearsonCorrelation", "MCC",
           "NegativeLogLikelihood", "CustomMetric", "CompositeEvalMetric",
           "create"]


def _as_raw(x):
    return x._data if hasattr(x, "_data") else jnp.asarray(x)


def _listify(x):
    return x if isinstance(x, (list, tuple)) else [x]


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None):
        self.name = name
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = jnp.zeros(())

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(self.sum_metric) / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        return list(zip(_listify(name), _listify(value)))


class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kw):
        self.axis = axis
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label, pred = _as_raw(label), _as_raw(pred)
            if pred.ndim > label.ndim:
                pred = jnp.argmax(pred, axis=self.axis)
            pred = pred.reshape(-1).astype(jnp.int32)
            label = label.reshape(-1).astype(jnp.int32)
            self.sum_metric = self.sum_metric + jnp.sum(pred == label)
            self.num_inst += label.size


class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kw):
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}", **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label, pred = _as_raw(label), _as_raw(pred)
            idx = jnp.argsort(pred, axis=-1)[:, -self.top_k:]
            hit = jnp.any(idx == label.astype(jnp.int32)[:, None], axis=-1)
            self.sum_metric = self.sum_metric + jnp.sum(hit)
            self.num_inst += label.shape[0]


class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kw):
        super().__init__(name, **kw)
        self.average = average

    def reset(self):
        self.tp = self.fp = self.fn = 0.0
        self.num_inst = 0
        self.sum_metric = jnp.zeros(())

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = np.asarray(_as_raw(label)).reshape(-1).astype(int)
            p = np.asarray(_as_raw(pred))
            pred_lab = p.argmax(axis=-1).reshape(-1) if p.ndim > 1 else (p > 0.5).astype(int).reshape(-1)
            self.tp += float(((pred_lab == 1) & (label == 1)).sum())
            self.fp += float(((pred_lab == 1) & (label == 0)).sum())
            self.fn += float(((pred_lab == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1e-12)
        rec = self.tp / max(self.tp + self.fn, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return self.name, f1


class MAE(EvalMetric):
    def __init__(self, name="mae", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label, pred = _as_raw(label), _as_raw(pred)
            self.sum_metric = self.sum_metric + jnp.sum(jnp.abs(label.reshape(pred.shape) - pred))
            self.num_inst += pred.size


class MSE(EvalMetric):
    def __init__(self, name="mse", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label, pred = _as_raw(label), _as_raw(pred)
            self.sum_metric = self.sum_metric + jnp.sum(jnp.square(label.reshape(pred.shape) - pred))
            self.num_inst += pred.size


class RMSE(MSE):
    def __init__(self, name="rmse", **kw):
        super().__init__(name, **kw)

    def get(self):
        name, value = super().get()
        return name, value ** 0.5


class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kw):
        self.eps = eps
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label, pred = _as_raw(label), _as_raw(pred)
            prob = jnp.take_along_axis(pred, label.astype(jnp.int32).reshape(-1, 1), axis=-1)
            self.sum_metric = self.sum_metric + jnp.sum(-jnp.log(prob + self.eps))
            self.num_inst += label.shape[0]


class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kw):
        super().__init__(name=name, **kw)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label, pred = _as_raw(label), _as_raw(pred)
            lab = label.reshape(-1).astype(jnp.int32)
            prob = jnp.take_along_axis(pred.reshape(lab.shape[0], -1), lab[:, None], axis=-1)[:, 0]
            if self.ignore_label is not None:
                mask = lab != self.ignore_label
                self.sum_metric = self.sum_metric + jnp.sum(-jnp.log(prob + self.eps) * mask)
                self.num_inst += int(jnp.sum(mask))
            else:
                self.sum_metric = self.sum_metric + jnp.sum(-jnp.log(prob + self.eps))
                self.num_inst += lab.shape[0]

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(np.exp(float(self.sum_metric) / self.num_inst))


class Loss(EvalMetric):
    def __init__(self, name="loss", **kw):
        super().__init__(name, **kw)

    def update(self, _, preds):
        for pred in _listify(preds):
            pred = _as_raw(pred)
            self.sum_metric = self.sum_metric + jnp.sum(pred)
            self.num_inst += pred.size


class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pcc", **kw):
        super().__init__(name, **kw)

    def reset(self):
        self._x, self._y = [], []
        self.num_inst = 0
        self.sum_metric = jnp.zeros(())

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            self._x.append(np.asarray(_as_raw(label)).reshape(-1))
            self._y.append(np.asarray(_as_raw(pred)).reshape(-1))
            self.num_inst += 1

    def get(self):
        if not self._x:
            return self.name, float("nan")
        x, y = np.concatenate(self._x), np.concatenate(self._y)
        return self.name, float(np.corrcoef(x, y)[0, 1])


class MCC(EvalMetric):
    def __init__(self, name="mcc", **kw):
        super().__init__(name, **kw)

    def reset(self):
        self.tp = self.tn = self.fp = self.fn = 0.0
        self.num_inst = 0
        self.sum_metric = jnp.zeros(())

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = np.asarray(_as_raw(label)).reshape(-1).astype(int)
            p = np.asarray(_as_raw(pred))
            pred_lab = p.argmax(axis=-1).reshape(-1) if p.ndim > 1 else (p > 0.5).astype(int).reshape(-1)
            self.tp += float(((pred_lab == 1) & (label == 1)).sum())
            self.tn += float(((pred_lab == 0) & (label == 0)).sum())
            self.fp += float(((pred_lab == 1) & (label == 0)).sum())
            self.fn += float(((pred_lab == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        num = self.tp * self.tn - self.fp * self.fn
        den = ((self.tp + self.fp) * (self.tp + self.fn) * (self.tn + self.fp) * (self.tn + self.fn)) ** 0.5
        return self.name, num / den if den else 0.0


class NegativeLogLikelihood(EvalMetric):
    """Mean -log P(label) (reference metric.py NegativeLogLikelihood)."""

    def __init__(self, eps=1e-12, name="nll-loss", **kw):
        self.eps = eps
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            lab = np.asarray(_as_raw(label)).astype(np.int64).ravel()
            p = np.asarray(_as_raw(pred)).reshape(len(lab), -1)
            picked = p[np.arange(len(lab)), lab]
            self.sum_metric = self.sum_metric - np.log(picked + self.eps).sum()
            self.num_inst += len(lab)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kw):
        self._feval = feval
        super().__init__(f"custom({name})", **kw)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            v = self._feval(np.asarray(_as_raw(label)), np.asarray(_as_raw(pred)))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric = self.sum_metric + s
                self.num_inst += n
            else:
                self.sum_metric = self.sum_metric + v
                self.num_inst += 1


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kw):
        self.metrics = [create(m) for m in (metrics or [])]
        super().__init__(name, **kw)

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()
        self.num_inst = 0
        self.sum_metric = jnp.zeros(())

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, vals = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(_listify(n))
            vals.extend(_listify(v))
        return names, vals


_REGISTRY = {
    "acc": Accuracy, "accuracy": Accuracy, "top_k_accuracy": TopKAccuracy, "top_k_acc": TopKAccuracy,
    "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE, "ce": CrossEntropy, "cross-entropy": CrossEntropy,
    "perplexity": Perplexity, "loss": Loss, "pcc": PearsonCorrelation, "mcc": MCC,
    "nll_loss": NegativeLogLikelihood, "nll-loss": NegativeLogLikelihood,
}


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, (list, tuple)):
        return CompositeEvalMetric(list(metric))
    return _REGISTRY[metric.lower()](*args, **kwargs)


np_metric = create
