#!/usr/bin/env python
"""Multi-process launcher (reference: ``tools/launch.py`` + dmlc_tracker).

The reference spawned scheduler/server/worker processes and exported
``DMLC_*`` env vars for ps-lite. Here there are only *workers*: each process
is one jax.distributed participant; the coordinator is worker 0. Same UX::

    python tools/launch.py -n 4 python train.py --kv-store dist_sync

Local mode forks N processes on this host (the reference's ``--launcher
local`` CI topology, SURVEY §4 fixture #5); ssh mode prints per-host
commands (zero-egress environments can't ssh out, so it stops at the plan).
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(n: int, command: list[str]) -> int:
    port = free_port()
    coord = f"127.0.0.1:{port}"
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "MXNET_TPU_COORDINATOR": coord,
            "MXNET_TPU_NPROC": str(n),
            "MXNET_TPU_PROCID": str(rank),
            # all-local launch: local_rank == rank, local_size == n
            "MXNET_TPU_LOCAL_RANK": str(rank),
            "MXNET_TPU_LOCAL_SIZE": str(n),
            # reference-compat aliases so DMLC-era scripts keep working
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(rank),
        })
        procs.append(subprocess.Popen(command, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference compat; there is no server "
                         "role (state is sharded with workers)")
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, args.command))
    # ssh plan (zero-egress: print what would run per host)
    hosts = open(args.hostfile).read().split() if args.hostfile else ["host%d" % i for i in range(args.num_workers)]
    port = free_port()
    for rank, host in enumerate(hosts[: args.num_workers]):
        print(f"ssh {host} MXNET_TPU_COORDINATOR={hosts[0]}:{port} "
              f"MXNET_TPU_NPROC={args.num_workers} MXNET_TPU_PROCID={rank} "
              + " ".join(args.command))


if __name__ == "__main__":
    main()
