"""Operator forward checks vs numpy oracle + finite-difference gradients
(reference: tests/python/unittest/test_operator.py + check_numeric_gradient)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _fd_grad(fn, x, eps=1e-3):
    """Central finite differences of scalar-valued fn at x (numpy)."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


@pytest.mark.parametrize("name,npfn", [
    ("exp", np.exp), ("log", lambda x: np.log(np.abs(x) + 1)), ("tanh", np.tanh),
    ("sqrt", lambda x: np.sqrt(np.abs(x))), ("square", np.square),
    ("abs", np.abs), ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
])
def test_unary(name, npfn):
    x = np.random.randn(3, 4).astype(np.float32)
    if name in ("log",):
        arg = np.abs(x) + 1
    elif name == "sqrt":
        arg = np.abs(x)
    else:
        arg = x
    out = getattr(nd, name)(nd.array(arg)).asnumpy()
    np.testing.assert_allclose(out, npfn(x) if name not in ("log", "sqrt") else npfn(x), rtol=1e-5, atol=1e-6)


def test_broadcast_binary():
    a = np.random.rand(3, 1, 4).astype(np.float32)
    b = np.random.rand(1, 5, 4).astype(np.float32)
    np.testing.assert_allclose(nd.broadcast_add(nd.array(a), nd.array(b)).asnumpy(), a + b, rtol=1e-6)
    np.testing.assert_allclose(nd.broadcast_mul(nd.array(a), nd.array(b)).asnumpy(), a * b, rtol=1e-6)
    np.testing.assert_allclose(nd.broadcast_maximum(nd.array(a), nd.array(b)).asnumpy(), np.maximum(a, b))


def test_dot_variants():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 5).astype(np.float32)
    np.testing.assert_allclose(nd.dot(nd.array(a), nd.array(b)).asnumpy(), a @ b, rtol=1e-5)
    np.testing.assert_allclose(
        nd.dot(nd.array(a.T), nd.array(b), transpose_a=True).asnumpy(), a @ b, rtol=1e-5)
    np.testing.assert_allclose(
        nd.dot(nd.array(a), nd.array(b.T), transpose_b=True).asnumpy(), a @ b, rtol=1e-5)
    x = np.random.rand(2, 3, 4).astype(np.float32)
    y = np.random.rand(2, 4, 5).astype(np.float32)
    np.testing.assert_allclose(nd.batch_dot(nd.array(x), nd.array(y)).asnumpy(), x @ y, rtol=1e-5)


def test_softmax_family():
    x = np.random.randn(4, 7).astype(np.float32)
    sm = nd.softmax(nd.array(x)).asnumpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    np.testing.assert_allclose(sm, e / e.sum(-1, keepdims=True), rtol=1e-5)
    ls = nd.log_softmax(nd.array(x)).asnumpy()
    np.testing.assert_allclose(ls, np.log(e / e.sum(-1, keepdims=True)), rtol=1e-4, atol=1e-5)


def test_fully_connected():
    x = np.random.rand(2, 8).astype(np.float32)
    w = np.random.rand(3, 8).astype(np.float32)
    b = np.random.rand(3).astype(np.float32)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=3).asnumpy()
    np.testing.assert_allclose(out, x @ w.T + b, rtol=1e-5)


def test_convolution_vs_naive():
    x = np.random.rand(1, 2, 5, 5).astype(np.float32)
    w = np.random.rand(3, 2, 3, 3).astype(np.float32)
    out = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                         num_filter=3, no_bias=True).asnumpy()
    ref = np.zeros((1, 3, 3, 3), np.float32)
    for o in range(3):
        for i in range(3):
            for j in range(3):
                ref[0, o, i, j] = (x[0, :, i:i + 3, j:j + 3] * w[o]).sum()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    mx_out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max").asnumpy()
    np.testing.assert_allclose(mx_out, [[[[5, 7], [13, 15]]]])
    avg = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg").asnumpy()
    np.testing.assert_allclose(avg, [[[[2.5, 4.5], [10.5, 12.5]]]])
    g = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg").asnumpy()
    np.testing.assert_allclose(g, [[[[7.5]]]])


def test_batchnorm_layernorm():
    x = np.random.rand(4, 3, 2, 2).astype(np.float32)
    gamma, beta = np.ones(3, np.float32), np.zeros(3, np.float32)
    mean, var = np.zeros(3, np.float32), np.ones(3, np.float32)
    out, bm, bv = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                               nd.array(mean), nd.array(var), training=True)
    m = x.mean(axis=(0, 2, 3))
    v = x.var(axis=(0, 2, 3))
    ref = (x - m[None, :, None, None]) / np.sqrt(v + 1e-5)[None, :, None, None]
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(bm.asnumpy(), m, rtol=1e-5)

    g2 = np.random.rand(5).astype(np.float32)
    b2 = np.random.rand(5).astype(np.float32)
    x2 = np.random.rand(3, 5).astype(np.float32)
    ln = nd.LayerNorm(nd.array(x2), nd.array(g2), nd.array(b2)).asnumpy()
    mu = x2.mean(-1, keepdims=True)
    sd = np.sqrt(x2.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(ln, (x2 - mu) / sd * g2 + b2, rtol=1e-4, atol=1e-5)


def test_take_embedding_onehot():
    w = np.random.rand(10, 4).astype(np.float32)
    idx = np.array([1, 3, 5])
    np.testing.assert_allclose(
        nd.Embedding(nd.array(idx), nd.array(w), input_dim=10, output_dim=4).asnumpy(),
        w[idx])
    oh = nd.one_hot(nd.array(idx), depth=10).asnumpy()
    assert oh.shape == (3, 10)
    assert (oh.argmax(-1) == idx).all()
    t = nd.take(nd.array(w), nd.array(idx), axis=0).asnumpy()
    np.testing.assert_allclose(t, w[idx])


def test_topk_sort():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], np.float32)
    idx = nd.topk(nd.array(x), k=2).asnumpy()
    np.testing.assert_allclose(idx, [[0, 2], [1, 2]])
    vals = nd.topk(nd.array(x), k=2, ret_typ="value").asnumpy()
    np.testing.assert_allclose(vals, [[3, 2], [5, 4]])
    np.testing.assert_allclose(nd.sort(nd.array(x)).asnumpy(), np.sort(x))


def test_reduce_safe_accumulation_bf16():
    x = nd.full((1000,), 1.0, dtype="bfloat16")
    # naive bf16 accumulation loses precision well below 1000; f32 accumulate
    assert abs(float(nd.sum(x).astype("float32").asnumpy()) - 1000.0) < 16


def test_pick():
    x = np.random.rand(4, 6).astype(np.float32)
    idx = np.array([0, 2, 5, 1])
    out = nd.pick(nd.array(x), nd.array(idx), axis=1).asnumpy()
    np.testing.assert_allclose(out, x[np.arange(4), idx])


def test_optimizer_ops():
    from mxnet_tpu.ops import optimizer_ops as oo

    w = np.random.rand(5).astype(np.float32)
    g = np.random.rand(5).astype(np.float32)
    new_w = np.asarray(oo.sgd_update(w, g, lr=0.1, wd=0.0, rescale_grad=1.0))
    np.testing.assert_allclose(new_w, w - 0.1 * g, rtol=1e-6)

    mom = np.zeros(5, np.float32)
    w2, m2 = oo.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9)
    np.testing.assert_allclose(np.asarray(w2), w - 0.1 * g, rtol=1e-6)

    mean = np.zeros(5, np.float32)
    var = np.zeros(5, np.float32)
    w3, m3, v3 = oo.adam_update(w, g, mean, var, lr=0.01)
    assert np.isfinite(np.asarray(w3)).all()


def test_rnn_op_lstm_shapes():
    T, B, C, H, L = 3, 2, 4, 5, 1
    ng = 4
    psize = ng * H * C + ng * H * H + 2 * ng * H
    params = np.random.randn(psize).astype(np.float32) * 0.1
    x = np.random.randn(T, B, C).astype(np.float32)
    h0 = np.zeros((L, B, H), np.float32)
    out, hn, cn = nd.RNN(nd.array(x), nd.array(params), nd.array(h0), nd.array(h0),
                         state_size=H, num_layers=L, mode="lstm")
    assert out.shape == (T, B, H)
    assert hn.shape == (L, B, H)
    assert np.isfinite(out.asnumpy()).all()


def test_random_ops_reproducible():
    mx.random.seed(7)
    a = nd.random.uniform(shape=(4,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.uniform(shape=(4,)).asnumpy()
    np.testing.assert_allclose(a, b)
    c = nd.random.normal(loc=1.0, scale=0.0, shape=(3,)).asnumpy()
    np.testing.assert_allclose(c, np.ones(3), atol=1e-6)


def test_attention_interleaved_matches_reference_shape():
    T, B, H, Ch = 4, 2, 3, 8
    qkv = np.random.randn(T, B, H * 3 * Ch).astype(np.float32)
    scores = nd._contrib_interleaved_matmul_selfatt_qk(nd.array(qkv), heads=H)
    assert scores.shape == (B * H, T, T)
    att = nd.softmax(scores, axis=-1)
    out = nd._contrib_interleaved_matmul_selfatt_valatt(nd.array(qkv), att, heads=H)
    assert out.shape == (T, B, H * Ch)
    # oracle: explicit attention
    x = qkv.reshape(T, B, H, 3, Ch)
    q, k, v = x[..., 0, :], x[..., 1, :], x[..., 2, :]
    q = q.transpose(1, 2, 0, 3) / np.sqrt(Ch)
    k = k.transpose(1, 2, 0, 3)
    v = v.transpose(1, 2, 0, 3)
    s = q @ k.transpose(0, 1, 3, 2)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = (p @ v).transpose(2, 0, 1, 3).reshape(T, B, H * Ch)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_softmax_output_fused_gradient():
    """SoftmaxOutput with a label carries the reference's fused backward:
    d(data) = (softmax - one_hot(label)) * grad_scale, INDEPENDENT of the
    incoming cotangent — that is what makes SoftmaxOutput-headed symbols
    train under Module.backward's ones seed."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.registry import get as get_op

    so = get_op("SoftmaxOutput").fn
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 5), jnp.float32)
    y = jnp.asarray(rs.randint(0, 5, (4,)), jnp.float32)

    p = jax.nn.softmax(x, axis=-1)
    expected = p - jax.nn.one_hot(y.astype(jnp.int32), 5)

    # ones cotangent (Module's seed)
    _, vjp = jax.vjp(lambda x: so(x, y), x)
    (dx,) = vjp(jnp.ones((4, 5), jnp.float32))
    np.testing.assert_allclose(np.asarray(dx), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)
    # ANY cotangent gives the same gradient (reference output-op semantics)
    (dx2,) = vjp(jnp.full((4, 5), 7.0, jnp.float32))
    np.testing.assert_allclose(np.asarray(dx2), np.asarray(dx))

    # grad_scale and ignore_label (EVERY row carrying the ignored id zeroes)
    ignored = int(y[0])
    _, vjp3 = jax.vjp(lambda x: so(x, y, grad_scale=0.5, use_ignore=True,
                                   ignore_label=ignored), x)
    (dx3,) = vjp3(jnp.ones((4, 5), jnp.float32))
    d3 = np.asarray(dx3)
    keep = np.asarray(y) != ignored
    np.testing.assert_allclose(d3[~keep], 0.0)
    np.testing.assert_allclose(d3[keep], 0.5 * np.asarray(expected)[keep],
                               rtol=1e-5, atol=1e-6)

    # label-free: plain differentiable softmax (cotangent-dependent)
    _, vjp4 = jax.vjp(lambda x: so(x), x)
    (dx4,) = vjp4(jnp.ones((4, 5), jnp.float32))
    np.testing.assert_allclose(np.asarray(dx4), 0.0, atol=1e-6)
