"""gluon.contrib (reference: ``python/mxnet/gluon/contrib/``)."""
from . import estimator  # noqa: F401
from . import nn  # noqa: F401
from .estimator import Estimator  # noqa: F401
