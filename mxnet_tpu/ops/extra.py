"""Operator long-tail: sequence ops, extra activations, normalizations,
spatial-transformer family, misc tensor ops.

Reference homes: ``src/operator/sequence_last.cc`` / ``sequence_reverse.cc``,
``src/operator/nn/lrn.cc``, ``src/operator/nn/group_norm.cc`` (1.6+),
``src/operator/spatial_transformer.cc`` / ``bilinear_sampler.cc`` /
``grid_generator.cc``, ``src/operator/tensor/ravel.cc``, ``matrix_op.cc``
(split_v2), ``src/operator/contrib/krprod.cc`` (khatri_rao),
``broadcast_reduce_op` (moments). Each is a jnp/lax composition; gradients
come from jax autodiff.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register

# --------------------------------------------------------------------------
# activations (standalone op forms; Activation(act_type=...) covers some)
# --------------------------------------------------------------------------
register("hard_sigmoid")(
    lambda data, alpha=0.2, beta=0.5: jnp.clip(alpha * data + beta, 0.0, 1.0))
register("softmin")(
    lambda data, axis=-1: jax.nn.softmax(-data, axis=int(axis)))
register("relu6")(lambda data: jnp.clip(data, 0.0, 6.0))
register("selu")(lambda data: jax.nn.selu(data))
register("gelu")(lambda data: jax.nn.gelu(data, approximate=False))
register("softrelu")(lambda data: jax.nn.softplus(data))
register("log_sigmoid")(lambda data: jax.nn.log_sigmoid(data))
register("logsumexp")(
    lambda data, axis=None, keepdims=False: jax.scipy.special.logsumexp(
        data, axis=None if axis is None else tuple(axis) if isinstance(axis, (list, tuple)) else int(axis),
        keepdims=keepdims))


# --------------------------------------------------------------------------
# sequence ops (time-major by default, like SequenceMask)
# --------------------------------------------------------------------------
@register("SequenceLast", aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0):
    """Last valid step of each sequence (reference: sequence_last.cc)."""
    axis = int(axis)
    if not use_sequence_length or sequence_length is None:
        return lax.index_in_dim(data, data.shape[axis] - 1, axis,
                                keepdims=False)
    idx = (sequence_length.astype(jnp.int32) - 1)  # (B,)
    dm = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        dm, idx.reshape((1, -1) + (1,) * (dm.ndim - 2)), axis=0)[0]


@register("SequenceReverse", aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    """Reverse each sequence along time, keeping padding in place
    (reference: sequence_reverse.cc)."""
    axis = int(axis)
    dm = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    T = dm.shape[0]
    steps = jnp.arange(T)
    if not use_sequence_length or sequence_length is None:
        out = dm[::-1]
    else:
        L = sequence_length.astype(jnp.int32)  # (B,)
        # row t of sequence b reads from (L[b]-1-t) while t < L[b], else t
        src = jnp.where(steps[:, None] < L[None, :],
                        L[None, :] - 1 - steps[:, None], steps[:, None])
        out = jnp.take_along_axis(
            dm, src.reshape(src.shape + (1,) * (dm.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


# --------------------------------------------------------------------------
# normalizations
# --------------------------------------------------------------------------
@register("GroupNorm", aliases=("group_norm",))
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """Group normalization over NCHW (reference: nn/group_norm.cc).

    The reference op takes (num_groups,)-shaped gamma/beta (scale per
    group); per-channel (C,) parameters — the PyTorch/GluonCV convention —
    are accepted too and applied per channel.
    """
    n, c = data.shape[0], data.shape[1]
    g = int(num_groups)
    x = data.reshape((n, g, c // g) + data.shape[2:])
    red = tuple(range(2, x.ndim))
    mean = x.mean(axis=red, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=red, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    if gamma.shape[0] == g and g != c:  # reference layout: per group
        expand = (1, g, 1) + (1,) * (data.ndim - 2)
        x = x * gamma.reshape(expand) + beta.reshape(expand)
        return x.reshape(data.shape)
    x = x.reshape(data.shape)
    expand = (1, c) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(expand) + beta.reshape(expand)


@register("LRN", aliases=("lrn",))
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Across-channel local response normalization over NCHW
    (reference: nn/lrn.cc — the AlexNet-era op)."""
    nsize = int(nsize)
    sq = data * data
    # windowed channel sum via padded cumulative trick (static shapes)
    pad = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (pad, pad)] + [(0, 0)] * (data.ndim - 2))
    acc = sum(
        lax.slice_in_dim(padded, i, i + data.shape[1], axis=1)
        for i in range(nsize))
    return data / jnp.power(knorm + (alpha / nsize) * acc, beta)


# --------------------------------------------------------------------------
# spatial transformer family
# --------------------------------------------------------------------------
def _identity_grid(h, w):
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    return gx, gy  # each (h, w)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Sampling grids (reference: grid_generator.cc).

    affine: data (N, 6) affine params -> grid (N, 2, H, W), xy order.
    warp:   data (N, 2, H, W) flow (pixels) -> identity grid + flow.
    """
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        gx, gy = _identity_grid(h, w)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], 0).reshape(3, h * w)  # (3, HW)
        theta = data.reshape((-1, 2, 3)).astype(jnp.float32)
        out = jnp.einsum("nij,jk->nik", theta, base)  # (N, 2, HW)
        return out.reshape((-1, 2, h, w))
    if transform_type == "warp":
        n, _, h, w = data.shape
        gx, gy = _identity_grid(h, w)
        # pixel flow -> normalized coords
        fx = data[:, 0] * (2.0 / max(w - 1, 1))
        fy = data[:, 1] * (2.0 / max(h - 1, 1))
        return jnp.stack([gx[None] + fx, gy[None] + fy], 1)
    raise ValueError(f"GridGenerator: unknown transform_type {transform_type!r}")


@register("BilinearSampler")
def bilinear_sampler(data, grid):
    """Sample NCHW ``data`` at normalized ``grid`` (N, 2, Ho, Wo), xy in
    [-1, 1]; zero padding outside (reference: bilinear_sampler.cc)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0  # (N, Ho, Wo)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yy, xx):
        inb = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)  # (N,1,HoWo)
        vals = jnp.take_along_axis(flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape((n, c) + yy.shape[1:])
        return vals * inb[:, None].astype(data.dtype)

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[:, None])
    return out.astype(data.dtype)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear"):
    """Affine spatial transformer network block = GridGenerator +
    BilinearSampler (reference: spatial_transformer.cc)."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("SpatialTransformer supports affine + bilinear")
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


# --------------------------------------------------------------------------
# misc tensor ops
# --------------------------------------------------------------------------
@register("batch_take")
def batch_take(a, indices):
    """out[i] = a[i, indices[i]] (reference: indexing_op.cc batch_take)."""
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32).reshape(-1, 1), axis=1)[:, 0]


@register("khatri_rao")
def khatri_rao(*matrices):
    """Column-wise Kronecker product (reference: contrib/krprod.cc)."""
    out = matrices[0]
    for m in matrices[1:]:
        k = out.shape[1]
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, k)
    return out


@register("unravel_index", aliases=("_unravel_index",))
def unravel_index(data, shape=None):
    """Flat indices -> coordinate matrix (ndim, N) row-major
    (reference: tensor/ravel.cc)."""
    coords = jnp.unravel_index(data.astype(jnp.int32), tuple(int(s) for s in shape))
    return jnp.stack(coords, 0)


@register("ravel_multi_index", aliases=("_ravel_multi_index",))
def ravel_multi_index(data, shape=None):
    """Coordinate matrix (ndim, N) -> flat indices (reference: ravel.cc)."""
    shape = tuple(int(s) for s in shape)
    idx = jnp.zeros(data.shape[1:], jnp.int32)
    stride = 1
    for d in range(len(shape) - 1, -1, -1):
        idx = idx + data[d].astype(jnp.int32) * stride
        stride *= shape[d]
    return idx


@register("split_v2", aliases=("_split_v2",))
def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False):
    """numpy-style split (reference: matrix_op.cc split_v2, 1.5+)."""
    axis = int(axis)
    if isinstance(indices_or_sections, (tuple, list)):
        pieces = jnp.split(data, [int(i) for i in indices_or_sections], axis=axis)
    else:
        pieces = jnp.split(data, int(indices_or_sections), axis=axis)
    if squeeze_axis:
        pieces = [jnp.squeeze(p, axis=axis) for p in pieces]
    return tuple(pieces)


@register("moments", nout=2)
def moments(data, axes=None, keepdims=False):
    """(mean, variance) in one op (reference: nn/moments.cc)."""
    ax = None if axes is None else tuple(int(a) for a in axes) \
        if isinstance(axes, (tuple, list)) else (int(axes),)
    mean = data.mean(axis=ax, keepdims=keepdims)
    mk = data.mean(axis=ax, keepdims=True)
    var = ((data - mk) ** 2).mean(axis=ax, keepdims=keepdims)
    return mean, var


@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (reference: src/operator/correlation.cc).

    For every spatial position, the (mean) inner product between a patch of
    ``data1`` and displaced patches of ``data2`` over a (2d+1)^2 displacement
    grid. Expressed as a dense shift-and-reduce so XLA lowers it to fused
    elementwise + reductions — no gather scatter, TPU-tileable.
    """
    if kernel_size != 1:
        raise ValueError("Correlation: native tier implements kernel_size=1 "
                         "(the FlowNet configuration)")
    n, c, h, w = data1.shape
    d = int(max_displacement)
    p = int(pad_size)
    s1 = int(stride1)
    a = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    b = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    hp, wp = h + 2 * p, w + 2 * p
    # centers sampled every stride1 pixels (reference uses ceil)
    out_h = -(-(hp - 2 * d) // s1)
    out_w = -(-(wp - 2 * d) // s1)
    lim_h, lim_w = d + (out_h - 1) * s1 + 1, d + (out_w - 1) * s1 + 1
    a_c = lax.slice(a, (0, 0, d, d), (n, c, lim_h, lim_w), (1, 1, s1, s1))
    rows = []
    for dy in range(-d, d + 1, int(stride2)):
        for dx in range(-d, d + 1, int(stride2)):
            b_c = lax.slice(b, (0, 0, d + dy, d + dx),
                            (n, c, dy + lim_h, dx + lim_w), (1, 1, s1, s1))
            if is_multiply:
                rows.append((a_c * b_c).mean(axis=1))
            else:
                rows.append(jnp.abs(a_c - b_c).mean(axis=1))
    return jnp.stack(rows, axis=1)


# --------------------------------------------------------------------------
# AMP graph-pass ops (reference: src/operator/tensor/amp_cast.cc,
# src/operator/contrib/all_finite.cc). The TPU AMP implementation is
# policy-based (contrib/amp.py casts at the matmul boundary), but exported
# symbol JSONs and reference scripts name these ops explicitly — so they
# exist as real registry entries with reference semantics.
# --------------------------------------------------------------------------
@register("amp_cast")
def amp_cast(data, dtype="float32"):
    """Float-to-float cast inserted by the AMP graph pass; non-float inputs
    pass through unchanged (reference AMPCastType behavior)."""
    if not jnp.issubdtype(data.dtype, jnp.floating):
        return data
    return data.astype(jnp.dtype(dtype))


@register("amp_multicast", nout=-1)
def amp_multicast(*data, num_outputs=None):
    """Cast every floating input to the widest floating dtype present
    (reference AMPMultiCastType: common widest type across inputs)."""
    floats = [a.dtype for a in data if jnp.issubdtype(a.dtype, jnp.floating)]
    if not floats:
        return tuple(data)
    target = jnp.result_type(*floats)
    return tuple(a.astype(target) if jnp.issubdtype(a.dtype, jnp.floating)
                 else a for a in data)


@register("all_finite")
def all_finite(data, init_output=True):
    """1-element float array: 1.0 iff every element is finite (reference
    all_finite.cc — the dynamic-loss-scaling overflow probe)."""
    return jnp.isfinite(data).all().astype(jnp.float32).reshape((1,))


@register("multi_all_finite", nout=1)
def multi_all_finite(*data, num_arrays=None, init_output=True):
    """AND of all_finite over every input array in one fused op (reference
    multi_all_finite — one kernel over the whole gradient set)."""
    ok = jnp.array(True)
    for a in data:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.astype(jnp.float32).reshape((1,))


# --------------------------------------------------------------------------
# explicit sharding constraint (TPU-native; no reference analog — the
# reference's placement is group2ctx/PlaceDevice, which GSPMD annotations
# replace per SURVEY §2.3). Model code pins layouts at known transition
# points so the partitioner never falls back to involuntary remat.
# --------------------------------------------------------------------------
@register("_sharding_constraint")
def sharding_constraint(data, spec=()):
    """``jax.lax.with_sharding_constraint`` against the active mesh.

    ``spec`` entries per dimension: None (unconstrained), an axis name, a
    tuple of axis names, or the alias ``"data"`` (= every batch-bearing mesh
    axis present: dp, fsdp). Identity when no mesh is active, when a named
    axis is absent/size-1, or when the axis product does not divide the dim —
    so the op is safe in eager/single-chip paths.
    """
    from .. import _mesh_state

    mesh = _mesh_state.current_mesh()
    if mesh is None:
        return data

    def axes_of(entry):
        if entry is None:
            return ()
        if entry == "data":
            names = ("dp", "fsdp")
        elif isinstance(entry, (tuple, list)):
            names = tuple(entry)
        else:
            names = (entry,)
        return tuple(n for n in names
                     if n in mesh.shape and mesh.shape[n] > 1)

    resolved = []
    for dim, entry in zip(data.shape, tuple(spec)[: data.ndim]):
        axes = axes_of(entry)
        prod = 1
        for n in axes:
            prod *= mesh.shape[n]
        if not axes or dim % prod != 0:
            resolved.append(None)
        else:
            resolved.append(axes if len(axes) > 1 else axes[0])
    resolved += [None] * (data.ndim - len(resolved))
    if all(r is None for r in resolved):
        return data
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(
        data, NamedSharding(mesh, P(*resolved)))


# --------------------------------------------------------------------------
# canonical-surface completion (round-4 verdict ask #7: freeze mx.nd the way
# mx.np is frozen; these are the reference-generated names that were absent)
# --------------------------------------------------------------------------

@register("add_n", aliases=("ElementWiseSum",))
def add_n(*args, num_args=None):
    """Sum of N arrays in one op (reference elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("argmax_channel")
def argmax_channel(data):
    """argmax over axis 1, returned as float (reference broadcast_reduce_op:
    the old SoftmaxOutput-era label extractor)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


def _index_dtype():
    # base.py's x64 stance: int64 out when x64 is on, else int32 (no warning)
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


@register("shape_array")
def shape_array(data):
    """Shape as a 1-D tensor (reference shape_array: int64 out; narrows to
    int32 when x64 is disabled, consistent with base.py's int64 policy)."""
    return jnp.asarray(data.shape, dtype=_index_dtype())


@register("size_array")
def size_array(data):
    """Total element count as a 1-element tensor (reference size_array)."""
    return jnp.asarray([data.size], dtype=_index_dtype())


@register("im2col")
def im2col(data, kernel, stride=None, dilate=None, pad=None):
    """Sliding-window patch extraction, (N,C,H,W) -> (N, C*prod(kernel), L)
    in the reference's channel-major (c, kh, kw) patch layout
    (src/operator/nn/im2col.h). Lowers to one
    ``lax.conv_general_dilated_patches`` — XLA's native patch op — whose
    layout matches the reference's directly (asserted in tests)."""
    from jax import lax

    kernel = tuple(kernel)
    nspatial = len(kernel)
    stride = tuple(stride) if stride else (1,) * nspatial
    dilate = tuple(dilate) if dilate else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    dn = ("NCHW", "OIHW", "NCHW") if nspatial == 2 else ("NCW", "OIW", "NCW")
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=kernel, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn)
    return patches.reshape(data.shape[0], patches.shape[1], -1)


@register("col2im")
def col2im(data, output_size, kernel, stride=None, dilate=None, pad=None):
    """Adjoint of im2col: scatter-add patches back into (N, C, *output_size)
    (reference col2im in im2col.h). im2col is linear, so its vjp IS col2im —
    one jax.vjp instead of a hand scatter kernel."""
    import math

    kernel = tuple(kernel)
    output_size = tuple(output_size)
    n = data.shape[0]
    c = data.shape[1] // math.prod(kernel)
    zeros = jnp.zeros((n, c) + output_size, data.dtype)
    _, vjp = jax.vjp(
        lambda x: im2col(x, kernel, stride=stride, dilate=dilate, pad=pad),
        zeros)
    return vjp(data)[0]


# -- quantization trio (reference: quantize.cc / quantize_v2.cc /
# dequantize.cc — the graph-pass ops; the contrib.quantization module owns
# calibration and the int8 layers) --

@register("quantize", nout=3)
def quantize(data, min_range, max_range, out_type="uint8"):
    """Affine quantization with explicit range inputs (reference quantize.cc:
    uint8 affine over [min,max]; int8 symmetric over max(|min|,|max|))."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    xf = data.astype(jnp.float32)
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(mx_ - mn, 1e-12)
        q = jnp.clip(jnp.round((xf - mn) * scale), 0, 255).astype(jnp.uint8)
    else:
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        scale = 127.0 / jnp.maximum(amax, 1e-12)
        q = jnp.clip(jnp.round(xf * scale), -127, 127).astype(jnp.int8)
    return q, mn.reshape((1,)), mx_.reshape((1,))


@register("quantize_v2", nout=3)
def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Self-calibrating quantization (reference quantize_v2.cc): ranges from
    calibration when given, else from the data itself."""
    xf = data.astype(jnp.float32)
    mn = jnp.asarray(min_calib_range if min_calib_range is not None
                     else jnp.min(xf), jnp.float32).reshape(())
    mx_ = jnp.asarray(max_calib_range if max_calib_range is not None
                      else jnp.max(xf), jnp.float32).reshape(())
    return quantize(data, mn, mx_, out_type=out_type)


@register("dequantize")
def dequantize(data, min_range, max_range, out_type="float32"):
    """Inverse of quantize, dispatching on the stored integer dtype."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    if data.dtype == jnp.uint8:
        scale = jnp.maximum(mx_ - mn, 1e-12) / 255.0
        out = data.astype(jnp.float32) * scale + mn
    else:
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        out = data.astype(jnp.float32) * (jnp.maximum(amax, 1e-12) / 127.0)
    return out.astype(jnp.dtype(out_type))


@register("bincount")
def bincount(data, weights=None, minlength=0):
    """Histogram of non-negative ints (reference np-compat surface). The
    output length is data-dependent, so this op is eager-only (under jit,
    pass minlength >= 1 + max to fix the shape)."""
    d = data.astype(jnp.int32).reshape(-1)
    try:
        length = max(int(jnp.max(d)) + 1 if d.size else 1, int(minlength))
    except Exception:  # tracer: static length must come from minlength
        if int(minlength) <= 0:
            raise ValueError(
                "bincount under jit needs minlength >= 1 + max(data)")
        length = int(minlength)
    w = weights.reshape(-1) if weights is not None else None
    return jnp.bincount(d, weights=w, length=length)


@register("onehot_encode")
def onehot_encode(indices, out):
    """Legacy 0.x-era one-hot (reference ndarray_function.cc OnehotEncode):
    the second arg supplies the output shape (n, k)."""
    return jax.nn.one_hot(indices.astype(jnp.int32), out.shape[-1],
                          dtype=out.dtype)


@register("choose_element_0index")
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] (reference ndarray_function.cc; the pre-pick
    batch gather the legacy RNN/softmax examples used)."""
    idx = rhs.astype(jnp.int32).reshape(-1)
    return lhs[jnp.arange(lhs.shape[0]), idx]


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i] (reference counterpart of
    choose_element_0index; functional here — returns the filled copy)."""
    idx = rhs.astype(jnp.int32).reshape(-1)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs.reshape(-1))
