#!/usr/bin/env python
"""`make obs` gate: a tiny LeNet training run with full telemetry on, then
assert `tools/obs_report.py` renders a non-empty summary covering every
subsystem the ISSUE acceptance names — step/loss/throughput metrics, at
least one recompile event, KVStore byte/latency histograms, checkpoint
durations, and retry counters consistent with `resilience.retry.attempt_log`.

Also provides ``--chaos-check`` (used by `make chaos`): run one retried
operation under injected faults and assert the registry's retry counters
are non-zero and agree with the attempt log.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _fail(msg):
    print(f"obs_smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)


def chaos_check():
    """Assert retry counters flow into the metrics registry under injection."""
    import tempfile

    from mxnet_tpu import kv, nd, observability as obs, optimizer as opt
    from mxnet_tpu.resilience import faults, retry

    retry.clear_log("kv.save_states")
    store = kv.create("local")
    store.set_optimizer(opt.create("sgd"))
    store.init("w", nd.ones((2,)))
    before = obs.REGISTRY.counter("retry_attempts_total").total()
    with tempfile.TemporaryDirectory() as d:
        with faults.inject("kv.save_states", on=1):
            store.save_optimizer_states(os.path.join(d, "states"))
    attempts = retry.attempt_log("kv.save_states")
    delta = obs.REGISTRY.counter("retry_attempts_total").total() - before
    if not attempts:
        _fail("no retry attempts recorded under injected fault")
    if delta != len(attempts):
        _fail(f"registry retry counter delta {delta} != attempt_log "
              f"{len(attempts)}")
    failed = obs.REGISTRY.counter("retry_attempts_total").value(
        site="kv.save_states", ok="false")
    if failed < 1:
        _fail("no failed attempt counted for kv.save_states")
    print(f"obs_smoke: chaos-check OK ({len(attempts)} attempts, "
          f"{int(failed)} failed, counters match attempt_log)")


def main():
    if "--chaos-check" in sys.argv:
        chaos_check()
        return

    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, observability as obs, optimizer as opt
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep
    from mxnet_tpu.resilience import faults, retry

    run_dir = tempfile.mkdtemp(prefix="obs_smoke_")
    # fleet view (docs/OBSERVABILITY.md "Fleet view"): arm the single-rank
    # snapshot writer so the gate also exercises tools/fleetreport.py
    from mxnet_tpu import config

    fleet_dir = os.path.join(run_dir, "fleet")
    config.set("fleet_dir", fleet_dir)
    obs.enable(run_dir)
    mx.random.seed(0)

    # -- 2-step LeNet train under TrainStep (step/loss/gnorm/recompile) ------
    net = nn.HybridSequential()
    net.add(nn.Conv2D(6, 5, padding=2, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Flatten(),
            nn.Dense(32, activation="tanh"),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(8, 1, 28, 28).astype(np.float32))
    y = nd.array(np.arange(8) % 10)
    _ = net(x)
    from mxnet_tpu import gluon

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, loss_fn, opt.create("adam", learning_rate=1e-3))
    for _i in range(2):
        step(x, y)

    # -- checkpoint save/restore metrics -------------------------------------
    step.save(os.path.join(run_dir, "ckpt"))
    step.restore(os.path.join(run_dir, "ckpt"))

    # -- KVStore collective metrics + retry counters -------------------------
    # single-host smoke: arming the fault registry forces the instrumented
    # DCN path (process_count==1 short-circuits otherwise), and an injected
    # transient on the psum exercises retry accounting end to end
    retry.clear_log("kv.dcn_psum")
    store = mx.kv.create("dist_sync")
    store.init("g", nd.zeros((16,)))
    with faults.inject("kv.dcn_psum", on=1):
        store.push("g", nd.ones((16,)))
    out = nd.zeros((16,))
    store.pull("g", out=out)
    attempts = retry.attempt_log("kv.dcn_psum")
    obs.shutdown()

    # -- assertions over the rendered report ---------------------------------
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import obs_report

    summary = obs_report.summarize(run_dir)
    if summary is None:
        _fail(f"empty telemetry dir {run_dir}")
    text = obs_report.render(summary)
    print(text)
    t = summary["train"]
    if t["steps"] < 2 or t["loss_last"] is None:
        _fail("missing step/loss metrics")
    if not t["samples_per_sec"] or not t["tokens_per_sec"]:
        _fail("missing throughput metrics")
    if t["recompiles"] < 1:
        _fail("no recompile events recorded")
    if "psum" not in summary["kv"] or summary["kv"]["psum"]["bytes"] <= 0:
        _fail("missing KVStore byte/latency metrics")
    if summary["checkpoint"]["saves"] < 1 or summary["checkpoint"]["loads"] < 1:
        _fail("missing checkpoint metrics")
    site = summary["retries"].get("kv.dcn_psum")
    if site is None:
        _fail("missing retry counters")
    if site["ok"] + site["failed"] != len(attempts):
        _fail(f"retry counters {site} disagree with attempt_log "
              f"({len(attempts)} records)")

    # -- fleet report over the single-rank snapshot --------------------------
    import fleetreport

    if fleetreport.main([fleet_dir]) != 0:
        _fail(f"fleetreport found no rank telemetry under {fleet_dir}")
    from mxnet_tpu.observability.fleet import FleetAggregator

    freport = FleetAggregator(fleet_dir).collect()
    if freport is None or 0 not in freport.ranks:
        _fail("fleet aggregator missing rank 0")
    if freport.ranks[0].step_hist["count"] < 2:
        _fail("fleet report missing the run's step timings")
    if freport.ranks[0].flops_per_step is None:
        _fail("fleet report missing the FLOPs/step gauge")
    if freport.goodput is None or freport.goodput.buckets["train"] <= 0:
        _fail("fleet goodput ledger missing productive train time")
    # the overhead guards below measure the record path in isolation —
    # the fleet snapshot cadence thread must not re-arm on re-enable
    config.set("fleet_dir", "")

    # -- telemetry-off overhead < 1% of a warm step --------------------------
    # the off-path adds exactly: the enabled() gate, the recompile-signature
    # set lookup, and the (empty) monitor loop. Time those extras in
    # isolation against a warm compiled step.
    import time as _time

    obs.disable()
    step(x, y)  # warm the telemetry-off program
    t0 = _time.perf_counter()
    for _i in range(5):
        step(x, y)
    jax.block_until_ready(step.params)
    step_s = (_time.perf_counter() - t0) / 5
    lr_mult, wd_mult = step._resolve_mults()
    cache_key = (2, tuple(sorted(lr_mult.items())),
                 tuple(sorted(wd_mult.items())), False)
    raws = (x._data, y._data)
    t0 = _time.perf_counter()
    for _i in range(1000):
        obs.enabled()
        step._note_recompile(cache_key, raws)
        for _m in step._monitors:
            pass
    extra_s = (_time.perf_counter() - t0) / 1000
    ratio = extra_s / step_s
    print(f"telemetry-off overhead: {extra_s * 1e6:.1f} us per step "
          f"({ratio * 100:.3f}% of a {step_s * 1e3:.2f} ms warm step)")
    if ratio >= 0.01:
        _fail(f"telemetry-off overhead {ratio * 100:.2f}% >= 1%")

    # -- telemetry-ON record-path budget (ISSUE 9 satellite) -----------------
    # the per-step extras when telemetry is on (beyond the documented
    # device sync): _record_step = device fetch of ready futures, ~8
    # registry ops, the FLOPs-memo lookup, one JSONL event write. Budget
    # (docs/OBSERVABILITY.md): <= 0.15% of a >=200 ms production step,
    # enforced here as a 300 us absolute ceiling (this gate's LeNet step
    # is ~10 ms, where the same absolute cost reads as ~2-3%).
    import tempfile as _tf

    obs.enable(_tf.mkdtemp(prefix="obs_smoke_on_"))
    loss = step(x, y)  # telemetry-on program (adds the gnorm output)
    jax.block_until_ready(loss)
    raws_on = (x._data, y._data)
    key_on = step._step_cache_key(2, True)
    step._record_step(_time.perf_counter(), raws_on, loss, loss, key_on)
    rec_s = None
    for _round in range(5):  # min-of-rounds: robust to CI load spikes
        t0 = _time.perf_counter()
        for _i in range(200):
            step._record_step(_time.perf_counter(), raws_on, loss, loss,
                              key_on)
        d = (_time.perf_counter() - t0) / 200
        rec_s = d if rec_s is None or d < rec_s else rec_s
    budget = max(0.0015 * step_s, 300e-6)
    print(f"telemetry-on record path: {rec_s * 1e6:.1f} us per step "
          f"(budget {budget * 1e6:.0f} us = 0.15% of a >=200 ms step)")
    obs.disable()
    if rec_s > budget:
        _fail(f"telemetry-on record path {rec_s * 1e6:.1f} us exceeds the "
              f"{budget * 1e6:.0f} us budget")

    print(f"\nobs_smoke: OK (run dir {run_dir})")


if __name__ == "__main__":
    main()
