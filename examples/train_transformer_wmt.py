#!/usr/bin/env python
"""Driver config #4: Transformer for WMT En-De machine translation.

Reference shape: GluonNLP ``scripts/machine_translation/train_transformer.py``
(transformer_base, label-smoothed CE, inverse-sqrt warmup LR, bucketed
variable-length batches). TPU-native differences:

  - bucketing = a jit cache over padded length buckets: batches are padded to
    the bucket ceiling and the hybridized net re-jits once per bucket shape —
    the idiomatic analog of ``BucketingModule``'s per-bucket executors
    (``python/mxnet/module/bucketing_module.py``);
  - one ``gluon.Trainer`` step per batch; the whole fwd+bwd+update runs as
    donated jit programs, no per-parameter optimizer launches.

With no WMT corpus on disk this trains on a synthetic copy/reverse parallel
corpus (``--synthetic``, default) — the acceptance smoke is falling
label-smoothed loss + rising token accuracy; point ``--src/--tgt`` at
tokenized id files (one sentence of space-separated ints per line) for real
data.
"""
import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models.transformer import get_transformer, label_smoothing_loss

PAD, BOS, EOS = 0, 1, 2
N_SPECIAL = 3


def synthetic_corpus(n_sent, vocab_size, min_len=4, max_len=28, seed=0):
    """Toy parallel data: target = reversed source (forces real attention —
    position i of the target attends to position L-i of the source)."""
    rs = np.random.RandomState(seed)
    src, tgt = [], []
    for _ in range(n_sent):
        L = rs.randint(min_len, max_len + 1)
        s = rs.randint(N_SPECIAL, vocab_size, size=L)
        src.append(s)
        tgt.append(s[::-1].copy())
    return src, tgt


def load_corpus(src_path, tgt_path):
    def read(path):
        with open(path) as f:
            return [np.array([int(t) for t in ln.split()], np.int64)
                    for ln in f if ln.strip()]
    return read(src_path), read(tgt_path)


def bucket_batches(src, tgt, buckets, batch_size, seed):
    """Assign sentence pairs to length buckets, pad to the bucket ceiling,
    yield shuffled fixed-shape batches (the jit-cache-friendly layout)."""
    rs = np.random.RandomState(seed)
    by_bucket = {b: [] for b in buckets}
    for s, t in zip(src, tgt):
        # +2 on target: BOS/EOS are added below
        need = max(len(s), len(t) + 2)
        for b in buckets:
            if need <= b:
                by_bucket[b].append((s, t))
                break
    batches = []
    for b, pairs in by_bucket.items():
        rs.shuffle(pairs)
        for i in range(0, len(pairs) - batch_size + 1, batch_size):
            chunk = pairs[i:i + batch_size]
            src_ids = np.full((batch_size, b), PAD, np.int32)
            tgt_in = np.full((batch_size, b), PAD, np.int32)
            tgt_out = np.full((batch_size, b), PAD, np.int32)
            src_valid = np.zeros((batch_size,), np.int32)
            for j, (s, t) in enumerate(chunk):
                src_ids[j, :len(s)] = s
                src_valid[j] = len(s)
                tgt_in[j, 0] = BOS
                tgt_in[j, 1:len(t) + 1] = t
                tgt_out[j, :len(t)] = t
                tgt_out[j, len(t)] = EOS
            batches.append((src_ids, tgt_in, tgt_out, src_valid))
    rs.shuffle(batches)
    return batches


class InvSqrtWarmup(mx.lr_scheduler.LRScheduler):
    """Transformer LR: d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (the GluonNLP machine_translation schedule)."""

    def __init__(self, units, warmup_steps=4000, scale=1.0):
        super().__init__(base_lr=1.0)
        self.units = units
        self.warmup = warmup_steps
        self.scale = scale

    def __call__(self, num_update):
        step = max(num_update, 1)
        return self.scale * self.units ** -0.5 * min(
            step ** -0.5, step * self.warmup ** -1.5)


def train(args):
    mx.random.seed(args.seed)
    if args.src and args.tgt:
        src, tgt = load_corpus(args.src, args.tgt)
    else:
        src, tgt = synthetic_corpus(args.n_sent, args.vocab_size,
                                    min_len=args.min_len,
                                    max_len=args.max_len, seed=args.seed)
    buckets = [int(b) for b in args.buckets.split(",")]

    overrides = {"vocab_size": args.vocab_size}
    if args.num_layers:  # small-model override for smoke tests
        overrides.update(num_layers=args.num_layers, units=args.units,
                         hidden_size=args.hidden_size,
                         num_heads=args.num_heads)
    net = get_transformer(args.model, dropout=args.dropout, **overrides)
    net.initialize(mx.init.Xavier())
    net.hybridize()

    sched = InvSqrtWarmup(net._units, args.warmup_steps, scale=args.lr_scale)
    trainer = gluon.Trainer(
        net.collect_params(), "adam",
        {"learning_rate": sched(1), "beta1": 0.9, "beta2": 0.98,
         "epsilon": 1e-9, "lr_scheduler": sched})

    step = 0
    history = []
    for epoch in range(args.epochs):
        batches = bucket_batches(src, tgt, buckets, args.batch_size,
                                 args.seed + epoch)
        t0 = time.time()
        tokens = 0
        for src_ids, tgt_in, tgt_out, src_valid in batches:
            xs = nd.array(src_ids, dtype="int32")
            yi = nd.array(tgt_in, dtype="int32")
            yo = nd.array(tgt_out, dtype="int32")
            sv = nd.array(src_valid, dtype="int32")
            with autograd.record():
                logits = net(xs, yi, sv)
                loss = label_smoothing_loss(logits, yo,
                                            epsilon=args.label_smoothing,
                                            ignore_index=PAD)
            loss.backward()
            trainer.step(1)  # loss is already token-normalized
            step += 1
            tokens += int((tgt_out != PAD).sum())
            if step % args.log_interval == 0:
                lval = float(loss.asnumpy())
                history.append(lval)
                wps = tokens / max(time.time() - t0, 1e-9)
                print(f"epoch {epoch} step {step} loss {lval:.4f} "
                      f"lr {sched(step):.2e} tok/s {wps:.0f}", flush=True)
        # per-epoch eval: token accuracy on a fresh synthetic batch
        ev = bucket_batches(src[:args.batch_size * 4], tgt[:args.batch_size * 4],
                            buckets, args.batch_size, seed=999)
        correct = total = 0
        for src_ids, tgt_in, tgt_out, src_valid in ev:
            logits = net(nd.array(src_ids, dtype="int32"),
                         nd.array(tgt_in, dtype="int32"),
                         nd.array(src_valid, dtype="int32"))
            pred = logits.asnumpy().argmax(-1)
            m = tgt_out != PAD
            correct += int((pred[m] == tgt_out[m]).sum())
            total += int(m.sum())
        print(f"epoch {epoch} done: token_acc {correct / max(total, 1):.4f}",
              flush=True)
    if args.export:
        net.export(args.export,
                   input_names=("src_ids", "tgt_ids", "src_valid"))
    return history


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="transformer_base")
    ap.add_argument("--src"), ap.add_argument("--tgt")
    ap.add_argument("--synthetic", action="store_true", default=True)
    ap.add_argument("--n-sent", type=int, default=4096)
    ap.add_argument("--min-len", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=28)
    ap.add_argument("--vocab-size", type=int, default=36500)
    ap.add_argument("--buckets", default="8,16,24,32")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--dropout", type=float, default=0.1)
    ap.add_argument("--label-smoothing", type=float, default=0.1)
    ap.add_argument("--warmup-steps", type=int, default=4000)
    ap.add_argument("--lr-scale", type=float, default=1.0)
    ap.add_argument("--log-interval", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--export", default="")
    # small-model overrides (smoke tests)
    ap.add_argument("--num-layers", type=int, default=0)
    ap.add_argument("--units", type=int, default=512)
    ap.add_argument("--hidden-size", type=int, default=2048)
    ap.add_argument("--num-heads", type=int, default=8)
    return ap


if __name__ == "__main__":
    train(build_parser().parse_args())
