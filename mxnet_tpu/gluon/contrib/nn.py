"""``gluon.contrib.nn`` (reference: ``python/mxnet/gluon/contrib/nn/
basic_layers.py``): Concurrent/HybridConcurrent, Identity, SparseEmbedding,
SyncBatchNorm, PixelShuffle2D."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn import BatchNorm, Embedding, HybridSequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle2D"]


class HybridConcurrent(HybridSequential):
    """Feed the input to every child, concat outputs on ``axis``."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        outs = [block(x) for block in self._children.values()]
        from ... import ndarray as nd_mod
        from ... import symbol as sym_mod

        F = sym_mod if isinstance(outs[0], sym_mod.Symbol) else nd_mod
        return F.concat(*outs, dim=self.axis)


class Concurrent(HybridConcurrent):
    """Imperative alias (the reference kept a non-hybrid variant)."""


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding whose gradient is row_sparse (reference: sparse_grad=True
    Embedding backed by rsp EmbeddingOpBackward). On TPU dense gather is the
    fast path; the rsp-gradient contract survives through the optimizer's
    lazy row update (``Optimizer._update_lazy``), so this is a thin alias
    documenting that semantics rather than a distinct kernel."""

    def __init__(self, input_dim, output_dim, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embedding = Embedding(input_dim, output_dim, dtype=dtype)

    def hybrid_forward(self, F, x):
        return self.embedding(x)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm. In the reference this synchronizes batch
    statistics across GPUs with a key-value handshake
    (``src/operator/contrib/sync_batch_norm.cc``); under GSPMD the batch
    axis is sharded on the mesh and the mean/var reductions inside
    ``batch_norm`` lower to all-reduces over ICI automatically, so the
    single-device graph IS the synchronized graph. ``num_devices`` is
    accepted for API compat and unused."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)


class PixelShuffle2D(HybridBlock):
    """(N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2) sub-pixel upsampling."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factors = ((int(factor),) * 2 if not isinstance(factor, (list, tuple))
                         else tuple(int(f) for f in factor))

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        n, c_in, h, w = x.shape
        c = c_in // (f1 * f2)
        x = x.reshape((n, c, f1, f2, h, w))
        x = x.transpose((0, 1, 4, 2, 5, 3))  # n c h f1 w f2
        return x.reshape((n, c, h * f1, w * f2))
