"""Elastic multi-host training (docs/RESILIENCE.md "Elastic training"):
mesh re-formation plumbing, heartbeat peer-loss detection, retrying
``dist_init``, and world-size-agnostic checkpoints resharded on restore —
all on single-process CPU via deterministic injection (the real 4-process
kill-a-worker drill lives in test_launch_dist.py / ``make chaos-elastic``).
"""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, gluon, nd, observability as obs, optimizer
from mxnet_tpu.checkpoint import (CheckpointCorruptError, latest_checkpoint,
                                  load_train_state, save_train_state)
from mxnet_tpu.contrib.amp import Policy
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import MeshConfig, ShardingRules, TrainStep, make_mesh
from mxnet_tpu.parallel.mesh import refit_config
from mxnet_tpu.resilience import elastic, faults, retry
from mxnet_tpu.resilience.elastic import (ELASTIC_RESTART_EXIT,
                                          ElasticContext, HeartbeatMonitor,
                                          PeerLost, ReformExit)


@pytest.fixture(autouse=True)
def _isolated():
    """Clean injector/retry-log/elastic context per test; re-arm the env
    chaos spec on the way out (same contract as test_resilience)."""
    faults.reset()
    retry.clear_log()
    elastic._reset_context()
    yield
    elastic._reset_context()
    retry.clear_log()
    faults.reload_from_env()


@pytest.fixture
def _fast_retry():
    config.set("retry_base_delay", 0.002)
    config.set("retry_max_delay", 0.05)
    yield
    config._values.pop("retry_base_delay", None)
    config._values.pop("retry_max_delay", None)


# -- mesh re-fitting (refit_config) ------------------------------------------

def test_refit_scales_data_axes_only():
    # pure-dp world shrinks and grows along dp
    assert refit_config(MeshConfig(dp=4), 2) == MeshConfig(dp=2)
    assert refit_config(MeshConfig(dp=2), 8) == MeshConfig(dp=8)
    # fsdp layout is preserved at the new width
    assert refit_config(MeshConfig(fsdp=4), 2) == MeshConfig(dp=1, fsdp=2)
    assert refit_config(MeshConfig(fsdp=2), 8) == MeshConfig(dp=1, fsdp=8)
    # dp x fsdp keeps the fsdp width when it still divides
    assert refit_config(MeshConfig(dp=2, fsdp=2), 8) == \
        MeshConfig(dp=4, fsdp=2)
    # model axes survive unchanged; data capacity absorbs the change
    assert refit_config(MeshConfig(dp=2, tp=2), 8) == MeshConfig(dp=4, tp=2)


def test_refit_rejects_world_that_cannot_hold_model_axes():
    with pytest.raises(ValueError, match="model axes"):
        refit_config(MeshConfig(dp=2, tp=2), 3)


# -- heartbeat peer-loss detection -------------------------------------------

def test_heartbeat_beat_and_stale_detection(tmp_path):
    d = str(tmp_path)
    a = HeartbeatMonitor(d, rank=0, world=2, interval=0.03, timeout=0.25)
    b = HeartbeatMonitor(d, rank=1, world=2, interval=0.03, timeout=0.25)
    a.start()
    b.start()
    try:
        a.check()  # both beating: no peer loss
        b.stop()   # rank 1 "dies": its file goes stale
        deadline = time.time() + 5.0
        while time.time() < deadline:
            try:
                a.check()
            except PeerLost as e:
                assert e.ranks == [1]
                assert e.cause == "heartbeat_timeout"
                break
            time.sleep(0.05)
        else:
            pytest.fail("stale peer never detected")
    finally:
        a.stop()
        b.stop()


def test_heartbeat_missing_peer_gets_startup_grace(tmp_path):
    # world=2 but rank 1 never appears: inside the grace window (2x timeout
    # from monitor start) that's "still booting", after it it's dead.
    # timeout=0.5 -> a 1s grace budget: the pre-grace check below must not
    # flake when a loaded CI machine stalls between start() and check()
    m = HeartbeatMonitor(str(tmp_path), rank=0, world=2,
                         interval=0.05, timeout=0.5)
    m.start()
    try:
        m.check()  # within grace: no false positive
        deadline = time.time() + 5.0
        while time.time() < deadline:
            try:
                m.check()
            except PeerLost as e:
                assert e.ranks == [1]
                break
            time.sleep(0.05)
        else:
            pytest.fail("never-started peer never declared dead")
    finally:
        m.stop()


def test_heartbeat_fault_site_models_failed_probe(tmp_path):
    m = HeartbeatMonitor(str(tmp_path), rank=0, world=1,
                         interval=0.05, timeout=5.0)
    faults.arm("dist.heartbeat", on=1)
    with pytest.raises(PeerLost) as ei:
        m.check()
    assert ei.value.cause == "heartbeat_fault"
    m.check()  # one-shot trigger: the next probe is clean


# -- ElasticContext: the worker-side loop ------------------------------------

def test_context_built_from_supervisor_env(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_ELASTIC", "1")
    monkeypatch.setenv("MXNET_TPU_PROCID", "2")
    monkeypatch.setenv("MXNET_TPU_NPROC", "3")
    monkeypatch.setenv("MXNET_TPU_GENERATION", "1")
    monkeypatch.setenv("MXNET_TPU_ELASTIC_CAUSE", "worker_killed:sig9")
    monkeypatch.setenv("MXNET_TPU_PREV_WORLD", "4")
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_DIR", str(tmp_path / "hb"))
    elastic._reset_context()
    ctx = elastic.context()
    assert ctx is not None
    assert (ctx.rank, ctx.world, ctx.generation) == (2, 3, 1)
    assert ctx.prev_world == 4 and ctx.cause == "worker_killed:sig9"
    assert ctx.monitor is not None
    assert elastic.context() is ctx  # cached


def test_context_absent_outside_elastic_launch(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_ELASTIC", raising=False)
    elastic._reset_context()
    assert elastic.context() is None


def test_preemption_becomes_reform_request():
    ctx = ElasticContext(rank=0, world=2)
    guard = ctx.install_preemption()
    try:
        ctx.check()  # nothing pending
        guard.request(signum=15)
        with pytest.raises(ReformExit) as ei:
            ctx.check()
        assert ei.value.code == ELASTIC_RESTART_EXIT
        assert ei.value.cause == "preempted"
    finally:
        ctx.shutdown()


def test_peer_loss_becomes_reform_request(tmp_path):
    ctx = ElasticContext(rank=0, world=2, heartbeat_dir=str(tmp_path),
                         hb_interval=0.05, hb_timeout=0.1)
    ctx.start()
    try:
        # fabricate a peer that beat once, long ago
        stale = os.path.join(str(tmp_path), "hb-1")
        with open(stale, "w") as f:
            f.write("0")
        past = time.time() - 60
        os.utime(stale, (past, past))
        with pytest.raises(ReformExit) as ei:
            ctx.check()
        assert ei.value.code == ELASTIC_RESTART_EXIT
        assert ei.value.cause == "heartbeat_timeout"
    finally:
        ctx.shutdown()


def test_generation_start_and_resume_telemetry(tmp_path):
    obs.enable(str(tmp_path / "obs"))
    try:
        ctx = ElasticContext(rank=0, world=3, generation=1,
                             cause="worker_killed:sig9", prev_world=4)
        ctx.start()
        got = ctx.resume(lambda: 7, ckpt_step=7)
        assert got == 7
        assert obs.REGISTRY.get("mesh_reformations_total").value(
            cause="worker_killed:sig9") == 1
        assert obs.REGISTRY.get("elastic_world_size").value() == 3
        hist = obs.REGISTRY.get("elastic_restore_seconds")
        assert hist.stats()["count"] == 1
        ctx.shutdown()
    finally:
        obs.disable()
    events = obs.read_events(str(tmp_path / "obs"))
    reform = [e for e in events if e["event"] == "mesh_reformation"]
    restore = [e for e in events if e["event"] == "elastic_restore"]
    assert len(reform) == 1 and len(restore) == 1
    for e in reform + restore:  # the acceptance contract: cause + worlds
        assert e["cause"] == "worker_killed:sig9"
        assert (e["old_world"], e["new_world"]) == (4, 3)
    assert restore[0]["ckpt_step"] == 7


def test_exit_for_reform_carries_contract_exit_code(tmp_path):
    obs.enable(str(tmp_path / "obs"))
    try:
        with pytest.raises(ReformExit) as ei:
            elastic.exit_for_reform("peer_lost")
        assert ei.value.code == ELASTIC_RESTART_EXIT == 75
    finally:
        obs.disable()
    events = obs.read_events(str(tmp_path / "obs"))
    assert any(e["event"] == "elastic_reform_request" and
               e["cause"] == "peer_lost" for e in events)


# -- dist.init retry (replacement worker racing the coordinator port) --------

def test_dist_init_retries_with_backoff(monkeypatch, _fast_retry):
    from mxnet_tpu.parallel import distributed_trainer as dt

    calls = []
    monkeypatch.setattr(dt.jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(dt, "_already_bootstrapped", lambda: False)
    monkeypatch.setattr(dt, "_initialized", False)
    faults.arm("dist.init", on=1)  # first dial: coordinator not up yet
    dt.init("127.0.0.1:9", num_processes=2, process_id=1, retries=3)
    assert len(calls) == 1  # second attempt connected
    log = retry.attempt_log("dist.init")
    assert [a["ok"] for a in log] == [False, True]
    assert obs.REGISTRY.get("retry_attempts_total").value(
        site="dist.init", ok="false") >= 1


def test_dist_init_failed_attempt_does_not_poison_retry(monkeypatch,
                                                        _fast_retry):
    """jax's State.initialize registers global_state.client BEFORE
    client.connect(): a failed dial that *raises* must not leave the
    half-built client behind, or attempt 2 dies on "should only be called
    once" (and _already_bootstrapped() reports the failure as success)."""
    from jax._src import distributed as jdist

    from mxnet_tpu.parallel import distributed_trainer as dt

    calls = []

    def _initialize(**kw):
        if jdist.global_state.client is not None:
            raise RuntimeError(
                "distributed.initialize should only be called once.")
        jdist.global_state.client = object()  # assigned pre-connect...
        calls.append(kw)
        if len(calls) == 1:
            raise IOError("connect: coordinator not up")  # ...then the dial

    monkeypatch.setattr(dt.jax.distributed, "initialize", _initialize)
    monkeypatch.setattr(dt, "_already_bootstrapped", lambda: False)
    monkeypatch.setattr(dt, "_initialized", False)
    monkeypatch.setattr(jdist.global_state, "client", None)
    monkeypatch.setattr(jdist.global_state, "service", None)
    try:
        dt.init("127.0.0.1:9", num_processes=2, process_id=1, retries=3)
    finally:
        jdist.global_state.client = None
    assert len(calls) == 2  # attempt 2 re-dialed instead of "called once"
    assert [a["ok"] for a in retry.attempt_log("dist.init")] == [False, True]


def test_dist_init_exhausted_retries_fail(monkeypatch, _fast_retry):
    from mxnet_tpu.parallel import distributed_trainer as dt

    monkeypatch.setattr(dt.jax.distributed, "initialize",
                        lambda **kw: None)
    monkeypatch.setattr(dt, "_already_bootstrapped", lambda: False)
    monkeypatch.setattr(dt, "_initialized", False)
    faults.arm("dist.init", every=1)  # coordinator never comes up
    with pytest.raises(retry.RetryError):
        dt.init("127.0.0.1:9", num_processes=2, process_id=1, retries=2)
    assert not dt._initialized


def test_reform_tears_down_then_rejoins(monkeypatch, tmp_path):
    from mxnet_tpu.parallel import distributed_trainer as dt

    order = []
    monkeypatch.setattr(dt, "shutdown", lambda: order.append("shutdown"))
    monkeypatch.setattr(
        dt, "init",
        lambda coord, n, pid, timeout=None: order.append(("init", coord, n,
                                                          pid)))
    obs.enable(str(tmp_path / "obs"))
    try:
        got = elastic.reform("127.0.0.1:7", 3, 1)
        assert got is None  # no mesh_config
        assert order == ["shutdown", ("init", "127.0.0.1:7", 3, 1)]
        assert obs.REGISTRY.get("mesh_reformations_total").value(
            cause="reform_call") == 1
        assert obs.REGISTRY.get("elastic_world_size").value() == 3
    finally:
        obs.disable()
    events = obs.read_events(str(tmp_path / "obs"))
    assert any(e["event"] == "mesh_reformation" and e["new_world"] == 3
               for e in events)


# -- world-size-agnostic checkpoints + reshard-on-restore --------------------

def _fsdp_ts(mesh, seed=7):
    """Adam + f16 dynamic loss scaling on an fsdp-sharded MLP: the state a
    resharded restore must carry bit-exactly (params, Adam (mean, var) and
    t, the loss-scale carry)."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"),
            nn.Dense(4, in_units=16))
    net.initialize()
    _ = net(nd.ones((8, 8)))
    rules = ShardingRules(fsdp_axis="fsdp", min_fsdp_size=1)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    return TrainStep(net, lambda o, y: loss_fn(o, y),
                     optimizer.Adam(learning_rate=1e-2), mesh=mesh,
                     rules=rules, amp=Policy("float16", loss_scale=8.0))


def _state_arrays(ts):
    """(sorted flat params, sorted flat opt leaves) as host numpy — names
    differ across fresh nets (gluon name counters) but sorted order
    corresponds structurally (same contract as test_resilience)."""
    import jax

    params = [np.asarray(ts.params[k]) for k in sorted(ts.params)]
    opt = [np.asarray(x)
           for k in sorted(ts.opt_state)
           for x in jax.tree_util.tree_leaves(ts.opt_state[k])]
    return params, opt


_XY = lambda: (nd.ones((8, 8)), nd.array([0, 1, 2, 3, 0, 1, 2, 3]))  # noqa: E731


@pytest.fixture
def _sharded_ckpt():
    config.set("ckpt_sharded", True)
    yield
    config._values.pop("ckpt_sharded", None)


@pytest.mark.parametrize("restore_world", [4, 2, 1])
def test_reshard_on_restore_bit_identical(tmp_path, _sharded_ckpt,
                                          restore_world):
    """Save at a world=4 fsdp layout; restore at world 4 / 2 / 1. The
    restored params and opt state (incl. Adam's t and the f16 loss-scale
    carry) must be bit-identical whatever the restoring world — elastic
    scale-down and scale-up change only the layout, never the numbers."""
    d = str(tmp_path / "ckpt")
    x, y = _XY()
    ts = _fsdp_ts(make_mesh(MeshConfig(fsdp=4)))
    for _ in range(3):
        ts(x, y)
    ts.save(d)
    want_params, want_opt = _state_arrays(ts)
    want_scale = ts.loss_scale

    # the manifest is the world-size-agnostic contract: global shape +
    # partition spec per array, per-shard index windows
    from mxnet_tpu.resilience import integrity
    mf = integrity.read_manifest(latest_checkpoint(d))
    assert mf["format"] == "npz-shards"
    recs = mf["arrays"].values()
    assert all("global_shape" in r and "spec" in r for r in recs)
    assert any(len(r["shards"]) > 1 for r in recs)  # actually sharded

    mesh = make_mesh(MeshConfig(fsdp=restore_world)) \
        if restore_world > 1 else None
    ts2 = _fsdp_ts(mesh, seed=23)  # different init: restore must overwrite
    assert ts2.restore(d)
    got_params, got_opt = _state_arrays(ts2)
    for a, b in zip(want_params, got_params):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(want_opt, got_opt):
        np.testing.assert_array_equal(a, b)
    # the schedule clock, Adam's applied-step t, and the amp carry
    assert ts2.optimizer.num_update == ts.optimizer.num_update == 3
    assert int(np.asarray(ts2.step_count)) == int(np.asarray(ts.step_count))
    assert ts2.loss_scale == want_scale
    if restore_world > 1:  # state actually landed in the new fsdp layout
        anyp = next(iter(ts2.params.values()))
        assert len(anyp.sharding.device_set) == restore_world
    ts2(x, y)  # the re-laid-out state trains


def test_scale_back_up_after_scale_down(tmp_path, _sharded_ckpt):
    """down (4 -> 2) then up (2 -> 4): both directions ride the same
    manifest; numbers never change."""
    d1, d2 = str(tmp_path / "c1"), str(tmp_path / "c2")
    x, y = _XY()
    ts4 = _fsdp_ts(make_mesh(MeshConfig(fsdp=4)))
    ts4(x, y)
    ts4.save(d1)
    ts2 = _fsdp_ts(make_mesh(MeshConfig(fsdp=2)), seed=23)
    assert ts2.restore(d1)
    ts2(x, y)
    ts2.save(d2)
    back4 = _fsdp_ts(make_mesh(MeshConfig(fsdp=4)), seed=31)
    assert back4.restore(d2)
    p2, o2 = _state_arrays(ts2)
    p4, o4 = _state_arrays(back4)
    for a, b in zip(p2 + o2, p4 + o4):
        np.testing.assert_array_equal(a, b)
    assert back4.optimizer.num_update == 2


def test_sharded_roundtrip_ml_dtypes_leaf(tmp_path, _sharded_ckpt):
    """np.savez degrades ml_dtypes leaves (bf16-stored weights are a
    supported AMP configuration) to raw void records — restore must
    reinterpret them against the manifest dtype, in both the npz-shards
    and flat-npz formats, not crash on 'no cast function'."""
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    w = np.arange(8, dtype=bf16)
    like = ({"w": np.zeros(8, bf16)}, {})
    for name, sharded in (("s", True), ("f", False)):
        d = str(tmp_path / name)
        save_train_state(d, 1, {"w": w}, {}, sharded=sharded)
        params, _, step = load_train_state(os.path.join(d, "ckpt-1"),
                                           like=like)
        assert step == 1
        assert params["w"].dtype == bf16, (name, params["w"].dtype)
        np.testing.assert_array_equal(params["w"], w)


def test_resume_flag_return_does_not_fake_ckpt_step(tmp_path):
    """A restore_fn returning a restored *flag* (TrainStep.restore does)
    must not put ``ckpt_step: true`` in the elastic_restore event."""
    obs.enable(str(tmp_path / "obs"))
    try:
        ctx = ElasticContext(rank=0, world=2, generation=1, cause="x")
        assert ctx.resume(lambda: True) is True
        ctx.shutdown()
    finally:
        obs.disable()
    events = obs.read_events(str(tmp_path / "obs"))
    restore = [e for e in events if e["event"] == "elastic_restore"]
    assert len(restore) == 1 and restore[0]["ckpt_step"] is None


def test_sharded_manifest_verifies_shards(tmp_path, _sharded_ckpt):
    """A tampered shard payload fails file-level validation (skipped by
    latest_checkpoint) and, read directly, per-shard sha256 verification."""
    d = str(tmp_path / "ckpt")
    x, y = _XY()
    ts = _fsdp_ts(make_mesh(MeshConfig(fsdp=4)))
    ts(x, y)
    path = ts.save(d)
    npz = os.path.join(path, "shards-h0.npz")
    blob = bytearray(open(npz, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(npz, "wb") as f:
        f.write(bytes(blob))
    assert latest_checkpoint(d) is None  # file sha mismatch: not a candidate
    with pytest.raises(CheckpointCorruptError):
        load_train_state(path, like=(ts.params, ts.opt_state))


def test_corruption_is_not_retried(tmp_path, _fast_retry):
    """CheckpointCorruptError.retryable = False: deterministic corruption
    surfaces unwrapped after ONE attempt instead of burning the backoff
    budget into a RetryError."""
    d = str(tmp_path / "c")
    save_train_state(d, 1, {"w": np.arange(8.0, dtype=np.float32)}, {})
    path = os.path.join(d, "ckpt-1")
    npz = os.path.join(path, "arrays.npz")
    blob = bytearray(open(npz, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(npz, "wb") as f:
        f.write(bytes(blob))
    retry.clear_log()
    with pytest.raises(CheckpointCorruptError):
        load_train_state(path, like=({"w": np.zeros(8, np.float32)}, {}))
    assert len(retry.attempt_log("ckpt.load")) == 1


def test_multihost_meta_written_last(tmp_path, _sharded_ckpt, monkeypatch):
    """The save-barrier ordering contract on one process: every barrier in
    the collective save runs in stage -> shards -> commit order, and
    ``meta.json`` does not exist until after the all-shards barrier — so a
    host that dies mid-save can never leave a checkpoint that
    ``latest_checkpoint`` would adopt."""
    from mxnet_tpu import checkpoint as ck

    seen = []

    def _spy(name):
        seen.append(name)
        if name == "ckpt.save.shards":
            # at the all-shards barrier the manifest/meta must NOT be
            # committed yet (rank 0 writes them after this barrier)
            assert not os.path.exists(
                os.path.join(str(tmp_path / "c"), "ckpt-1", "meta.json"))

    monkeypatch.setattr(ck, "_barrier", _spy)
    save_train_state(str(tmp_path / "c"), 1,
                     {"w": np.arange(8.0, dtype=np.float32)}, {})
    assert seen == ["ckpt.save.stage", "ckpt.save.shards", "ckpt.save.commit"]
    assert latest_checkpoint(str(tmp_path / "c")).endswith("ckpt-1")
