"""Initializer registry (reference: ``python/mxnet/initializer.py``).

Initializers are pure: ``init_array(name, shape, dtype, key)`` returns a jax
array. Name-based dispatch (`.*weight` → init, `.*bias` → zero, etc.) matches
the reference's ``InitDesc`` pattern matching.
"""
from __future__ import annotations

import math
import re

import jax
import jax.numpy as jnp

from .base import dtype_np

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "registry", "create"]


class Initializer:
    def init_array(self, shape, dtype, key):
        raise NotImplementedError

    # dispatch mimicking reference InitDesc attr handling
    def __call__(self, desc, arr=None):
        from .ndarray import NDArray

        name = desc if isinstance(desc, str) else getattr(desc, "name", str(desc))
        key = jax.random.key(abs(hash(name)) % (2 ** 31))
        data = self.init_for_name(name, arr.shape, arr.dtype, key)
        arr._data = jnp.asarray(data, arr._data.dtype)

    def init_for_name(self, name, shape, dtype, key):
        if name.endswith("bias") or name.endswith("beta") or name.endswith("running_mean"):
            return jnp.zeros(shape, dtype_np(dtype))
        if name.endswith("gamma") or name.endswith("running_var"):
            return jnp.ones(shape, dtype_np(dtype))
        return self.init_array(shape, dtype, key)


class Zero(Initializer):
    def init_array(self, shape, dtype, key):
        return jnp.zeros(shape, dtype_np(dtype))


class One(Initializer):
    def init_array(self, shape, dtype, key):
        return jnp.ones(shape, dtype_np(dtype))


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def init_array(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype_np(dtype))


class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale

    def init_array(self, shape, dtype, key):
        return jax.random.uniform(key, shape, jnp.float32, -self.scale, self.scale).astype(dtype_np(dtype))


class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def init_array(self, shape, dtype, key):
        return (jax.random.normal(key, shape, jnp.float32) * self.sigma).astype(dtype_np(dtype))


class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale

    def init_array(self, shape, dtype, key):
        flat = (shape[0], int(jnp.prod(jnp.array(shape[1:])))) if len(shape) > 1 else (shape[0], 1)
        a = jax.random.normal(key, flat, jnp.float32)
        q, r = jnp.linalg.qr(a if flat[0] >= flat[1] else a.T)
        q = q if flat[0] >= flat[1] else q.T
        q = q * jnp.sign(jnp.diagonal(r))[None, :q.shape[1]]
        return (self.scale * q.reshape(shape)).astype(dtype_np(dtype))


def _fan(shape):
    if len(shape) < 2:
        return shape[0] if shape else 1, shape[0] if shape else 1
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type, self.factor_type, self.magnitude = rnd_type, factor_type, float(magnitude)

    def init_array(self, shape, dtype, key):
        fan_in, fan_out = _fan(shape)
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            out = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            out = jax.random.normal(key, shape, jnp.float32) * scale
        return out.astype(dtype_np(dtype))


class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)


class Bilinear(Initializer):
    def init_array(self, shape, dtype, key):
        import numpy as np

        weight = np.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype_np(dtype))


class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        self.forget_bias = forget_bias

    def init_array(self, shape, dtype, key):
        b = jnp.zeros(shape, jnp.float32)
        n = shape[0] // 4
        return b.at[n:2 * n].set(self.forget_bias).astype(dtype_np(dtype))


registry = {
    "zeros": Zero, "zero": Zero, "ones": One, "one": One, "constant": Constant,
    "uniform": Uniform, "normal": Normal, "gaussian": Normal, "orthogonal": Orthogonal,
    "xavier": Xavier, "msra_prelu": MSRAPrelu, "bilinear": Bilinear, "lstmbias": LSTMBias,
}


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return registry[name.lower()](**kwargs)
