"""``mx.operator`` — user-defined operators (CustomOp).

Reference: ``python/mxnet/operator.py`` + ``src/operator/custom/custom.cc``.
There the user's Python ``forward``/``backward`` are called back from the
engine on a dedicated GIL-aware thread; here the TPU-native shape is
``jax.custom_vjp``: the user's ``forward`` defines the primal, the user's
``backward`` defines the VJP, and both trace into the surrounding XLA
program — so a CustomOp composes with ``hybridize()``/``jit`` instead of
punching an engine-callback hole the compiler cannot see through.

The user's code runs on NDArray handles whose buffers may be tracers, so it
must stay inside the ``mx.nd`` op surface (the overwhelmingly common case in
reference CustomOps). NumPy round-trips (``asnumpy``) cannot trace; such ops
belong behind ``jax.pure_callback`` — see ``HostCallbackOp`` below, the
escape hatch matching the reference's host-side execution semantics.
"""
from __future__ import annotations

from typing import Dict, List, Type

import jax
import numpy as _np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_class"]


class CustomOp:
    """Base class of user ops (reference: ``mx.operator.CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the write/add/null request."""
        if req == "null":
            return
        raw = src._data if hasattr(src, "_data") else src
        if req == "add":
            dst._data = dst._data + raw
        else:  # write / inplace
            dst._data = raw


class CustomOpProp:
    """Shape/type inference + operator factory (reference: CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes) -> CustomOp:
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        return list(out_grad) + list(in_data) + list(out_data)


_CUSTOM_PROPS: Dict[str, Type[CustomOpProp]] = {}


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type=reg_name``."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(f"{prop_cls} must subclass CustomOpProp")
        # module-import-time registration (the reference's C API contract);
        # no worker thread registers custom ops
        _CUSTOM_PROPS[reg_name] = prop_cls  # lint: disable=JH005
        return prop_cls

    return deco


def get_prop_class(op_type):
    try:
        return _CUSTOM_PROPS[op_type]
    except KeyError:
        raise MXNetError(
            f"custom op {op_type!r} is not registered; "
            f"known: {sorted(_CUSTOM_PROPS)}") from None


def _dtype_name(dt):
    name = _np.dtype(dt).name if not str(dt) == "bfloat16" else "bfloat16"
    return name


def make_custom_fn(op_type, kwargs):
    """Build (pure_fn, nout) for ``nd.Custom``: a ``jax.custom_vjp`` whose
    primal/vjp run the user's forward/backward on NDArray views."""
    from .ndarray import NDArray

    prop = get_prop_class(op_type)(**{k: str(v) for k, v in kwargs.items()})
    n_in = len(prop.list_arguments())
    n_out = len(prop.list_outputs())

    def _run_forward(raws, is_train):
        in_shapes = [list(r.shape) for r in raws]
        in_shapes, out_shapes, _aux_shapes = prop.infer_shape(in_shapes)
        in_types = [_dtype_name(r.dtype) for r in raws]
        _, out_types, _ = prop.infer_type(in_types)
        op = prop.create_operator(None, in_shapes + out_shapes, in_types + out_types)
        in_data = [NDArray(r) for r in raws]
        from .base import dtype_np

        out_data = [NDArray(jax.numpy.zeros(tuple(s), dtype_np(t)))
                    for s, t in zip(out_shapes, out_types)]
        op.forward(is_train, ["write"] * n_out, in_data, out_data, [])
        return op, in_data, out_data

    @jax.custom_vjp
    def fn(*raws):
        _, _, out_data = _run_forward(raws, True)
        outs = tuple(o._data for o in out_data)
        return outs if n_out > 1 else outs[0]

    def fwd(*raws):
        _, _, out_data = _run_forward(raws, True)
        outs = tuple(o._data for o in out_data)
        # residual carries only the inputs: backward re-derives outputs, so
        # saving them would pin dead buffers across the fwd->bwd gap
        return (outs if n_out > 1 else outs[0]), raws

    def bwd(raws, gs):
        gs = gs if isinstance(gs, tuple) else (gs,)
        # a fresh operator instance re-derives forward state for backward
        op, in_data, out_data = _run_forward(raws, True)
        in_grad = [a._empty_like() for a in in_data]
        op.backward(["write"] * n_in, [NDArray(g) for g in gs], in_data,
                    out_data, in_grad, [])
        return tuple(g._data for g in in_grad)

    fn.defvjp(fwd, bwd)
    return fn, n_out
