"""Golden-program sharding + communication gate (ISSUE 8,
docs/ANALYSIS.md): `make shardcheck` as a test — the committed goldens
match the current programs, a synthetic extra all-gather fails the build,
and the --update-golden rebless workflow round-trips.

Runs tools/shardcheck.py in-process (importlib) so each case can pick one
cheap program family and capture the JSON verdict without a subprocess
per family.
"""
import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def shardcheck():
    spec = importlib.util.spec_from_file_location(
        "shardcheck_mod", os.path.join(REPO, "tools", "shardcheck.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _verdict(capsys):
    out = capsys.readouterr().out
    row, _ = json.JSONDecoder().raw_decode(out, out.index("{"))
    return row, out


def test_gate_matches_committed_goldens(shardcheck, capsys):
    """ISSUE 8 acceptance: the committed goldens describe the current
    programs — zero contract violations, no new collective kinds, comm
    bytes within tolerance."""
    rc = shardcheck.main(["--family", "step_fsdp"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    fam = row["families"]["step_fsdp"]
    assert fam["contract_violations"] == []
    assert fam["accidental_reshards"] == []
    assert fam["carry_donation"] == 1.0
    assert fam["comm_total_bytes"] > 0          # a non-empty CommReport
    assert set(fam["comm_by_axis"]) == {"fsdp", "dp×fsdp"}


def test_injected_all_gather_fails_gate(shardcheck, capsys):
    """ISSUE 8 acceptance: a synthetic extra all-gather (the --inject
    test hook) must fail the build — as a NEW collective kind on the
    all-reduce-only dp family, and as a comm-byte regression."""
    rc = shardcheck.main(["--family", "step_dp8", "--inject-all-gather"])
    _, out = _verdict(capsys)
    assert rc == 1
    assert "new collective kind(s) ['all_gather']" in out
    assert "comm bytes" in out and "regressed" in out


def test_paged_families_match_goldens(shardcheck, capsys):
    """ISSUE 11 satellite: the paged decode + speculative verify program
    families are pinned to committed goldens — zero collectives (the
    serving contract) and a fully donated page-table + pool carry."""
    rc = shardcheck.main(["--family", "decode_paged",
                          "--family", "verify_spec"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    for fam in ("decode_paged", "verify_spec"):
        assert row["families"][fam]["collectives"] == {}
        assert row["families"][fam]["carry_donation"] == 1.0


def test_inject_cannot_combine_with_update_golden(shardcheck, capsys):
    """The failure-path hook must never bless the injected census into
    the committed goldens."""
    with pytest.raises(SystemExit) as exc:
        shardcheck.main(["--update-golden", "--inject-all-gather"])
    assert exc.value.code == 2
    assert "cannot be combined" in capsys.readouterr().err


def test_update_golden_rebless_roundtrip(shardcheck, capsys, monkeypatch,
                                         tmp_path):
    """--update-golden writes a fresh golden that the plain gate then
    passes against; with no golden at all the gate fails with the
    rebless instruction instead of crashing."""
    monkeypatch.setattr(shardcheck, "GOLDEN_DIR", str(tmp_path))
    rc = shardcheck.main(["--family", "decode"])
    _, out = _verdict(capsys)
    assert rc == 1 and "no committed golden" in out
    assert "--update-golden" in out
    rc = shardcheck.main(["--family", "decode", "--update-golden"])
    assert rc == 0
    golden = json.loads((tmp_path / "decode.json").read_text())
    assert golden["collectives"] == {}          # serving: zero collectives
    assert golden["carry_donation"] == 1.0
    rc = shardcheck.main(["--family", "decode"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
