"""Data pipeline: DataLoader, NDArrayIter, RecordIO wire format
(reference: tests/python/unittest/test_io.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.io import NDArrayIter, MXRecordIO, IndexedRecordIO
from mxnet_tpu.io.recordio import IRHeader, pack, unpack, pack_img, unpack_img


def test_ndarray_iter_basic():
    data = np.arange(20).reshape(10, 2).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 2)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_discard():
    it = NDArrayIter(np.zeros((10, 2)), np.zeros(10), batch_size=4,
                     last_batch_handle="discard")
    assert len(list(it)) == 2


def test_dataloader_batching_and_shuffle():
    ds = gluon.data.ArrayDataset(np.arange(10).astype(np.float32),
                                 np.arange(10).astype(np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=3, shuffle=False)
    batches = list(loader)
    assert len(batches) == 4
    np.testing.assert_allclose(batches[0][0].asnumpy(), [0, 1, 2])

    loader2 = gluon.data.DataLoader(ds, batch_size=5, shuffle=True, last_batch="discard")
    batches2 = list(loader2)
    assert len(batches2) == 2


def test_dataloader_transform():
    ds = gluon.data.ArrayDataset(np.ones((6, 2), np.float32))
    ds2 = ds.transform(lambda x: x * 2)
    loader = gluon.data.DataLoader(ds2, batch_size=2)
    for (b,) in [(b,) for b in loader]:
        np.testing.assert_allclose(b.asnumpy(), np.full((2, 2), 2.0))


def test_recordio_roundtrip(tmp_path):
    f = str(tmp_path / "x.rec")
    w = MXRecordIO(f, "w")
    records = [b"hello", b"x" * 1000, b"", b"abc" * 7]
    for r in records:
        w.write(r)
    w.close()
    r = MXRecordIO(f, "r")
    out = []
    while True:
        item = r.read()
        if item is None:
            break
        out.append(item)
    assert out == records


def test_indexed_recordio(tmp_path):
    f = str(tmp_path / "y.rec")
    idx = str(tmp_path / "y.idx")
    w = IndexedRecordIO(idx, f, "w")
    for i in range(5):
        w.write_idx(i, f"rec{i}".encode())
    w.close()
    r = IndexedRecordIO(idx, f, "r")
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    assert len(r.keys) == 5


def test_pack_unpack_header():
    h = IRHeader(0, 3.0, 7, 0)
    s = pack(h, b"payload")
    h2, data = unpack(s)
    assert h2.label == 3.0 and h2.id == 7 and data == b"payload"
    # vector label
    hv = IRHeader(0, np.array([1.0, 2.0], np.float32), 1, 0)
    s = pack(hv, b"p2")
    h3, d3 = unpack(s)
    np.testing.assert_allclose(h3.label, [1.0, 2.0])


def test_pack_img_roundtrip():
    # .npy format: lossless
    img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
    s = pack_img(IRHeader(0, 1.0, 0, 0), img, img_fmt=".npy")
    h, img2 = unpack_img(s)
    np.testing.assert_array_equal(img, img2)
    # default .jpg format: lossy but close on smooth content, decoded by the
    # native baseline decoder
    yy, xx = np.mgrid[0:16, 0:16]
    smooth = np.stack([yy * 8, xx * 8, yy * 4 + xx * 4], 2).astype(np.uint8)
    s = pack_img(IRHeader(0, 1.0, 0, 0), smooth)
    h, img3 = unpack_img(s)
    assert img3.shape == smooth.shape
    assert np.abs(img3.astype(int) - smooth.astype(int)).mean() < 4.0


def test_vision_datasets_synthetic():
    ds = gluon.data.vision.MNIST(train=True)
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) < 10
    c = gluon.data.vision.CIFAR10(train=False)
    x, y = c[5]
    assert x.shape == (32, 32, 3)


def test_prefetching_iter():
    from mxnet_tpu.io import PrefetchingIter

    base = NDArrayIter(np.zeros((8, 2)), np.zeros(8), batch_size=4)
    pf = PrefetchingIter(base)
    n = 0
    for _ in pf:
        n += 1
    assert n == 2


def _make_fixture_rec(tmp_path, n=24, size=(36, 48), jpeg=True):
    """Pack a small im2rec-style fixture; JPEG via cv2 when available."""
    from mxnet_tpu.io.recordio import IndexedRecordIO, IRHeader, pack_img

    rec = IndexedRecordIO(str(tmp_path / "fix.idx"), str(tmp_path / "fix.rec"), "w")
    rs = np.random.RandomState(0)
    for i in range(n):
        yy, xx = np.mgrid[0:size[0], 0:size[1]]
        img = np.stack([(yy * (i + 1)) % 256, (xx * 2) % 256,
                        (yy + xx + i) % 256], axis=2).astype(np.uint8)
        fmt = ".jpg" if jpeg else ".npy"
        rec.write_idx(i, pack_img(IRHeader(0, float(i % 3), i, 0), img,
                                  img_fmt=fmt))
    rec.close()
    return str(tmp_path / "fix.rec"), str(tmp_path / "fix.idx")


def test_native_jpeg_decode_matches_cv2(tmp_path):
    """The dependency-free baseline decoder agrees with cv2 on 4:2:0 JPEG."""
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu.native import available, jpeg_decode

    if not available():
        pytest.skip("native lib not built")
    yy, xx = np.mgrid[0:50, 0:70]
    img = np.stack([yy % 256, (xx * 3) % 256, (xx + yy) % 256], 2).astype(np.uint8)
    ok, enc = cv2.imencode(".jpg", cv2.cvtColor(img, cv2.COLOR_RGB2BGR),
                           [cv2.IMWRITE_JPEG_QUALITY, 95])
    assert ok
    mine = jpeg_decode(enc.tobytes())
    ref = cv2.cvtColor(cv2.imdecode(enc, cv2.IMREAD_COLOR), cv2.COLOR_BGR2RGB)
    assert mine.shape == ref.shape
    d = np.abs(mine.astype(int) - ref.astype(int))
    # nearest-neighbor chroma upsample vs libjpeg fancy upsample: tiny mean
    assert d.mean() < 3.0


def test_image_record_iter_end_to_end(tmp_path):
    """im2rec-packed JPEG fixture -> ImageRecordIter: decode, short-edge
    resize, crop, mean/std, NCHW batches, correct labels, sharding."""
    from mxnet_tpu.io import ImageRecordIter

    recf, idxf = _make_fixture_rec(tmp_path)
    it = ImageRecordIter(path_imgrec=recf, data_shape=(3, 28, 28),
                         batch_size=8, resize=32, shuffle=False,
                         mean_r=123.0, mean_g=117.0, mean_b=104.0,
                         std_r=58.4, std_g=57.1, std_b=57.4,
                         preprocess_threads=2)
    assert it.provide_data[0].shape == (8, 3, 28, 28)
    batches = list(it)
    assert len(batches) == 3
    b0 = batches[0]
    assert b0.data[0].shape == (8, 3, 28, 28)
    assert str(b0.data[0]._data.dtype) == "float32"
    np.testing.assert_allclose(np.asarray(b0.label[0]._data),
                               [i % 3 for i in range(8)])
    # normalized pixels land in a sane range
    v = np.asarray(b0.data[0]._data)
    assert np.abs(v).max() < 6.0
    # epoch 2 after reset
    it.reset()
    assert sum(1 for _ in it) == 3
    it.close()

    # sharding: 2 parts see disjoint halves
    it0 = ImageRecordIter(path_imgrec=recf, data_shape=(3, 28, 28),
                          batch_size=4, num_parts=2, part_index=0)
    it1 = ImageRecordIter(path_imgrec=recf, data_shape=(3, 28, 28),
                          batch_size=4, num_parts=2, part_index=1)
    l0 = np.concatenate([np.asarray(b.label[0]._data) for b in it0])
    l1 = np.concatenate([np.asarray(b.label[0]._data) for b in it1])
    assert len(l0) == len(l1) == 12
    np.testing.assert_allclose(l0, [i % 3 for i in range(0, 24, 2)])
    np.testing.assert_allclose(l1, [i % 3 for i in range(1, 24, 2)])
    it0.close(); it1.close()


def test_image_record_iter_idx_shuffle_augment(tmp_path):
    from mxnet_tpu.io import ImageRecordIter

    recf, idxf = _make_fixture_rec(tmp_path, jpeg=False)  # npy payload path
    it = ImageRecordIter(path_imgrec=recf, path_imgidx=idxf,
                         data_shape=(3, 24, 24), batch_size=6, shuffle=True,
                         rand_crop=True, rand_mirror=True, seed=7)
    labels_e1 = np.concatenate([np.asarray(b.label[0]._data) for b in it])
    it.reset()
    labels_e2 = np.concatenate([np.asarray(b.label[0]._data) for b in it])
    assert len(labels_e1) == 24
    # shuffled epochs differ (with overwhelming probability given 24!)
    assert not np.array_equal(labels_e1, labels_e2)
    it.close()


def test_imdecode_public_api():
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu import image as mimg
    from mxnet_tpu.native import available

    if not available():
        pytest.skip("native lib not built")
    img = np.full((16, 20, 3), 128, np.uint8)
    ok, enc = cv2.imencode(".jpg", img)
    out = mimg.imdecode(enc.tobytes())
    assert out.shape == (16, 20, 3)
    assert abs(int(np.asarray(out._data).mean()) - 128) <= 2


def test_prefetching_iter_close_then_next_raises(tmp_path):
    """close() joins the prefetch thread; a later next() raises instead of
    hanging on the drained queue."""
    from mxnet_tpu.io.io import NDArrayIter, PrefetchingIter

    import numpy as np

    it = NDArrayIter(np.ones((16, 2), np.float32),
                     np.zeros((16,), np.float32), batch_size=4)
    pf = PrefetchingIter(it)
    b = pf.next()
    assert b is not None
    pf.close()
    with pytest.raises(StopIteration):
        pf.next()


def test_image_folder_dataset(tmp_path):
    """class-per-subdirectory layout -> (image, label) samples."""
    import numpy as np

    from mxnet_tpu.gluon.data.vision import ImageFolderDataset

    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            np.save(str(d / f"img{i}.npy"),
                    (np.random.rand(8, 8, 3) * 255).astype(np.uint8))
    ds = ImageFolderDataset(str(tmp_path))
    assert len(ds) == 6
    assert ds.synsets == ["cat", "dog"]
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and label == 0
    img, label = ds[5]
    assert label == 1
    # empty dir raises
    import pytest as _pytest

    empty = tmp_path / "empty_root"
    empty.mkdir()
    with _pytest.raises(ValueError, match="no images"):
        ImageFolderDataset(str(empty))


def test_opperf_runner(tmp_path):
    """tools/opperf.py (reference benchmark/opperf analog) runs a subset and
    emits the table + json."""
    import json
    import subprocess
    import sys

    json_path = str(tmp_path / "opperf.json")
    out = subprocess.run(
        [sys.executable, "tools/opperf.py", "--ops", "dot,softmax,LayerNorm",
         "--reps", "3", "--json", json_path,
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert out.returncode == 0, out.stderr[-400:]
    assert "Operator" in out.stdout and "dot" in out.stdout
    rows = json.load(open(json_path))
    assert {r["op"] for r in rows} == {"dot", "softmax", "LayerNorm"}
    assert all(r["p50_us"] > 0 for r in rows)


def test_image_folder_dataset_grayscale_and_case(tmp_path):
    import numpy as np

    from mxnet_tpu.gluon.data.vision import ImageFolderDataset

    d = tmp_path / "cls"
    d.mkdir()
    np.save(str(d / "UPPER.NPY"),
            (np.random.rand(6, 6, 3) * 255).astype(np.uint8))
    ds = ImageFolderDataset(str(tmp_path))
    img, label = ds[0]  # uppercase .NPY routes via magic sniffing
    assert img.shape == (6, 6, 3)
    ds0 = ImageFolderDataset(str(tmp_path), flag=0)
    gray, _ = ds0[0]
    assert gray.shape == (6, 6, 1)


def test_iobench_artifact_gate():
    """SURVEY M2 gate evidence (round-4 verdict ask #6): the committed
    IOBENCH.json artifact must exist, carry real numbers, and show the
    input pipeline outrunning the CPU-step consumer. Regenerate with
    `python tools/iobench.py --json IOBENCH.json` after pipeline changes."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "IOBENCH.json")
    assert os.path.exists(path), "IOBENCH.json missing — run tools/iobench.py"
    art = json.load(open(path))
    assert art["value"] > 50, art  # imgs/s through decode+aug+batchify
    assert art["pipeline_covers_cpu_step"] is True
    assert art["resnet50_cpu_step_imgs_per_sec"] > 0
    assert "imgs_per_sec_by_threads" in art
