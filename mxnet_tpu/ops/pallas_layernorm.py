"""Fused LayerNorm Pallas kernel (SURVEY §7 M6: the second marquee kernel
after flash attention).

Reference analog: ``src/operator/nn/layer_norm.cc``'s fused CUDA kernel
(one pass: mean/var + normalize + affine). XLA already fuses the naive
composition well; the kernel's wins are (a) a single VMEM-resident pass —
the row is loaded once for mean, variance AND normalize (Welford-free
two-moment accumulation in f32), and (b) no intermediate f32 materialization
of the whole activation when the input is bf16.

Forward is the kernel; backward is the analytic LN VJP expressed in jnp
(fusion-friendly, matches the flash-attention design split). Gated like the
flash kernel: TPU backend + feature dim a 128-lane multiple; callers fall
back to the jnp composition otherwise.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .pallas_common import HAS_PLTPU as _HAS_PLTPU
from .pallas_common import LANES as _LANES
from .pallas_common import on_tpu as _on_tpu

_BLOCK_ROWS = 256
# feature-dim cap: a (rows, d) f32 block must fit VMEM with room for the
# output block and the in-kernel f32 copy (~16MB total per core)
_MAX_D = 8192


def ln_kernel_supported(x, axis=-1) -> bool:
    # opt-in on hardware (MXNET_TPU_FUSED_LAYERNORM=1). Interactive round-3
    # runs (v5e, tools/kernelbench.py) saw oracle-exact results and
    # 1.00-1.03x vs the XLA-fused jnp composition at (8k-32k rows,
    # d 1024-4096), but NO committed artifact contains ln rows — treat as
    # pending hardware. Either way XLA already fuses this pattern well, so
    # the default stays the composition and the kernel remains an opt-in
    # (useful as a fusion-regression guard)
    from .. import config as _config

    if not _config.get("fused_layernorm"):
        return False
    ax = axis % x.ndim
    return (_HAS_PLTPU and _on_tpu() and ax == x.ndim - 1
            and x.shape[-1] % _LANES == 0 and x.shape[-1] <= _MAX_D
            and x.dtype in (jnp.float32, jnp.bfloat16))


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)  # (rows, d) resident in VMEM once
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * lax.rsqrt(var + eps)
    o_ref[...] = (y * g_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_forward(x2, gamma, beta, eps, interpret=False):
    n, d = x2.shape
    # scale the row block down as d grows: keep in+out+f32-copy well under
    # VMEM (2^21 f32 elements ~ 8MB for the input block)
    rows = max(8, min(_BLOCK_ROWS, (2 ** 21) // max(d, 1), n))
    # pad rows so the grid divides evenly (padded rows normalize garbage,
    # sliced off below — cheap, keeps BlockSpecs static)
    n_pad = -(-n // rows) * rows
    if n_pad != n:
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((n_pad, d), x2.dtype),
        grid=(n_pad // rows,),
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, gamma, beta)
    return out[:n] if n_pad != n else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln(x2, gamma, beta, eps, interpret):
    return _ln_forward(x2, gamma, beta, eps, interpret)


def _ln_fwd(x2, gamma, beta, eps, interpret):
    return _ln_forward(x2, gamma, beta, eps, interpret), (x2, gamma)


def _ln_bwd(eps, interpret, res, g):
    # analytic LN backward in f32 (reference layer_norm.cc backward math)
    x2, gamma = res
    x = x2.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    d = x.shape[-1]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = xc * rstd
    dy = gf * gamma.astype(jnp.float32)
    dx = rstd * (dy - jnp.mean(dy, axis=-1, keepdims=True)
                 - xhat * jnp.mean(dy * xhat, axis=-1, keepdims=True))
    dgamma = jnp.sum(gf * xhat, axis=0)
    dbeta = jnp.sum(gf, axis=0)
    return (dx.astype(x2.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


_ln.defvjp(_ln_fwd, _ln_bwd)


def layer_norm_fused(data, gamma, beta, eps=1e-5, interpret=None):
    """Fused LN over the last axis; any leading shape (flattened to rows)."""
    if interpret is None:
        interpret = not _on_tpu()
    d = data.shape[-1]
    x2 = data.reshape(-1, d)
    out = _ln(x2, gamma, beta, float(eps), bool(interpret))
    return out.reshape(data.shape)
