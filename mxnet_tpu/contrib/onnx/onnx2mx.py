"""ONNX importer (reference: ``python/mxnet/contrib/onnx/onnx2mx/import_model.py``).

Parses an ONNX protobuf into a Symbol graph over the central op registry,
returning ``(sym, arg_params, aux_params)`` exactly like the reference API so
``gluon.SymbolBlock(sym, inputs)`` / ``Module`` can run or fine-tune it.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ...base import MXNetError
from . import proto


def _attr_pair(v, default):
    if v is None:
        return tuple(default)
    return tuple(int(x) for x in v)


def import_model(onnx_file):
    from ... import symbol as sym_mod
    from ...ndarray import NDArray

    with open(onnx_file, "rb") as f:
        model = proto.parse_model(f.read())
    graph = model["graph"]
    inits = graph["initializers"]

    env: Dict[str, object] = {}
    arg_params = {name: NDArray(np.asarray(arr)) for name, arr in inits.items()}

    for name, _elem, _shape in graph["inputs"]:
        if name not in inits:
            env[name] = sym_mod.var(name)
    for name in inits:
        env[name] = sym_mod.var(name)

    def apply(op, inputs, kwargs, name):
        return sym_mod._apply(op, [env[i] for i in inputs], kwargs, name)

    for node in graph["nodes"]:
        op, ins, outs, a = node["op_type"], node["inputs"], node["outputs"], node["attrs"]
        name = node["name"] or outs[0]
        if op == "Conv":
            pads = a.get("pads", [0, 0, 0, 0])
            if pads[:len(pads) // 2] != pads[len(pads) // 2:]:
                raise MXNetError("asymmetric Conv pads are not supported")
            w = inits[ins[1]]
            out = apply("Convolution", ins, {
                "kernel": _attr_pair(a.get("kernel_shape"), w.shape[2:]),
                "stride": _attr_pair(a.get("strides"), (1, 1)),
                "pad": tuple(pads[:len(pads) // 2]),
                "dilate": _attr_pair(a.get("dilations"), (1, 1)),
                "num_group": int(a.get("group", 1)),
                "num_filter": int(w.shape[0]),
                "no_bias": len(ins) < 3,
            }, name)
        elif op == "Gemm":
            if a.get("transA"):
                raise MXNetError("Gemm with transA=1 is not supported")
            alpha, beta = a.get("alpha", 1.0), a.get("beta", 1.0)
            w_name = ins[1]
            w = inits.get(w_name)
            if w is None:
                raise MXNetError("Gemm weight must be an initializer")
            if not a.get("transB"):
                w = np.ascontiguousarray(w.T)
            if alpha != 1.0:
                w = w * alpha
            arg_params[w_name] = NDArray(w)
            if len(ins) > 2 and beta != 1.0:
                arg_params[ins[2]] = NDArray(np.asarray(inits[ins[2]]) * beta)
            out = apply("FullyConnected", ins, {
                "num_hidden": int(w.shape[0]), "flatten": False,
                "no_bias": len(ins) < 3,
            }, name)
        elif op == "MatMul":
            out = apply("dot", ins, {}, name)
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                   "Softplus": "softrelu", "Softsign": "softsign"}[op]
            out = apply("Activation", ins, {"act_type": act}, name)
        elif op in ("MaxPool", "AveragePool"):
            pads = a.get("pads", [0, 0, 0, 0])
            # ONNX spec defaults: strides = 1 along each axis,
            # count_include_pad = 0
            out = apply("Pooling", ins, {
                "kernel": _attr_pair(a.get("kernel_shape"), (2, 2)),
                "stride": _attr_pair(a.get("strides"), (1, 1)),
                "pad": tuple(pads[:len(pads) // 2]),
                "pool_type": "max" if op == "MaxPool" else "avg",
                "count_include_pad": bool(a.get("count_include_pad", 0)),
            }, name)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            out = apply("Pooling", ins, {
                "global_pool": True,
                "pool_type": "max" if op == "GlobalMaxPool" else "avg",
            }, name)
        elif op == "BatchNormalization":
            out = apply("BatchNorm", ins, {
                "eps": float(a.get("epsilon", 1e-5)),
                "momentum": float(a.get("momentum", 0.9)),
                "use_global_stats": True,
            }, name)[0]
        elif op == "Flatten":
            out = apply("flatten", ins, {}, name)
        elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
            mx_op = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                     "Mul": "broadcast_mul", "Div": "broadcast_div",
                     "Pow": "broadcast_power"}[op]
            out = apply(mx_op, ins, {}, name)
        elif op in ("Exp", "Log", "Sqrt", "Neg", "Abs"):
            out = apply({"Exp": "exp", "Log": "log", "Sqrt": "sqrt",
                         "Neg": "negative", "Abs": "abs"}[op], ins, {}, name)
        elif op == "Softmax":
            out = apply("softmax", ins, {"axis": int(a.get("axis", -1))}, name)
        elif op == "LogSoftmax":
            out = apply("log_softmax", ins, {"axis": int(a.get("axis", -1))}, name)
        elif op == "Concat":
            out = apply("concat", ins, {"dim": int(a.get("axis", 1))}, name)
        elif op == "Reshape":
            shape = tuple(int(x) for x in inits[ins[1]])
            out = apply("reshape", ins[:1], {"shape": shape}, name)
            arg_params.pop(ins[1], None)
        elif op == "Transpose":
            out = apply("transpose", ins, {"axes": tuple(a["perm"]) if a.get("perm") else None}, name)
        elif op in ("ReduceSum", "ReduceMean", "ReduceMax", "ReduceMin"):
            axes = a.get("axes")
            out = apply({"ReduceSum": "sum", "ReduceMean": "mean",
                         "ReduceMax": "max", "ReduceMin": "min"}[op], ins, {
                "axis": tuple(axes) if axes else None,
                "keepdims": bool(a.get("keepdims", 1)),
            }, name)
        elif op in ("Dropout", "Identity"):
            out = env[ins[0]]  # inference identity
        else:
            raise MXNetError(f"ONNX import: unsupported operator {op!r}")
        env[outs[0]] = out

    head = graph["outputs"][0][0] if graph["outputs"] else None
    if head is None or head not in env:
        # fall back to the last node's output
        head = list(env)[-1]
    return env[head], arg_params, {}
