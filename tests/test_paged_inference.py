"""Paged KV-cache + speculative decoding (ISSUE 11 acceptance):

  - paged greedy tokens are BIT-IDENTICAL to the dense engine (the page
    indirection changes storage, never math: masked entries get an exact
    0.0 softmax weight in both layouts);
  - pages are reclaimed on release/EOS and safely reused (a released
    row's cleared table redirects its writes to the trash page, so a
    reallocated page can never be corrupted);
  - page exhaustion force-finishes rows (evict counter, batcher
    finish_reason="page_exhausted") instead of overflowing mid-decode;
  - batcher admission is bounded by free pages, with
    ``gen_admission_rejects_total{reason}`` on submit-rejects/deferrals;
  - speculative decoding is token-identical to non-speculative greedy at
    every accept rate — full accept (self-draft), partial accept
    (scripted draft, exact per-round emit counts), full reject — i.e. the
    frontier rollback is correct;
  - compiled-program count stays (buckets used + 1 decode) for the paged
    engine and (buckets + 1 decode + 1 verify) when speculating, flat
    under traffic;
  - ``engine.audit()``: 100% donation on the paged carry (page table +
    pools) and zero host transfers in decode + verify programs.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.inference import ContinuousBatcher, GenerationEngine
from mxnet_tpu.models import gpt2
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.observability import REGISTRY

VOCAB, EOS, PAD = 97, 96, 0


def _gpt2(max_length=64, seed=0):
    mx.random.seed(seed)
    net = gpt2.GPT2Model(num_layers=2, units=64, num_heads=4,
                         max_length=max_length, vocab_size=VOCAB, dropout=0.0)
    net.initialize()
    _ = net(nd.array(np.zeros((1, 4)), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def net():
    return _gpt2()


def _engine(net, paged=True, **kw):
    kw.setdefault("batch_size", 3)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("eos_id", EOS)
    kw.setdefault("pad_id", PAD)
    if paged:
        kw.setdefault("page_size", 8)
    return GenerationEngine(net, paged=paged, **kw)


def _prompt(n, seed, lo=1, hi=EOS):
    return list(np.random.RandomState(seed).randint(lo, hi, n))


def _counter_total(name, **labels):
    c = REGISTRY.get(name)
    if c is None:
        return 0
    return c.value(**labels) if labels else c.total()


class ScriptedDraft:
    """Duck-typed draft model whose greedy token at sequence position p is
    exactly ``script[p]`` — lets tests pin the accept/reject pattern."""

    def __init__(self, script, vocab, max_length):
        assert len(script) == max_length
        self._script = jnp.asarray(np.asarray(script, np.int32))
        self._vocab = vocab
        self._max_length = max_length

    def collect_params(self):
        return {}

    def init_paged_cache(self, num_pages, page_size, dtype="float32"):
        return [(jnp.zeros((num_pages + 1, 1, page_size, 1), jnp.float32),
                 jnp.zeros((num_pages + 1, 1, page_size, 1), jnp.float32))]

    def __call__(self, tokens, cache=None, start_pos=None, page_table=None):
        t = tokens._data.shape[1]
        pos = (start_pos._data.reshape(-1, 1)
               + jnp.arange(t, dtype=jnp.int32)[None, :])
        pos = jnp.clip(pos, 0, self._max_length - 1)
        logits = jax.nn.one_hot(self._script[pos], self._vocab,
                                dtype=jnp.float32) * 10.0
        return NDArray(logits), cache


# ---------------------------------------------------------------------------
# paged == dense, bit-identical greedy
# ---------------------------------------------------------------------------
class TestPagedEquivalence:
    def test_paged_matches_dense_greedy(self, net):
        prompts = [_prompt(5, 10), _prompt(12, 11), _prompt(3, 12)]
        ref = _engine(net, paged=False).generate(prompts, max_new_tokens=10)
        got = _engine(net).generate(prompts, max_new_tokens=10)
        assert got == ref

    def test_paged_logits_match_dense_per_step(self, net):
        dense = _engine(net, paged=False, batch_size=2)
        paged = _engine(net, batch_size=2)
        for i, p in enumerate([_prompt(5, 20), _prompt(12, 21)]):
            dense.prefill(p, slot=i)
            paged.prefill(p, slot=i)
        for _ in range(6):
            _, _, lg_d = dense.decode_step()
            _, _, lg_p = paged.decode_step()
            np.testing.assert_array_equal(np.array(lg_d), np.array(lg_p))

    def test_paged_bf16_cache_matches_dense_bf16(self, net):
        prompts = [_prompt(5, 31), _prompt(9, 32)]
        ref = _engine(net, paged=False, batch_size=2,
                      cache_dtype="bfloat16").generate(prompts,
                                                       max_new_tokens=8)
        eng = _engine(net, batch_size=2, cache_dtype="bfloat16")
        for k_pool, v_pool in eng.pools:
            assert k_pool.dtype == jnp.bfloat16 and v_pool.dtype == jnp.bfloat16
        assert eng.generate(prompts, max_new_tokens=8) == ref

    def test_odd_page_size_rounds_capacity_up(self, net):
        # max_length 64 with page_size 6 -> 11 page slots per row; the
        # extra masked capacity must not change tokens
        prompts = [_prompt(7, 40), _prompt(11, 41)]
        ref = _engine(net, paged=False, batch_size=2).generate(
            prompts, max_new_tokens=9)
        got = _engine(net, batch_size=2, page_size=6).generate(
            prompts, max_new_tokens=9)
        assert got == ref


# ---------------------------------------------------------------------------
# page lifecycle: allocation, reclaim, reuse
# ---------------------------------------------------------------------------
class TestPageLifecycle:
    def test_pages_reclaimed_and_reused(self, net):
        eng = _engine(net, batch_size=2, num_pages=8)  # 8 x 8 = 64 tokens
        total = eng.num_pages
        assert eng.free_pages == total
        ref = _engine(net, paged=False, batch_size=2)
        for wave in range(3):  # reuse the same pool across waves
            prompts = [_prompt(5, 50 + wave), _prompt(9, 60 + wave)]
            want = ref.generate(prompts, max_new_tokens=6)
            assert eng.generate(prompts, max_new_tokens=6) == want
        # rows finished by the token budget release their pages
        assert eng.free_pages == total
        assert _counter_total("gen_pages_reclaimed_total") > 0

    def test_release_slot_returns_pages(self, net):
        eng = _engine(net, batch_size=2)
        eng.prefill(_prompt(9, 70), slot=0)  # 9 tokens -> 2 pages of 8
        assert eng.pages_in_use == 2
        eng.release_slot(0)
        assert eng.pages_in_use == 0 and eng.free_pages == eng.num_pages

    def test_released_row_cannot_corrupt_reused_pages(self, net):
        # row 0 is released mid-decode; its pages go to row 1's prefill.
        # Row 0's next (masked) writes must land in the trash page, so row
        # 1's stream must equal a solo run.
        eng = _engine(net, batch_size=2, num_pages=3)
        solo = _engine(net, paged=False, batch_size=1)
        p1 = _prompt(10, 81)
        want_first = solo.prefill(p1, slot=0)
        want = [want_first]
        for _ in range(5):
            tok, _, _ = solo.decode_step()
            want.append(int(tok[0]))
        eng.prefill(_prompt(6, 80), slot=0)
        eng.decode_step()
        eng.release_slot(0)  # frees its page for row 1
        got = [eng.prefill(p1, slot=1)]  # takes 2 of 3 pages
        for _ in range(5):
            tok, _, _ = eng.decode_step()
            got.append(int(tok[1]))
        assert got == want


# ---------------------------------------------------------------------------
# page exhaustion
# ---------------------------------------------------------------------------
class TestPageExhaustion:
    def test_decode_exhaustion_force_finishes_row(self, net):
        # pool of 3 pages (8 tokens each), two 7-token prompts: one page
        # each; the third page goes to the first row that grows past 8 —
        # the other row is evicted, the winner decodes on
        evict0 = _counter_total("gen_page_evictions_total")
        eng = _engine(net, batch_size=2, num_pages=3, eos_id=None)
        outs = eng.generate([_prompt(7, 90), _prompt(7, 91)],
                            max_new_tokens=6)
        assert _counter_total("gen_page_evictions_total") - evict0 == 1
        assert bool(eng.page_exhausted.any())
        # the evicted row stopped early; the surviving row ran to budget
        lens = sorted(len(o) for o in outs)
        assert lens[0] < 6 and lens[1] == 6

    def test_batcher_reports_page_exhausted(self, net):
        eng = _engine(net, batch_size=2, num_pages=3, eos_id=None)
        bat = ContinuousBatcher(eng)
        reqs = [bat.submit(_prompt(7, 92 + i), max_new_tokens=6)
                for i in range(2)]
        bat.run_until_idle(max_steps=100)
        reasons = sorted(r.finish_reason for r in reqs)
        assert reasons == ["length", "page_exhausted"]
        evicted = next(r for r in reqs if r.finish_reason == "page_exhausted")
        # the pad emitted on the eviction step must not reach the output
        assert PAD not in evicted.output[1:]

    def test_failed_prefill_preserves_pending_clear(self, net):
        # a released slot's device-table clear must survive a prefill that
        # fails on free pages — losing it would let the released row's
        # masked writes corrupt pages reallocated to other rows
        eng = _engine(net, batch_size=2, num_pages=2, eos_id=None)
        eng.prefill(_prompt(6, 96), slot=0)
        eng.prefill(_prompt(6, 97), slot=1)
        eng.release_slot(1)
        assert 1 in eng._pending_clear
        with pytest.raises(RuntimeError):
            eng.prefill(_prompt(16, 98), slot=1)  # needs 2 pages, 1 free
        assert 1 in eng._pending_clear  # not lost on the error path
        # the surviving row's stream must match a solo run (row 0 will
        # grow into the freed page; the shipped clear protects it)
        solo = _engine(net, batch_size=2, num_pages=2, eos_id=None)
        solo.prefill(_prompt(6, 96), slot=0)
        want = [int(solo.decode_step()[0][0]) for _ in range(8)]
        got = [int(eng.decode_step()[0][0]) for _ in range(8)]
        assert got == want

    def test_cache_end_still_reported_as_cache_full(self, net):
        small = _gpt2(max_length=16)
        eng = GenerationEngine(small, batch_size=1, max_length=16,
                               prefill_buckets=(8,), eos_id=EOS,
                               paged=True, page_size=8)
        bat = ContinuousBatcher(eng)
        req = bat.submit(_prompt(6, 95), max_new_tokens=100)
        bat.run_until_idle(max_steps=100)
        assert req.finish_reason == "cache_full"


# ---------------------------------------------------------------------------
# batcher: page-bounded admission
# ---------------------------------------------------------------------------
class TestPagedAdmission:
    def test_admission_bounded_by_free_pages(self, net):
        # 4 slots but the pool only covers 2 concurrent sequences (9-token
        # prompts -> 2 pages each, no growth below position 16): admission
        # must defer, everything completes, and results equal the dense
        # engine's
        prompts = [_prompt(9, 100 + i) for i in range(4)]
        dense = _engine(net, paged=False, batch_size=4)
        bat_d = ContinuousBatcher(dense)
        want = [bat_d.submit(p, max_new_tokens=5) for p in prompts]
        bat_d.run_until_idle(max_steps=200)

        defer0 = _counter_total("gen_admission_rejects_total",
                                reason="free_pages")
        eng = _engine(net, batch_size=4, num_pages=4)
        bat = ContinuousBatcher(eng)
        reqs = [bat.submit(p, max_new_tokens=5) for p in prompts]
        peak = 0
        while bat.step():
            peak = max(peak, bat.active)
        assert peak <= 2  # page-bounded, not slot-bounded
        assert _counter_total("gen_admission_rejects_total",
                              reason="free_pages") > defer0
        assert [r.result() for r in reqs] == [r.result() for r in want]

    def test_submit_rejects_unservable_prompts(self, net):
        eng = _engine(net, batch_size=2, num_pages=1)  # 8-token pool
        bat = ContinuousBatcher(eng)
        r0 = _counter_total("gen_admission_rejects_total",
                            reason="prompt_pages")
        with pytest.raises(ValueError):
            bat.submit(_prompt(12, 110), max_new_tokens=2)  # needs 2 pages
        assert _counter_total("gen_admission_rejects_total",
                              reason="prompt_pages") == r0 + 1
        r1 = _counter_total("gen_admission_rejects_total",
                            reason="prompt_length")
        with pytest.raises(ValueError):
            bat.submit(_prompt(17, 111), max_new_tokens=2)  # no bucket
        assert _counter_total("gen_admission_rejects_total",
                              reason="prompt_length") == r1 + 1


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------
class TestSpeculative:
    def test_self_draft_identical_full_accept(self, net):
        prompts = [_prompt(5, 120), _prompt(12, 121), _prompt(3, 122)]
        ref = _engine(net).generate(prompts, max_new_tokens=11)
        acc0 = _counter_total("gen_spec_accepted_tokens_total")
        d0 = _counter_total("gen_spec_drafted_tokens_total")
        spec = _engine(net, draft_net=net, speculate_k=4)
        assert spec.generate(prompts, max_new_tokens=11) == ref
        acc = _counter_total("gen_spec_accepted_tokens_total") - acc0
        drafted = _counter_total("gen_spec_drafted_tokens_total") - d0
        assert drafted > 0 and acc == drafted  # self-draft: full accept
        assert REGISTRY.get("gen_spec_accept_rate").value() == 1.0

    def test_scripted_partial_accept_exact_counts(self, net):
        # learn the target's greedy continuation, then script a draft that
        # is right once and wrong afterwards: round 1 must accept exactly 1
        # draft + 1 correction (m=2), later rounds reject all (m=1)
        p = _prompt(6, 130)
        probe = _engine(net, batch_size=1, eos_id=None)
        t0 = probe.prefill(p, slot=0)
        cont = []
        for _ in range(6):
            tok, _, _ = probe.decode_step()
            cont.append(int(tok[0]))
        script = np.zeros(64, np.int32)
        L = len(p)
        script[L] = cont[0]                      # d1 correct
        script[L + 1] = (cont[1] + 1) % VOCAB    # d2 wrong
        draft = ScriptedDraft(script, VOCAB, 64)
        spec = GenerationEngine(net, batch_size=1, prefill_buckets=(8, 16),
                                eos_id=None, pad_id=PAD, paged=True,
                                page_size=8, draft_net=draft, speculate_k=3)
        assert spec.prefill(p, slot=0) == t0
        toks, m, _ = spec.spec_step()
        assert int(m[0]) == 2  # 1 accepted draft + the correction token
        assert [int(toks[0, j]) for j in range(2)] == cont[:2]
        toks, m, _ = spec.spec_step()  # all-zero script: full reject
        assert int(m[0]) == 1
        assert int(toks[0, 0]) == cont[2]

    def test_reject_all_rollback_identical(self, net):
        # a draft that is always wrong forces a full rollback every round;
        # the emitted stream must still equal plain greedy
        prompts = [_prompt(5, 140), _prompt(9, 141)]
        ref = _engine(net, batch_size=2).generate(prompts, max_new_tokens=9)
        draft = ScriptedDraft(np.full(64, EOS - 1, np.int32), VOCAB, 64)
        spec = _engine(net, batch_size=2, draft_net=draft, speculate_k=3)
        got = spec.generate(prompts, max_new_tokens=9)
        # (if any ref token happened to equal the constant script the
        # draft would be "right"; identity is the contract either way)
        assert got == ref

    def test_spec_eos_mid_window(self, net):
        # declare the 3rd greedy token EOS: the speculative engine must
        # stop emission exactly there, like the non-speculative engine
        p = _prompt(7, 150)
        probe = _engine(net, batch_size=1, eos_id=None)
        probe.prefill(p, slot=0)
        cont = []
        for _ in range(4):
            tok, _, _ = probe.decode_step()
            cont.append(int(tok[0]))
        eos = cont[2]
        ref = GenerationEngine(net, batch_size=1, prefill_buckets=(8, 16),
                               eos_id=eos, paged=True,
                               page_size=8).generate([p], max_new_tokens=12)
        spec = GenerationEngine(net, batch_size=1, prefill_buckets=(8, 16),
                                eos_id=eos, paged=True, page_size=8,
                                draft_net=net, speculate_k=4)
        got = spec.generate([p], max_new_tokens=12)
        assert got == ref
        assert got[0][-1] == eos or len(got[0]) == 12

    def test_spec_cache_end_clamp(self):
        # rounds near the cache end must clamp emission at capacity and
        # force-finish exactly like the single-token path
        small = _gpt2(max_length=16, seed=2)
        common = dict(batch_size=1, max_length=16, prefill_buckets=(8,),
                      eos_id=None, paged=True, page_size=8)
        ref = GenerationEngine(small, **common).generate(
            [_prompt(6, 160)], max_new_tokens=100)
        spec = GenerationEngine(small, draft_net=small, speculate_k=4,
                                **common)
        got = spec.generate([_prompt(6, 160)], max_new_tokens=100)
        assert got == ref
        assert bool(spec.done[0])

    def test_draft_cache_writes_last_drafted_token(self, net):
        # full-accept rounds advance the frontier past position p+k; the
        # draft scan must have written d_k's K/V there (a skipped write
        # would leave a permanent zero-K/V hole below the draft frontier,
        # silently degrading later accept rates)
        spec = GenerationEngine(net, batch_size=1, prefill_buckets=(8,),
                                eos_id=None, pad_id=PAD, paged=True,
                                page_size=8, draft_net=net, speculate_k=4)
        spec.prefill(_prompt(6, 210), slot=0)
        for _ in range(6):
            spec.spec_step()
        frontier = int(spec.positions[0])
        table = np.array(spec.page_table)[0]
        k_pool = np.array(spec.draft_pools[0][0])
        t_pool = np.array(spec.pools[0][0])
        assert frontier > 12  # several full-accept rounds ran
        for pos in range(frontier):
            pid = table[pos // 8]
            # self-draft: the draft entry must equal the target's, and in
            # particular must not be the all-zero initial page content
            np.testing.assert_array_equal(k_pool[pid, :, pos % 8, :],
                                          t_pool[pid, :, pos % 8, :])
            assert np.abs(k_pool[pid, :, pos % 8, :]).sum() > 0.0

    def test_spec_batcher_matches_solo(self, net):
        prompts = [_prompt(4, 170), _prompt(11, 171), _prompt(7, 172)]
        solo = _engine(net)
        want = solo.generate(prompts, max_new_tokens=7)
        spec = _engine(net, batch_size=2, draft_net=net, speculate_k=4)
        bat = ContinuousBatcher(spec)
        reqs = [bat.submit(p, max_new_tokens=7) for p in prompts]
        bat.run_until_idle(max_steps=100)
        assert [r.result() for r in reqs] == want

    def test_config_validation(self, net):
        with pytest.raises(ValueError):
            _engine(net, draft_net=net)  # speculate_k missing
        with pytest.raises(ValueError):
            _engine(net, speculate_k=4)  # draft_net missing
        with pytest.raises(ValueError):
            _engine(net, paged=False, draft_net=net, speculate_k=4)
        # stochastic speculation is legal (rejection-sampling verify,
        # tests/test_prefix_sharing.py) — only the degenerate
        # temperature=0 non-greedy config is refused (residual undefined)
        from mxnet_tpu.inference import SamplingConfig
        assert _engine(net, draft_net=net, speculate_k=4,
                       sampling="temperature").speculative
        with pytest.raises(ValueError):
            _engine(net, draft_net=net, speculate_k=4,
                    sampling=SamplingConfig(method="temperature",
                                            temperature=0.0))
        with pytest.raises(ValueError):
            _engine(net, num_pages=0)  # explicit 0 must not hit the default


# ---------------------------------------------------------------------------
# compiled-program count: buckets + 1 decode (+ 1 verify), flat under traffic
# ---------------------------------------------------------------------------
class TestPagedProgramCount:
    def test_paged_buckets_plus_one_stable(self, net):
        eng = _engine(net)  # buckets (8, 16)
        prompts = [_prompt(5, 180), _prompt(12, 181), _prompt(3, 182)]
        eng.generate(prompts, max_new_tokens=9)
        used = {eng.bucket_for(len(p)) for p in prompts}
        assert eng.compiled_programs == len(used) + 1
        bat = ContinuousBatcher(eng)
        for i in range(5):
            bat.submit(_prompt(2 + i, 190 + i), max_new_tokens=6)
        bat.run_until_idle(max_steps=200)
        assert eng.compiled_programs == len(used) + 1

    def test_spec_buckets_plus_two_stable(self, net):
        before_v = _counter_total("gen_recompiles_total", reason="verify")
        eng = _engine(net, draft_net=net, speculate_k=4)
        prompts = [_prompt(5, 200), _prompt(12, 201)]
        eng.generate(prompts, max_new_tokens=9)
        used = {eng.bucket_for(len(p)) for p in prompts}
        assert eng.compiled_programs == len(used) + 2  # draft scan + verify
        assert _counter_total("gen_recompiles_total",
                              reason="verify") - before_v == 1
        eng.generate([_prompt(7, 202)], max_new_tokens=12)
        assert eng.compiled_programs == len(used) + 2

    def test_decode_step_refused_on_spec_engine(self, net):
        eng = _engine(net, draft_net=net, speculate_k=2)
        with pytest.raises(RuntimeError):
            eng.decode_step()
        plain = _engine(net)
        with pytest.raises(RuntimeError):
            plain.spec_step()


# ---------------------------------------------------------------------------
# audit: paged carry donation + zero host transfers (ISSUE 11 acceptance)
# ---------------------------------------------------------------------------
class TestPagedAudit:
    def test_paged_decode_and_prefill_audit(self):
        mx.random.seed(0)
        net = gpt2.get_gpt2("gpt2_tiny", dropout=0.0, num_layers=2,
                            units=32, num_heads=2, max_length=64,
                            vocab_size=64)
        net.initialize()
        _ = net(nd.array(np.zeros((1, 4), np.int32)))
        eng = GenerationEngine(net, batch_size=2, max_length=64,
                               prefill_buckets=(8,), paged=True,
                               page_size=16)
        for audit in (eng.audit(), eng.audit(bucket=8)):
            assert audit.carry_donation() == 1.0
            assert not audit.compiled.host_transfers()
            assert audit.comm.total_bytes() == 0

    def test_spec_draft_and_verify_audit(self):
        mx.random.seed(0)
        net = gpt2.get_gpt2("gpt2_tiny", dropout=0.0, num_layers=2,
                            units=32, num_heads=2, max_length=64,
                            vocab_size=64)
        net.initialize()
        _ = net(nd.array(np.zeros((1, 4), np.int32)))
        eng = GenerationEngine(net, batch_size=2, max_length=64,
                               prefill_buckets=(8,), paged=True,
                               page_size=16, draft_net=net, speculate_k=4)
        for audit in (eng.audit(), eng.audit(program="verify"),
                      eng.audit(bucket=8)):
            assert audit.carry_donation() == 1.0
            assert not audit.compiled.host_transfers()
            assert audit.comm.total_bytes() == 0
