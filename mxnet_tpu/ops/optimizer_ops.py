"""Fused optimizer update operators.

Reference: ``src/operator/optimizer_op.cc`` — ``sgd_update``,
``sgd_mom_update``, ``adam_update``, ``lamb_update_phase1/2``, multi-tensor
``multi_sgd_*`` and mixed-precision ``mp_*`` variants. On TPU each update is
one jit-fused elementwise program; the multi-tensor fusion the reference
hand-rolled falls out of jit-ing the whole parameter pytree at once
(see ``mxnet_tpu.optimizer``). ``mp_*`` = bf16 weights + f32 master copy.

All functions are pure: they *return* updated tensors instead of mutating.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..registry import register


def _apply_wd(grad, weight, wd, rescale_grad, clip_gradient):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight.astype(jnp.float32)


@register("sgd_update")
def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@register("sgd_mom_update", nout=2)
def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=False):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    mom_new = momentum * mom.astype(jnp.float32) - lr * g
    w = weight.astype(jnp.float32) + mom_new
    return w.astype(weight.dtype), mom_new.astype(mom.dtype)


@register("nag_mom_update", nout=2)
def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    mom_new = momentum * mom.astype(jnp.float32) + g
    w = weight.astype(jnp.float32) - lr * (g + momentum * mom_new)
    return w.astype(weight.dtype), mom_new.astype(mom.dtype)


@register("adam_update", nout=3)
def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    m = beta1 * mean.astype(jnp.float32) + (1 - beta1) * g
    v = beta2 * var.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    w = weight.astype(jnp.float32) - lr * m / (jnp.sqrt(v) + epsilon)
    return w.astype(weight.dtype), m.astype(mean.dtype), v.astype(var.dtype)


@register("rmsprop_update", nout=2)
def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n.astype(jnp.float32)
    w = weight.astype(jnp.float32) - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w.astype(weight.dtype), n_new.astype(n.dtype)


@register("ftml_update", nout=4)
def ftml_update(weight, grad, d, v, z, lr, t=1, beta1=0.6, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_grad if clip_grad > 0 else None)
    v_new = beta2 * v.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d.astype(jnp.float32)
    z_new = beta1 * z.astype(jnp.float32) + (1 - beta1) * g - sigma * weight.astype(jnp.float32)
    w = -z_new / d_t
    return w.astype(weight.dtype), d_t.astype(d.dtype), v_new.astype(v.dtype), z_new.astype(z.dtype)


@register("adagrad_update", nout=2)
def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    h = history.astype(jnp.float32) + jnp.square(g)
    w = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(h) + epsilon)
    return w.astype(weight.dtype), h.astype(history.dtype)


@register("ftrl_update", nout=3)
def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w = weight.astype(jnp.float32)
    n_old = n.astype(jnp.float32)
    n_new = n_old + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n_old)) / lr
    z_new = z.astype(jnp.float32) + g - sigma * w
    w_new = jnp.where(
        jnp.abs(z_new) <= lamda1,
        0.0,
        -(z_new - jnp.sign(z_new) * lamda1) / ((beta + jnp.sqrt(n_new)) / lr + wd),
    )
    return w_new.astype(weight.dtype), z_new.astype(z.dtype), n_new.astype(n.dtype)


@register("signsgd_update")
def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    return (weight.astype(jnp.float32) - lr * jnp.sign(g)).astype(weight.dtype)


# -- LAMB (reference: lamb_update_phase1/phase2, the BERT optimizer) ---------
@register("lamb_update_phase1")
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999, epsilon=1e-6,
                       t=1, bias_correction=True, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean.astype(jnp.float32) + (1 - beta1) * g
    v = beta2 * var.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    mh, vh = m, v
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    update = mh / (jnp.sqrt(vh) + epsilon) + wd * weight.astype(jnp.float32)
    return update, m.astype(mean.dtype), v.astype(var.dtype)


@register("lamb_update_phase2")
def lamb_update_phase2(weight, g_update, r1, r2, lr, lower_bound=-1.0, upper_bound=-1.0):
    r1 = jnp.where(r1 > 0, r1, jnp.ones_like(r1))
    r2 = jnp.where(r2 > 0, r2, jnp.ones_like(r2))
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    trust = r1 / r2
    return (weight.astype(jnp.float32) - lr * trust * g_update).astype(weight.dtype)
