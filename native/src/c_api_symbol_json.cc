// Exported-graph loading: <prefix>-symbol.json -> composed symbol graph.
//
// Reference analog: MXSymbolCreateFromFile (src/c_api/c_api_symbolic.cc over
// nnvm LoadJSON) + MXSymbolListArguments — the deploy path SymbolBlock.
// imports uses. Builds the graph purely through the public symbol ABI
// (CreateVariable / CreateAtomicSymbol / Compose), so this TU needs no
// access to the graph tier's internals.
//
// The exporter (gluon/block.py export -> symbol/__init__.py tojson) writes
// each node's params twice: "attrs" (display strings, reference-style) and
// "_raw_attrs" (true JSON types). This loader consumes "_raw_attrs" and
// re-serializes it to the flat param JSON the invoke ABI takes.
#include "../include/mxtpu_c_api.h"

#include <cctype>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace {

// -- minimal recursive-descent JSON parser ----------------------------------

struct JVal {
  enum Kind { Null, Bool, Num, Str, Arr, Obj } kind = Null;
  bool b = false;
  double num = 0.0;
  std::string str;
  std::vector<JVal> arr;
  std::map<std::string, JVal> obj;

  const JVal* get(const std::string& k) const {
    auto it = obj.find(k);
    return it == obj.end() ? nullptr : &it->second;
  }
};

struct JParser {
  const char* p;
  const char* end;
  std::string err;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() { while (p < end && std::isspace(static_cast<unsigned char>(*p))) ++p; }

  bool fail(const char* msg) { if (err.empty()) err = msg; return false; }

  bool parse_string(std::string* out) {
    if (*p != '"') return fail("expected string");
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out->push_back('\n'); break;
          case 't': out->push_back('\t'); break;
          case 'r': out->push_back('\r'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'u':  // \uXXXX: keep ASCII, replace the rest with '?'
            if (p + 4 < end) {
              unsigned code = 0;
              std::sscanf(p + 1, "%4x", &code);
              out->push_back(code < 128 ? static_cast<char>(code) : '?');
              p += 4;
            }
            break;
          default: out->push_back(*p);
        }
      } else {
        out->push_back(*p);
      }
      ++p;
    }
    if (p >= end) return fail("unterminated string");
    ++p;
    return true;
  }

  bool parse(JVal* out) {
    ws();
    if (p >= end) return fail("unexpected end of input");
    if (*p == '{') {
      ++p;
      out->kind = JVal::Obj;
      ws();
      if (p < end && *p == '}') { ++p; return true; }
      while (true) {
        ws();
        std::string key;
        if (!parse_string(&key)) return false;
        ws();
        if (p >= end || *p != ':') return fail("expected ':'");
        ++p;
        if (!parse(&out->obj[key])) return false;
        ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == '}') { ++p; return true; }
        return fail("expected ',' or '}'");
      }
    }
    if (*p == '[') {
      ++p;
      out->kind = JVal::Arr;
      ws();
      if (p < end && *p == ']') { ++p; return true; }
      while (true) {
        out->arr.emplace_back();
        if (!parse(&out->arr.back())) return false;
        ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == ']') { ++p; return true; }
        return fail("expected ',' or ']'");
      }
    }
    if (*p == '"') { out->kind = JVal::Str; return parse_string(&out->str); }
    if (std::strncmp(p, "true", 4) == 0 && end - p >= 4) {
      out->kind = JVal::Bool; out->b = true; p += 4; return true;
    }
    if (std::strncmp(p, "false", 5) == 0 && end - p >= 5) {
      out->kind = JVal::Bool; out->b = false; p += 5; return true;
    }
    if (std::strncmp(p, "null", 4) == 0 && end - p >= 4) {
      out->kind = JVal::Null; p += 4; return true;
    }
    char* num_end = nullptr;
    double v = std::strtod(p, &num_end);
    if (num_end == p) return fail("bad value");
    out->kind = JVal::Num; out->num = v; p = num_end;
    return true;
  }
};

// _raw_attrs JVal -> flat param JSON for MXTPUImperativeInvoke
std::string attrs_to_param_json(const JVal& attrs) {
  std::ostringstream os;
  os << "{";
  bool first = true;
  for (auto& kv : attrs.obj) {
    const JVal& v = kv.second;
    std::string piece;
    char buf[64];
    switch (v.kind) {
      case JVal::Num:
        std::snprintf(buf, sizeof(buf), "%.17g", v.num);
        piece = buf;
        break;
      case JVal::Bool:
        piece = v.b ? "true" : "false";
        break;
      case JVal::Str: {
        piece = "\"";
        for (char c : v.str) {  // re-escape: embedded quotes/backslashes
          if (c == '"' || c == '\\') piece.push_back('\\');
          piece.push_back(c);
        }
        piece.push_back('"');
        break;
      }
      case JVal::Arr: {
        std::ostringstream as;
        as << "[";
        for (size_t i = 0; i < v.arr.size(); ++i) {
          if (v.arr[i].kind != JVal::Num) { piece.clear(); break; }
          if (i) as << ", ";
          std::snprintf(buf, sizeof(buf), "%.17g", v.arr[i].num);
          as << buf;
        }
        as << "]";
        piece = as.str();
        break;
      }
      default:
        continue;  // null / nested obj attrs are not op params
    }
    if (piece.empty()) continue;
    if (!first) os << ", ";
    os << "\"" << kv.first << "\": " << piece;
    first = false;
  }
  os << "}";
  return os.str();
}

struct GraphRec {
  std::vector<MXTPUSymHandle> nodes;  // owned, every node incl. variables
  MXTPUSymHandle head = nullptr;      // borrowed (one of nodes)
  std::vector<std::string> arg_names;
  std::vector<const char*> arg_ptrs;

  ~GraphRec() {
    for (auto h : nodes)
      if (h) MXTPUSymbolFree(h);
  }
};

}  // namespace

extern "C" {

int MXTPUGraphLoadJSON(const char* path, MXTPUGraphHandle* out) {
  if (path == nullptr || out == nullptr) {
    MXTPUSetLastError("GraphLoadJSON: null arg");
    return -1;
  }
  std::ifstream f(path);
  if (!f) {
    MXTPUSetLastError("GraphLoadJSON: cannot open file");
    return -1;
  }
  std::stringstream ss;
  ss << f.rdbuf();
  std::string text = ss.str();
  JParser jp(text);
  JVal root;
  if (!jp.parse(&root) || root.kind != JVal::Obj) {
    MXTPUSetLastError(("GraphLoadJSON: " +
                       (jp.err.empty() ? "not a JSON object" : jp.err))
                          .c_str());
    return -1;
  }
  const JVal* nodes = root.get("nodes");
  const JVal* heads = root.get("heads");
  if (nodes == nullptr || nodes->kind != JVal::Arr || nodes->arr.empty()) {
    MXTPUSetLastError("GraphLoadJSON: missing nodes array");
    return -1;
  }
  auto* g = new GraphRec();
  auto fail = [&](const std::string& msg) {
    delete g;
    MXTPUSetLastError(("GraphLoadJSON: " + msg).c_str());
    return -1;
  };
  for (const JVal& n : nodes->arr) {
    const JVal* op = n.get("op");
    const JVal* name = n.get("name");
    if (op == nullptr || op->kind != JVal::Str || name == nullptr ||
        name->kind != JVal::Str)
      return fail("node missing op/name");
    MXTPUSymHandle h = nullptr;
    if (op->str == "null") {
      if (MXTPUSymbolCreateVariable(name->str.c_str(), &h) != 0) {
        delete g;
        return -1;
      }
      g->arg_names.push_back(name->str);
    } else {
      const JVal* raw = n.get("_raw_attrs");
      std::string pj = raw && raw->kind == JVal::Obj ? attrs_to_param_json(*raw)
                                                     : "{}";
      if (MXTPUSymbolCreateAtomicSymbol(op->str.c_str(), pj.c_str(),
                                        name->str.c_str(), &h) != 0) {
        delete g;
        return -1;
      }
      const JVal* ins = n.get("inputs");
      std::vector<MXTPUSymHandle> in_handles;
      if (ins != nullptr && ins->kind == JVal::Arr) {
        for (const JVal& e : ins->arr) {
          // entry [node_id, out_index, version]
          if (e.kind != JVal::Arr || e.arr.empty() ||
              e.arr[0].kind != JVal::Num)
            { MXTPUSymbolFree(h); return fail("bad input entry"); }
          // the native symbol ABI has no output selection — a graph that
          // consumes a secondary output must be rejected, not rebuilt
          // silently wrong
          if (e.arr.size() >= 2 && e.arr[1].kind == JVal::Num &&
              e.arr[1].num != 0)
            { MXTPUSymbolFree(h);
              return fail("input consumes a non-first output (multi-output "
                          "nodes are not representable in the native "
                          "symbol tier)"); }
          size_t idx = static_cast<size_t>(e.arr[0].num);
          if (idx >= g->nodes.size())
            { MXTPUSymbolFree(h); return fail("input references later node"); }
          in_handles.push_back(g->nodes[idx]);
        }
      }
      if (MXTPUSymbolCompose(h, in_handles.data(),
                             static_cast<int>(in_handles.size())) != 0) {
        MXTPUSymbolFree(h);
        delete g;
        return -1;
      }
    }
    g->nodes.push_back(h);
  }
  size_t head_idx = g->nodes.size() - 1;
  if (heads != nullptr && heads->kind == JVal::Arr && !heads->arr.empty()) {
    const JVal& h0 = heads->arr[0];
    if (h0.kind == JVal::Arr && !h0.arr.empty() &&
        h0.arr[0].kind == JVal::Num) {
      head_idx = static_cast<size_t>(h0.arr[0].num);
      if (h0.arr.size() >= 2 && h0.arr[1].kind == JVal::Num &&
          h0.arr[1].num != 0)
        return fail("head selects a non-first output (not representable)");
    }
    if (head_idx >= g->nodes.size())
      return fail("head index out of range");
  }
  g->head = g->nodes[head_idx];
  for (auto& s : g->arg_names) g->arg_ptrs.push_back(s.c_str());
  *out = g;
  return 0;
}

int MXTPUGraphGetSymbol(MXTPUGraphHandle gh, MXTPUSymHandle* head) {
  if (gh == nullptr || head == nullptr) {
    MXTPUSetLastError("GraphGetSymbol: null arg");
    return -1;
  }
  *head = static_cast<GraphRec*>(gh)->head;
  return 0;
}

int MXTPUGraphListArguments(MXTPUGraphHandle gh, int* n, const char*** names) {
  if (gh == nullptr || n == nullptr) {
    MXTPUSetLastError("GraphListArguments: null arg");
    return -1;
  }
  auto* g = static_cast<GraphRec*>(gh);
  *n = static_cast<int>(g->arg_ptrs.size());
  if (names) *names = g->arg_ptrs.data();
  return 0;
}

int MXTPUGraphFree(MXTPUGraphHandle gh) {
  delete static_cast<GraphRec*>(gh);
  return 0;
}

}  // extern "C"
