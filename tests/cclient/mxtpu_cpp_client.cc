// C++ user-API smoke client (header-only mxtpu_cpp.hpp over the C ABI).
// Reference analog: cpp-package examples — proves a C++ program can train-
// adjacent compute through the binding surface without Python.
// Linked against libmxtpu.so (like the reference cpp-package links
// libmxnet.so). Exit 0 iff all checks pass.
#include <cmath>
#include <cstdio>

#include "../../native/include/mxtpu_cpp.hpp"

int main() {
  try {
    // y = softmax(relu(A) @ B + C-ish chain)
    mxtpu::NDArray a({1, -2, 3, -4, 5, -6}, {2, 3});
    mxtpu::NDArray b({1, 0, 0, 1, 1, 1}, {3, 2});
    auto r = mxtpu::relu(a);                         // [[1,0,3],[0,5,0]]
    auto c = mxtpu::dot(r, b);                       // [[4,3],[0,5]]
    auto shape = c.shape();
    if (shape.size() != 2 || shape[0] != 2 || shape[1] != 2) {
      std::fprintf(stderr, "bad dot shape\n");
      return 1;
    }
    auto v = c.to_vector();
    const float expect[4] = {4, 3, 0, 5};
    for (int i = 0; i < 4; ++i)
      if (std::fabs(v[i] - expect[i]) > 1e-5f) {
        std::fprintf(stderr, "dot value mismatch at %d: %f\n", i, v[i]);
        return 1;
      }
    auto s = mxtpu::softmax(c);
    auto sv = s.to_vector();
    if (std::fabs(sv[0] + sv[1] - 1.0f) > 1e-5f ||
        std::fabs(sv[2] + sv[3] - 1.0f) > 1e-5f) {
      std::fprintf(stderr, "softmax rows don't sum to 1\n");
      return 1;
    }
    // error path: exception carries the C-side message
    bool threw = false;
    try {
      mxtpu::invoke("not_a_real_op_zzz", {&a});
    } catch (const mxtpu::Error& e) {
      threw = std::string(e.what()).find("not_a_real_op_zzz") !=
              std::string::npos;
    }
    if (!threw) {
      std::fprintf(stderr, "error path failed\n");
      return 1;
    }
  } catch (const std::exception& e) {
    std::fprintf(stderr, "unexpected: %s\n", e.what());
    return 1;
  }
  std::printf("mxtpu_cpp_client: all checks passed\n");
  return 0;
}
