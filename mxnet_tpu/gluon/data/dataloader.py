"""DataLoader (reference: ``python/mxnet/gluon/data/dataloader.py``).

The reference forks worker *processes* that serialize NDArrays through
shared memory (``ConnectionWrapper``/``worker_loop``). TPU hosts feed one
logical device mesh, so the design here is: workers produce **numpy** batches
(cheap to pickle / zero device state), and the loader moves only the final
batch to device — optionally double-buffered (``prefetch``) so H2D overlaps
compute, which is what the reference's ``PrefetcherIter`` did.
"""
from __future__ import annotations

import multiprocessing as mp
import time

import numpy as np

from ... import observability as _obs
from ...ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples -> one numpy batch (nested tuples preserved)."""
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    first = data[0]
    if isinstance(first, NDArray):
        return np.stack([d.asnumpy() for d in data])
    return np.stack([np.asarray(d) for d in data])


def _to_device(batch, pin=False):
    if isinstance(batch, tuple):
        return tuple(_to_device(b) for b in batch)
    return array(batch)


_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


_retry_policy = None


def _fetch_batch(dataset, samples, batchify_fn):
    """One batch fetch+batchify — fault site ``data.batch`` under the retry
    policy, so a flaky storage read costs a retry instead of the epoch.
    The policy object is built once per process: per-batch construction
    re-reads six config knobs and seeds a fresh RNG from os.urandom, pure
    fixed overhead on the input hot path."""
    global _retry_policy
    from ...resilience import faults, retry

    if _retry_policy is None:
        _retry_policy = retry.RetryPolicy()

    def _fetch():
        faults.fire("data.batch")
        return batchify_fn([dataset[i] for i in samples])

    return retry.retry_call(_fetch, site="data.batch", policy=_retry_policy)


def _worker_fn(samples, batchify_fn):
    return _fetch_batch(_worker_dataset, samples, batchify_fn)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None else 2 * self._num_workers)
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool

                self._pool = ThreadPool(self._num_workers,
                                        initializer=_worker_init, initargs=(dataset,))
            else:
                ctx = mp.get_context("fork")
                self._pool = ctx.Pool(self._num_workers,
                                      initializer=_worker_init, initargs=(dataset,))

    def __len__(self):
        return len(self._batch_sampler)

    def host_batches(self):
        """Host-side (numpy) batch stream, no device placement — the feed
        for :meth:`prefetch_to_device`, whose background thread does the
        sharded ``device_put`` + window stacking off the hot path."""
        if self._pool is None:
            for samples in self._batch_sampler:
                yield _fetch_batch(self._dataset, samples, self._batchify_fn)
            return

        # async pool pipeline with bounded in-flight requests
        import collections

        queue = collections.deque()
        it = iter(self._batch_sampler)

        def issue():
            try:
                samples = next(it)
            except StopIteration:
                return False
            queue.append(self._pool.apply_async(_worker_fn, (samples, self._batchify_fn)))
            return True

        for _ in range(self._prefetch or 1):
            if not issue():
                break
        while queue:
            batch = queue.popleft().get()
            issue()
            yield batch

    def __iter__(self):
        # input-pipeline telemetry (docs/OBSERVABILITY.md): "wait" is the
        # time this generator spends producing a ready device batch, and
        # "compute" the time the consumer holds between yields. A stall is
        # one iteration where the pipeline made the step loop wait longer
        # than the step itself took — the input-bound signal.
        obs_on = _obs.enabled()

        def _note(wait, compute):
            _obs.histogram("data_batch_wait_seconds",
                           "time the step loop waited on the input pipeline",
                           unit="s").observe(wait)
            if compute is not None:
                _obs.histogram("data_compute_seconds",
                               "consumer time between batches",
                               unit="s").observe(compute)
                if wait > compute:
                    _obs.counter("data_stalls_total",
                                 "iterations where batch-wait exceeded "
                                 "consumer compute").inc()
                    _obs.emit("data_stall", wait_seconds=round(wait, 6),
                              compute_seconds=round(compute, 6))

        prev = None  # 1-deep device prefetch: overlap H2D with consumption
        compute = None
        src = self.host_batches()
        while True:
            t0 = time.perf_counter() if obs_on else 0.0
            try:
                batch = next(src)
            except StopIteration:
                break
            cur = _to_device(batch)
            if obs_on:
                _note(time.perf_counter() - t0, compute)
            if prev is not None:
                y0 = time.perf_counter() if obs_on else 0.0
                yield prev
                compute = time.perf_counter() - y0 if obs_on else None
            prev = cur
        if prev is not None:
            yield prev

    def prefetch_to_device(self, train_step=None, window=1, accum=1, depth=2):
        """Feed a ``TrainStep`` without per-step ``device_put`` on the
        caller thread: worker batches stay numpy, and the prefetch thread
        does the sharded placement + ``window`` stacking for the compiled
        k-step scan window (``TrainStep.run``; docs/PERFORMANCE.md)."""
        from ...io.prefetch import DevicePrefetcher

        return DevicePrefetcher(self.host_batches(), train_step=train_step,
                                window=window, accum=accum, depth=depth)

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()
