#!/usr/bin/env python
"""Measured-profiling CI gate (``make profcheck``; docs/OBSERVABILITY.md
"Measured profiling", ISSUE 14).

Traces two of the shared golden program families (tools/families.py — the
SAME builders shardcheck/memcheck/schedcheck audit, so the profiled
programs can never drift from the gated ones): 2 real training steps of
the fsdp TrainStep and a window of real decode steps of the serving
engine, both on CPU with 8 virtual devices. The gate FAILS unless:

  - the **measured op timeline is non-empty** for both families — the
    XPlane parser produced real per-device op rows with timestamps;
  - ``calibrate()`` **emits predicted/measured ratios** per op class
    against each program's live :class:`ScheduleReport` — whose
    critical path must sit within ``--golden-band`` of the committed
    ``sched_*.json`` golden (the telemetry-mode grad-norm output makes
    the profiled step a slightly larger program than the golden's
    telemetry-off one; the band absorbs that, schedcheck pins the
    exact program);
  - **measured overlap** is computed and reported next to
    ``ScheduleReport.overlap_fraction`` (zero measured overlap is
    allowed — CPU compiles collectives synchronously);
  - the **measured step time** sits within a sane band of the metrics
    registry's ``train_step_seconds`` histogram over the same steps
    (both watches timed the same wall clock);
  - ``prof_captures_total{trigger="api"}`` counted every capture.

``--inject-empty-trace`` is the failure-path test hook: it swaps each
family's timeline for an empty trace dir's, and the gate must exit 1
(tests/test_profcheck.py pins this).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

GOLDEN_DIR = os.path.join(REPO, "mxnet_tpu", "analysis", "goldens")

#: measured-vs-registry step-time agreement band (both are wall clocks of
#: the same steps; the trace adds parse/snapshot overhead outside the
#: step windows, so the band is generous but not vacuous)
STEP_TIME_BAND = (0.2, 5.0)


def _families():
    spec = importlib.util.spec_from_file_location(
        "profcheck_families_loader", os.path.join(REPO, "tools",
                                                  "families.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load()


def _sched_golden(name: str):
    try:
        with open(os.path.join(GOLDEN_DIR, f"sched_{name}.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _inject_empty(cap):
    """Failure-path hook: replace the capture's parsed result with what
    an empty trace dir yields — every downstream assertion must fail."""
    from mxnet_tpu.observability import profiling

    empty = tempfile.mkdtemp(prefix="profcheck-empty-")
    cap.timeline = profiling.parse_trace(empty)
    cap.report = profiling.measured_report(cap.timeline)
    if cap.calibration is not None:
        cap.calibration = profiling.calibrate(
            _Dummy(), cap.report, emit=False)
    return cap


class _Dummy:
    op_class_seconds: dict = {}
    critical_path_seconds = 0.0
    overlap_fraction = 0.0


def check_family(name, cap, schedule, golden, golden_band, fails, notes):
    """Run one family's assertions; returns the JSON row."""
    r = cap.report
    row = {
        "n_op_rows": len(r.op_rows),
        "devices": r.devices(),
        "measured_step_seconds": (sum(r.step_seconds())
                                  / len(r.step_seconds()))
        if r.step_seconds() else None,
        "hot_ops": [h["name"] for h in r.hot_ops(5)],
        "overlap_measured": round(r.overlap_fraction, 6),
        "overlap_predicted": round(schedule.overlap_fraction, 6)
        if schedule is not None else None,
    }
    if not r.op_rows:
        fails.append(f"{name}: measured op timeline is EMPTY — the trace "
                     "produced no device op rows (capture or parser "
                     "broken)")
    if not r.step_seconds():
        fails.append(f"{name}: no prof_step windows in the trace — step "
                     "correlation broken")
    cal = cap.calibration
    if cal is None or not cal.rows:
        fails.append(f"{name}: calibrate() produced no predicted/measured "
                     "rows")
    else:
        both = [c for c in cal.rows
                if c.predicted_seconds > 0 and c.measured_seconds > 0]
        if not both:
            fails.append(f"{name}: calibration table has no op class with "
                         "BOTH a predicted and a measured side")
        row["calibration"] = cal.summary()
    if schedule is not None and golden is not None:
        g, c = golden["critical_path_seconds"], \
            schedule.critical_path_seconds
        row["golden_critical_path_seconds"] = g
        row["live_critical_path_seconds"] = c
        if not (g * (1 - golden_band) <= c <= g * (1 + golden_band)):
            fails.append(
                f"{name}: live schedule critical path {c:.3e}s sits "
                f"outside ±{golden_band:.0%} of the committed golden "
                f"{g:.3e}s — the calibration's predicted side no longer "
                "matches what schedcheck pins (rebless the sched golden "
                "first)")
        if golden.get("constants") != schedule.constants:
            notes.append(f"{name}: roofline constants differ from the "
                         "golden's (env overrides?)")
    elif golden is None:
        notes.append(f"{name}: no committed sched golden to anchor the "
                     "predicted side (run tools/schedcheck.py "
                     "--update-golden)")
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=2,
                    help="traced steps per family (default 2)")
    ap.add_argument("--golden-band", type=float, default=0.5,
                    help="allowed relative gap between the live schedule "
                         "critical path and the committed sched golden "
                         "(default 50%% — the profiled step compiles the "
                         "telemetry grad-norm in; schedcheck pins the "
                         "exact telemetry-off program)")
    ap.add_argument("--inject-empty-trace", action="store_true",
                    help="test hook: parse an empty trace dir instead of "
                         "the real capture (the gate must fail)")
    args = ap.parse_args(argv)

    from mxnet_tpu import observability as obs

    run_dir = tempfile.mkdtemp(prefix="profcheck-obs-")
    obs.enable(run_dir)

    fams = _families()
    fails, notes = [], []
    row = {"gate": "profcheck", "families": {}}

    # -- family 1: the fsdp training step (step_fsdp golden family) ----------
    ts, batch = fams._fsdp_step()
    # compile + warm OUTSIDE the cross-check window: the first
    # telemetry-on step pays XLA compile and would dominate the registry
    # mean the measured (post-warmup) step time is checked against
    ts(*batch)
    ts(*batch)
    hist = obs.REGISTRY.get("train_step_seconds")
    c0 = hist.total_count() if hist is not None else 0
    s0 = hist.total_sum() if hist is not None else 0.0
    trace_dir = tempfile.mkdtemp(prefix="profcheck-step-")
    cap = ts.profile(*batch, steps=args.steps, warmup=1,
                     trace_dir=trace_dir)
    if args.inject_empty_trace:
        cap = _inject_empty(cap)
    # the predicted side rides the capture (profile() audited once)
    row["families"]["step_fsdp"] = check_family(
        "step_fsdp", cap, cap.schedule, _sched_golden("step_fsdp"),
        args.golden_band, fails, notes)

    # measured step time vs the metrics registry's step histogram over
    # the SAME (warm) steps: two watches on one wall clock must agree
    meas = row["families"]["step_fsdp"]["measured_step_seconds"]
    hist = obs.REGISTRY.get("train_step_seconds")
    reg_mean = None
    if hist is not None and hist.total_count() > c0:
        reg_mean = (hist.total_sum() - s0) / (hist.total_count() - c0)
    row["families"]["step_fsdp"]["registry_step_seconds_mean"] = reg_mean
    if meas is None or not reg_mean:
        fails.append("step_fsdp: no measured/registry step time to "
                     "cross-check")
    elif not (STEP_TIME_BAND[0] * reg_mean <= meas
              <= STEP_TIME_BAND[1] * reg_mean):
        fails.append(
            f"step_fsdp: measured step time {meas:.4f}s disagrees with "
            f"the registry step histogram mean {reg_mean:.4f}s beyond "
            f"{STEP_TIME_BAND} — the trace windows and the wall clock "
            "watched different steps")

    # -- family 2: the serving decode step (decode golden family) ------------
    eng = fams._engine()
    trace_dir = tempfile.mkdtemp(prefix="profcheck-decode-")
    cap = eng.profile(steps=max(2, args.steps), warmup=1,
                      trace_dir=trace_dir)
    if args.inject_empty_trace:
        cap = _inject_empty(cap)
    row["families"]["decode"] = check_family(
        "decode", cap, cap.schedule, _sched_golden("decode"),
        args.golden_band, fails, notes)

    # -- capture accounting ---------------------------------------------------
    ctr = obs.REGISTRY.get("prof_captures_total")
    n_caps = int(ctr.total()) if ctr is not None else 0
    row["captures_total"] = n_caps
    if n_caps < 2:
        fails.append(f"prof_captures_total = {n_caps}, expected >= 2 "
                     "(one per family)")

    row["ok"] = not fails
    if fails:
        row["failures"] = fails
    if notes:
        row["notes"] = notes
    print(json.dumps(row, indent=1, sort_keys=True, default=str))
    for msg in notes:
        print(f"NOTE: {msg}")
    if fails:
        for msg in fails:
            print(f"FAIL: {msg}")
        return 1
    print("OK: measured op timelines non-empty for 2 shared golden "
          "families, calibration table emitted against the sched goldens, "
          "measured overlap reported next to the predicted fraction, "
          "measured step time agrees with the registry histogram")
    return 0


if __name__ == "__main__":
    sys.exit(main())
