"""gluon.rnn (reference: ``python/mxnet/gluon/rnn/``)."""
from .rnn_layer import RNN, LSTM, GRU  # noqa: F401
from .rnn_cell import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,  # noqa: F401
                       ModifierCell, DropoutCell, ResidualCell, ZoneoutCell,
                       BidirectionalCell)
