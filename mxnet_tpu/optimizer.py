"""Optimizer registry (reference: ``python/mxnet/optimizer/optimizer.py``).

Same surface — ``Optimizer.create_state / update(index, weight, grad, state)``
with lr/wd multipliers, rescale_grad, clip_gradient — but every update
delegates to the fused pure ops in ``mxnet_tpu.ops.optimizer_ops``, and the
*blessed* path jit-fuses updates across the whole parameter pytree
(``update_multi``), which is what the reference's hand-rolled
``multi_sgd_update`` multi-tensor kernels were approximating.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from . import registry as _registry
from .lr_scheduler import LRScheduler

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdaGrad", "RMSProp", "FTRL",
           "SignSGD", "LAMB", "AdamW", "create", "register"]

_OPT_REGISTRY: Dict[str, type] = {}


def register(cls):
    # import-time decorator on the class definitions below (JH005-exempt)
    _OPT_REGISTRY[cls.__name__.lower()] = cls  # lint: disable=JH005
    return cls


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    try:
        cls = _OPT_REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; available: "
                         f"{sorted(_OPT_REGISTRY)}") from None
    return cls(**kwargs)


class Optimizer:
    def __init__(self, learning_rate=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=None,
                 lr_scheduler: Optional[LRScheduler] = None, param_dict=None,
                 multi_precision=False, **kwargs):
        self.lr = learning_rate
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient if clip_gradient is not None else -1.0
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.multi_precision = multi_precision
        self.num_update = 0
        self._index_update_count: Dict[int, int] = {}
        self.lr_mult: Dict = {}
        self.wd_mult: Dict = {}
        self.param_dict = param_dict or {}
        self.idx2name: Dict[int, str] = {}

    # -- reference-compatible knobs -----------------------------------------
    def set_learning_rate(self, lr):
        self.lr = lr
        if self.lr_scheduler is not None:
            self.lr_scheduler.base_lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return float(self.lr_scheduler(self.num_update))
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        self._index_update_count[index] = self._index_update_count.get(index, 0) + 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _get_lr(self, index):
        lr = self.learning_rate
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= getattr(self.param_dict[name], "lr_mult", 1.0)
        lr *= self.lr_mult.get(name, self.lr_mult.get(index, 1.0))
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= getattr(self.param_dict[name], "wd_mult", 1.0)
        wd *= self.wd_mult.get(name, self.wd_mult.get(index, 1.0))
        return wd

    # -- pure-state protocol (also used by the pjit train_step path) ---------
    def create_state(self, index, weight):
        """Return the per-parameter state pytree (raw jax arrays)."""
        raise NotImplementedError

    def update_raw(self, w, g, state, lr, wd, t):
        """Pure update: (w, g, state, lr, wd, step) -> (new_w, new_state).
        ``lr``/``wd``/``t`` arrive as traced scalars so per-step hyperparam
        changes never retrigger XLA compilation."""
        raise NotImplementedError

    def update_raw_mp(self, w, g, state, lr, wd, t, out_dtype):
        """Master-weight variant of :meth:`update_raw`: also returns the
        updated weight cast to the stored low precision —
        ``(new_w, new_state, new_w_lowp)``. The default is the two-pass
        composition (update, then cast); optimizers with a fused Pallas
        kernel (Adam, see ``ops/pallas_optimizer.py``) override it to emit
        the cast as a second kernel output in the same pass over the
        weight bytes."""
        new_w, new_state = self.update_raw(w, g, state, lr, wd, t)
        return new_w, new_state, new_w.astype(out_dtype)

    # -- fp32 master weights (reference: multi_precision optimizers) ---------
    def _needs_master(self, raw):
        return self.multi_precision and raw.dtype in (jnp.float16, jnp.bfloat16)

    def create_state_multi_precision(self, index, weight):
        """Like ``create_state``, but when ``multi_precision`` is set and the
        weight is stored low-precision, the state carries an fp32 master
        copy: ``{"master": f32, "base": base_state_of_master}``. The dict
        layout is deliberately self-describing — no optimizer's plain state
        is a dict, so a plain-layout state (created or checkpoint-restored
        before ``multi_precision`` was flipped) can never be misread as a
        master tuple; :meth:`update_multi_precision` ADOPTS such states as
        the base and re-derives the master from the current weight. (The
        compiled ``TrainStep`` path never needs any of this: its stored
        params ARE the fp32 masters and the policy casts at compute time.)"""
        raw = weight._data if hasattr(weight, "_data") else weight
        if self._needs_master(raw):
            master = raw.astype(jnp.float32)
            return {"master": master, "base": self.create_state(index, master)}
        return self.create_state(index, weight)

    def update_multi_precision(self, index, weight, grad, state):
        """Update against the fp32 master (grad upcast, math f32), then cast
        the result back into the stored low-precision weight."""
        from .ndarray import NDArray
        from .ndarray.sparse import RowSparseNDArray

        raw = weight._data if hasattr(weight, "_data") else weight
        if not self._needs_master(raw):
            return self.update(index, weight, grad, state)
        if isinstance(state, dict) and "master" in state:
            master, base = state["master"], state["base"]
        else:
            # plain-layout state from before the multi_precision flip
            # (in-process init_trainer, or Trainer.load_states /
            # Updater.set_states restoring an old checkpoint): keep it as
            # the base — momentum survives — and re-derive the master
            master, base = raw.astype(jnp.float32), state
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        if isinstance(grad, RowSparseNDArray):
            # lazy rows-only update, run against the f32 master (values
            # upcast like the dense branch)
            g32 = RowSparseNDArray(grad._data.astype(jnp.float32),
                                   grad._aux, tuple(grad.shape))
            master_nd = NDArray(master)
            new_base = self._update_lazy(master_nd, g32, base, lr, wd, t)
            new_master = master_nd._data
        else:
            graw = grad._data if hasattr(grad, "_data") else grad
            new_master, new_base, low = self.update_raw_mp(
                master, graw.astype(jnp.float32), base,
                jnp.float32(lr), jnp.float32(wd), jnp.int32(t), raw.dtype)
            weight._data = low
            return {"master": new_master, "base": new_base}
        weight._data = new_master.astype(raw.dtype)
        return {"master": new_master, "base": new_base}

    # -- imperative protocol (Trainer / KVStore updater) ---------------------
    def update(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        if isinstance(grad, RowSparseNDArray):
            return self._update_lazy(weight, grad, state, lr, wd, t)
        new_w, new_state = self.update_raw(weight._data, grad._data, state,
                                           jnp.float32(lr), jnp.float32(wd), jnp.int32(t))
        weight._data = new_w
        return new_state

    def _update_lazy(self, weight, grad, state, lr, wd, t):
        """Lazy update for row_sparse gradients (reference: sgd lazy_update in
        ``src/operator/optimizer_op.cc`` SGDUpdateRspImpl): only the rows
        present in the gradient are read, updated, and scattered back — the
        embedding-table path. Gather→row-update→scatter lowers to XLA
        gather/scatter, keeping the touched-rows working set on-chip."""
        rows = grad._aux[0]

        def _gather(leaf):
            if hasattr(leaf, "shape") and leaf.ndim >= 1 and leaf.shape[:1] == weight._data.shape[:1]:
                return leaf[rows]
            return leaf

        def _scatter(full, part):
            if hasattr(full, "shape") and full.ndim >= 1 and full.shape[:1] == weight._data.shape[:1]:
                return full.at[rows].set(part)
            return part

        sub_state = jax.tree_util.tree_map(_gather, state)
        new_w_rows, new_sub = self.update_raw(weight._data[rows], grad._data, sub_state,
                                              jnp.float32(lr), jnp.float32(wd), jnp.int32(t))
        weight._data = weight._data.at[rows].set(new_w_rows)
        return jax.tree_util.tree_map(_scatter, state, new_sub) if state is not None else new_sub

    def update_multi(self, indices, weights, grads, states):
        """Fused whole-pytree update (one XLA program for all params)."""
        for i in indices:
            self._update_count(i)
        lrs = [jnp.float32(self._get_lr(i)) for i in indices]
        wds = [jnp.float32(self._get_wd(i)) for i in indices]
        ts = [jnp.int32(self._index_update_count[i]) for i in indices]

        new = _jit_multi(self.__class__.__name__, self._hyper_key(), self.update_raw,
                         tuple(w._data for w in weights), tuple(g._data for g in grads),
                         tuple(states), tuple(lrs), tuple(wds), tuple(ts))
        new_ws, new_states = new
        for w, nw in zip(weights, new_ws):
            w._data = nw
        return list(new_states)

    def _hyper_key(self):
        return (self.rescale_grad, self.clip_gradient)


import functools


@functools.lru_cache(maxsize=64)
def _multi_impl(opt_name, hyper_key, update_raw):
    @jax.jit
    def run(ws, gs, states, lrs, wds, ts):
        outs = [update_raw(w, g, s, lr, wd, t)
                for w, g, s, lr, wd, t in zip(ws, gs, states, lrs, wds, ts)]
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    return run


def _jit_multi(opt_name, hyper_key, update_raw, ws, gs, states, lrs, wds, ts):
    return _multi_impl(opt_name, hyper_key, update_raw)(ws, gs, states, lrs, wds, ts)


from .ops import optimizer_ops as _oo  # noqa: E402


@register
class SGD(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        raw = weight._data if hasattr(weight, "_data") else weight
        return jnp.zeros_like(raw, jnp.float32)

    def update_raw(self, w, g, state, lr, wd, t):
        if self.momentum == 0.0:
            return _oo.sgd_update(w, g, lr, wd, self.rescale_grad, self.clip_gradient), None
        return _oo.sgd_mom_update(w, g, state, lr, self.momentum, wd, self.rescale_grad, self.clip_gradient)


@register
class NAG(SGD):
    def update_raw(self, w, g, state, lr, wd, t):
        if self.momentum == 0.0:
            return _oo.sgd_update(w, g, lr, wd, self.rescale_grad, self.clip_gradient), None
        return _oo.nag_mom_update(w, g, state, lr, self.momentum, wd, self.rescale_grad, self.clip_gradient)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        raw = weight._data if hasattr(weight, "_data") else weight
        return (jnp.zeros_like(raw, jnp.float32), jnp.zeros_like(raw, jnp.float32))

    def _lr_t(self, lr, t):
        tf = jnp.asarray(t, jnp.float32)
        # bias correction folded into lr like the reference adam_update
        coef1 = 1.0 - jnp.power(self.beta1, tf)
        coef2 = 1.0 - jnp.power(self.beta2, tf)
        return lr * jnp.sqrt(coef2) / coef1

    def update_raw(self, w, g, state, lr, wd, t):
        from .ops import pallas_optimizer as _po

        mean, var = state
        lr_t = self._lr_t(lr, t)
        if _po.fused_adam_supported(w, g, mean):
            new_w, m, v = _po.adam_update_fused(
                w, g, mean, var, lr_t, beta1=self.beta1, beta2=self.beta2,
                epsilon=self.epsilon, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient)
            return new_w, (m, v)
        new_w, m, v = _oo.adam_update(w, g, mean, var, lr_t, self.beta1, self.beta2,
                                      self.epsilon, wd, self.rescale_grad, self.clip_gradient)
        return new_w, (m, v)

    def update_raw_mp(self, w, g, state, lr, wd, t, out_dtype):
        from .ops import pallas_optimizer as _po

        mean, var = state
        if _po.fused_adam_supported(w, g, mean):
            new_w, m, v, low = _po.adam_update_fused(
                w, g, mean, var, self._lr_t(lr, t), beta1=self.beta1,
                beta2=self.beta2, epsilon=self.epsilon, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient, out_dtype=out_dtype)
            return new_w, (m, v), low
        return super().update_raw_mp(w, g, state, lr, wd, t, out_dtype)


@register
class AdamW(Adam):
    """Decoupled weight decay (used by BERT fine-tune scripts)."""

    def update_raw_mp(self, w, g, state, lr, wd, t, out_dtype):
        # decoupled decay is applied after the Adam step, so it cannot ride
        # the fused coupled-wd kernel pass Adam overrides this with
        return Optimizer.update_raw_mp(self, w, g, state, lr, wd, t, out_dtype)

    def update_raw(self, w, g, state, lr, wd, t):
        mean, var = state
        tf = jnp.asarray(t, jnp.float32)
        coef1 = 1.0 - jnp.power(self.beta1, tf)
        coef2 = 1.0 - jnp.power(self.beta2, tf)
        lr_t = lr * jnp.sqrt(coef2) / coef1
        new_w, m, v = _oo.adam_update(w, g, mean, var, lr_t, self.beta1, self.beta2,
                                      self.epsilon, 0.0, self.rescale_grad, self.clip_gradient)
        new_w = (new_w.astype(jnp.float32) - lr * wd * w.astype(jnp.float32)).astype(w.dtype)
        return new_w, (m, v)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        raw = weight._data if hasattr(weight, "_data") else weight
        return jnp.zeros_like(raw, jnp.float32)

    def update_raw(self, w, g, state, lr, wd, t):
        return _oo.adagrad_update(w, g, state, lr, self.float_stable_eps, wd,
                                  self.rescale_grad, self.clip_gradient)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered
        self.clip_weights = clip_weights if clip_weights is not None else -1.0

    def create_state(self, index, weight):
        raw = weight._data if hasattr(weight, "_data") else weight
        return jnp.zeros_like(raw, jnp.float32)

    def update_raw(self, w, g, state, lr, wd, t):
        return _oo.rmsprop_update(w, g, state, lr, self.gamma1, self.epsilon, wd,
                                  self.rescale_grad, self.clip_gradient, self.clip_weights)


@register
class FTRL(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        raw = weight._data if hasattr(weight, "_data") else weight
        return (jnp.zeros_like(raw, jnp.float32), jnp.zeros_like(raw, jnp.float32))

    def update_raw(self, w, g, state, lr, wd, t):
        z, n = state
        new_w, new_z, new_n = _oo.ftrl_update(w, g, z, n, lr, self.lamda1, self.beta, wd,
                                              self.rescale_grad, self.clip_gradient)
        return new_w, (new_z, new_n)


@register
class SignSGD(Optimizer):
    def create_state(self, index, weight):
        return None

    def update_raw(self, w, g, state, lr, wd, t):
        return _oo.signsgd_update(w, g, lr, wd, self.rescale_grad, self.clip_gradient), None


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (the BERT pretrain optimizer;
    reference: lamb_update_phase1/2 in src/operator/optimizer_op.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lower_bound=None, upper_bound=None, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound if lower_bound is not None else -1.0
        self.upper_bound = upper_bound if upper_bound is not None else -1.0
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        raw = weight._data if hasattr(weight, "_data") else weight
        return (jnp.zeros_like(raw, jnp.float32), jnp.zeros_like(raw, jnp.float32))

    def update_raw(self, w, g, state, lr, wd, t):
        mean, var = state
        upd, m, v = _oo.lamb_update_phase1(w, g, mean, var, self.beta1, self.beta2,
                                           self.epsilon, jnp.asarray(t, jnp.float32),
                                           self.bias_correction, wd,
                                           self.rescale_grad, self.clip_gradient)
        r1 = jnp.linalg.norm(w.astype(jnp.float32))
        r2 = jnp.linalg.norm(upd)
        new_w = _oo.lamb_update_phase2(w, upd, r1, r2, lr, self.lower_bound, self.upper_bound)
        return new_w, (m, v)


class Updater:
    """KVStore server-side updater (reference ``Optimizer.get_updater``)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict = {}

    def __call__(self, index, grad, weight):
        # multi-precision aware (reference Updater dispatch): f16/bf16
        # weights under a multi_precision optimizer get the fp32-master
        # state and update, same as Trainer._update
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
        self.states[index] = self.optimizer.update_multi_precision(
            index, weight, grad, self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle

        return pickle.dumps((self.states, self.optimizer) if dump_optimizer else self.states)

    def set_states(self, states):
        import pickle

        obj = pickle.loads(states)
        if isinstance(obj, tuple):
            self.states, self.optimizer = obj
        else:
            self.states = obj


def get_updater(optimizer):
    return Updater(optimizer)
