"""Active-mesh context shared between the parallel package and the op layer.

GSPMD inserts collectives from sharding annotations, but left to itself it
sometimes picks layouts that force an "Involuntary full rematerialization"
(observed on the BERT MLM-head loss path, round-3 verdict weak #2). The fix
is explicit ``with_sharding_constraint`` at the layout transition — which
requires model/loss code to know the mesh it is being staged over. This tiny
dependency-free module carries that mesh: ``TrainStep`` (and other staged
contexts) set it around the functional trace, and the ``_sharding_constraint``
registry op reads it, degrading to identity when no mesh is active (eager
single-device runs, shape inference, tests).
"""
from __future__ import annotations

import contextlib
import threading

_STATE = threading.local()

__all__ = ["active_mesh", "current_mesh"]


def current_mesh():
    """The mesh the surrounding staged computation is sharded over, or None."""
    return getattr(_STATE, "mesh", None)


@contextlib.contextmanager
def active_mesh(mesh):
    """Declare ``mesh`` as the active mesh for sharding-constraint ops."""
    prev = getattr(_STATE, "mesh", None)
    _STATE.mesh = mesh
    try:
        yield mesh
    finally:
        _STATE.mesh = prev
