"""Runtime config / env-var layer (reference SURVEY §5.6: the ``MXNET_*``
env-var tier read via ``dmlc::GetEnv`` at use sites).

One typed module: every knob has a declared type/default and an ``MXNET_*``
alias where the reference semantics survive on TPU. Knobs whose mechanism is
deleted (engine type, GPU mem pool, cuDNN autotune) are accepted and mapped
to their closest analog or a no-op, so reference launch scripts run.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict

__all__ = ["get", "set", "knobs", "describe", "apply_compile_cache"]

# name -> (type, default, env aliases, doc)
_KNOBS: Dict[str, tuple] = {
    "safe_accumulation": (bool, True, ("MXNET_SAFE_ACCUMULATION",),
                          "accumulate low-precision reductions in f32"),
    "engine_type": (str, "xla", ("MXNET_ENGINE_TYPE",),
                    "reference: ThreadedEnginePerDevice/NaiveEngine; here "
                    "'xla' (async) or 'naive' (sync eager via jax.disable_jit "
                    "debugging semantics)"),
    "exec_bulk_exec_train": (bool, True, ("MXNET_EXEC_BULK_EXEC_TRAIN",),
                             "reference op-bulking; here jit fusion (no-op)"),
    "gpu_mem_pool_type": (str, "xla", ("MXNET_GPU_MEM_POOL_TYPE",),
                          "allocator pooling is XLA's BFC arena (no-op)"),
    "cudnn_autotune_default": (int, 0, ("MXNET_CUDNN_AUTOTUNE_DEFAULT",),
                               "XLA autotunes convs itself (no-op)"),
    "kvstore_usetree": (bool, False, ("MXNET_KVSTORE_USETREE",),
                        "comm-tree selection is XLA's collective scheduling"),
    "kvstore_bigarray_bound": (int, 1000000, ("MXNET_KVSTORE_BIGARRAY_BOUND",),
                               "kept for API compat"),
    "use_fusion": (bool, True, ("MXNET_USE_FUSION",),
                   "pointwise fusion — always on via XLA"),
    "fused_layernorm": (bool, False, ("MXNET_TPU_FUSED_LAYERNORM",),
                        "route LayerNorm through the Pallas kernel on TPU "
                        "(off until hardware-validated; interpret-mode "
                        "tested)"),
    "flash_attention": (bool, True, ("MXNET_TPU_FLASH_ATTENTION",),
                        "use the Pallas flash kernel when shapes allow"),
    "flash_pallas_bwd": (bool, True, ("MXNET_TPU_FLASH_PALLAS_BWD",),
                         "FlashAttention-2 Pallas backward kernels (dq + "
                         "dkv); off = XLA chunked-recompute backward "
                         "(~2.5x slower on v5e but kernel-free)"),
    "paged_attention_kernel": (bool, True, ("MXNET_TPU_PAGED_ATTENTION_KERNEL",),
                               "paged decode/verify read path through the "
                               "Pallas page-table kernel (in-kernel page "
                               "gather, no pool-wide materialization); off "
                               "= XLA pool[page_table] gather fallback"),
    "fused_adam": (bool, False, ("MXNET_TPU_FUSED_ADAM",),
                   "route Adam/AdamW updates through the fused Pallas "
                   "kernel on TPU (one pass over grad/m/v/master; off "
                   "until hardware-validated; interpret-mode tested)"),
    "fused_softmax_xent": (bool, False, ("MXNET_TPU_FUSED_SOFTMAX_XENT",),
                           "fused softmax-cross-entropy Pallas kernel "
                           "(custom VJP) for sparse-label gluon loss on "
                           "TPU (off until hardware-validated; "
                           "interpret-mode tested)"),
    "default_dtype": (str, "float32", ("MXNET_DEFAULT_DTYPE",), "creation dtype"),
    "storage_fallback_warn": (bool, True, ("MXNET_STORAGE_FALLBACK_WARN",),
                              "warn when a sparse input densifies at an op "
                              "boundary (reference: 'Storage type fallback' "
                              "log in executor/infer_graph_attr_pass)"),
    "profiler_dir": (str, "/tmp/mxnet_tpu_profile", ("MXNET_PROFILER_DIR",),
                     "xplane trace output directory"),
    "num_cpu_workers": (int, 4, ("MXNET_CPU_WORKER_NTHREADS", "OMP_NUM_THREADS"),
                        "host-side data worker default"),
    # -- resilience subsystem (docs/RESILIENCE.md) ---------------------------
    "faults": (str, "", ("MXNET_TPU_FAULTS",),
               "fault-injection spec armed at import, e.g. "
               "'ckpt.save:every=3;kv.dcn_psum:on=2:times=2;seed=7' — "
               "deterministic failures at named sites for chaos testing"),
    "retry_max_attempts": (int, 3, ("MXNET_TPU_RETRY_MAX_ATTEMPTS",),
                           "attempts per IO/DCN site before RetryError"),
    "retry_base_delay": (float, 0.05, ("MXNET_TPU_RETRY_BASE_DELAY",),
                         "first backoff delay in seconds"),
    "retry_max_delay": (float, 2.0, ("MXNET_TPU_RETRY_MAX_DELAY",),
                        "backoff ceiling in seconds"),
    "retry_jitter": (float, 0.25, ("MXNET_TPU_RETRY_JITTER",),
                     "max fractional jitter added to each backoff delay"),
    "retry_timeout": (float, 0.0, ("MXNET_TPU_RETRY_TIMEOUT",),
                      "per-site wall-clock budget across all attempts of "
                      "one call, seconds (0 = unlimited)"),
    "ckpt_keep_last": (int, 0, ("MXNET_TPU_CKPT_KEEP_LAST",),
                       "retention sweep after each save_train_state: keep "
                       "the newest N committed checkpoints (0 = keep all)"),
    "ckpt_sharded": (bool, False, ("MXNET_TPU_CKPT_SHARDED",),
                     "force the world-size-agnostic npz-shards checkpoint "
                     "format even for fully-addressable single-process "
                     "state (multi-process and non-addressable saves use "
                     "it regardless)"),
    # -- elastic training (docs/RESILIENCE.md "Elastic training") ------------
    "dist_init_retries": (int, 3, ("MXNET_TPU_DIST_INIT_RETRIES",),
                          "attempts for jax.distributed bootstrap (site "
                          "dist.init) — a replacement worker joining before "
                          "the coordinator port is up retries instead of "
                          "hard-failing"),
    "dist_init_timeout": (float, 0.0, ("MXNET_TPU_DIST_INIT_TIMEOUT",),
                          "per-attempt jax.distributed.initialize timeout "
                          "in seconds (0 = jax default)"),
    "elastic_hb_interval": (float, 0.5, ("MXNET_TPU_ELASTIC_HB_INTERVAL",),
                            "seconds between heartbeat-file touches"),
    "elastic_hb_timeout": (float, 5.0, ("MXNET_TPU_ELASTIC_HB_TIMEOUT",),
                           "heartbeat staleness after which a peer counts "
                           "as lost and the worker requests a mesh "
                           "re-formation"),
    # -- serving resilience (docs/RESILIENCE.md "Serving resilience") --------
    "serve_default_deadline": (float, 0.0, ("MXNET_TPU_SERVE_DEADLINE",),
                               "default per-request deadline in seconds "
                               "applied at submit when the caller passes "
                               "none (0 = no deadline)"),
    "serve_max_queue": (int, 0, ("MXNET_TPU_SERVE_MAX_QUEUE",),
                        "bounded admission queue: submits past this depth "
                        "are shed per serve_queue_policy (0 = unbounded)"),
    "serve_queue_policy": (str, "reject", ("MXNET_TPU_SERVE_QUEUE_POLICY",),
                           "full-queue policy: 'reject' sheds the NEW "
                           "request; 'shed' evicts the oldest queued "
                           "request already past its deadline (falls back "
                           "to reject when none is)"),
    "serve_shed_page_floor": (int, 0, ("MXNET_TPU_SERVE_SHED_PAGE_FLOOR",),
                              "load-shed watermark: with a backlog queued, "
                              "shed new submits while free KV pages are "
                              "below this floor (0 = off)"),
    "serve_head_aging_steps": (int, 8, ("MXNET_TPU_SERVE_HEAD_AGING_STEPS",),
                               "admission aging guard: after this many "
                               "step-boundary deferrals of the queue head "
                               "on free pages, freed pages are reserved "
                               "for the head and bypass admission stops "
                               "(prevents head starvation behind a stream "
                               "of small requests)"),
    "serve_spec_window": (int, 8, ("MXNET_TPU_SERVE_SPEC_WINDOW",),
                          "speculative accept-rate window (rounds) the "
                          "degradation governor decides on"),
    "serve_spec_floor": (float, 0.125, ("MXNET_TPU_SERVE_SPEC_FLOOR",),
                         "windowed accept rate below which speculation "
                         "falls back to plain paged decode (break-even "
                         "is ~1/speculate_k)"),
    "serve_spec_cooldown": (int, 16, ("MXNET_TPU_SERVE_SPEC_COOLDOWN",),
                            "plain decode steps before a fallen-back "
                            "engine re-arms speculation"),
    "serve_watchdog_s": (float, 0.0, ("MXNET_TPU_SERVE_WATCHDOG_S",),
                         "soft per-dispatch timeout for the serving loop: "
                         "a dispatch exceeding it emits gen_stuck_dispatch "
                         "(event + counter) instead of hanging silently "
                         "(0 = off)"),
    # -- fleet serving tier (docs/INFERENCE.md "Fleet serving") --------------
    "router_hb_timeout": (float, 5.0, ("MXNET_TPU_ROUTER_HB_TIMEOUT",),
                          "replica heartbeat staleness (seconds since the "
                          "last published snapshot) after which fleet "
                          "health marks it DEGRADED"),
    "router_drain_after": (float, 5.0, ("MXNET_TPU_ROUTER_DRAIN_AFTER",),
                           "seconds a replica may stay DEGRADED before the "
                           "router drains it (no new admissions, queued "
                           "work redistributed)"),
    "router_dead_grace": (float, 30.0, ("MXNET_TPU_ROUTER_DEAD_GRACE",),
                          "seconds a DRAINING replica gets for in-flight "
                          "rows to finish or expire before it is declared "
                          "DEAD and its remaining work redistributed"),
    "router_queue_bound": (int, 4, ("MXNET_TPU_ROUTER_QUEUE_BOUND",),
                           "max published admission-queue depth the router "
                           "will dispatch onto; deeper replicas keep the "
                           "request in the router backlog"),
    "router_classes": (str, "interactive,normal,batch",
                       ("MXNET_TPU_ROUTER_CLASSES",),
                       "priority classes in admission order (first = "
                       "dispatched first under contention)"),
    "router_affinity": (bool, True, ("MXNET_TPU_ROUTER_AFFINITY",),
                        "pin a session's requests to the replica holding "
                        "its prefix pages while that replica is LIVE"),
    "router_seed": (int, 0, ("MXNET_TPU_ROUTER_SEED",),
                    "seed for the power-of-two-choices candidate sampling "
                    "(deterministic routing in drills and tests)"),
    "router_prefix_tokens": (int, 16, ("MXNET_TPU_ROUTER_PREFIX_TOKENS",),
                             "sessionless affinity: requests whose first N "
                             "prompt tokens match are routed to the same "
                             "replica so its radix prefix cache keeps the "
                             "shared pages hot; 0 disables"),
    # -- request tracing + SLO ledger (docs/OBSERVABILITY.md
    #    "Request tracing & SLO ledger") -------------------------------------
    "trace": (bool, False, ("MXNET_TPU_TRACE",),
              "per-request span tracing for the serving tier: router and "
              "replicas append span JSONL into the fleet dir, joined by "
              "request id at aggregation (off = one attribute read per "
              "emission site)"),
    "trace_sample": (float, 0.01, ("MXNET_TPU_TRACE_SAMPLE",),
                     "fraction of HEALTHY traces whose spans are kept "
                     "(deterministic hash of trace id, so router and "
                     "replicas agree without coordinating); anomalous/"
                     "slow/low-margin traces are always kept"),
    "trace_seed": (int, 0, ("MXNET_TPU_TRACE_SEED",),
                   "seed of the deterministic healthy-sampling hash"),
    "trace_slow_pct": (float, 95.0, ("MXNET_TPU_TRACE_SLOW_PCT",),
                       "tail-sampling slow percentile: traces at or above "
                       "this percentile of recent end-to-end latency are "
                       "always kept"),
    "trace_margin_floor": (float, 0.0, ("MXNET_TPU_TRACE_MARGIN_FLOOR",),
                           "deadline-margin floor (seconds): a trace "
                           "finishing with less margin is always kept AND "
                           "requests a measured-profile capture on its "
                           "replica (prof-request contract); 0 = off"),
    "trace_slo_target": (float, 0.99, ("MXNET_TPU_TRACE_SLO_TARGET",),
                         "SLO attainment target the burn rates are "
                         "computed against (burn = violation rate / "
                         "(1 - target); > 1 burns budget)"),
    "trace_slo_windows": (str, "60,300,3600", ("MXNET_TPU_TRACE_SLO_WINDOWS",),
                          "comma-separated burn-rate window lengths in "
                          "seconds, anchored at the newest finish "
                          "timestamp the aggregator sees"),
    # -- compilation (docs/PERFORMANCE.md) -----------------------------------
    "compile_cache": (str, "", ("MXNET_TPU_COMPILE_CACHE",),
                      "persistent XLA compilation-cache directory "
                      "(jax_compilation_cache_dir), honored at import: "
                      "re-runs skip lowering+compile for every already-seen "
                      "program signature, including the k-step window "
                      "programs; empty = disabled"),
    # -- observability subsystem (docs/OBSERVABILITY.md) ---------------------
    "telemetry": (bool, False, ("MXNET_TPU_TELEMETRY",),
                  "arm hot-path telemetry at first use: step/comm/data/ckpt "
                  "metrics + the JSONL event log (off = one bool check per "
                  "instrumented call)"),
    "telemetry_dir": (str, "/tmp/mxnet_tpu_telemetry", ("MXNET_TPU_TELEMETRY_DIR",),
                      "run directory for events-h{host}.jsonl + metrics.json/"
                      ".prom exports"),
    "telemetry_rotate_mb": (int, 64, ("MXNET_TPU_TELEMETRY_ROTATE_MB",),
                            "event-log rotation threshold per file (rotated "
                            "segments are gzip-compressed)"),
    "events_keep_bytes": (int, 0, ("MXNET_TPU_EVENTS_KEEP_BYTES",),
                          "cap on total bytes of retained rotated event-log "
                          "segments (.jsonl.N.gz); 0 = keep exactly one "
                          "rotated segment (the pre-cap behavior)"),
    # -- measured profiling (docs/OBSERVABILITY.md "Measured profiling") -----
    "prof_every_n_steps": (int, 0, ("MXNET_TPU_PROF_EVERY_N_STEPS",),
                           "trace every N-th training step into a capture "
                           "dir (periodic measured baseline); 0 = off"),
    "prof_keep_bytes": (int, 512 * 1024 * 1024, ("MXNET_TPU_PROF_KEEP_BYTES",),
                        "retention cap on total bytes of kept step-capture "
                        "trace dirs (oldest swept first, newest always "
                        "kept); 0 = unbounded"),
    # -- fleet observability (docs/OBSERVABILITY.md "Fleet view") ------------
    "fleet_dir": (str, "", ("MXNET_TPU_FLEET_DIR",),
                  "shared directory for cross-rank telemetry snapshots "
                  "(telemetry-h{rank}/ per rank, same contract as the "
                  "elastic heartbeat dir); empty = fleet snapshots off"),
    "fleet_snapshot_interval": (float, 5.0,
                                ("MXNET_TPU_FLEET_SNAPSHOT_INTERVAL",),
                                "seconds between per-rank fleet telemetry "
                                "snapshots"),
    "straggler_factor": (float, 3.0, ("MXNET_TPU_STRAGGLER_FACTOR",),
                         "a rank whose step / collective-wait time exceeds "
                         "the fleet median by this factor is flagged as a "
                         "straggler"),
    "peak_flops": (float, 0.0, ("MXNET_TPU_PEAK_FLOPS",),
                   "accelerator peak FLOP/s per process for train_mfu "
                   "(e.g. 1.97e14 for one v5e chip); 0 = MFU not computed"),
    # -- schedule auditor roofline constants (docs/ANALYSIS.md
    # "Schedule & overlap"); 0/empty = the analysis.schedule defaults
    # (one v5e chip), sched_peak_flops falls back to peak_flops first ----
    "sched_peak_flops": (float, 0.0, ("MXNET_TPU_SCHED_PEAK_FLOPS",),
                         "peak FLOP/s the schedule auditor's roofline "
                         "prices compute at; 0 = peak_flops, else the "
                         "v5e default"),
    "sched_hbm_gbps": (float, 0.0, ("MXNET_TPU_SCHED_HBM_GBPS",),
                       "HBM bandwidth (GB/s) for the roofline's memory "
                       "side; 0 = the v5e default"),
    "sched_ici_gbps": (float, 0.0, ("MXNET_TPU_SCHED_ICI_GBPS",),
                       "ICI link bandwidth (GB/s) collectives are priced "
                       "at; 0 = the v5e default"),
    "sched_dcn_gbps": (float, 0.0, ("MXNET_TPU_SCHED_DCN_GBPS",),
                       "DCN bandwidth (GB/s) for collectives spanning a "
                       "sched_dcn_axes axis; 0 = the default"),
    "sched_dcn_axes": (str, "", ("MXNET_TPU_SCHED_DCN_AXES",),
                       "comma-separated mesh axes priced at DCN speed by "
                       "the schedule auditor (e.g. 'dp' on a multi-pod "
                       "fleet); empty = every collective rides ICI"),
}

_values: Dict[str, Any] = {}
# set() may be called while loader/telemetry threads resolve knobs (JH005)
_values_lock = threading.Lock()


def _coerce(typ, raw):
    if typ is bool:
        return str(raw).lower() in ("1", "true", "yes", "on")
    return typ(raw)


def get(name: str):
    if name in _values:
        return _values[name]
    typ, default, envs, _doc = _KNOBS[name]
    for e in envs:
        if e in os.environ:
            return _coerce(typ, os.environ[e])
    return default


def set(name: str, value) -> None:
    typ, _d, _e, _doc = _KNOBS[name]
    with _values_lock:
        _values[name] = _coerce(typ, value)


def knobs():
    return sorted(_KNOBS)


def describe(name: str) -> str:
    typ, default, envs, doc = _KNOBS[name]
    return f"{name} ({typ.__name__}, default={default!r}, env={'/'.join(envs)}): {doc}"


def apply_compile_cache():
    """Honor ``MXNET_TPU_COMPILE_CACHE`` at init: point jax's persistent
    compilation cache at the directory so a restarted run pays zero XLA
    compile time for every program signature it has seen before (the
    single-step programs AND the per-(window, shapes) scan windows).
    Called from package import; returns the applied directory or None."""
    d = get("compile_cache")
    if not d:
        return None
    import warnings

    import jax

    d = os.path.abspath(d)
    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
    except (OSError, AttributeError) as e:
        warnings.warn(f"MXNET_TPU_COMPILE_CACHE={d!r} not applied: {e}")
        return None
    # cache tiny/fast programs too — the CI dry-runs and unit meshes are
    # exactly the programs worth skipping on the next run
    for knob, v in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                    ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, v)
        except Exception:  # older jax: knob absent
            pass
    return d
