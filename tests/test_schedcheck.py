"""Golden-program schedule gate (ISSUE 13, docs/ANALYSIS.md "Schedule &
overlap"): `make schedcheck` as a test — the committed sched_* goldens
match the current programs, an injected exposed collective fails the
build, the --update-golden rebless workflow round-trips (and refuses the
inject hook), and the family builders are the SAME shared definition the
shardcheck/memcheck gates consume (tools/families.py — no drift).

Runs tools/schedcheck.py in-process (importlib) so each case can pick one
cheap program family and capture the JSON verdict without a subprocess
per family.
"""
import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_mod", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def schedcheck():
    return _load("schedcheck")


def _verdict(capsys):
    out = capsys.readouterr().out
    row, _ = json.JSONDecoder().raw_decode(out, out.index("{"))
    return row, out


def test_gate_matches_committed_goldens(schedcheck, capsys):
    """ISSUE 13 acceptance: the committed goldens describe the current
    programs — critical path within tolerance, overlap intact, the
    CPU-sync exposed census unchanged."""
    rc = schedcheck.main(["--family", "step_fsdp"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    fam = row["families"]["step_fsdp"]
    assert fam["critical_path_seconds"] > 0
    assert fam["comm_seconds"] > 0
    # the audit schedules the asyncified view (the layout overlap
    # policy), so part of the collective time hides behind compute —
    # the gate pins that gain against ever dropping back toward the
    # sync-CPU 0.0 baseline
    assert 0.0 < fam["overlap_fraction"] < 1.0
    assert fam["hidden_comm_seconds"] > 0
    assert fam["exposed_collectives"].get("all_reduce", 0) > 0
    assert set(fam["exposed_by_axis_bytes"]) == {"fsdp", "dp×fsdp"}
    assert fam["carry_donation"] == 1.0


def test_injected_exposed_collective_fails_gate(schedcheck, capsys):
    """ISSUE 13 acceptance: a synthetic exposed all-gather (the --inject
    test hook) must fail the build — as a newly exposed collective, an
    exposed-byte regression, and a critical-path regression."""
    rc = schedcheck.main(["--family", "step_dp8",
                          "--inject-exposed-collective"])
    _, out = _verdict(capsys)
    assert rc == 1
    assert "newly exposed collective" in out
    assert "critical-path latency regressed" in out
    assert "exposed comm bytes" in out


def test_serving_families_have_no_exposed_comm(schedcheck, capsys):
    """The serving contract seen through the schedule lens: zero
    collective time, overlap vacuously perfect, a positive MFU bound."""
    rc = schedcheck.main(["--family", "decode"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]
    fam = row["families"]["decode"]
    assert fam["comm_seconds"] == 0.0
    assert fam["overlap_fraction"] == 1.0
    assert fam["exposed_collectives"] == {}
    assert 0 < fam["mfu_bound"] <= 1.0


def test_inject_cannot_combine_with_update_golden(schedcheck, capsys):
    """The failure-path hook must never bless the injected exposure into
    the committed goldens."""
    with pytest.raises(SystemExit) as exc:
        schedcheck.main(["--update-golden", "--inject-exposed-collective"])
    assert exc.value.code == 2
    assert "cannot be combined" in capsys.readouterr().err


def test_update_golden_rebless_roundtrip(schedcheck, capsys, monkeypatch,
                                         tmp_path):
    """--update-golden writes a fresh golden the plain gate then passes
    against; with no golden at all the gate fails with the rebless
    instruction instead of crashing."""
    monkeypatch.setattr(schedcheck, "GOLDEN_DIR", str(tmp_path))
    rc = schedcheck.main(["--family", "prefill"])
    _, out = _verdict(capsys)
    assert rc == 1 and "no committed golden" in out
    assert "--update-golden" in out
    rc = schedcheck.main(["--family", "prefill", "--update-golden"])
    assert rc == 0
    golden = json.loads((tmp_path / "sched_prefill.json").read_text())
    assert golden["comm_seconds"] == 0.0
    assert golden["critical_path_seconds"] > 0
    assert golden["constants"]["ici_gbps"] > 0
    rc = schedcheck.main(["--family", "prefill"])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"]


def test_families_are_the_shared_definition(schedcheck):
    """ISSUE 13 satellite: shardcheck, memcheck and schedcheck all
    consume tools/families.py — the SAME memoized module instance, so a
    family change cannot drift between gates."""
    shardcheck = _load("shardcheck")
    memcheck = _load("memcheck")
    assert schedcheck.families() is shardcheck.FAMILIES
    assert memcheck.families() is shardcheck.FAMILIES
    assert set(schedcheck.FAMILY_NAMES) == set(shardcheck.FAMILIES)
    assert schedcheck.FAMILY_NAMES == memcheck.FAMILY_NAMES
