"""Observability subsystem (docs/OBSERVABILITY.md): metrics registry
semantics, JSONL event-log schema, and the step/comm/ckpt/retry
instrumentation contracts from the ISSUE acceptance criteria."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, observability as obs
from mxnet_tpu.observability import events as ev_mod
from mxnet_tpu.observability.metrics import Registry


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    """Tests arm telemetry per-case; never leak the gate (or an open event
    log) into the rest of the suite."""
    yield
    obs.disable()


# -- registry semantics ------------------------------------------------------

def test_counter_labels_and_totals():
    r = Registry()
    c = r.counter("reqs_total", "requests")
    c.inc(2, site="a")
    c.inc(site="a")
    c.inc(5, site="b")
    assert c.value(site="a") == 3
    assert c.value(site="b") == 5
    assert c.value(site="nope") == 0
    assert c.total() == 8
    with pytest.raises(ValueError):
        c.inc(-1)
    # re-registering the same name+kind returns the same object; kind clash raises
    assert r.counter("reqs_total") is c
    with pytest.raises(ValueError):
        r.gauge("reqs_total")


def test_gauge_set_and_value():
    r = Registry()
    g = r.gauge("temp")
    assert g.value() is None
    g.set(1.5)
    g.set(2.5, zone="hot")
    assert g.value() == 1.5
    assert g.value(zone="hot") == 2.5


def test_histogram_buckets_stats_percentile():
    r = Registry()
    h = r.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v, op="x")
    s = h.stats(op="x")
    assert s["count"] == 4
    assert s["min"] == 0.005 and s["max"] == 5.0
    assert abs(s["sum"] - 5.555) < 1e-9
    # one observation per bucket incl. the +Inf overflow
    assert s["buckets"] == [1, 1, 1, 1]
    assert h.percentile(0.5, op="x") == 0.1
    assert h.percentile(1.0, op="x") == 5.0  # max, not an edge
    assert h.total_count() == 4


def test_snapshot_reset_roundtrip():
    r = Registry()
    r.counter("c").inc(3, k="v")
    r.histogram("h", buckets=(1.0,)).observe(0.5)
    snap = r.snapshot()
    assert snap["c"]["kind"] == "counter"
    assert snap["c"]["series"][0] == {"labels": {"k": "v"}, "value": 3.0}
    hseries = snap["h"]["series"][0]["value"]
    assert hseries["count"] == 1 and hseries["buckets"]["1.0"] == 1
    # snapshot is JSON-safe
    json.loads(r.to_json())
    r.reset("c")
    assert r.counter("c").total() == 0
    assert r.histogram("h").total_count() == 1
    r.reset()
    assert r.histogram("h").total_count() == 0


def test_prometheus_export_format():
    r = Registry()
    r.counter("n_total", "help text").inc(2, site="a")
    r.histogram("h_seconds", buckets=(0.1, 1.0)).observe(0.05, op="x")
    text = r.to_prometheus()
    assert '# TYPE n_total counter' in text
    assert 'n_total{site="a"} 2.0' in text
    # cumulative buckets + +Inf + sum/count
    assert 'h_seconds_bucket{le="0.1",op="x"} 1' in text
    assert 'h_seconds_bucket{le="+Inf",op="x"} 1' in text
    assert 'h_seconds_count{op="x"} 1' in text


# -- event log ---------------------------------------------------------------

def test_event_log_schema_roundtrip(tmp_path):
    log = ev_mod.EventLog()
    log.configure(str(tmp_path / "events.jsonl"), run_id="r1")
    log.set_step(7)
    assert log.emit("unit", foo=1, bar="baz")
    assert log.emit("unit2", step=9, val=2.5)
    log.close()
    recs = ev_mod.read_events(str(tmp_path / "events.jsonl"))
    assert len(recs) == 2
    for rec in recs:
        assert set(rec) >= {"ts", "run", "host", "step", "event"}
        assert rec["run"] == "r1"
    assert recs[0]["event"] == "unit" and recs[0]["step"] == 7 and recs[0]["foo"] == 1
    assert recs[1]["step"] == 9  # explicit step overrides the monotonic one


def test_event_log_rotation(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = ev_mod.EventLog()
    log.configure(path, rotate_bytes=4096)  # exactly one rotation over 40 records
    for i in range(40):
        log.emit("tick", i=i, pad="x" * 64)
    log.close()
    # rotated segments are gzip-compressed, numbered oldest-first
    assert os.path.exists(path + ".1.gz"), "rotation never happened"
    recs = ev_mod.read_events(path)
    # nothing lost across a single rotation boundary, order preserved
    assert [r["i"] for r in recs] == list(range(40))
    # directory-mode read finds the same records (gz read transparently)
    assert len(ev_mod.read_events(str(tmp_path))) == 40
    # many rotations at the default keep_bytes=0: disk stays bounded at
    # the live file + exactly ONE rotated segment holding the tail
    log2 = ev_mod.EventLog()
    log2.configure(str(tmp_path / "e2.jsonl"), rotate_bytes=512)
    for i in range(64):
        log2.emit("tick", i=i, pad="x" * 64)
    log2.close()
    assert len(ev_mod.rotated_segments(str(tmp_path / "e2.jsonl"))) == 1
    tail = [r["i"] for r in ev_mod.read_events(str(tmp_path / "e2.jsonl"))]
    assert tail == list(range(tail[0], 64)) and len(tail) >= 2


def test_emit_noop_when_unconfigured():
    log = ev_mod.EventLog()
    assert log.emit("nope") is False


# -- TrainStep instrumentation ----------------------------------------------

def _tiny_train_step():
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    net = nn.Dense(4, in_units=3)
    net.initialize()
    _ = net(nd.ones((2, 3)))
    return TrainStep(net, lambda out, y: (out - y) ** 2,
                     opt.create("sgd", learning_rate=0.1))


def test_recompile_counter_increments_once_on_shape_change(tmp_path):
    obs.enable(str(tmp_path))
    rc = obs.counter("train_recompiles_total")
    step = _tiny_train_step()
    before = rc.total()
    step(nd.ones((2, 3)), nd.ones((2, 4)))
    step(nd.ones((2, 3)), nd.ones((2, 4)))
    assert rc.total() == before + 1  # first lowering, steady state after
    step(nd.ones((6, 3)), nd.ones((6, 4)))  # batch-shape change
    assert rc.total() == before + 2
    assert rc.value(reason="shape") >= 1
    step(nd.ones((6, 3)), nd.ones((6, 4)))  # same shape: cached
    assert rc.total() == before + 2
    obs.shutdown()
    recs = [e for e in obs.read_events(str(tmp_path)) if e["event"] == "recompile"]
    assert len(recs) == 2
    assert recs[1]["reason"] == "shape" and recs[1]["shapes"][0] == [6, 3]


def test_train_step_metrics_and_events(tmp_path):
    obs.enable(str(tmp_path))
    step = _tiny_train_step()
    steps_c = obs.counter("train_steps_total")
    before = steps_c.value(loop="train_step")
    step(nd.ones((2, 3)), nd.ones((2, 4)))
    step(nd.ones((2, 3)), nd.ones((2, 4)))
    assert steps_c.value(loop="train_step") == before + 2
    assert obs.REGISTRY.get("train_step_seconds").total_count() >= 2
    assert obs.gauge("train_loss").value() is not None
    assert obs.gauge("train_grad_norm").value() is not None
    obs.shutdown()
    recs = [e for e in obs.read_events(str(tmp_path)) if e["event"] == "train_step"]
    assert len(recs) == 2
    for r in recs:
        assert r["loss"] is not None and r["grad_norm"] is not None
        assert r["samples"] == 2 and r["tokens"] == 6
        assert r["step_seconds"] > 0


def test_telemetry_off_records_nothing(tmp_path):
    # off by default in the suite: the step loop must not touch step metrics
    h = obs.REGISTRY.get("train_step_seconds")
    before = h.total_count() if h else 0
    step = _tiny_train_step()
    step(nd.ones((2, 3)), nd.ones((2, 4)))
    h = obs.REGISTRY.get("train_step_seconds")
    assert (h.total_count() if h else 0) == before
    assert not ev_mod.LOG.configured


# -- KVStore instrumentation -------------------------------------------------

def test_kv_psum_metrics_single_process(tmp_path):
    from mxnet_tpu.resilience import faults

    obs.enable(str(tmp_path))
    lat = obs.REGISTRY.histogram("kv_psum_seconds")
    byt = obs.counter("kv_psum_bytes_total")
    c0, b0 = lat.total_count(), byt.value(op="psum")
    # arming any site forces the instrumented DCN path at process_count==1
    faults.arm("obs.test.dummy", on=10 ** 9)
    try:
        store = mx.kv.create("dist_sync")
        store.init("w", nd.zeros((8,)))
        store.push("w", nd.ones((8,)))
        out = nd.zeros((8,))
        store.pull("w", out=out)
    finally:
        faults.disarm("obs.test.dummy")
    assert lat.total_count() == c0 + 1
    assert byt.value(op="psum") == b0 + 8 * 4  # 8 x f32
    assert obs.counter("kv_push_total").total() >= 1
    assert obs.counter("kv_pull_total").total() >= 1


def test_kv_psum_batch_dtype_buckets(tmp_path):
    from mxnet_tpu.resilience import faults

    obs.enable(str(tmp_path))
    buckets = obs.counter("kv_psum_dtype_buckets_total")
    f32_0 = buckets.value(dtype="float32")
    i32_0 = buckets.value(dtype="int32")
    faults.arm("obs.test.dummy", on=10 ** 9)
    try:
        store = mx.kv.create("dist_sync")
        vals = [nd.ones((4,)), nd.ones((2, 2)),
                nd.array(np.arange(3, dtype=np.int32), dtype="int32")]
        store.init(["a", "b", "c"], [v.copy() for v in vals])
        store.pushpull_batch(["a", "b", "c"], vals)
    finally:
        faults.disarm("obs.test.dummy")
    # two f32 leaves share one transfer bucket entry count; the int32 leaf
    # keeps its own dtype (no f32 funnel)
    assert buckets.value(dtype="float32") == f32_0 + 2
    assert buckets.value(dtype="int32") == i32_0 + 1
    assert obs.counter("kv_psum_bytes_total").value(op="psum_batch") > 0


# -- retry bridge ------------------------------------------------------------

def test_retry_counters_match_attempt_log():
    from mxnet_tpu.resilience import RetryPolicy, faults, retry

    site = "obs.test.retry"
    retry.clear_log(site)
    c = obs.counter("retry_attempts_total")
    ok0, fail0 = c.value(site=site, ok="true"), c.value(site=site, ok="false")
    with faults.inject(site, every=1, times=2):
        retry.retry_call(lambda: faults.fire(site), site=site,
                         policy=RetryPolicy(max_attempts=5, base_delay=0.001))
    log = retry.attempt_log(site)
    assert len(log) == 3  # 2 injected failures + 1 success
    assert c.value(site=site, ok="false") - fail0 == 2
    assert c.value(site=site, ok="true") - ok0 == 1
    assert (c.value(site=site, ok="true") + c.value(site=site, ok="false")
            - ok0 - fail0) == len(log)


@pytest.mark.chaos
def test_retry_counters_under_env_spec(tmp_path, monkeypatch):
    """MXNET_TPU_FAULTS-style arming (the make chaos path) also lands in the
    registry: counters, attempt log, and the report tool agree."""
    from mxnet_tpu.resilience import faults, retry

    retry.clear_log("ckpt.save")
    c = obs.counter("retry_attempts_total")
    before = (c.value(site="ckpt.save", ok="true")
              + c.value(site="ckpt.save", ok="false"))
    faults.load_spec("ckpt.save:on=1")
    try:
        from mxnet_tpu.checkpoint import save_train_state

        save_train_state(str(tmp_path), 1, {"w": np.ones((2,))}, {})
    finally:
        faults.disarm("ckpt.save")
    log = retry.attempt_log("ckpt.save")
    after = (c.value(site="ckpt.save", ok="true")
             + c.value(site="ckpt.save", ok="false"))
    assert after - before == len(log) >= 2


# -- DataLoader instrumentation ----------------------------------------------

def test_dataloader_wait_compute_metrics(tmp_path):
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    obs.enable(str(tmp_path))
    wait = obs.REGISTRY.histogram("data_batch_wait_seconds")
    w0 = wait.total_count()
    ds = ArrayDataset(nd.array(np.random.rand(32, 4).astype(np.float32)),
                      nd.array(np.arange(32, dtype=np.float32)))
    loader = DataLoader(ds, batch_size=8)
    n = sum(1 for _ in loader)
    assert n == 4
    assert wait.total_count() == w0 + 4
    comp = obs.REGISTRY.get("data_compute_seconds")
    assert comp is not None and comp.total_count() >= 1


# -- checkpoint instrumentation ----------------------------------------------

def test_checkpoint_metrics_and_events(tmp_path):
    from mxnet_tpu.checkpoint import load_train_state, save_train_state

    obs.enable(str(tmp_path / "tele"))
    saves = obs.counter("ckpt_saves_total")
    loads = obs.counter("ckpt_loads_total")
    s0, l0 = saves.total(), loads.total()
    params = {"w": np.ones((4, 4), np.float32)}
    opt_state = {"m": np.zeros((4, 4), np.float32)}
    path = save_train_state(str(tmp_path / "ck"), 3, params, opt_state)
    load_train_state(path, like=(params, opt_state))
    assert saves.total() == s0 + 1 and loads.total() == l0 + 1
    assert obs.counter("ckpt_bytes_total").value(op="save") > 0
    assert obs.REGISTRY.get("ckpt_save_seconds").total_count() >= 1
    assert obs.REGISTRY.get("ckpt_verify_seconds").total_count() >= 1
    obs.shutdown()
    kinds = {e["event"] for e in obs.read_events(str(tmp_path / "tele"))}
    assert {"checkpoint_save", "checkpoint_restore"} <= kinds


# -- wiring: Monitor / Trainer / Speedometer / span --------------------------

def test_monitor_wired_into_trainer(tmp_path):
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    obs.enable(str(tmp_path))
    net = nn.Dense(3, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    mon = mx.Monitor(interval=1).install(net, trainer=trainer)
    x = nd.ones((4, 2))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(4)  # tic/toc run inside step now — no manual driving
    assert mon.step == 1
    obs.shutdown()
    stats = [e for e in obs.read_events(str(tmp_path))
             if e["event"] == "monitor_stat"]
    names = {e["tensor"] for e in stats}
    assert any("weight" in n for n in names)
    assert any(n.endswith("_grad") for n in names)


def test_trainer_step_metrics_feed_speedometer(tmp_path):
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.gluon import nn

    obs.enable(str(tmp_path))
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    sp = Speedometer(batch_size=4, frequent=1)
    assert sp._registry_speed() is None  # primes the baseline
    for _ in range(2):
        x = nd.ones((4, 2))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(4)
    speed = sp._registry_speed()
    assert speed is not None and speed > 0  # registry path, not local clock
    assert obs.counter("train_samples_total").value(loop="trainer") >= 8


def test_span_times_and_labels(tmp_path):
    obs.enable(str(tmp_path))
    h = obs.REGISTRY.histogram("span_seconds")
    before = h.total_count()
    with obs.span("unit_region", phase="t"):
        nd.ones((4, 4)).sum().asnumpy()
    assert h.total_count() == before + 1
    s = h.stats(span="unit_region", phase="t")
    assert s is not None and s["count"] >= 1 and s["sum"] > 0
    # disabled -> no-op
    obs.disable()
    with obs.span("unit_region", phase="t"):
        pass
    assert h.stats(span="unit_region", phase="t")["count"] == s["count"]


# -- report tool -------------------------------------------------------------

def test_obs_report_renders_summary(tmp_path):
    import importlib.util

    obs.enable(str(tmp_path))
    step = _tiny_train_step()
    step(nd.ones((2, 3)), nd.ones((2, 4)))
    obs.shutdown()
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "obs_report.py"))
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)
    summary = obs_report.summarize(str(tmp_path))
    assert summary is not None
    assert summary["train"]["steps"] >= 1
    text = obs_report.render(summary)
    assert "telemetry report" in text and "training" in text
    assert obs_report.summarize(str(tmp_path / "empty_nonexistent")) is None
