"""Attention operators.

Re-designs the reference's fused transformer kernels
(``src/operator/contrib/transformer.cc``/``.cu`` —
``_contrib_interleaved_matmul_selfatt_qk`` / ``_valatt`` /
``_contrib_interleaved_matmul_encdec_*`` / ``_contrib_div_sqrt_dim``, the ops
GluonNLP BERT calls) for TPU:

  - the interleaved-matmul API is preserved exactly (projections stored
    interleaved as (T, B, H*3*Ch)) so GluonNLP-shaped model code runs;
  - the *blessed* path is ``multi_head_attention`` which dispatches to a
    Pallas flash-attention kernel on TPU (O(L) memory, MXU-tiled) and a
    jnp reference path elsewhere — see ``mxnet_tpu.ops.flash_attention``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..registry import register


@register("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], jnp.float32)).astype(data.dtype)


def _split_interleaved_qkv(qkv, heads):
    """(T, B, H*3*Ch) interleaved per head -> q, k, v each (B, H, T, Ch)."""
    t, b, hc3 = qkv.shape
    ch = hc3 // (heads * 3)
    x = qkv.reshape(t, b, heads, 3, ch)
    q, k, v = x[:, :, :, 0], x[:, :, :, 1], x[:, :, :, 2]
    # (T,B,H,Ch) -> (B,H,T,Ch)
    to_bhtc = lambda a: a.transpose(1, 2, 0, 3)
    return to_bhtc(q), to_bhtc(k), to_bhtc(v)


@register("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(qkv, heads=1):
    """scores = scaled Q @ K^T, output (B*H, T, T) like the reference."""
    from ..contrib.amp import cast_inputs

    orig_dtype = qkv.dtype
    (qkv,) = cast_inputs(qkv)
    q, k, v = _split_interleaved_qkv(qkv, int(heads))
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32)).astype(q.dtype)
    scores = jnp.einsum("bhqc,bhkc->bhqk", q * scale, k)
    b, h, t, _ = scores.shape
    # restore the caller's dtype: downstream mask arithmetic / softmax on the
    # scores must not change precision because a global AMP flag flipped
    return scores.reshape(b * h, t, t).astype(orig_dtype)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(qkv, att, heads=1):
    """out = att @ V, returned (T, B, H*Ch) like the reference."""
    q, k, v = _split_interleaved_qkv(qkv, int(heads))
    b, h, t, ch = v.shape
    att = att.reshape(b, h, t, t)
    out = jnp.einsum("bhqk,bhkc->bhqc", att, v)
    return out.transpose(2, 0, 1, 3).reshape(t, b, h * ch)


@register("_contrib_interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(q_proj, kv_proj, heads=1):
    tq, b, hc = q_proj.shape
    ch = hc // int(heads)
    q = q_proj.reshape(tq, b, int(heads), ch).transpose(1, 2, 0, 3)
    tk = kv_proj.shape[0]
    kv = kv_proj.reshape(tk, b, int(heads), 2, ch)
    k = kv[:, :, :, 0].transpose(1, 2, 0, 3)
    scale = 1.0 / jnp.sqrt(jnp.asarray(ch, jnp.float32)).astype(q.dtype)
    scores = jnp.einsum("bhqc,bhkc->bhqk", q * scale, k)
    return scores.reshape(b * int(heads), tq, tk)


@register("_contrib_interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(kv_proj, att, heads=1):
    tk, b, hc2 = kv_proj.shape
    ch = hc2 // (2 * int(heads))
    kv = kv_proj.reshape(tk, b, int(heads), 2, ch)
    v = kv[:, :, :, 1].transpose(1, 2, 0, 3)  # (B,H,Tk,Ch)
    h = int(heads)
    tq = att.shape[1]
    att = att.reshape(b, h, tq, tk)
    out = jnp.einsum("bhqk,bhkc->bhqc", att, v)
    return out.transpose(2, 0, 1, 3).reshape(tq, b, h * ch)


# --------------------------------------------------------------------------
# blessed fused attention entry point
# --------------------------------------------------------------------------
def _reference_mha(q, k, v, mask=None, causal=False):
    """jnp O(L^2) reference attention; q,k,v (B,H,T,Ch)."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    scores = jnp.einsum("bhqc,bhkc->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((t_q, t_k), bool), t_k - t_q)
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkc->bhqc", att, v)


@register("multi_head_attention", aliases=("_contrib_multi_head_attention",))
def multi_head_attention(q, k, v, mask=None, causal=False, use_flash="auto"):
    """Fused scaled-dot-product attention over (B, H, T, Ch) tensors.

    ``use_flash='auto'`` picks the Pallas flash kernel on TPU backends when
    shapes are tile-friendly, otherwise the XLA einsum path.
    """
    from . import flash_attention as fa
    from ..contrib.amp import cast_inputs

    orig_dtype = q.dtype
    q, k, v = cast_inputs(q, k, v)  # AMP: score/context matmuls on the MXU
    if use_flash == "auto":
        use_flash = fa.flash_supported(q, k, v, mask)
    if use_flash:
        out = fa.flash_attention(q, k, v, mask=mask, causal=causal)
    else:
        out = _reference_mha(q, k, v, mask=mask, causal=causal)
    return out.astype(orig_dtype)
