"""Fused softmax-cross-entropy Pallas kernel (sparse labels, custom VJP).

Reference analog: ``src/operator/nn/softmax-inl.h`` +
``SoftmaxCrossEntropyLoss`` — the training loss of every LM head in the
model zoo. The unfused gluon composition (``log_softmax`` → ``pick``)
materializes the full (N, C) log-probability tensor just to read one
column per row; at LM-head widths (C = vocab) that is the largest
activation in the backward residual set. The kernel computes the per-row
loss ``logsumexp(x) - x[label]`` in one VMEM-resident pass over the
logits — the (N, C) intermediate never exists — and the custom VJP
recomputes ``softmax(x) - onehot`` from the saved *logits* (f32-stable,
fusion-friendly jnp, mirroring the flash-attention/layernorm design
split: Pallas forward, analytic jnp backward).

Gating mirrors ``pallas_layernorm``: opt-in knob (``fused_softmax_xent``
/ ``MXNET_TPU_FUSED_SOFTMAX_XENT``), TPU backend, lane-aligned class dim.
CPU CI exercises the same kernel (forward AND vjp) under
``interpret=True`` in the parity tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..registry import register
from .pallas_common import HAS_PLTPU as _HAS_PLTPU
from .pallas_common import LANES as _LANES
from .pallas_common import on_tpu as _on_tpu

_BLOCK_ROWS = 128
# class-dim cap: one (rows, C) f32 block + its exp copy must sit in VMEM
_MAX_C = 65536


def xent_kernel_supported(pred, axis=-1) -> bool:
    """Opt-in (``MXNET_TPU_FUSED_SOFTMAX_XENT=1``), hardware-only, and the
    class axis must be last, lane-aligned, and VMEM-bounded; the gluon
    loss falls back to the ``log_softmax``→``pick`` composition
    otherwise."""
    from .. import config as _config

    if not _config.get("fused_softmax_xent"):
        return False
    ax = axis % pred.ndim if pred.ndim else 0
    return (_HAS_PLTPU and _on_tpu() and pred.ndim >= 2
            and ax == pred.ndim - 1
            and pred.shape[-1] % _LANES == 0 and pred.shape[-1] <= _MAX_C
            and pred.dtype in (jnp.float32, jnp.bfloat16))


def _xent_kernel(x_ref, l_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)              # (rows, C) in VMEM once
    lbl = l_ref[...]                                 # (rows, 1) int32
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = m[:, 0] + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1))
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(col == lbl, x, 0.0), axis=-1)
    o_ref[...] = (lse - picked)[:, None]


def _xent_forward(x2, labels, interpret=False):
    n, c = x2.shape
    rows = max(8, min(_BLOCK_ROWS, n))
    n_pad = -(-n // rows) * rows
    if n_pad != n:
        # padded rows pick class 0 of zero logits -> finite garbage, sliced off
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
        labels = jnp.pad(labels, (0, n_pad - n))
    out = pl.pallas_call(
        _xent_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        grid=(n_pad // rows,),
        in_specs=[
            pl.BlockSpec((rows, c), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, labels.reshape(-1, 1).astype(jnp.int32))
    return out[:n, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _xent(x2, labels, interpret):
    return _xent_forward(x2, labels, interpret)


def _xent_vjp_fwd(x2, labels, interpret):
    # residuals are the raw logits — the (N, C) log-softmax intermediate of
    # the unfused composition is never materialized in either direction
    return _xent_forward(x2, labels, interpret), (x2, labels)


def _xent_vjp_bwd(interpret, res, g):
    x2, labels = res
    xf = x2.astype(jnp.float32)
    p = jax.nn.softmax(xf, axis=-1)
    onehot = jax.nn.one_hot(labels, x2.shape[-1], dtype=jnp.float32)
    dx = (p - onehot) * g[:, None].astype(jnp.float32)
    return dx.astype(x2.dtype), None


_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


@register("softmax_cross_entropy_fused")
def softmax_cross_entropy_fused(pred, label, interpret=None):
    """Per-row sparse-label cross entropy ``logsumexp(pred) - pred[label]``
    over the last axis; leading shape preserved (f32 output, the dtype the
    unfused f32 ``log_softmax`` path produces)."""
    if interpret is None:
        interpret = not _on_tpu()
    c = pred.shape[-1]
    lead = pred.shape[:-1]
    x2 = pred.reshape(-1, c)
    lbl = jnp.asarray(label, jnp.int32).reshape(-1)
    return _xent(x2, lbl, bool(interpret)).reshape(lead)
