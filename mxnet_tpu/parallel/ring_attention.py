"""Ring attention: context parallelism over a ``sp`` mesh axis.

New capability (absent in the reference — SURVEY §5.7): sequences sharded
across chips, K/V blocks rotated around the ring with ``lax.ppermute`` while
each chip accumulates online-softmax partials — comm overlaps compute over
ICI. Published pattern: Ring Attention (Liu et al.) / blockwise attention.

Implementation: ``shard_map`` over the sequence axis; per-shard compute uses
the same f32 online-softmax update as the Pallas flash kernel; differentiable
end-to-end (jax AD through shard_map/ppermute gives the rotating backward).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention"]


def _block_attn(q, k, v, m_prev, l_prev, acc, scale, mask_val=None):
    """One online-softmax accumulation step; q (B,H,Tq,D), k/v (B,H,Tk,D)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if mask_val is not None:
        s = jnp.where(mask_val, s, -jnp.inf)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    m_safe = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isinf(s), 0.0, p)
    corr = jnp.where(jnp.isinf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
    l_new = corr * l_prev + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False):
    """Attention over sequence-sharded q/k/v (B, H, T_global, D).

    Each chip holds T_global / sp_size of the sequence; K/V rotate around the
    ring. Returns the sequence-sharded output with the same sharding as q.
    """
    sp = mesh.shape[axis]
    scale = 1.0 / (q.shape[-1] ** 0.5)

    def per_shard(q_blk, k_blk, v_blk):
        idx = lax.axis_index(axis)
        B, H, Tq, D = q_blk.shape
        Tk = k_blk.shape[2]
        if causal and Tq != Tk:
            # the per-step full-skip below (src_idx > idx) is only sound
            # when shards partition one shared sequence axis evenly
            raise ValueError(
                f"causal ring attention requires equal q/kv shards, got "
                f"Tq={Tq} Tk={Tk}")
        m = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
        l = jnp.zeros((B, H, Tq), jnp.float32)
        acc = jnp.zeros((B, H, Tq, D), jnp.float32)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def body(step, carry):
            m, l, acc, k_cur, v_cur = carry
            src_idx = (idx - step) % sp  # which shard's K/V we now hold
            if causal:
                # ring steps where the visiting K/V shard lies entirely in
                # the future (src_idx > idx) are fully masked — branch them
                # out instead of computing-then-masking, saving ~half the
                # attention FLOPs across the ring on average. The mask is
                # built INSIDE the branch: cond hoists closed-over values,
                # so constructing it outside would materialize the (Tq, Tk)
                # iotas on skipped steps too.
                def _compute(args):
                    m, l, acc = args
                    q_pos = idx * Tq + lax.broadcasted_iota(
                        jnp.int32, (Tq, Tk), 0)
                    k_pos = src_idx * Tk + lax.broadcasted_iota(
                        jnp.int32, (Tq, Tk), 1)
                    mask = (q_pos >= k_pos)[None, None]
                    return _block_attn(q_blk, k_cur, v_cur, m, l, acc, scale,
                                       mask)

                m, l, acc = lax.cond(src_idx <= idx, _compute,
                                     lambda args: args, (m, l, acc))
            else:
                m, l, acc = _block_attn(q_blk, k_cur, v_cur, m, l, acc, scale,
                                        None)
            # rotate K/V to the next chip (overlaps with next step's compute;
            # the collective stays OUTSIDE the cond — every device must
            # participate in every rotation)
            k_nxt = lax.ppermute(k_cur, axis, perm)
            v_nxt = lax.ppermute(v_cur, axis, perm)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc, _, _ = lax.fori_loop(0, sp, body, (m, l, acc, k_blk, v_blk),
                                        unroll=True)
        l = jnp.where(l == 0.0, 1.0, l)
        return (acc / l[..., None]).astype(q_blk.dtype)

    spec = P(None, None, axis, None)
    try:
        fn = shard_map(per_shard, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # jax < 0.6 spells the replication check 'check_rep'
        fn = shard_map(per_shard, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    return fn(q, k, v)
