"""Measured-profiling gate (ISSUE 14, docs/OBSERVABILITY.md "Measured
profiling"): `make profcheck` as a test — real traces of the shared
golden families produce non-empty op timelines, the calibration table is
emitted against the committed sched goldens, measured overlap sits next
to the predicted fraction, and the --inject-empty-trace failure hook
fails the build.

Runs tools/profcheck.py in-process (importlib) so the memoized family
builders (tools/families.py) are shared with the other gate tests in
this process.
"""
import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_mod", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def profcheck():
    return _load("profcheck")


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    # the gate enables telemetry process-wide; later tests in this
    # session must not inherit it
    from mxnet_tpu import observability as obs

    yield
    obs.disable()


def _verdict(capsys):
    out = capsys.readouterr().out
    row, _ = json.JSONDecoder().raw_decode(out, out.index("{"))
    return row, out


def test_gate_passes_and_reports_measured_next_to_predicted(profcheck,
                                                            capsys):
    """ISSUE 14 acceptance: non-empty measured op timeline for >= 2
    shared golden families, a calibration table with both sides
    populated, and measured overlap reported 1:1 next to
    ScheduleReport.overlap_fraction (zero allowed on CPU)."""
    rc = profcheck.main([])
    row, _ = _verdict(capsys)
    assert rc == 0 and row["ok"], row.get("failures")
    assert set(row["families"]) == {"step_fsdp", "decode"}
    for name, fam in row["families"].items():
        assert fam["n_op_rows"] > 0, name
        assert fam["measured_step_seconds"] > 0, name
        assert 0.0 <= fam["overlap_measured"] <= 1.0
        assert fam["overlap_predicted"] is not None
        cal = fam["calibration"]
        assert any(r["predicted_seconds"] > 0 and r["measured_seconds"] > 0
                   for r in cal["rows"]), name
    # the predicted side is anchored on the committed sched goldens
    assert row["families"]["step_fsdp"]["golden_critical_path_seconds"] > 0
    assert row["captures_total"] >= 2


def test_injected_empty_trace_fails_gate(profcheck, capsys):
    """The failure path stays tested: an empty trace (capture or parser
    broken) must fail the build with the op-timeline check."""
    rc = profcheck.main(["--inject-empty-trace"])
    row, out = _verdict(capsys)
    assert rc == 1 and not row["ok"]
    assert any("EMPTY" in f for f in row["failures"]), row["failures"]
