"""gluon.utils (reference: ``python/mxnet/gluon/utils.py``).

``split_and_load`` is kept for script compat but on TPU the idiomatic path is
a *sharded global array* (one jax.Array laid out across the mesh), so it
returns a single global-device view when given a mesh-aware context list.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(f"batch size {size} not divisible by {num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(i * step, (i + 1) * step if i < num_slice - 1 else size)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    return [s.as_in_context(c) for s, c in zip(split_data(data, len(ctx_list), batch_axis, even_split), ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a._data.astype(jnp.float32))) for a in arrays))
    scale = jnp.minimum(max_norm / (total + 1e-8), 1.0)
    for a in arrays:
        a._data = (a._data.astype(jnp.float32) * scale).astype(a._data.dtype)
    return float(total)


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5, verify_ssl=True):
    raise RuntimeError("no network egress in this environment; place files locally")
