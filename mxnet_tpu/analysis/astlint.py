"""jit-hazard linter: custom AST rules over the package source.

``compileall`` (the Makefile's old "lint floor") only proves the source
parses. The hazards that actually burn this codebase are *semantic*:
a ``float()`` host sync hiding inside a compiled hot path, a Python
``if`` on a traced value, wall-clock or global-RNG nondeterminism in op
code, a mutable default argument, an unlocked mutation of a process-global
registry that DataLoader worker threads also touch. Each is an AST
pattern, so each is a rule here.

Rules (docs/ANALYSIS.md has the full catalog with examples):

  JH001 host-sync-in-hot-path   ``.item()``/``.asnumpy()``/``.tolist()``,
                                ``float()/int()/bool()``, ``np.asarray``/
                                ``np.array``, ``jax.device_get`` inside a
                                compiled hot path.
  JH002 traced-branch           Python ``if``/``while`` testing a traced
                                function argument inside a hot path
                                (trace-time branching; use ``lax.cond``/
                                ``jnp.where``).
  JH003 nondeterminism          ``time.time``/``datetime.now``/global
                                ``np.random.*``/stdlib ``random.*`` in op
                                modules or hot paths.
  JH004 mutable-default-arg     ``def f(x=[], y={}, z=set())``.
  JH005 unlocked-global-mutation  mutating a module-global dict/list/set
                                outside any ``with <lock>:`` block.
  JH007 traced-constant-capture  a jitted/scanned closure reading a name
                                bound to a host ``np.ndarray`` (or a
                                large literal) — traced into the program
                                as a baked constant: silent resident
                                bytes and a recompile when it changes.
  JH008 sync-per-dispatch       a driver loop calling a jitted/compiled
                                callable and immediately materializing
                                its result (``block_until_ready``,
                                ``.item()``, ``float()``, ``np.asarray``,
                                ``device_get``) inside the loop body —
                                the host blocks on every step, so async
                                dispatch pipelining is defeated.
  JH006 unknown-mesh-axis       a ``PartitionSpec``/``P``/``named_sharding``
                                call site passing an axis-name string
                                literal outside the MeshConfig vocabulary
                                (dp/fsdp/tp/sp/pp/ep) — GSPMD silently
                                replicates the tensor on a typo'd axis.

**Hot paths** are found two ways: structurally — any function passed to
(or decorated with) ``jax.jit``/``pmap``/``checkpoint``/``shard_map``,
including everything lexically nested inside it — and by registration
(:data:`EXTRA_HOT_PATHS` names the helpers those jitted closures call,
e.g. ``TrainStep._loss_of``, which tracing reaches interprocedurally).

**Suppressions** are per-rule and inline::

    x = float(y)  # lint: disable=JH001  -- TTFT sync point, documented

on the flagged line (or the line above). A comment on a ``def`` line
suppresses the rule for the whole function body. File-level:
``# lint: disable-file=JH005`` anywhere in the file. Suppressing takes a
rule list (``disable=JH001,JH004``) or ``all``.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["LintRule", "Violation", "lint_source", "lint_file",
           "lint_paths", "list_rules", "RULES", "EXTRA_HOT_PATHS"]


RULES: Dict[str, str] = {
    "JH001": "host-sync-in-hot-path: host transfer/sync call inside a "
             "compiled hot path (device round-trip per step)",
    "JH002": "traced-branch: Python if/while on a traced argument inside a "
             "compiled hot path (trace-time constant or ConcretizationError"
             " — use lax.cond/jnp.where)",
    "JH003": "nondeterminism: wall clock or global RNG in op/compiled code "
             "(breaks replay, fingerprints and the compile cache)",
    "JH004": "mutable-default-arg: shared mutable state across calls",
    "JH005": "unlocked-global-mutation: module-global registry mutated "
             "outside a lock (loader/dispatch threads also import/mutate)",
    "JH006": "unknown-mesh-axis: PartitionSpec/named_sharding axis-name "
             "literal not in the MeshConfig vocabulary (dp/fsdp/tp/sp/pp/"
             "ep) — a typo'd axis name silently replicates the tensor",
    "JH007": "traced-constant-capture: a jitted/scanned function closes "
             "over a host np.ndarray or large Python literal — it is "
             "baked into the program as a constant (silent resident "
             "bytes, and any change recompiles); pass it as an argument",
    "JH008": "sync-per-dispatch: a driver loop calls a jitted/compiled "
             "callable and immediately materializes its result "
             "(block_until_ready/.item()/float()/np.asarray/device_get) "
             "in the same loop body — the host blocks on every step and "
             "async dispatch pipelining is defeated; keep results as "
             "device futures and materialize once after the loop",
}

#: the mesh-axis vocabulary (mirror of parallel.layout.AXES, the
#: declarative layout spec that owns it — kept literal so the linter
#: stays stdlib-only; tests/test_analysis.py pins the two in sync)
_MESH_AXES = frozenset({"dp", "fsdp", "tp", "sp", "pp", "ep"})

# JH006: call names that take PartitionSpec axis-name strings. `P` is the
# conventional PartitionSpec alias throughout the codebase; NamedSharding
# literals reach here via the nested P(...) call.
_SPEC_CALLS = frozenset({"PartitionSpec", "P", "named_sharding"})

#: helpers reached by tracing but not lexically inside a jitted closure —
#: registered hot paths, keyed by a path suffix. Extend when adding a new
#: compiled subsystem (docs/ANALYSIS.md "Registering hot paths").
EXTRA_HOT_PATHS: Dict[str, Tuple[str, ...]] = {
    "parallel/train_step.py": (
        "TrainStep._loss_of", "TrainStep._grad_fn", "TrainStep._amp_cast",
        "TrainStep._apply_update", "TrainStep._scaled_update",
        "TrainStep._next_amp_state", "TrainStep._finite_all",
    ),
    "inference/engine.py": (
        "GenerationEngine._prefill_fn", "GenerationEngine._decode_fn",
        "GenerationEngine._sample",
    ),
    # step-boundary probes: called from inside the training loop every
    # step, so host-sync/branch/determinism hazards apply even though
    # nothing here is jit-traced
    "resilience/elastic.py": (
        "HeartbeatMonitor.check", "HeartbeatMonitor.stale_peers",
        "HeartbeatMonitor.beat", "ElasticContext.check",
    ),
    # fleet telemetry snapshot writer: maybe_snapshot runs at every step
    # boundary of an elastic run (throttled, but the gate itself is hot);
    # snapshot/_write also fire from the cadence thread concurrent with
    # training
    "observability/fleet.py": (
        "FleetSnapshotter.maybe_snapshot", "FleetSnapshotter.snapshot",
        "FleetSnapshotter._write", "FleetSnapshotter._copy_events",
        "FleetSnapshotter._append_range",
    ),
    # measured profiling's step-boundary probe: step_capture_begin /
    # begin_if_due run once per training step while armed (the trace
    # start/stop paths themselves are rare and excluded)
    "observability/profiling.py": (
        "step_capture_begin", "CaptureController.begin_if_due",
        "CaptureController._consume_request",
    ),
    # request-tracing emission probes: span() buffers on every serving
    # dispatch round and finish()/decide() run per terminal request —
    # hot-path rules hold them to the injected clock (no wall clock, no
    # global RNG; the sampling hash is deterministic by construction)
    "observability/tracing.py": (
        "Tracer.span", "Tracer.finish", "TailSampler.decide",
    ),
}

# function names that wrap a python callable into a compiled/traced one
_JIT_WRAPPERS = frozenset({
    "jit", "pjit", "pmap", "checkpoint", "remat", "shard_map", "vmap",
    "grad", "value_and_grad", "custom_vjp", "custom_jvp", "scan",
    "while_loop", "fori_loop", "cond", "switch",
})

# JH007: numpy constructors that materialize a HOST array — a name bound
# to one of these and read inside a jitted closure is baked into the
# program as a constant
_NP_ARRAY_MAKERS = frozenset({
    "array", "asarray", "zeros", "ones", "arange", "full", "eye",
    "linspace", "empty", "identity", "tri", "ascontiguousarray",
})
# JH007: a literal list/tuple/dict this big folded into a traced program
# is a constant worth flagging too
_LARGE_LITERAL_ELEMS = 32

# JH001: attribute calls that synchronize/copy to host
_SYNC_ATTRS = frozenset({"item", "asnumpy", "tolist", "__array__"})
# JH008: jit-wrapper leaves whose call result is a compiled dispatchable
# (vmap/grad et al. stay out: calling them returns a transform, and the
# hazard is the per-step dispatch of a COMPILED callable)
_DISPATCH_WRAPPERS = frozenset({"jit", "pjit", "pmap"})
# JH008: attribute calls that force the dispatched result on host (the
# sync attrs plus jax's explicit blocking call)
_JH008_SYNC_ATTRS = _SYNC_ATTRS | {"block_until_ready"}
# JH001: numpy namespace calls that materialize on host
_NP_HOST_FNS = frozenset({"asarray", "array", "asnumpy", "ascontiguousarray"})
_BUILTIN_SYNCS = frozenset({"float", "int", "bool"})

# JH003: nondeterminism sources
_TIME_FNS = frozenset({"time", "time_ns", "monotonic", "perf_counter",
                       "perf_counter_ns", "monotonic_ns"})
_NP_RANDOM_FNS = frozenset({
    "rand", "randn", "randint", "random", "random_sample", "sample",
    "normal", "uniform", "choice", "shuffle", "permutation", "seed",
    "standard_normal", "beta", "binomial", "poisson", "exponential",
})
_MUTATING_METHODS = frozenset({
    "update", "append", "add", "pop", "popitem", "clear", "extend",
    "remove", "discard", "insert", "setdefault", "__setitem__",
})

_DISABLE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,\s]+|all)")
_DISABLE_FILE = re.compile(r"#\s*lint:\s*disable-file=([A-Za-z0-9_,\s]+|all)")


@dataclasses.dataclass
class Violation:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclasses.dataclass(frozen=True)
class LintRule:
    rule_id: str
    summary: str


def list_rules() -> List[LintRule]:
    return [LintRule(k, v) for k, v in sorted(RULES.items())]


# -- suppression parsing -----------------------------------------------------
def _suppressions(source: str):
    """(line -> set of rules disabled on that line, file-wide set).

    Directives are honored only in real COMMENT tokens — a docstring or
    string literal that merely *documents* the syntax (this module's own
    docstring quotes ``disable-file``) must not activate it."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()

    def rules_of(spec: str) -> Set[str]:
        spec = spec.strip()
        if spec == "all":
            return set(RULES)
        return {r.strip().upper() for r in spec.split(",") if r.strip()}

    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE.search(tok.string)
            if m:
                per_line.setdefault(tok.start[0], set()).update(
                    rules_of(m.group(1)))
            m = _DISABLE_FILE.search(tok.string)
            if m:
                file_wide.update(rules_of(m.group(1)))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # source already parsed via ast before this runs, so tokenize
        # failures are effectively unreachable; fail open (no suppressions)
        pass
    return per_line, file_wide


# -- hot-path discovery ------------------------------------------------------
def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _callee_names(node: ast.AST, assignments: Dict[str, List[ast.AST]],
                  depth: int = 0) -> Set[str]:
    """Resolve an expression to the local function names it may denote:
    handles Name, `a if c else b`, and one level of local reassignment
    (`fn = step_scaled if scaling else step; jax.jit(fn)`)."""
    out: Set[str] = set()
    if depth > 4:
        return out
    if isinstance(node, ast.Name):
        out.add(node.id)
        for rhs in assignments.get(node.id, []):
            out |= _callee_names(rhs, assignments, depth + 1)
    elif isinstance(node, ast.IfExp):
        out |= _callee_names(node.body, assignments, depth + 1)
        out |= _callee_names(node.orelse, assignments, depth + 1)
    elif isinstance(node, ast.Attribute):
        # jax.jit(self._decode_fn) -> method name in the enclosing class
        out.add(node.attr)
    elif isinstance(node, ast.Call):
        # functools.partial(fn, ...) / jax.checkpoint(fn) wrappers
        if node.args:
            out |= _callee_names(node.args[0], assignments, depth + 1)
    return out


class _HotPathFinder(ast.NodeVisitor):
    """Mark FunctionDef nodes that become compiled/traced code: decorated
    with a jit wrapper, or referenced (possibly through a local alias or
    ``functools.partial``) as the function argument of one."""

    def __init__(self, extra_qualnames: Sequence[str]):
        self.extra = set(extra_qualnames)
        self.hot: Set[ast.AST] = set()
        self._scope: List[ast.AST] = []
        self._qualname: List[str] = []
        self._defs: Dict[str, List[ast.AST]] = {}  # name -> def nodes (any scope)
        self._assigns: Dict[str, List[ast.AST]] = {}

    # pass 1: collect defs/assigns + decorator-marked hot roots
    def visit_FunctionDef(self, node):
        qual = ".".join(self._qualname + [node.name])
        self._defs.setdefault(node.name, []).append(node)
        node._lint_qualname = qual
        if qual in self.extra or node.name in self.extra:
            self.hot.add(node)
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = _dotted(target)
            if name.rsplit(".", 1)[-1] in _JIT_WRAPPERS:
                self.hot.add(node)
        self._qualname.append(node.name)
        self.generic_visit(node)
        self._qualname.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._qualname.append(node.name)
        self.generic_visit(node)
        self._qualname.pop()

    def visit_Assign(self, node):
        for t in node.targets:
            if isinstance(t, ast.Name):
                self._assigns.setdefault(t.id, []).append(node.value)
        self.generic_visit(node)

    def visit_Call(self, node):
        name = _dotted(node.func).rsplit(".", 1)[-1]
        if name in _JIT_WRAPPERS and node.args:
            for fname in _callee_names(node.args[0], self._assigns):
                for d in self._defs.get(fname, []):
                    self.hot.add(d)
            # donate/static kwargs forms: jax.jit(fn=...) not used here
        self.generic_visit(node)

    def resolve(self, tree: ast.AST) -> Set[ast.AST]:
        """Two passes so a ``jax.jit(self._decode_fn)`` in ``__init__`` can
        mark a method defined later in the class."""
        self.visit(tree)
        self._scope = []
        self._qualname = []
        # second sweep: Call sites were visited before some defs existed
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _dotted(node.func).rsplit(".", 1)[-1]
                if name in _JIT_WRAPPERS and node.args:
                    for fname in _callee_names(node.args[0], self._assigns):
                        for d in self._defs.get(fname, []):
                            self.hot.add(d)
        return self.hot


# -- the rule engine ---------------------------------------------------------
class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str, is_op_module: bool,
                 hot_defs: Set[ast.AST]):
        self.path = path
        self.lines = source.splitlines()
        self.is_op_module = is_op_module
        self.hot_defs = hot_defs
        self.violations: List[Violation] = []
        self._fn_stack: List[ast.AST] = []   # enclosing FunctionDefs
        self._hot_stack: List[bool] = []
        self._hot_args: List[Set[str]] = []  # traced arg names per hot fn
        self._with_lock_depth = 0
        self._module_globals: Set[str] = set()
        self._suppressed_fn_lines: List[int] = []
        # JH007: names bound to host arrays / large literals, per scope —
        # module level plus one set per enclosing function (closures)
        self._module_host_consts: Set[str] = set()
        self._fn_host_consts: List[Set[str]] = []
        self._jh007_candidates: List[Set[str]] = []
        self._jh007_reported: Set[Tuple[int, str]] = set()
        # JH008: names bound to a compiled dispatchable (jax.jit(...)
        # assignment targets, file-scoped heuristic) and, per enclosing
        # driver loop, the names holding a dispatch's device result
        self._compiled_names: Set[str] = set()
        self._loop_results: List[Set[str]] = []

    # -- context helpers ---------------------------------------------------
    @property
    def in_hot(self) -> bool:
        return bool(self._hot_stack and self._hot_stack[-1])

    def _traced_args(self) -> Set[str]:
        return self._hot_args[-1] if self._hot_args else set()

    def report(self, rule: str, node: ast.AST, msg: str):
        self.violations.append(Violation(
            self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), rule, msg))

    # -- module prep --------------------------------------------------------
    def visit_Module(self, node):
        for stmt in node.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and stmt.value:
                targets = [stmt.target]
            if not targets:
                continue
            value = stmt.value
            if isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
                    isinstance(value, ast.Call)
                    and _dotted(value.func) in
                    ("dict", "list", "set", "collections.OrderedDict",
                     "collections.defaultdict", "OrderedDict",
                     "defaultdict")):
                for t in targets:
                    self._module_globals.add(t.id)
            if self._is_host_const_expr(value):
                for t in targets:
                    self._module_host_consts.add(t.id)
            else:
                # a later rebinding to a non-host expression (the common
                # `X = np.arange(n); X = jnp.asarray(X)` build-then-
                # transfer pattern) clears the hazard — the traced read
                # sees the device array
                for t in targets:
                    self._module_host_consts.discard(t.id)
        self.generic_visit(node)

    # -- JH007 helpers -------------------------------------------------------
    @staticmethod
    def _is_host_const_expr(value: ast.AST) -> bool:
        """An expression that materializes a HOST constant a trace would
        bake in: an ``np.*`` array constructor, or a literal container
        with >= _LARGE_LITERAL_ELEMS scalar elements."""
        if isinstance(value, ast.Call):
            dotted = _dotted(value.func)
            if dotted.startswith(("np.", "numpy.")) and \
                    dotted.rsplit(".", 1)[-1] in _NP_ARRAY_MAKERS:
                return True
            # method chains stay host arrays: np.arange(n).reshape(a, b)
            if isinstance(value.func, ast.Attribute):
                return _Linter._is_host_const_expr(value.func.value)
            return False
        if isinstance(value, (ast.List, ast.Tuple, ast.Dict)):
            n = sum(1 for x in ast.walk(value)
                    if isinstance(x, ast.Constant))
            return n >= _LARGE_LITERAL_ELEMS
        return False

    # -- function scope ------------------------------------------------------
    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        hot = (node in self.hot_defs) or self.in_hot
        self._fn_stack.append(node)
        self._hot_stack.append(hot)
        args = node.args
        names = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)} - {"self", "cls"}
        if args.vararg:
            names.add(args.vararg.arg)
        # nested hot fns see enclosing traced names too (closures)
        if hot:
            names |= self._traced_args()
        self._hot_args.append(names if hot else set())
        # JH007: names this hot closure could capture as traced constants
        # — module-level + enclosing-function host arrays, minus anything
        # the function itself binds (args or local stores shadow)
        if hot:
            local_stores = {n.id for n in ast.walk(node)
                            if isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Store)}
            cands = set(self._module_host_consts)
            for s in self._fn_host_consts:
                cands |= s
            self._jh007_candidates.append(cands - names - local_stores)
        else:
            self._jh007_candidates.append(set())
        self._fn_host_consts.append(set())
        # a def inside `with lock:` does NOT run under that lock — it runs
        # whenever the callback is invoked, on whatever thread — so JH005
        # must not inherit the enclosing lock depth into the body
        saved_lock_depth = self._with_lock_depth
        self._with_lock_depth = 0
        self.generic_visit(node)
        self._with_lock_depth = saved_lock_depth
        self._fn_host_consts.pop()
        self._jh007_candidates.pop()
        self._hot_args.pop()
        self._hot_stack.pop()
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_defaults(self, node):
        args = node.args
        for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
            bad = isinstance(d, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(d, ast.Call)
                and _dotted(d.func) in ("dict", "list", "set"))
            if bad:
                self.report("JH004", d,
                            f"mutable default argument in {node.name}()")

    # -- JH001 / JH003: calls ------------------------------------------------
    def visit_Call(self, node):
        dotted = _dotted(node.func)
        leaf = dotted.rsplit(".", 1)[-1]
        if self.in_hot:
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_ATTRS:
                self.report("JH001", node,
                            f".{node.func.attr}() forces a device->host "
                            "sync inside a compiled hot path")
            elif dotted in ("jax.device_get", "device_get"):
                self.report("JH001", node,
                            "jax.device_get inside a compiled hot path")
            elif dotted.startswith(("np.", "numpy.")) and \
                    leaf in _NP_HOST_FNS:
                self.report("JH001", node,
                            f"{dotted} materializes a host array inside a "
                            "compiled hot path (use jnp)")
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in _BUILTIN_SYNCS and node.args and \
                    self._mentions_traced(node.args[0]):
                # only when a traced argument feeds the cast: float(topk)
                # on a static op param is legal trace-time specialization
                self.report("JH001", node,
                            f"{node.func.id}() on a traced value is a host "
                            "sync inside a compiled hot path")
        # JH005 fires on the call wherever it sits — bare statement,
        # assignment RHS (`h = _REG.setdefault(k, [])`), return value —
        # the mutation happens regardless of what the result feeds
        self._visit_mutating_call(node)
        # JH008: a materializer on a dispatch result inside a driver loop
        self._check_jh008(node, dotted, leaf)
        # JH006: axis-name literals at PartitionSpec construction sites
        if leaf in _SPEC_CALLS:
            args = node.args
            if leaf == "named_sharding" and args:
                args = args[1:]  # named_sharding(mesh, *spec)
            for a in args:
                for lit in self._axis_literals(a):
                    if lit.value not in _MESH_AXES:
                        self.report(
                            "JH006", lit,
                            f"axis name {lit.value!r} is not a MeshConfig "
                            "axis (dp/fsdp/tp/sp/pp/ep) — GSPMD silently "
                            "replicates on an unknown axis")
        if self.in_hot or self.is_op_module:
            if dotted.startswith("time.") and leaf in _TIME_FNS:
                self.report("JH003", node,
                            f"{dotted}() wall clock in op/compiled code")
            elif leaf == "now" and "datetime" in dotted:
                self.report("JH003", node,
                            f"{dotted}() wall clock in op/compiled code")
            elif (dotted.startswith(("np.random.", "numpy.random."))
                  and leaf in _NP_RANDOM_FNS):
                self.report("JH003", node,
                            f"{dotted}() draws from the process-global "
                            "numpy RNG (pass an explicit key/RandomState)")
            elif dotted.startswith("random.") and dotted.count(".") == 1 \
                    and leaf != "RandomState":
                self.report("JH003", node,
                            f"stdlib {dotted}() global RNG in op/compiled "
                            "code")
        self.generic_visit(node)

    @staticmethod
    def _axis_literals(arg: ast.AST) -> List[ast.Constant]:
        """String-literal axis names in one PartitionSpec argument: a bare
        string, or strings inside a tuple/list entry (``P(("dp",
        "fsdp"))``). Non-literals (variables, ``*spec`` splats) are the
        caller's responsibility — only what is visibly a literal is
        checked."""
        out: List[ast.Constant] = []
        nodes = [arg]
        if isinstance(arg, (ast.Tuple, ast.List)):
            nodes = list(arg.elts)
        for n in nodes:
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                out.append(n)
        return out

    def _mentions_traced(self, expr: ast.AST) -> Optional[str]:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in self._traced_args():
                return n.id
        return None

    # -- JH002: trace-time branches ------------------------------------------
    def _test_on_traced(self, test: ast.AST) -> Optional[str]:
        """Traced name used in a branch test — minus the two *structural*
        comparison idioms that are static under tracing: ``x is (not)
        None`` (a tracer is never None) and ``name (not) in container``
        membership over a pytree container's keys."""
        structural: Set[int] = set()
        for n in ast.walk(test):
            if not isinstance(n, ast.Compare):
                continue
            ops = n.ops
            comparators = n.comparators
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in ops) and all(
                    isinstance(c, ast.Constant) and c.value is None
                    for c in comparators):
                structural.update(id(x) for x in ast.walk(n))
            elif all(isinstance(o, (ast.In, ast.NotIn)) for o in ops):
                for c in comparators:  # the container side only
                    structural.update(id(x) for x in ast.walk(c))
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in self._traced_args() \
                    and id(n) not in structural:
                return n.id
        return None

    def visit_If(self, node):
        if self.in_hot:
            name = self._test_on_traced(node.test)
            if name:
                self.report("JH002", node,
                            f"Python `if` on traced argument {name!r} "
                            "(trace-time constant; use lax.cond/jnp.where)")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.in_hot:
            name = self._test_on_traced(node.test)
            if name:
                self.report("JH002", node,
                            f"Python `while` on traced argument {name!r} "
                            "(use lax.while_loop)")
        self._loop_results.append(set())
        self.generic_visit(node)
        self._loop_results.pop()

    def visit_For(self, node):
        self._loop_results.append(set())
        self.generic_visit(node)
        self._loop_results.pop()

    visit_AsyncFor = visit_For

    # -- JH008: sync-per-dispatch driver loops -------------------------------
    def _is_compiled_callee(self, func_expr: ast.AST) -> bool:
        """Does this call expression dispatch a compiled program? A name/
        attribute assigned from ``jax.jit(...)`` (tracked file-wide), a
        leaf name containing ``jit`` (the ``self._decode_jit`` naming
        convention), or a direct ``jax.jit(f)(x)`` immediate call."""
        if isinstance(func_expr, ast.Call):
            inner = _dotted(func_expr.func).rsplit(".", 1)[-1]
            return inner in _DISPATCH_WRAPPERS
        leaf = _dotted(func_expr).rsplit(".", 1)[-1]
        if not leaf:
            return False
        return "jit" in leaf or leaf in self._compiled_names

    def _expr_is_dispatch(self, expr: ast.AST) -> bool:
        """Is ``expr`` (the materializer's operand) a compiled dispatch's
        result — a tracked result name from an enclosing loop, or the
        dispatch call itself (``float(step(x))``)?"""
        if isinstance(expr, ast.Call) and self._is_compiled_callee(expr.func):
            return True
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and any(n.id in frame for frame in self._loop_results):
                return True
        return False

    def _check_jh008(self, node: ast.Call, dotted: str, leaf: str):
        """Materializer applied to a dispatch result inside a driver
        loop: the host blocks on every step — async dispatch pipelining
        (the whole point of the compiled step/decode programs) is gone."""
        if not self._loop_results or self.in_hot:
            return
        hit = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _JH008_SYNC_ATTRS:
            if self._expr_is_dispatch(node.func.value):
                hit = f".{node.func.attr}()"
        elif dotted in ("jax.device_get", "device_get") and node.args and \
                self._expr_is_dispatch(node.args[0]):
            hit = "jax.device_get"
        elif isinstance(node.func, ast.Name) and \
                node.func.id in _BUILTIN_SYNCS and node.args and \
                self._expr_is_dispatch(node.args[0]):
            hit = f"{node.func.id}()"
        elif dotted.startswith(("np.", "numpy.")) and \
                leaf in _NP_HOST_FNS and node.args and \
                self._expr_is_dispatch(node.args[0]):
            hit = dotted
        if hit:
            self.report(
                "JH008", node,
                f"{hit} materializes a compiled dispatch's result inside "
                "the driver loop — the host blocks every iteration, "
                "defeating async dispatch pipelining; keep the device "
                "future and materialize once after the loop")

    # -- JH005: global registry mutation -------------------------------------
    def visit_With(self, node):
        is_lock = any(
            "lock" in _dotted(item.context_expr.func
                              if isinstance(item.context_expr, ast.Call)
                              else item.context_expr).lower()
            for item in node.items)
        self._with_lock_depth += 1 if is_lock else 0
        self.generic_visit(node)
        self._with_lock_depth -= 1 if is_lock else 0

    def _global_mutation(self, target_expr: ast.AST) -> Optional[str]:
        base = target_expr
        while isinstance(base, (ast.Subscript, ast.Attribute)):
            base = base.value
        if isinstance(base, ast.Name) and base.id in self._module_globals:
            return base.id
        return None

    def visit_Assign(self, node):
        # JH008 bookkeeping: `fn = jax.jit(...)` / `self._x_jit =
        # jax.jit(...)` marks a compiled dispatchable; inside a driver
        # loop, a call to one marks its result names as device futures
        if isinstance(node.value, ast.Call):
            vleaf = _dotted(node.value.func).rsplit(".", 1)[-1]
            if vleaf in _DISPATCH_WRAPPERS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._compiled_names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        self._compiled_names.add(t.attr)
            elif self._loop_results and not self.in_hot and \
                    self._is_compiled_callee(node.value.func):
                for t in node.targets:
                    elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                        else [t]
                    for e in elts:
                        if isinstance(e, ast.Name):
                            self._loop_results[-1].add(e.id)
        # JH007 bookkeeping: a host-array binding in THIS function is a
        # capture candidate for any closure defined after it; rebinding
        # the name to a non-host expression clears it again
        if self._fn_stack and self._fn_host_consts:
            host = self._is_host_const_expr(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if host:
                        self._fn_host_consts[-1].add(t.id)
                    else:
                        self._fn_host_consts[-1].discard(t.id)
        if self._fn_stack and not self._with_lock_depth:
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = self._global_mutation(t)
                    if name:
                        self.report("JH005", node,
                                    f"unlocked write to module-global "
                                    f"{name!r} (guard with a threading.Lock"
                                    " or suppress if import-time only)")
        self.generic_visit(node)

    # -- JH007: traced-constant capture --------------------------------------
    def visit_Name(self, node):
        if self.in_hot and isinstance(node.ctx, ast.Load) and \
                self._jh007_candidates and \
                node.id in self._jh007_candidates[-1]:
            key = (id(self._fn_stack[-1]), node.id)
            if key not in self._jh007_reported:
                self._jh007_reported.add(key)
                self.report(
                    "JH007", node,
                    f"host array {node.id!r} is closed over by a jitted/"
                    "scanned function and baked into the program as a "
                    "constant (resident bytes + a recompile when it "
                    "changes) — pass it as an argument or move it to jnp")
        self.generic_visit(node)

    def visit_Delete(self, node):
        if self._fn_stack and not self._with_lock_depth:
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = self._global_mutation(t)
                    if name:
                        self.report("JH005", node,
                                    f"unlocked del on module-global {name!r}")
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if self._fn_stack and not self._with_lock_depth and \
                isinstance(node.target, ast.Subscript):
            name = self._global_mutation(node.target)
            if name:
                self.report("JH005", node,
                            f"unlocked augmented write to module-global "
                            f"{name!r} (read-modify-write race)")
        self.generic_visit(node)

    def _visit_mutating_call(self, node):
        if not (self._fn_stack and not self._with_lock_depth):
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATING_METHODS:
            name = self._global_mutation(node.func.value)
            if name:
                self.report("JH005", node,
                            f"unlocked .{node.func.attr}() on module-global "
                            f"{name!r}")


def _function_spans(tree: ast.AST) -> List[Tuple[int, int, int]]:
    """(def-line, body-start, body-end) for suppression-on-def semantics."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            spans.append((node.lineno, node.lineno, end))
    return spans


def lint_source(source: str, path: str = "<string>") -> List[Violation]:
    """Lint one file's source; returns unsuppressed violations sorted by
    line. ``path`` decides op-module scope (JH003) and registered hot
    paths (JH001/2)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 0, 0, "JH000",
                          f"syntax error: {e.msg}")]
    posix = path.replace(os.sep, "/")
    extra: List[str] = []
    for suffix, quals in EXTRA_HOT_PATHS.items():
        if posix.endswith(suffix):
            extra.extend(quals)
    hot = _HotPathFinder(extra).resolve(tree)
    is_op_module = "/ops/" in posix or posix.endswith("random.py")
    linter = _Linter(path, source, is_op_module, hot)
    linter.visit(tree)

    per_line, file_wide = _suppressions(source)
    spans = _function_spans(tree)

    def suppressed(v: Violation) -> bool:
        if v.rule in file_wide:
            return True
        for line in (v.line, v.line - 1):
            if v.rule in per_line.get(line, set()):
                return True
        # a suppression on the `def` line covers the whole function body
        for def_line, lo, hi in spans:
            if lo <= v.line <= hi and v.rule in per_line.get(def_line, set()):
                return True
        return False

    return sorted((v for v in linter.violations if not suppressed(v)),
                  key=lambda v: (v.line, v.col, v.rule))


def lint_file(path: str) -> List[Violation]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_paths(paths: Iterable[str],
               exclude: Sequence[str] = ()) -> List[Violation]:
    """Lint every ``.py`` under each path (file or directory tree)."""
    out: List[Violation] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.extend(lint_file(path))
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs
                       if d != "__pycache__" and not d.startswith(".")]
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                if any(x in full.replace(os.sep, "/") for x in exclude):
                    continue
                out.extend(lint_file(full))
    return out
