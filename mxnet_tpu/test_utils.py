"""Test utilities (reference: ``python/mxnet/test_utils.py`` — the backbone
of the reference's entire python test suite, SURVEY §4).

Ports the *oracle machinery*: dtype-aware ``assert_almost_equal``, the
finite-difference gradient checker, and ``check_consistency`` recast as
CPU-vs-TPU / eager-vs-jit comparison (the reference compared CPU vs GPU
kernels; here the second backend is the compiled path).
"""
from __future__ import annotations

import numpy as np

from . import autograd
from .base import dtype_np
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = ["default_context", "assert_almost_equal", "almost_equal",
           "check_numeric_gradient", "check_consistency", "rand_ndarray",
           "same_array", "default_rtols"]

_DEFAULT_RTOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-4,
    np.dtype(np.float64): 1e-6,
}
_DEFAULT_ATOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-5,
    np.dtype(np.float64): 1e-7,
}


def default_rtols(dtype):
    d = np.dtype(dtype) if not str(dtype).startswith("bfloat") else np.dtype(np.float16)
    return _DEFAULT_RTOL.get(d, 1e-4), _DEFAULT_ATOL.get(d, 1e-5)


def list_gpus():
    """Reference ``test_utils.list_gpus``: CUDA device indices — always []
    on TPU (feature-gated reference tests then skip their GPU branches)."""
    return []


def list_tpus():
    import jax

    return list(range(len([d for d in jax.devices()
                           if d.platform == "tpu"])))


def default_context():
    return current_context()


def _np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _np(a), _np(b)
    rt, at = default_rtols(a.dtype)
    return np.allclose(a, b, rtol=rtol or rt, atol=atol or at)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_np, b_np = _np(a), _np(b)
    rt, at = default_rtols(a_np.dtype)
    np.testing.assert_allclose(a_np, b_np, rtol=rtol or rt, atol=atol or at,
                               err_msg=f"{names[0]} vs {names[1]}")


def rand_ndarray(shape, dtype="float32", ctx=None, scale=1.0):
    data = (np.random.randn(*shape) * scale).astype(dtype_np(dtype))
    return array(data, ctx=ctx)


def same_array(a, b):
    """Handle-level aliasing check (buffer identity is meaningless with
    functional updates; the reference checked raw pointers)."""
    return a is b or a._data is b._data


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4,
                           input_grads=None):
    """Compare autograd gradients of ``fn(*inputs)`` (scalar output) against
    central finite differences (reference: check_numeric_gradient)."""
    nds = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in nds:
        x.attach_grad()
    with autograd.record():
        out = fn(*nds)
        if out.size != 1:
            out = out.sum()
    out.backward()
    analytic = [x.grad.asnumpy() for x in nds]

    for xi, x in enumerate(nds):
        base = x.asnumpy().astype(np.float64)
        fd = np.zeros_like(base)
        it = np.nditer(base, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            xp = base.copy(); xp[idx] += eps
            xm = base.copy(); xm[idx] -= eps

            def eval_at(v):
                args = [array(v.astype(base.dtype)) if j == xi else nds[j]
                        for j in range(len(nds))]
                o = fn(*args)
                return float(o.sum().asnumpy()) if o.size != 1 else float(o.asnumpy())

            fd[idx] = (eval_at(xp) - eval_at(xm)) / (2 * eps)
            it.iternext()
        np.testing.assert_allclose(analytic[xi], fd, rtol=rtol, atol=atol,
                                   err_msg=f"input {xi}: autograd vs finite-diff")


def check_consistency(fn, inputs, rtol=1e-4, atol=1e-5):
    """Eager vs jit-compiled equivalence — the TPU analog of the reference's
    cpu-vs-gpu check_consistency oracle."""
    import jax

    nds = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    eager = fn(*nds)
    eager_np = _np(eager)

    def pure(*raws):
        out = fn(*[NDArray(r) for r in raws])
        return out._data

    compiled = jax.jit(pure)(*[x._data for x in nds])
    np.testing.assert_allclose(eager_np, np.asarray(compiled), rtol=rtol,
                               atol=atol, err_msg="eager vs compiled")
