#!/usr/bin/env python
"""Driver config #2: ResNet-50 data-parallel training
(reference shape: example/image-classification/train_imagenet.py with
kvstore='device'; data parallelism here = GSPMD batch sharding over the mesh
inside one compiled train step)."""
import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, optimizer
from mxnet_tpu.gluon.model_zoo.vision import get_resnet
from mxnet_tpu.parallel import MeshConfig, TrainStep, make_mesh


def synthetic_batches(batch, steps, shape=(3, 224, 224), classes=1000):
    rs = np.random.RandomState(0)
    for _ in range(steps):
        yield (nd.array(rs.rand(batch, *shape).astype(np.float32)),
               nd.array(rs.randint(0, classes, batch)))


def record_batches(rec_path, batch, steps, size, threads):
    """Real data: the threaded JPEG-decode pipeline (ImageRecordIter over an
    im2rec .rec pack — reference iter_image_recordio_2.cc path), with
    ImageNet mean/std and random crop+mirror; reports decode throughput."""
    from mxnet_tpu.io import ImageRecordIter

    it = ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, size, size), batch_size=batch,
        shuffle=True, rand_crop=True, rand_mirror=True, resize=size * 256 // 224,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.393, std_g=57.12, std_b=57.375,
        preprocess_threads=threads)
    done = 0
    t0 = time.time()
    while done < steps:
        for b in it:
            yield b.data[0], b.label[0].astype("int32")
            done += 1
            if done >= steps:
                break
        it.reset()
    dt = time.time() - t0
    print(f"input pipeline: {done * batch / dt:.1f} img/s decoded+augmented "
          f"({threads} threads)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--layers", type=int, default=50)
    ap.add_argument("--dp", type=int, default=0, help="data-parallel degree "
                    "(0 = all devices)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--rec", default=None,
                    help="path to an im2rec .rec pack; omitted = synthetic data")
    ap.add_argument("--data-threads", type=int, default=4)
    args = ap.parse_args()

    import jax

    n = args.dp or len(jax.devices())
    mesh = make_mesh(MeshConfig(dp=n)) if n > 1 else None

    net = get_resnet(1, args.layers, classes=1000)
    net.initialize(mx.init.MSRAPrelu())
    x0, y0 = next(synthetic_batches(args.batch_size, 1,
                                    (3, args.image_size, args.image_size)))
    _ = net(x0)

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, lambda out, y: loss_fn(out, y),
                     optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4),
                     mesh=mesh)
    batches = (record_batches(args.rec, args.batch_size, args.steps,
                              args.image_size, args.data_threads)
               if args.rec else
               synthetic_batches(args.batch_size, args.steps,
                                 (3, args.image_size, args.image_size)))
    t0, seen = time.time(), 0
    for i, (x, y) in enumerate(batches):
        loss = step(x, y)
        seen += args.batch_size
        if i == 0:
            t0, seen = time.time(), 0  # skip compile
    import jax as j

    j.block_until_ready(step.params)
    dt = time.time() - t0
    print(f"resnet{args.layers} dp={n}: {seen / dt:.1f} img/s "
          f"(loss={float(np.asarray(j.device_get(loss))):.3f})")


if __name__ == "__main__":
    main()
