"""ONNX export/import roundtrip (reference: tests/python-pytest/onnx/ —
backend comparison; here the oracle is our own eager forward, since the
roundtrip exercises both translation tables and the protobuf codec)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib.onnx import export_model, import_model
from mxnet_tpu.contrib.onnx import proto
from mxnet_tpu.gluon import nn


def _roundtrip(net, x, tmp_path, rtol=1e-5, atol=1e-6):
    net.initialize()
    expected = net(x).asnumpy()
    sym_file, param_file = net.export(str(tmp_path / "m"))
    onnx_file = export_model(sym_file, param_file, input_shapes={"data": x.shape},
                             onnx_file=str(tmp_path / "m.onnx"))
    sym, arg_params, aux_params = import_model(onnx_file)
    inputs = [s for s in sym.list_arguments() if s not in arg_params]
    sb = gluon.SymbolBlock(sym, inputs, {**arg_params, **aux_params})
    got = sb(x).asnumpy()
    np.testing.assert_allclose(got, expected, rtol=rtol, atol=atol)
    return onnx_file


def test_onnx_mlp_roundtrip(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(5))
    x = nd.array(np.random.rand(4, 10).astype(np.float32))
    _roundtrip(net, x, tmp_path)


def test_onnx_lenet_roundtrip(tmp_path):
    net = gluon.model_zoo.get_model("lenet")
    x = nd.array(np.random.rand(2, 1, 28, 28).astype(np.float32))
    _roundtrip(net, x, tmp_path, rtol=1e-4, atol=1e-5)


def test_onnx_batchnorm_residual_roundtrip(tmp_path):
    net = gluon.model_zoo.get_model("resnet18_v1", classes=4)
    x = nd.array(np.random.rand(1, 3, 32, 32).astype(np.float32))
    _roundtrip(net, x, tmp_path, rtol=1e-3, atol=1e-4)


def test_onnx_file_is_wellformed_protobuf(tmp_path):
    """The emitted bytes parse as a ModelProto with graph/opset populated."""
    net = nn.HybridSequential()
    net.add(nn.Dense(3))
    net.initialize()
    x = nd.ones((1, 2))
    _ = net(x)
    sym_file, param_file = net.export(str(tmp_path / "m"))
    onnx_file = export_model(sym_file, param_file, input_shapes={"data": (1, 2)},
                             onnx_file=str(tmp_path / "m.onnx"))
    with open(onnx_file, "rb") as f:
        model = proto.parse_model(f.read())
    assert model["ir_version"] == 8
    assert model["opsets"] == [("", 12)]
    g = model["graph"]
    assert any(n["op_type"] == "Gemm" for n in g["nodes"])
    assert len(g["initializers"]) >= 2  # weight + bias
    names = [n for n, _, _ in g["inputs"]]
    assert names == ["data"]
    # input shape survives
    assert g["inputs"][0][2] == (1, 2)


def test_onnx_tensor_codec_dtypes():
    for dt in ("float32", "int64", "int32", "uint8"):
        arr = (np.random.rand(3, 4) * 10).astype(dt)
        name, back = proto.parse_tensor(proto.tensor_proto("t", arr))
        assert name == "t"
        np.testing.assert_array_equal(back, arr)


def test_onnx_tensor_typed_data_fields():
    """External ONNX files may store values in the typed repeated fields
    (float_data=4, int32_data=5, int64_data=7) instead of raw_data; int8/
    uint8/int32/bool all ride int32_data per onnx.proto."""
    cases = [
        (np.arange(6, dtype=np.float32).reshape(2, 3), 4),
        (np.array([[1, -2], [3, 4]], np.int64), 7),
        (np.array([[5, -6], [7, 8]], np.int32), 5),
        (np.array([[0, 255], [1, 2]], np.uint8), 5),
        (np.array([[-1, 2], [-3, 4]], np.int8), 5),
    ]
    for arr, field in cases:
        dt = proto.NP_TO_DT[arr.dtype.name]
        buf = b"".join(proto.f_varint(1, d) for d in arr.shape)
        buf += proto.f_varint(2, dt) + proto.f_str(8, "typed")
        if field == 4:
            buf += b"".join(proto.f_float(4, float(v)) for v in arr.ravel())
        else:
            buf += b"".join(proto.f_varint(field, int(v)) for v in arr.ravel())
        name, back = proto.parse_tensor(buf)
        assert name == "typed"
        assert back.dtype == arr.dtype
        np.testing.assert_array_equal(back, arr)


def test_onnx_attr_codec():
    cases = {"i": 7, "f": 1.5, "s": "hello", "ints": [1, 2, 3],
             "floats": [0.5, 0.25], "neg": -3}
    for k, v in cases.items():
        name, back = proto.parse_attr(proto.attr_proto(k, v))
        assert name == k
        if isinstance(v, list):
            np.testing.assert_allclose(back, v)
        else:
            assert back == v


def test_onnx_unsupported_op_errors(tmp_path):
    from mxnet_tpu import sym as S

    a = S.var("data")
    weird = S.topk(a, k=2)
    with pytest.raises(MXNetError, match="no translator"):
        export_model(weird, {}, onnx_file=str(tmp_path / "x.onnx"))
