"""Data pipeline: DataLoader, NDArrayIter, RecordIO wire format
(reference: tests/python/unittest/test_io.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.io import NDArrayIter, MXRecordIO, IndexedRecordIO
from mxnet_tpu.io.recordio import IRHeader, pack, unpack, pack_img, unpack_img


def test_ndarray_iter_basic():
    data = np.arange(20).reshape(10, 2).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 2)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_discard():
    it = NDArrayIter(np.zeros((10, 2)), np.zeros(10), batch_size=4,
                     last_batch_handle="discard")
    assert len(list(it)) == 2


def test_dataloader_batching_and_shuffle():
    ds = gluon.data.ArrayDataset(np.arange(10).astype(np.float32),
                                 np.arange(10).astype(np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=3, shuffle=False)
    batches = list(loader)
    assert len(batches) == 4
    np.testing.assert_allclose(batches[0][0].asnumpy(), [0, 1, 2])

    loader2 = gluon.data.DataLoader(ds, batch_size=5, shuffle=True, last_batch="discard")
    batches2 = list(loader2)
    assert len(batches2) == 2


def test_dataloader_transform():
    ds = gluon.data.ArrayDataset(np.ones((6, 2), np.float32))
    ds2 = ds.transform(lambda x: x * 2)
    loader = gluon.data.DataLoader(ds2, batch_size=2)
    for (b,) in [(b,) for b in loader]:
        np.testing.assert_allclose(b.asnumpy(), np.full((2, 2), 2.0))


def test_recordio_roundtrip(tmp_path):
    f = str(tmp_path / "x.rec")
    w = MXRecordIO(f, "w")
    records = [b"hello", b"x" * 1000, b"", b"abc" * 7]
    for r in records:
        w.write(r)
    w.close()
    r = MXRecordIO(f, "r")
    out = []
    while True:
        item = r.read()
        if item is None:
            break
        out.append(item)
    assert out == records


def test_indexed_recordio(tmp_path):
    f = str(tmp_path / "y.rec")
    idx = str(tmp_path / "y.idx")
    w = IndexedRecordIO(idx, f, "w")
    for i in range(5):
        w.write_idx(i, f"rec{i}".encode())
    w.close()
    r = IndexedRecordIO(idx, f, "r")
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    assert len(r.keys) == 5


def test_pack_unpack_header():
    h = IRHeader(0, 3.0, 7, 0)
    s = pack(h, b"payload")
    h2, data = unpack(s)
    assert h2.label == 3.0 and h2.id == 7 and data == b"payload"
    # vector label
    hv = IRHeader(0, np.array([1.0, 2.0], np.float32), 1, 0)
    s = pack(hv, b"p2")
    h3, d3 = unpack(s)
    np.testing.assert_allclose(h3.label, [1.0, 2.0])


def test_pack_img_roundtrip():
    img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
    s = pack_img(IRHeader(0, 1.0, 0, 0), img)
    h, img2 = unpack_img(s)
    np.testing.assert_array_equal(img, img2)


def test_vision_datasets_synthetic():
    ds = gluon.data.vision.MNIST(train=True)
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) < 10
    c = gluon.data.vision.CIFAR10(train=False)
    x, y = c[5]
    assert x.shape == (32, 32, 3)


def test_prefetching_iter():
    from mxnet_tpu.io import PrefetchingIter

    base = NDArrayIter(np.zeros((8, 2)), np.zeros(8), batch_size=4)
    pf = PrefetchingIter(base)
    n = 0
    for _ in pf:
        n += 1
    assert n == 2
