"""Static schedule analysis (ISSUE 13, docs/ANALYSIS.md "Schedule &
overlap"): the DAG scheduler on synthetic programs in both dialects with
hand-computed critical paths — an async start→done span hiding behind
compute vs a sync all-reduce fully exposed, partial hiding, while-body
recursion, tuple-result span sizing — plus live step/window/decode audits
asserting the report invariants (hidden + exposed == total comm time,
overlap ∈ [0, 1], MFU bound ∈ (0, 1]) and the ``train_mfu_bound`` gauge."""
import json

import numpy as np
import pytest

from mxnet_tpu.analysis import asyncify, audit_text, schedule_report

# fixed roofline constants for every hand-computed case: 1 GB/s HBM and
# ICI make seconds == bytes/1e9, peak 1e12 FLOP/s
_K = dict(peak_flops=1e12, hbm_gbps=1.0, ici_gbps=1.0)


# ---------------------------------------------------------------------------
# synthetic programs, compiled (hlo) dialect — scheduled text
# ---------------------------------------------------------------------------

_ASYNC_HIDDEN = """\
HloModule t, is_scheduled=true

ENTRY %main.9 (p0.1: f32[1024], p1.2: f32[1024,1024]) -> f32[1024] {
  %p0.1 = f32[1024]{0} parameter(0)
  %p1.2 = f32[1024,1024]{1,0} parameter(1)
  %ar.2 = (f32[1024]{0}, f32[1024]{0}) all-reduce-start(f32[1024]{0} %p0.1), replica_groups={{0,1,2,3}}, to_apply=%add
  %big.3 = f32[1024]{0} dot(f32[1024,1024]{1,0} %p1.2, f32[1024]{0} %p0.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ard.4 = f32[1024]{0} all-reduce-done((f32[1024]{0}, f32[1024]{0}) %ar.2)
  ROOT %e.5 = f32[1024]{0} add(f32[1024]{0} %ard.4, f32[1024]{0} %big.3)
}
"""


def test_async_span_fully_hidden_hand_computed():
    """The 8192 B all-reduce (2 x 4096 operand bytes / 1 GB/s =
    8.192 µs) hides entirely behind the dot scheduled inside its
    start→done span (4.2 ms of HBM-bound time); the critical path is the
    compute chain alone."""
    s = schedule_report(audit_text(_ASYNC_HIDDEN), **_K)
    assert s.comm_seconds == pytest.approx(8192 / 1e9)
    assert s.hidden_comm_seconds == pytest.approx(s.comm_seconds)
    assert s.exposed_comm_seconds == 0.0
    assert s.overlap_fraction == 1.0
    assert s.exposed_collectives() == {}
    # dot: hbm = 4 MiB lhs + 4 KiB rhs + 4 KiB result; add: 3 x 4 KiB
    dot_s = (1024 * 1024 * 4 + 4096 + 4096) / 1e9
    add_s = 3 * 4096 / 1e9
    assert s.compute_seconds == pytest.approx(dot_s + add_s)
    assert s.critical_path_seconds == pytest.approx(dot_s + add_s)
    assert s.dag_critical_seconds == pytest.approx(dot_s + add_s)
    assert s.flops_total == pytest.approx(2 * 1024 * 1024)
    span = s.spans[0]
    assert span.is_async and not span.is_exposed
    assert span.kind == "all_reduce"


def test_async_span_partially_hidden_hand_computed():
    """A 8.39 ms all-reduce over the big tensor with only a 12.3 µs add
    inside its span: hidden == the add's time, the rest is exposed, and
    hidden + exposed == total exactly."""
    prog = _ASYNC_HIDDEN.replace(
        "(f32[1024]{0}, f32[1024]{0}) all-reduce-start(f32[1024]{0} %p0.1)",
        "(f32[1024,1024]{1,0}, f32[1024,1024]{1,0}) "
        "all-reduce-start(f32[1024,1024]{1,0} %p1.2)").replace(
        "%big.3 = f32[1024]{0} dot(f32[1024,1024]{1,0} %p1.2, "
        "f32[1024]{0} %p0.1), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}",
        "%big.3 = f32[1024]{0} add(f32[1024]{0} %p0.1, f32[1024]{0} %p0.1)"
    ).replace(
        "((f32[1024]{0}, f32[1024]{0}) %ar.2)",
        "((f32[1024,1024]{1,0}, f32[1024,1024]{1,0}) %ar.2)").replace(
        "f32[1024]{0} all-reduce-done", "f32[1024,1024]{1,0} all-reduce-done"
    ).replace(
        "ROOT %e.5 = f32[1024]{0} add(f32[1024]{0} %ard.4, "
        "f32[1024]{0} %big.3)",
        "ROOT %e.5 = f32[1024]{0} slice(f32[1024,1024]{1,0} %ard.4), "
        "slice={[0:1], [0:1024]}")
    s = schedule_report(audit_text(prog), **_K)
    coll = 2 * 1024 * 1024 * 4 / 1e9          # 2 x 4 MiB operand
    window = 3 * 4096 / 1e9                   # the small add in the span
    assert s.comm_seconds == pytest.approx(coll)
    assert s.hidden_comm_seconds == pytest.approx(window)
    assert s.exposed_comm_seconds == pytest.approx(coll - window)
    assert s.hidden_comm_seconds + s.exposed_comm_seconds == \
        pytest.approx(s.comm_seconds)
    assert 0.0 < s.overlap_fraction < 0.01
    assert s.exposed_collectives() == {"all_reduce": 1}
    # the exposed collective dominates the critical path and tops the
    # serialization points
    assert s.serialization_points[0].kind == "collective"


def test_sync_all_reduce_fully_exposed():
    """The same collective without the start/done split hides nothing:
    sync collectives are fully exposed by definition."""
    prog = _ASYNC_HIDDEN.replace("all-reduce-start", "all-reduce").replace(
        "  %ard.4 = f32[1024]{0} all-reduce-done((f32[1024]{0}, "
        "f32[1024]{0}) %ar.2)\n", "").replace(
        "(f32[1024]{0}, f32[1024]{0}) all-reduce",
        "f32[1024]{0} all-reduce").replace("%ard.4", "%ar.2")
    s = schedule_report(audit_text(prog), **_K)
    assert s.comm_seconds == pytest.approx(8192 / 1e9)
    assert s.exposed_comm_seconds == pytest.approx(s.comm_seconds)
    assert s.hidden_comm_seconds == 0.0
    assert s.overlap_fraction == 0.0
    assert s.exposed_collectives() == {"all_reduce": 1}
    assert not s.spans[0].is_async
    # the sync collective sits ON the dependency path feeding the root
    assert s.dag_critical_seconds > 0
    assert s.critical_path_seconds == pytest.approx(
        s.compute_seconds + s.comm_seconds)


def test_tuple_result_async_span_sized_from_operand():
    """A variadic/bookkeeping start tuple must not inflate the comm
    price: the payload is the operand (16 B -> 32 B all-reduce bytes),
    not the tuple allocation."""
    prog = """\
HloModule t, is_scheduled=true

ENTRY %main.9 (p0.1: f32[4]) -> f32[4] {
  %p0.1 = f32[4]{0} parameter(0)
  %ars.2 = (f32[4]{0}, u32[], u32[]) all-reduce-start(f32[4]{0} %p0.1), replica_groups={{0,1,2,3}}, to_apply=%add
  %w.3 = f32[4]{0} multiply(f32[4]{0} %p0.1, f32[4]{0} %p0.1)
  %ard.4 = f32[4]{0} all-reduce-done((f32[4]{0}, u32[], u32[]) %ars.2)
  ROOT %e.5 = f32[4]{0} add(f32[4]{0} %ard.4, f32[4]{0} %w.3)
}
"""
    s = schedule_report(audit_text(prog), **_K)
    assert len(s.spans) == 1
    span = s.spans[0]
    assert span.bytes == 32 and span.is_async
    assert span.t_done > span.t_start
    assert s.comm_seconds == pytest.approx(32 / 1e9)


_WHILE_HLO = """\
HloModule t, is_scheduled=true

%body.1 (p.2: (s32[], f32[256,256])) -> (s32[], f32[256,256]) {
  %p.2 = (s32[], f32[256,256]) parameter(0)
  %i.3 = s32[] get-tuple-element((s32[], f32[256,256]) %p.2), index=0
  %x.4 = f32[256,256]{1,0} get-tuple-element((s32[], f32[256,256]) %p.2), index=1
  %d.5 = f32[256,256]{1,0} dot(f32[256,256]{1,0} %x.4, f32[256,256]{1,0} %x.4), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %c.6 = s32[] constant(1)
  %j.7 = s32[] add(s32[] %i.3, s32[] %c.6)
  ROOT %t.8 = (s32[], f32[256,256]) tuple(s32[] %j.7, f32[256,256]{1,0} %d.5)
}

%cond.9 (p.10: (s32[], f32[256,256])) -> pred[] {
  %p.10 = (s32[], f32[256,256]) parameter(0)
  %i.11 = s32[] get-tuple-element((s32[], f32[256,256]) %p.10), index=0
  %k.12 = s32[] constant(8)
  ROOT %lt.13 = pred[] compare(s32[] %i.11, s32[] %k.12), direction=LT
}

ENTRY %main.20 (p0.14: f32[256,256]) -> f32[256,256] {
  %p0.14 = f32[256,256]{1,0} parameter(0)
  %z.15 = s32[] constant(0)
  %t.16 = (s32[], f32[256,256]) tuple(s32[] %z.15, f32[256,256]{1,0} %p0.14)
  %w.17 = (s32[], f32[256,256]) while((s32[], f32[256,256]) %t.16), condition=%cond.9, body=%body.1
  ROOT %r.18 = f32[256,256]{1,0} get-tuple-element((s32[], f32[256,256]) %w.17), index=1
}
"""


def test_while_body_recursion_contributes_at_call_point():
    """The while body's dot (2*256^3 FLOPs, compute-bound at 1000 GB/s
    HBM) drives the entry critical path through the call node — without
    recursion the loop would look free. The body appears once in the
    text and is costed once (static per-dispatch census)."""
    s = schedule_report(audit_text(_WHILE_HLO), peak_flops=1e12,
                        hbm_gbps=1000.0, ici_gbps=1.0)
    dot_s = 2 * 256 ** 3 / 1e12
    assert s.flops_total == pytest.approx(2 * 256 ** 3)
    assert s.compute_seconds >= dot_s
    assert s.critical_path_seconds >= dot_s
    assert s.critical_path_seconds < 3 * dot_s  # once, not per iteration
    assert any(p.kind == "subcomputation" and p.op == "while"
               for p in s.serialization_points)


# ---------------------------------------------------------------------------
# lowered (stablehlo) dialect
# ---------------------------------------------------------------------------

_SYNC_MLIR = """\
module @jit_t attributes {mhlo.num_partitions = 2 : i32} {
  func.func public @main(%arg0: tensor<1024xf32>) -> tensor<1024xf32> {
    %0 = stablehlo.add %arg0, %arg0 : tensor<1024xf32>
    %1 = "stablehlo.all_reduce"(%0) {replica_groups = dense<[[0, 1]]> : tensor<1x2xi64>} : (tensor<1024xf32>) -> tensor<1024xf32>
    %2 = stablehlo.multiply %1, %0 : tensor<1024xf32>
    return %2 : tensor<1024xf32>
  }
}
"""


def test_stablehlo_sync_collective_priced_and_exposed():
    """The lowered dialect's sync all-reduce: 4096 B payload x 2 over
    1 GB/s, fully exposed, same invariants as the compiled spelling."""
    rep = audit_text(_SYNC_MLIR)
    assert rep.dialect == "stablehlo"
    s = schedule_report(rep, **_K)
    assert s.comm_seconds == pytest.approx(8192 / 1e9)
    assert s.exposed_comm_seconds == pytest.approx(s.comm_seconds)
    assert s.overlap_fraction == 0.0
    assert s.exposed_collectives() == {"all_reduce": 1}
    # the two elementwise ops are priced as HBM traffic
    assert s.compute_seconds == pytest.approx(2 * 3 * 4096 / 1e9)


def test_scan_lowered_func_call_recursion():
    """The lowered dialect's func.call scan body contributes its dot at
    the call point (recursion through subcomputations, 'call' op)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.analysis import audit_lowered

    def step(c, x):
        return jnp.tanh(c @ x), c.sum()

    lo = jax.jit(lambda c, xs: jax.lax.scan(step, c, xs)).lower(
        jnp.ones((64, 64)), jnp.ones((8, 64, 64)))
    rep = audit_lowered(lo)
    assert rep.subcomputations
    s = schedule_report(rep, peak_flops=1e12, hbm_gbps=1000.0)
    # the body dot: 2 * 64^3 FLOPs must appear in the totals
    assert s.flops_total >= 2 * 64 ** 3
    assert s.compute_seconds > 0
    assert s.critical_path_seconds >= 2 * 64 ** 3 / 1e12


# ---------------------------------------------------------------------------
# roofline constants & knobs
# ---------------------------------------------------------------------------

def test_dcn_axes_price_slower_than_ici():
    """A collective spanning a dcn_axes axis is priced at DCN speed —
    same program, slower link, proportionally more comm time."""
    rep = audit_text(_SYNC_MLIR)
    fast = schedule_report(rep, peak_flops=1e12, hbm_gbps=1.0,
                           ici_gbps=1.0, dcn_gbps=0.1, dcn_axes=())
    # without a mesh the axis key is "?": name it in dcn_axes to reroute
    slow = schedule_report(rep, peak_flops=1e12, hbm_gbps=1.0,
                           ici_gbps=1.0, dcn_gbps=0.1, dcn_axes=("?",))
    # "?" is the unattributed key, not a mesh axis name — axes tuple is
    # empty, so dcn_axes cannot match; both ride ICI. The knob is
    # exercised against a real mesh in the live fsdp test below.
    assert slow.comm_seconds == fast.comm_seconds

    env_default = schedule_report(rep)
    assert env_default.constants["ici_gbps"] > 0
    assert env_default.constants["peak_flops"] > 0
    assert json.dumps(env_default.summary())  # JSON-safe


# ---------------------------------------------------------------------------
# live programs: audit plumbing + invariants
# ---------------------------------------------------------------------------

def _mlp_step(mesh=None, rules=None):
    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    x = nd.ones((8, 16))
    _ = net(x)
    ts = TrainStep(net, lambda o, *l: ((o - l[0]) ** 2).mean(),
                   optimizer.Adam(learning_rate=1e-3), mesh=mesh,
                   rules=rules)
    return ts, (x, nd.zeros((8, 8)))


def _invariants(s):
    assert s.hidden_comm_seconds + s.exposed_comm_seconds == \
        pytest.approx(s.comm_seconds)
    assert 0.0 <= s.overlap_fraction <= 1.0
    assert 0.0 < s.mfu_bound <= 1.0
    assert s.critical_path_seconds >= s.dag_critical_seconds
    assert s.critical_path_seconds >= \
        s.compute_seconds + s.exposed_comm_seconds - 1e-18
    assert s.compute_seconds > 0 and s.n_nodes > 0
    json.dumps(s.summary())


# ---------------------------------------------------------------------------
# the asyncify pass (analysis.overlap): sync collectives rewritten into
# start→done spans the scheduler prices as hidden, hand-computed
# ---------------------------------------------------------------------------

_SYNC_HIDEABLE = """\
HloModule t, is_scheduled=true

ENTRY %main.9 (p0.1: f32[1024], p1.2: f32[1024,1024]) -> f32[1024] {
  %p0.1 = f32[1024]{0} parameter(0)
  %p1.2 = f32[1024,1024]{1,0} parameter(1)
  %ar.3 = f32[1024]{0} all-reduce(f32[1024]{0} %p0.1), replica_groups={{0,1,2,3}}, to_apply=%add
  %big.4 = f32[1024]{0} dot(f32[1024,1024]{1,0} %p1.2, f32[1024]{0} %p0.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %e.5 = f32[1024]{0} add(f32[1024]{0} %ar.3, f32[1024]{0} %big.4)
}
"""


def test_asyncify_fully_hides_hand_computed():
    """The sync 8192 B all-reduce is fully exposed as written; asyncify
    splits it into a start→done pair with the 4.2 ms dot scheduled inside
    the span, so the derived schedule hides ALL of it — the exact before/
    after the schedcheck overlap goldens lock in."""
    rep = audit_text(_SYNC_HIDEABLE)
    before = schedule_report(rep, **_K)
    assert before.overlap_fraction == 0.0
    assert before.exposed_comm_seconds == pytest.approx(8192 / 1e9)

    rep2, stats = asyncify(rep)
    assert stats.async_pairs == 1 and stats.deferred == 1
    assert sum(stats.per_computation.values()) == 1
    # the input report is untouched — asyncify derives, never mutates
    assert [v.op for v in rep.values].count("all_reduce_done") == 0
    # emission order: start … compute … done … consumer
    ops = [v.op for v in rep2.values]
    i_start = ops.index("all_reduce")
    i_done = ops.index("all_reduce_done")
    i_dot = ops.index("dot")
    i_root = len(ops) - 1
    assert i_start < i_dot < i_done < i_root
    # the consumer's use is rewritten onto the done value
    done_vid = rep2.values[i_done].vid
    assert done_vid in rep2.values[i_root].uses

    after = schedule_report(rep2, **_K)
    assert after.comm_seconds == pytest.approx(before.comm_seconds)
    assert after.hidden_comm_seconds == pytest.approx(after.comm_seconds)
    assert after.exposed_comm_seconds == 0.0
    assert after.overlap_fraction == 1.0
    assert after.exposed_collectives() == {}
    # comm off the critical path: the compute chain alone remains
    assert after.critical_path_seconds == \
        pytest.approx(after.compute_seconds)
    assert after.critical_path_seconds < before.critical_path_seconds
    span = after.spans[0]
    assert span.is_async and not span.is_exposed


_SYNC_PARTIAL = """\
HloModule t, is_scheduled=true

ENTRY %main.9 (p0.1: f32[1024,1024], p1.2: f32[1024]) -> f32[1024] {
  %p0.1 = f32[1024,1024]{1,0} parameter(0)
  %p1.2 = f32[1024]{0} parameter(1)
  %ar.3 = f32[1024,1024]{1,0} all-reduce(f32[1024,1024]{1,0} %p0.1), replica_groups={{0,1,2,3}}, to_apply=%add
  %sm.4 = f32[1024]{0} add(f32[1024]{0} %p1.2, f32[1024]{0} %p1.2)
  ROOT %e.5 = f32[1024]{0} dot(f32[1024,1024]{1,0} %ar.3, f32[1024]{0} %sm.4), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


def test_asyncify_partial_hiding_hand_computed():
    """Only the 12.288 µs add fits inside the 8.39 ms all-reduce span:
    hidden == the add's seconds exactly, the rest stays exposed, and
    hidden + exposed == total still holds on the derived schedule."""
    rep2, stats = asyncify(audit_text(_SYNC_PARTIAL))
    assert stats.async_pairs == 1
    s = schedule_report(rep2, **_K)
    comm = 2 * 1024 * 1024 * 4 / 1e9
    add_s = 3 * 4096 / 1e9
    assert s.comm_seconds == pytest.approx(comm)
    assert s.hidden_comm_seconds == pytest.approx(add_s)
    assert s.exposed_comm_seconds == pytest.approx(comm - add_s)
    assert s.overlap_fraction == pytest.approx(add_s / comm)
    assert s.exposed_collectives() == {"all_reduce": 1}  # mostly exposed
    _invariants(s)


def test_asyncify_no_collectives_is_identity():
    prog = """\
HloModule t, is_scheduled=true

ENTRY %main.3 (p0.1: f32[8]) -> f32[8] {
  %p0.1 = f32[8]{0} parameter(0)
  ROOT %m.2 = f32[8]{0} multiply(f32[8]{0} %p0.1, f32[8]{0} %p0.1)
}
"""
    rep = audit_text(prog)
    rep2, stats = asyncify(rep)
    assert stats.async_pairs == 0
    assert [v.vid for v in rep2.values] == [v.vid for v in rep.values]


def test_step_audit_schedule_and_gauges():
    """ISSUE 13 acceptance: TrainStep.audit(...).schedule returns a
    populated ScheduleReport on CPU, and exports the train_mfu_bound /
    train_comm_exposed_share gauges for the fleet report."""
    from mxnet_tpu import observability as obs

    ts, batch = _mlp_step()
    a = ts.audit(*batch)
    s = a.schedule
    assert s is not None
    _invariants(s)
    assert s.comm_seconds == 0.0         # mesh-less: no collectives
    assert s.overlap_fraction == 1.0
    assert s.serialization_points        # something is on the path
    assert obs.REGISTRY.get("train_mfu_bound").value() == \
        pytest.approx(s.mfu_bound)
    assert obs.REGISTRY.get("train_comm_exposed_share").value() == 0.0
    assert a.summary()["schedule"]["mfu_bound"] == round(s.mfu_bound, 6)


def test_fsdp_step_and_window_schedule():
    """The fsdp mesh step: collective time attributed to the fsdp /
    dp×fsdp axes. The audit schedules the asyncified view, so part of
    the collective time is hidden behind independent compute (XLA:CPU
    emits sync collectives, which score 0.0 overlap raw — the asyncify
    pass models what the TPU async runtime achieves); the fused window
    recurses its scan body and sees the same collectives once."""
    from mxnet_tpu.parallel import MeshConfig, ShardingRules, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, fsdp=4))
    ts, batch = _mlp_step(mesh, ShardingRules(fsdp_axis="fsdp",
                                              min_fsdp_size=1))
    a = ts.audit(*batch)
    s = a.schedule
    _invariants(s)
    assert s.comm_seconds > 0
    assert set(s.by_axis()) == {"fsdp", "dp×fsdp"}
    assert a.overlap is not None and a.overlap.async_pairs > 0
    assert 0.0 < s.overlap_fraction < 1.0
    assert s.hidden_comm_seconds > 0
    assert s.exposed_comm_seconds < s.comm_seconds
    # the raw (sync) compiled program still scores fully exposed
    raw = schedule_report(a.compiled, mesh, **_K)
    assert raw.overlap_fraction == 0.0
    assert obs_share_exposed(s) > 0
    # dcn pricing: routing the fsdp axis over a 100x slower link must
    # grow that axis's time proportionally
    rep = ts.audit(*batch).compiled
    slow = schedule_report(rep, mesh, dcn_axes=("fsdp",), dcn_gbps=0.9,
                           ici_gbps=90.0)
    fast = schedule_report(rep, mesh, dcn_axes=(), dcn_gbps=0.9,
                           ici_gbps=90.0)
    assert slow.by_axis()["fsdp"]["seconds"] == pytest.approx(
        100 * fast.by_axis()["fsdp"]["seconds"])

    w = ts.audit(*batch, window=2).schedule
    _invariants(w)
    assert w.comm_seconds == pytest.approx(s.comm_seconds)


def obs_share_exposed(s):
    return s.exposed_comm_seconds / s.critical_path_seconds


@pytest.fixture(scope="module")
def engine():
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.inference import GenerationEngine
    from mxnet_tpu.models import gpt2

    mx.random.seed(0)
    net = gpt2.get_gpt2("gpt2_tiny", dropout=0.0, num_layers=2, units=32,
                        num_heads=2, max_length=64, vocab_size=64)
    net.initialize()
    _ = net(nd.array(np.zeros((1, 4), np.int32)))
    return GenerationEngine(net, batch_size=2, max_length=64,
                            prefill_buckets=(8, 16))


def test_decode_audit_schedule(engine):
    """ISSUE 13 acceptance: GenerationEngine.audit(...).schedule is a
    populated ScheduleReport — serving programs are collective-free by
    contract, so nothing can be exposed."""
    s = engine.audit().schedule
    assert s is not None
    _invariants(s)
    assert s.comm_seconds == 0.0
    assert s.exposed_collectives() == {}
    assert s.flops_total > 0  # the decode step's dots are priced
    p = engine.audit(bucket=8).schedule
    _invariants(p)
    # prefill runs 8 positions; its modeled latency exceeds one decode
    assert p.critical_path_seconds > s.critical_path_seconds
