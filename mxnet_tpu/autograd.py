"""Imperative autograd: ``record()`` / ``backward()`` over ``jax.vjp``.

Reference: ``src/imperative/imperative.cc`` + ``python/mxnet/autograd.py`` —
a mutation tape whose backward builds an nnvm gradient graph and executes it
through the dependency engine. The TPU design records a lightweight *replay
tape* instead: each recorded op stores (pure-fn, inputs, kwargs); ``backward``
replays the subgraph as one pure function and differentiates it with
``jax.vjp``, so the whole backward is a single XLA program — no engine, no
per-op gradient kernels.

Stochastic ops (Dropout etc.) materialise their PRNG key at record time, so
the vjp replay sees identical randomness — the reference gets this from
saved cuDNN dropout masks.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "record", "pause", "train_mode", "predict_mode",
    "is_recording", "is_training", "backward", "grad",
    "mark_variables", "get_symbol",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


class _RecordScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._saved = (_STATE.recording, _STATE.training)
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._saved


def record(train_mode: bool = True):
    """``with autograd.record():`` — start taping ops (and set train mode)."""
    return _RecordScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordScope(False, train_mode)


def train_mode():
    return _RecordScope(None, True)


def predict_mode():
    return _RecordScope(None, False)


class TapeNode:
    """One recorded op application."""

    __slots__ = ("op", "kwargs", "inputs", "nout", "name")

    def __init__(self, op, kwargs, inputs, nout, name=""):
        self.op = op  # pure fn(*raw, **kwargs)
        self.kwargs = kwargs
        self.inputs = inputs  # list of NDArray (refs retained for replay)
        self.nout = nout
        self.name = name


def mark_variables(variables, gradients, grad_reqs="write"):
    """MXNet API: make arrays differentiable leaves with preallocated grads."""
    if not isinstance(variables, (list, tuple)):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad_req = req
        v._grad = g


def _collect(heads):
    """Topo-collect tape nodes + leaf NDArrays reachable from heads."""
    nodes, leaves, seen_nodes, seen_leaves = [], [], set(), set()

    def visit_array(arr):
        tape = getattr(arr, "_tape", None)
        if tape is not None:
            visit_node(tape[0])
        if getattr(arr, "_grad_req", "null") != "null" and id(arr) not in seen_leaves:
            seen_leaves.add(id(arr))
            leaves.append(arr)

    def visit_node(node):
        if id(node) in seen_nodes:
            return
        seen_nodes.add(id(node))
        for x in node.inputs:
            visit_array(x)
        nodes.append(node)

    for h in heads:
        visit_array(h)
    return nodes, leaves


def _build_replay(heads, leaves):
    """Return f(leaf_values) -> head_values, replaying the tape purely."""
    leaf_pos = {id(a): i for i, a in enumerate(leaves)}

    def run(leaf_vals):
        memo = {}

        def value_of(arr):
            key = id(arr)
            if key in leaf_pos:
                return leaf_vals[leaf_pos[key]]
            tape = getattr(arr, "_tape", None)
            if tape is None:
                return jax.lax.stop_gradient(arr._data)
            node, idx = tape
            if id(node) not in memo:
                raw = [value_of(x) for x in node.inputs]
                out = node.op(*raw, **node.kwargs)
                memo[id(node)] = out if isinstance(out, tuple) else (out,)
            return memo[id(node)][idx]

        return tuple(value_of(h) for h in heads)

    return run


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. all attached-grad leaves."""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    nodes, leaves = _collect(heads)
    if not leaves:
        raise ValueError("backward: no arrays with attach_grad() are reachable "
                         "from the given heads")
    replay = _build_replay(heads, leaves)
    leaf_vals = tuple(l._data for l in leaves)
    _, vjp_fn = jax.vjp(replay, leaf_vals)
    if head_grads is None:
        cts = tuple(jnp.ones_like(h._data) for h in heads)
    else:
        cts = tuple(
            jnp.ones_like(h._data) if g is None else (g._data if hasattr(g, "_data") else jnp.asarray(g))
            for h, g in zip(heads, head_grads)
        )
    (grads,) = vjp_fn(cts)
    for leaf, g in zip(leaves, grads):
        req = getattr(leaf, "_grad_req", "write")
        if req == "null":
            continue
        if leaf._grad is None or req == "write":
            if leaf._grad is None:
                leaf._grad = leaf._empty_like()
            leaf._grad._data = g.astype(leaf.dtype)
        elif req == "add":
            leaf._grad._data = leaf._grad._data + g.astype(leaf.dtype)
    if not retain_graph:
        for n in nodes:
            n.inputs = []


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Functional gradient API (``autograd.grad``). Returns grads as NDArrays.

    ``create_graph=True`` (higher-order grad — reference
    ``Imperative::Backward`` with ``create_graph``): the gradient computation
    itself is recorded on the tape as one differentiable op, so a second
    ``grad``/``backward`` differentiates through it via jax's vjp-of-vjp.
    """
    single = not isinstance(heads, (list, tuple))
    if single:
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
    n_vars = len(variables)
    # Under create_graph, replay over variables PLUS every other
    # attach_grad leaf reachable from heads: the second backward must reach
    # those leaves too (reference Imperative::Backward propagates to all
    # recorded inputs) — stop_gradient constants would silently zero their
    # second-order gradients. Without create_graph, keep the cheap
    # variables-only vjp (no wasted cotangents for large parameter sets).
    if create_graph:
        _, all_leaves = _collect(heads)
        var_ids = {id(v) for v in variables}
        extra_leaves = [l for l in all_leaves if id(l) not in var_ids]
        leaves = list(variables) + extra_leaves
    else:
        leaves = list(variables)
    replay = _build_replay(heads, leaves)
    fixed_cts = None if head_grads is None else tuple(
        g._data if hasattr(g, "_data") else jnp.asarray(g) for g in head_grads)

    def grad_fn(*leaf_vals):
        head_vals, vjp_fn = jax.vjp(replay, tuple(leaf_vals))
        cts = fixed_cts if fixed_cts is not None else tuple(
            jnp.ones_like(h) for h in head_vals)
        (gs,) = vjp_fn(cts)
        return tuple(gs[:n_vars])

    from . import ndarray as nd

    if create_graph:
        # route through the op-invoke tape: the returned NDArrays carry a
        # tape entry whose pure fn is grad_fn, so they are differentiable —
        # w.r.t. the variables AND the other leaves (all are taped inputs)
        from .registry import OpDef

        opdef = OpDef(name="grad", fn=grad_fn, nout=n_vars)
        with _RecordScope(True, None):
            res = nd.invoke(opdef, tuple(leaves), {})
        return list(res) if isinstance(res, tuple) else [res]

    grads = grad_fn(*(v._data for v in leaves))
    return [nd.NDArray(g) for g in grads]


def get_symbol(x):
    raise NotImplementedError("autograd.get_symbol: use mxnet_tpu.symbol tracing instead")


class Function:
    """User-defined differentiable function (reference:
    ``python/mxnet/autograd.py`` class Function / ``MXCustomFunctionRecord``).

    ``forward`` defines the primal on NDArray handles, ``backward`` the VJP;
    both are packaged into one ``jax.custom_vjp`` so the pair traces into
    compiled programs. The backward pass re-executes ``forward`` (functional
    re-derivation instead of the reference's saved-NDArray refs), so state
    stashed on ``self`` in ``forward`` is visible to ``backward``."""

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray, invoke
        from .registry import OpDef

        fn_self = self

        def _run_fwd(raws):
            outs = fn_self.forward(*[NDArray(r) for r in raws])
            outs = outs if isinstance(outs, (list, tuple)) else (outs,)
            return tuple(o._data for o in outs)

        @jax.custom_vjp
        def fn(*raws):
            outs = _run_fwd(raws)
            return outs if len(outs) > 1 else outs[0]

        def fwd(*raws):
            outs = _run_fwd(raws)
            return (outs if len(outs) > 1 else outs[0]), raws

        def bwd(raws, gs):
            _run_fwd(raws)  # re-derive any state stashed on self
            gs = gs if isinstance(gs, tuple) else (gs,)
            in_grads = fn_self.backward(*[NDArray(g) for g in gs])
            in_grads = in_grads if isinstance(in_grads, (list, tuple)) else (in_grads,)
            return tuple(g._data for g in in_grads)

        fn.defvjp(fwd, bwd)
        nout = len(jax.tree_util.tree_leaves(
            jax.eval_shape(fn, *[i._data for i in inputs])))
        opdef = OpDef(name=type(self).__name__, fn=fn, nout=nout)
        return invoke(opdef, inputs, {})
