"""Deterministic fault-injection registry (SURVEY §5.3 recovery story).

Production multi-host training dies in three places: checkpoint IO, DCN
collectives, and the input pipeline. Each of those call sites is annotated
with a named *fault site* (``fire(site)``); this module decides — fully
deterministically — whether that invocation fails. Arming is programmatic
(``arm`` / the ``inject`` context manager, for tests) or declarative via
``MXNET_TPU_FAULTS`` (for the ``make chaos`` CI pass), so every recovery
path in the framework is testable on CPU with no real signals, no real
flaky network, and no kill -9.

Two failure flavours:

  - :class:`InjectedFault` (an ``IOError``) — a *transient* failure the
    retry layer (``resilience.retry``) is expected to absorb;
  - :class:`InjectedCrash` (a ``BaseException``) — simulated process death
    mid-operation. It deliberately does NOT derive from ``Exception`` so no
    retry/except block in the framework can swallow it; whatever partial
    state was on disk at the fire point is what a restart sees.

Known sites (see docs/RESILIENCE.md):

  ======================  ====================================================
  ``ckpt.save``           inside ``save_train_state`` — after the array data
                          is written, before the manifest/commit rename
  ``ckpt.load``           inside ``load_train_state`` — before reading arrays
  ``kv.dcn_psum``         the per-key cross-process gradient all-reduce
  ``kv.dcn_psum_batch``   the batched (one-transfer) all-reduce
  ``kv.save_states``      ``KVStore.save_optimizer_states`` pre-commit
  ``data.batch``          one DataLoader batch fetch/batchify
  ``dist.init``           ``jax.distributed`` bootstrap (``dist_init``) —
                          a replacement worker dialing the coordinator
                          before its port is up; absorbed by the retry
                          policy around the bootstrap
  ``dist.heartbeat``      ``HeartbeatMonitor.check`` — a failed/partitioned
                          liveness probe; surfaces as ``PeerLost`` and
                          drives a mesh re-formation with no real dead
                          process
  ``gen.prefill``         ``GenerationEngine.prefill`` — before any page
                          allocation or dispatch, so a retried admission
                          replays cleanly (``ContinuousBatcher`` wraps it
                          in ``retry_call``)
  ``gen.decode``          one serving decode dispatch — fired at the top of
                          ``decode_step``/``plain_step`` and of each
                          speculative round, before any allocator mutation
  ``gen.verify``          the speculative verify dispatch — fired after the
                          draft half committed its carry, retried inside
                          ``spec_step`` (the round's host state is
                          re-entrant at that point)
  ======================  ====================================================

Env grammar (entries separated by ``;``, options by ``:``)::

  MXNET_TPU_FAULTS="ckpt.save:every=3;kv.dcn_psum:on=2:times=2;seed=1234"

  on=N      fire on the Nth invocation of the site (1-based)
  every=K   fire on every Kth invocation (periodic transient noise)
  times=M   total number of firings before the trigger disarms (default:
            unlimited for every=, 1 for on=)
  p=F       fire with probability F per invocation, drawn from a
            ``random.Random(seed ^ hash(site))`` stream — deterministic for
            a fixed seed (the ``seed=N`` entry, default 0)
  crash     raise InjectedCrash instead of InjectedFault
"""
from __future__ import annotations

import contextlib
import logging
import random as _random
import threading
from typing import Dict, List, Optional

__all__ = ["InjectedFault", "InjectedCrash", "arm", "disarm", "reset",
           "fire", "inject", "count", "armed", "load_spec", "reload_from_env"]

logger = logging.getLogger("mxnet_tpu.resilience.faults")


class InjectedFault(IOError):
    """A transient injected failure — the retry layer should absorb it."""

    def __init__(self, site: str, invocation: int):
        super().__init__(f"injected fault at site {site!r} (invocation {invocation})")
        self.site = site
        self.invocation = invocation


class InjectedCrash(BaseException):
    """Simulated process death at a fault site.

    Derives from BaseException so that no framework-level ``except
    Exception`` (including the retry layer) can absorb it — exactly like a
    SIGKILL, the operation stops where it stood and only a fresh process
    sees the aftermath.
    """

    def __init__(self, site: str, invocation: int):
        super().__init__(f"injected crash at site {site!r} (invocation {invocation})")
        self.site = site
        self.invocation = invocation


class _Trigger:
    def __init__(self, on: Optional[int] = None, every: Optional[int] = None,
                 p: Optional[float] = None, times: Optional[int] = None,
                 crash: bool = False, seed: int = 0, site: str = ""):
        if sum(x is not None for x in (on, every, p)) != 1:
            raise ValueError("exactly one of on=/every=/p= must be given")
        self.on = on
        self.every = every
        self.p = p
        self.times = times if times is not None else (1 if on is not None else None)
        self.crash = crash
        # per-(seed, site) stream so p= triggers are reproducible and
        # independent across sites; crc32 not hash() — str hashing is
        # randomized per interpreter, which would break the fixed-seed
        # reproducibility contract
        import zlib

        self._rng = _random.Random((seed << 32) ^ zlib.crc32(site.encode())) \
            if p is not None else None

    def matches(self, invocation: int) -> bool:
        if self.times is not None and self.times <= 0:
            return False
        if self.on is not None:
            hit = invocation == self.on
        elif self.every is not None:
            hit = invocation % self.every == 0
        else:
            hit = self._rng.random() < self.p
        if hit and self.times is not None:
            self.times -= 1
        return hit


_triggers: Dict[str, List[_Trigger]] = {}
_counts: Dict[str, int] = {}
_active = False
_env_loaded = False
# chaos runs fire() from DataLoader/prefetcher worker threads while the
# test thread arms/disarms — one lock covers both registries (JH005)
_lock = threading.Lock()
# guards the one-shot env-spec load (see _ensure_env)
_env_lock = threading.Lock()


def _recompute_active() -> None:
    global _active
    _active = any(_triggers.values())


def armed() -> bool:
    """Fast check used by hot call sites to skip counter bookkeeping."""
    _ensure_env()
    return _active


def arm(site: str, on: Optional[int] = None, every: Optional[int] = None,
        p: Optional[float] = None, times: Optional[int] = None,
        crash: bool = False, seed: int = 0) -> None:
    """Arm ``site`` to fail. See module docstring for trigger semantics."""
    with _lock:
        _triggers.setdefault(site, []).append(
            _Trigger(on=on, every=every, p=p, times=times, crash=crash,
                     seed=seed, site=site))
        _recompute_active()
    logger.info("fault armed: site=%s on=%s every=%s p=%s times=%s crash=%s",
                site, on, every, p, times, crash)


def disarm(site: Optional[str] = None) -> None:
    """Remove triggers for ``site`` (all sites when None); counters stay."""
    with _lock:
        if site is None:
            _triggers.clear()
        else:
            _triggers.pop(site, None)
        _recompute_active()


def reset() -> None:
    """Disarm everything and zero all invocation counters."""
    with _lock:
        _triggers.clear()
        _counts.clear()
        _recompute_active()


def count(site: str) -> int:
    """How many times ``site`` has fired its invocation counter.

    Counting only happens while any trigger is armed (the fast path is a
    single bool check), so this is a debugging/testing aid, not telemetry.
    """
    return _counts.get(site, 0)


def fire(site: str) -> None:
    """Mark one invocation of ``site``; raise if an armed trigger matches."""
    _ensure_env()
    if not _active:
        return
    fired = None
    with _lock:
        n = _counts.get(site, 0) + 1
        _counts[site] = n
        # matches() mutates trigger state (times countdown, RNG draw), so
        # it must run under the same lock as the registries — two threads
        # racing a times=1 trigger would otherwise both see times==1 and
        # fire it twice
        for trig in _triggers.get(site, ()):
            if trig.matches(n):
                fired = trig
                break
    if fired is not None:
        exc = InjectedCrash(site, n) if fired.crash else InjectedFault(site, n)
        logger.warning("fault fired: site=%s invocation=%d kind=%s",
                       site, n, type(exc).__name__)
        raise exc


@contextlib.contextmanager
def inject(site: str, **kwargs):
    """Arm ``site`` for the duration of a ``with`` block, then restore the
    site's previous triggers (counters are left running)."""
    prev = list(_triggers.get(site, ()))
    arm(site, **kwargs)
    try:
        yield
    finally:
        with _lock:
            if prev:
                _triggers[site] = prev
            else:
                _triggers.pop(site, None)
            _recompute_active()


def load_spec(spec: str) -> None:
    """Arm sites from a ``MXNET_TPU_FAULTS``-grammar string."""
    entries = [e.strip() for e in spec.split(";") if e.strip()]
    seed = 0
    body = []
    for entry in entries:  # seed= applies to all p= entries, wherever written
        if entry.startswith("seed="):
            seed = int(entry[5:])
        else:
            body.append(entry)
    for entry in body:
        parts = entry.split(":")
        site, opts = parts[0], parts[1:]
        kw: dict = {"seed": seed}
        for o in opts:
            if o == "crash":
                kw["crash"] = True
            elif "=" in o:
                k, v = o.split("=", 1)
                if k in ("on", "every", "times"):
                    kw[k] = int(v)
                elif k == "p":
                    kw["p"] = float(v)
                else:
                    raise ValueError(f"unknown fault option {o!r} in {entry!r}")
            else:
                raise ValueError(f"unknown fault option {o!r} in {entry!r}")
        arm(site, **kw)


def _ensure_env() -> None:
    global _env_loaded
    # double-checked under its own lock: two worker threads racing the
    # first fire() must not both load the env spec and arm every trigger
    # twice (a times=1 trigger would fire twice, breaking the fixed-seed
    # chaos schedule). A separate lock because load_spec -> arm() takes
    # _lock; the second thread blocks here until the triggers are armed.
    if _env_loaded:
        return
    with _env_lock:
        if _env_loaded:
            return
        # flag flips in the `finally`, AFTER the load: the unlocked
        # fast-path above may only skip the lock once the triggers are
        # fully armed (otherwise an early fire() escapes the fixed-seed
        # schedule); racing threads block on _env_lock until then. The
        # `finally` also makes the load strictly one-shot — a malformed
        # tail entry must not leave the valid head re-armed on every
        # later fire()
        try:
            from .. import config

            spec = config.get("faults")
            if spec:
                load_spec(spec)
        finally:
            _env_loaded = True


def reload_from_env() -> None:
    """Re-read ``MXNET_TPU_FAULTS`` (tests that mutate the env call this)."""
    global _env_loaded
    reset()
    _env_loaded = False
    _ensure_env()
