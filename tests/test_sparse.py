"""Sparse storage types (reference: tests/python/unittest/test_sparse_ndarray.py
and test_sparse_operator.py — numpy as the universal oracle)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ndarray import sparse


def _rand_dense_rows(shape, density=0.3):
    a = np.random.uniform(-1, 1, shape).astype(np.float32)
    keep = np.random.uniform(size=shape[0]) < density
    a[~keep] = 0
    return a


def test_cast_storage_row_sparse_roundtrip():
    a = _rand_dense_rows((10, 4))
    rsp = nd.array(a).tostype("row_sparse")
    assert rsp.stype == "row_sparse"
    assert rsp.shape == (10, 4)
    np.testing.assert_array_equal(rsp.asnumpy(), a)
    back = rsp.tostype("default")
    assert back.stype == "default"
    np.testing.assert_array_equal(back.asnumpy(), a)


def test_cast_storage_csr_roundtrip():
    a = np.random.uniform(-1, 1, (6, 8)).astype(np.float32)
    a[a < 0.3] = 0
    csr = nd.array(a).tostype("csr")
    assert csr.stype == "csr"
    np.testing.assert_array_equal(csr.asnumpy(), a)
    # structure invariants
    indptr = csr.indptr.asnumpy()
    assert indptr[0] == 0 and indptr[-1] == csr.data.shape[0]
    np.testing.assert_array_equal(csr.tostype("default").asnumpy(), a)


def test_row_sparse_array_from_tuple():
    data = np.arange(6, dtype=np.float32).reshape(3, 2)
    idx = np.array([4, 1, 7])
    rsp = sparse.row_sparse_array((data, idx), shape=(9, 2))
    dense = np.zeros((9, 2), np.float32)
    dense[idx] = data
    np.testing.assert_array_equal(rsp.asnumpy(), dense)
    # indices come back sorted (reference invariant)
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 4, 7])


def test_csr_matrix_from_tuple_and_row_slice():
    #  [[1 0 2], [0 0 0], [0 3 0]]
    csr = sparse.csr_matrix((np.array([1., 2., 3.], np.float32),
                             np.array([0, 2, 1]), np.array([0, 2, 2, 3])),
                            shape=(3, 3))
    expect = np.array([[1, 0, 2], [0, 0, 0], [0, 3, 0]], np.float32)
    np.testing.assert_array_equal(csr.asnumpy(), expect)
    sl = csr[1:3]
    np.testing.assert_array_equal(sl.asnumpy(), expect[1:3])


def test_sparse_retain():
    a = _rand_dense_rows((8, 3), density=1.0)
    rsp = sparse.row_sparse_array(nd.array(a))
    kept = sparse.retain(rsp, nd.array([1, 5], dtype="int64"))
    expect = np.zeros_like(a)
    expect[[1, 5]] = a[[1, 5]]
    np.testing.assert_array_equal(kept.asnumpy(), expect)


@pytest.mark.parametrize("transpose_a", [False, True])
def test_csr_dot_dense(transpose_a):
    a = np.random.uniform(-1, 1, (5, 7)).astype(np.float32)
    a[np.abs(a) < 0.5] = 0
    b = np.random.uniform(-1, 1, (5 if transpose_a else 7, 4)).astype(np.float32)
    csr = nd.array(a).tostype("csr")
    out = sparse.dot(csr, nd.array(b), transpose_a=transpose_a)
    expect = (a.T if transpose_a else a) @ b
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5, atol=1e-5)


def test_rsp_add_rsp():
    a = _rand_dense_rows((10, 3))
    b = _rand_dense_rows((10, 3))
    out = sparse.add(nd.array(a).tostype("row_sparse"), nd.array(b).tostype("row_sparse"))
    assert out.stype == "row_sparse"
    np.testing.assert_allclose(out.asnumpy(), a + b, rtol=1e-6)


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (4, 2))
    assert z.stype == "row_sparse" and z.shape == (4, 2)
    assert np.all(z.asnumpy() == 0)
    zc = sparse.zeros("csr", (3, 5))
    assert zc.stype == "csr" and np.all(zc.asnumpy() == 0)


def test_sparse_save_load(tmp_path):
    a = _rand_dense_rows((6, 2))
    b = np.random.uniform(size=(3, 3)).astype(np.float32)
    b[b < 0.5] = 0
    fname = str(tmp_path / "mixed.params")
    nd.save(fname, {"rsp": nd.array(a).tostype("row_sparse"),
                    "csr": nd.array(b).tostype("csr"),
                    "dense": nd.array(b)})
    loaded = nd.load(fname)
    assert loaded["rsp"].stype == "row_sparse"
    assert loaded["csr"].stype == "csr"
    assert loaded["dense"].stype == "default"
    np.testing.assert_array_equal(loaded["rsp"].asnumpy(), a)
    np.testing.assert_array_equal(loaded["csr"].asnumpy(), b)


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.random.uniform(size=(8, 4)).astype(np.float32)
    kv.init("emb", nd.array(w))
    out = sparse.zeros("row_sparse", (8, 4))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array([2, 6], dtype="int64"))
    expect = np.zeros_like(w)
    expect[[2, 6]] = w[[2, 6]]
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)


def test_optimizer_lazy_update_rows_only():
    """Rows absent from a row_sparse grad must NOT be touched (lazy update,
    reference sgd_update w/ lazy_update=True)."""
    opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9, rescale_grad=1.0, wd=0.0)
    w = nd.array(np.ones((6, 3), np.float32))
    state = opt.create_state(0, w)
    g = sparse.row_sparse_array((np.full((2, 3), 2.0, np.float32), np.array([1, 4])),
                                shape=(6, 3))
    state = opt.update(0, w, g, state)
    got = w.asnumpy()
    np.testing.assert_allclose(got[[0, 2, 3, 5]], 1.0)
    np.testing.assert_allclose(got[[1, 4]], 1.0 - 0.5 * 2.0)
    # second update exercises momentum state scatter
    state = opt.update(0, w, g, state)
    got2 = w.asnumpy()
    np.testing.assert_allclose(got2[[0, 2, 3, 5]], 1.0)
    assert np.all(got2[[1, 4]] < got[[1, 4]])


def test_gradient_compression_2bit():
    """Error-feedback 2-bit compression (reference: gradient_compression.cc):
    quantized push sends ±threshold/0; residual carries the error so the
    running sum converges to the true gradient sum."""
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", nd.zeros((4,)))
    # |g| <= threshold keeps the residual bounded; above threshold the 2-bit
    # scheme saturates at one ±threshold per push (same as the reference)
    g = np.array([0.3, 0.45, -0.5, 0.1], np.float32)
    total = np.zeros(4, np.float32)
    out = nd.zeros((4,))
    steps = 8
    for _ in range(steps):
        kv.push("w", nd.array(g))
        kv.pull("w", out=out)
        q = out.asnumpy()
        # every transmitted value is one of {-thr, 0, +thr}
        assert set(np.round(np.abs(q) / 0.5).astype(int)) <= {0, 1}
        total += q
    # error feedback: cumulative quantized sum tracks the true sum to within
    # one residual (±threshold) per element
    np.testing.assert_allclose(total, g * steps, atol=0.5 + 1e-6)


def test_kvstore_sparse_push_no_updater():
    """rsp push scatter-adds into a dense-stored table."""
    kv = mx.kv.create("local")
    kv.init("emb", nd.zeros((6, 2)))
    g = sparse.row_sparse_array((np.ones((2, 2), np.float32), np.array([1, 4])),
                                shape=(6, 2))
    kv.push("emb", g)
    kv.push("emb", g)
    out = nd.zeros((6, 2))
    kv.pull("emb", out=out)
    expect = np.zeros((6, 2), np.float32)
    expect[[1, 4]] = 2.0
    np.testing.assert_allclose(out.asnumpy(), expect)


def test_kvstore_sparse_push_lazy_optimizer():
    """rsp push through set_optimizer triggers the lazy row update (cold rows
    stay untouched) — the unreachable-path repro from review."""
    kv = mx.kv.create("device")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    w = np.ones((6, 2), np.float32)
    kv.init("emb", nd.array(w))
    g = sparse.row_sparse_array((np.full((2, 2), 0.25, np.float32), np.array([0, 3])),
                                shape=(6, 2))
    kv.push("emb", g)
    out = nd.zeros((6, 2))
    kv.pull("emb", out=out)
    got = out.asnumpy()
    np.testing.assert_allclose(got[[1, 2, 4, 5]], 1.0)
    np.testing.assert_allclose(got[[0, 3]], 0.75)


def test_kvstore_row_sparse_pull_requires_sparse_out():
    kv = mx.kv.create("local")
    kv.init("emb", nd.zeros((4, 2)))
    with pytest.raises(MXNetError, match="row_sparse out"):
        kv.row_sparse_pull("emb", out=nd.zeros((4, 2)), row_ids=nd.array([1]))


def test_sparse_errors():
    with pytest.raises(MXNetError):
        nd.array(np.ones((3,))).tostype("row_sparse")  # ndim < 2
    with pytest.raises(MXNetError):
        sparse.csr_matrix((np.ones(1), np.zeros(1), np.array([0, 1])))  # no shape


def test_int64_indices_narrow_cleanly():
    """int64 host indices narrow to int32 with NO jax truncation warning
    (round-2 verdict missing #5: the x64 stance)."""
    import warnings

    data = np.ones((3, 2), np.float32)
    idx = np.array([0, 2, 5], np.int64)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        rsp = sparse.row_sparse_array((data, idx), shape=(6, 2))
    assert rsp._aux[0].dtype == np.int32
    np.testing.assert_array_equal(np.asarray(rsp._aux[0]), [0, 2, 5])


def test_int64_indices_overflow_raises():
    from mxnet_tpu.base import MXNetError

    data = np.ones((2, 2), np.float32)
    idx = np.array([0, 2 ** 40], np.int64)
    with pytest.raises(MXNetError, match="int32 range"):
        sparse.row_sparse_array((data, idx), shape=(2 ** 40 + 1, 2))


def test_int64_csr_narrow_and_overflow():
    import warnings

    from mxnet_tpu.base import MXNetError

    data = np.array([1.0, 2.0], np.float32)
    indices = np.array([0, 1], np.int64)
    indptr = np.array([0, 1, 2], np.int64)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        csr = sparse.csr_matrix((data, indices, indptr), shape=(2, 2))
    assert csr._aux[0].dtype == np.int32
    with pytest.raises(MXNetError, match="int32 range"):
        sparse.csr_matrix((data, np.array([0, 2 ** 35], np.int64), indptr),
                          shape=(2, 2 ** 35 + 1))


def test_int64_params_roundtrip(tmp_path):
    """Saving int64 payloads keeps them int64 on disk; loading narrows with
    validation (and raises on values that cannot narrow)."""
    import warnings

    from mxnet_tpu import nd
    from mxnet_tpu.serialization import load_ndarrays, save_ndarrays

    f = str(tmp_path / "i64.params")
    vals = np.array([1, 2 ** 20, -5], np.int64)
    save_ndarrays(f, {"x": vals})
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        back = load_ndarrays(f)
    np.testing.assert_array_equal(back["x"].asnumpy(), vals)

    from mxnet_tpu.base import MXNetError

    save_ndarrays(f, {"big": np.array([2 ** 40], np.int64)})
    with pytest.raises(MXNetError, match="int32 range"):
        load_ndarrays(f)


def test_storage_fallback_warns_once():
    """Densify at an op boundary warns once per op (reference: 'Storage type
    fallback' executor log), silenceable via MXNET_STORAGE_FALLBACK_WARN=0."""
    import warnings

    from mxnet_tpu.ndarray import _DENSIFY_WARNED

    _DENSIFY_WARNED.discard("tanh")
    rsp = sparse.row_sparse_array(
        (np.ones((2, 3), np.float32), np.array([0, 2], np.int64)), shape=(4, 3))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _ = nd.tanh(rsp)
        _ = nd.tanh(rsp)  # second call: no new warning
    fallback = [x for x in w if "storage type fallback" in str(x.message).lower()]
    assert len(fallback) == 1


def test_storage_dispatch_dot_csr_no_densify_warning():
    """nd.dot(csr, dense) must take the registered sparse path (round-4
    FInferStorageType analog), not the densify fallback."""
    import warnings

    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import sparse as sp

    dense = np.zeros((4, 3), np.float32)
    dense[0, 1] = 2.0
    dense[2, 2] = 3.0
    csr = sp.cast_storage(nd.array(dense), "csr")
    rhs = nd.array(np.random.RandomState(0).rand(3, 5).astype(np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any densify warning -> failure
        out = nd.dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy(), rtol=1e-5)


def test_registry_lazy_sgd_touches_only_live_rows():
    """nd.sgd_update(..., lazy_update=True) with an rsp grad: untouched rows
    must see NO update — not even weight decay (reference SGDUpdateRspImpl)."""
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import sparse as sp

    w = nd.array(np.ones((6, 3), np.float32))
    grad = sp.row_sparse_array((np.full((2, 3), 1.0, np.float32), [1, 4]),
                               shape=(6, 3))
    new_w = nd.sgd_update(w, grad, lr=0.5, wd=0.1, lazy_update=True)
    out = new_w.asnumpy()
    # touched rows: w - lr*(g + wd*w) = 1 - 0.5*(1 + 0.1) = 0.45
    np.testing.assert_allclose(out[[1, 4]], 0.45, rtol=1e-6)
    # untouched rows: exactly unchanged (no wd decay — lazy semantics)
    np.testing.assert_array_equal(out[[0, 2, 3, 5]], 1.0)


def test_registry_lazy_adam_states_rows_only():
    """adam_update(lazy_update=True): mean/var state rows outside the grad
    stay zero — the rows-only state math that makes rsp worth having."""
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import sparse as sp

    w = nd.array(np.ones((5, 2), np.float32))
    mean = nd.array(np.zeros((5, 2), np.float32))
    var = nd.array(np.zeros((5, 2), np.float32))
    grad = sp.row_sparse_array((np.full((1, 2), 2.0, np.float32), [3]),
                               shape=(5, 2))
    new_w, new_m, new_v = nd.adam_update(w, grad, mean, var, lr=0.1,
                                         lazy_update=True)
    assert not np.allclose(new_w.asnumpy()[3], 1.0)
    np.testing.assert_array_equal(new_w.asnumpy()[[0, 1, 2, 4]], 1.0)
    np.testing.assert_array_equal(new_m.asnumpy()[[0, 1, 2, 4]], 0.0)
    assert np.all(new_m.asnumpy()[3] != 0.0)


def test_embedding_sparse_grad_end_to_end_no_densify():
    """Embedding(sparse_grad=True) + Trainer: the optimizer consumes a
    compacted RowSparseNDArray (no densify warning anywhere), untouched
    embedding rows stay bit-identical under wd>0, and training learns."""
    import warnings

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    vocab, dim = 50, 8
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    dense_out = nn.Dense(1)
    dense_out.initialize()
    params = {**emb.collect_params(), **dense_out.collect_params()}
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.5, "wd": 0.01})
    ids = nd.array(np.array([[1, 3], [3, 7]]), dtype="int32")
    w_before = emb.weight.data().asnumpy().copy()
    losses = []
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for _ in range(5):
            with autograd.record():
                h = emb(ids).reshape((2, -1))
                out = dense_out(h)
                loss = (out ** 2).sum()
            loss.backward()
            trainer.step(2)
            losses.append(float(loss.asnumpy()))
    w_after = emb.weight.data().asnumpy()
    touched = [1, 3, 7]
    untouched = [r for r in range(vocab) if r not in touched]
    # lazy semantics: untouched rows bit-identical despite wd=0.01
    np.testing.assert_array_equal(w_after[untouched], w_before[untouched])
    assert not np.allclose(w_after[touched], w_before[touched])
    assert losses[-1] < losses[0]


def test_embedding_sparse_grad_symbolic_export(tmp_path):
    """Round-4 advisor: HybridBlock.export of a sparse_grad Embedding must
    not crash in _record_rows (symbolic forward passes a Symbol, which is
    neither a Tracer nor a concrete array)."""
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn

    emb = nn.Embedding(20, 4, sparse_grad=True)
    emb.initialize()
    emb.hybridize()
    ids = nd.array(np.array([[1, 2]]), dtype="int32")
    emb(ids)
    emb.export(str(tmp_path / "emb"))
    assert (tmp_path / "emb-symbol.json").exists()


def test_embedding_sparse_rows_skip_inference_forwards():
    """Round-4 advisor: rows touched only by inference batches must NOT
    enter the next lazy update (reference lazy_update semantics: only rows
    present in the gradient are updated)."""
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import nn

    emb = nn.Embedding(20, 4, sparse_grad=True)
    emb.initialize()
    emb(nd.array(np.array([[5, 6]]), dtype="int32"))  # eval-only forward
    assert emb.weight._sparse_rows is None
    with autograd.record():
        emb(nd.array(np.array([[1, 2]]), dtype="int32"))
    rows = set(np.asarray(emb.weight._sparse_rows).tolist())
    assert rows == {1, 2}  # 5/6 from the eval batch are absent
