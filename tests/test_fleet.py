"""Fleet observability (ISSUE 9, docs/OBSERVABILITY.md "Fleet view"):
cross-rank snapshot/aggregation, straggler detection, goodput ledger, the
ProgramReport-derived FLOPs model feeding train_mfu, percentile exporters,
and the telemetry-off hot-path contract."""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, nd, observability as obs, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import fleet as fleet_mod
from mxnet_tpu.observability import goodput as gp
from mxnet_tpu.observability.fleet import FleetAggregator, FleetSnapshotter
from mxnet_tpu.observability.metrics import Registry, series_percentile
from mxnet_tpu.parallel import TrainStep


# -- helpers -----------------------------------------------------------------
def _dense_step(seed=0, units=16, in_units=8, batch=4):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(units, in_units=in_units, activation="relu"),
            nn.Dense(4, in_units=units))
    net.initialize()
    _ = net(nd.ones((batch, in_units)))
    ts = TrainStep(net, lambda o, y: ((o - y) ** 2).mean(),
                   opt.SGD(learning_rate=0.01))
    return ts, (nd.ones((batch, in_units)), nd.zeros((batch, 4)))


def _write_snapshot(fleet_dir, rank, gen, metrics=None, events=None,
                    ts=1000.0):
    """Fabricate one rank's snapshot files the way FleetSnapshotter
    writes them."""
    d = os.path.join(str(fleet_dir), f"telemetry-h{rank}")
    os.makedirs(d, exist_ok=True)
    if metrics is not None:
        payload = {"meta": {"rank": rank, "generation": gen, "pid": 1,
                            "run": "r", "ts": ts}, "metrics": metrics}
        with open(os.path.join(d, f"metrics-g{gen}.json"), "w") as f:
            json.dump(payload, f)
    if events is not None:
        with open(os.path.join(d, f"events-g{gen}.jsonl"), "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
    return d


def _step_hist(values, buckets=(0.1, 1.0, 10.0)):
    """A metrics-dump histogram entry from raw observations."""
    r = Registry()
    h = r.histogram("train_step_seconds", buckets=buckets)
    for v in values:
        h.observe(v, loop="train_step")
    return r.snapshot()


def _step_event(step, seconds, ts, run="r"):
    return {"ts": ts, "run": run, "host": 0, "step": step,
            "event": "train_step", "loss": 1.0, "step_seconds": seconds}


# -- percentile exporters (satellite 1) --------------------------------------
def test_histogram_percentiles_in_json_snapshot():
    r = Registry()
    h = r.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for _ in range(90):
        h.observe(0.05, op="x")
    for _ in range(10):
        h.observe(0.5, op="x")
    snap = r.snapshot()["lat_seconds"]["series"][0]["value"]
    assert snap["p50"] == 0.1   # bucket upper edge containing the median
    assert snap["p95"] == 1.0
    assert snap["p99"] == 1.0
    # consumers get the same numbers the live API computes
    assert snap["p50"] == h.percentile(0.5, op="x")
    assert snap["p95"] == h.percentile(0.95, op="x")


def test_histogram_percentiles_in_prometheus_export():
    r = Registry()
    h = r.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for _ in range(20):
        h.observe(0.05, op="x")
    text = r.to_prometheus()
    assert '# TYPE lat_seconds_p50 gauge' in text
    assert 'lat_seconds_p50{op="x"} 0.1' in text
    assert 'lat_seconds_p95{op="x"} 0.1' in text
    assert 'lat_seconds_p99{op="x"} 0.1' in text


def test_series_percentile_merged_buckets():
    # the fleet aggregator merges raw bucket counts across ranks, then
    # derives percentiles with the same shared helper
    s = {"count": 100, "max": 0.9,
         "buckets": [50, 45, 5]}  # edges (0.1, 1.0) + overflow
    assert series_percentile(s, (0.1, 1.0), 0.5) == 0.1
    # the 99th sample sits in the +Inf overflow bucket: the observed max
    # is the tightest honest answer
    assert series_percentile(s, (0.1, 1.0), 0.99) == 0.9
    assert series_percentile(None, (0.1,), 0.5) is None
    assert series_percentile({"count": 0, "max": None, "buckets": [0, 0]},
                             (0.1,), 0.5) is None


# -- FLOPs model (acceptance: hand-counted LeNet + tiny-GPT2) ---------------
def test_flops_lenet_step_hand_counted():
    """The LeNet step program's dot census against the hand count.

    Forward: conv (8,1,28,28)*(6,1,5,5)->(8,6,28,28) = 2*37632*25;
    dense1 (8,1176)x(1176,32) = 2*8*32*1176; dense2 = 2*8*10*32.
    Backward (params only — x is not differentiated, so no conv dgrad):
    conv wgrad mirrors the forward conv's cost; dense1/dense2 each add a
    wgrad + a dgrad mirroring their forward cost."""
    from mxnet_tpu import analysis, gluon

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(6, 5, padding=2, activation="tanh"),
            nn.MaxPool2D(2, 2), nn.Flatten(),
            nn.Dense(32, activation="tanh"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(8, 1, 28, 28).astype(np.float32))
    y = nd.array(np.arange(8) % 10)
    _ = net(x)
    ts = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                   opt.create("adam", learning_rate=1e-3))
    rep = analysis.audit_lowered(ts.lower_hlo(x, y))
    est = gp.program_flops(rep)
    conv_fwd = 2 * (8 * 6 * 28 * 28) * (1 * 5 * 5)
    d1_fwd = 2 * 8 * 32 * 1176
    d2_fwd = 2 * 8 * 10 * 32
    expected = (conv_fwd * 2) + (d1_fwd * 3) + (d2_fwd * 3)
    assert est.total == expected == 5584896
    assert est.n_approx == 0  # every dot priced from parsed dims
    assert est.by_op["convolution"] == conv_fwd * 2
    assert ts.model_flops_per_step(x, y) == expected


def test_flops_tiny_gpt2_step_hand_counted():
    """Tiny-GPT2 LM step = 3x the analytic forward dot count (every dot's
    lhs AND rhs need grads — the embedding gather feeds them all)."""
    from mxnet_tpu import analysis
    from mxnet_tpu.models import gpt2

    B, T, d, h, V = 2, 32, 32, 2, 64
    mx.random.seed(0)
    net = gpt2.get_gpt2("gpt2_tiny", dropout=0.0, num_layers=2, units=d,
                        num_heads=h, max_length=64, vocab_size=V)
    net.initialize()
    ids = nd.array(np.random.RandomState(0).randint(0, V, (B, T)),
                   dtype="int32")
    _ = net(ids)
    lbl = nd.array(np.random.RandomState(1).randint(0, V, (B, T)),
                   dtype="int32")
    ts = TrainStep(net, gpt2.lm_loss, opt.Adam(learning_rate=1e-3))
    est = gp.program_flops(analysis.audit_lowered(ts.lower_hlo(ids, lbl)))
    ch = d // h
    layer_fwd = (2 * B * T * d * 3 * d        # fused qkv projection
                 + 2 * (2 * B * h * T * T * ch)  # scores + att@V
                 + 2 * B * T * d * d          # output projection
                 + 2 * (2 * B * T * d * 4 * d))  # ffn1 + ffn2
    fwd = 2 * layer_fwd + 2 * B * T * d * V   # 2 layers + tied LM head
    assert est.total == 3 * fwd == 11796480
    assert est.n_approx == 0


def test_flops_window_census_counts_scan_body_once():
    ts, (x, y) = _dense_step()
    single = ts.model_flops_per_step(x, y)
    assert single and single > 0
    # the fused window's scan body appears once in the program text
    assert ts.model_flops_per_step(x, y, window=2) == single


def test_op_flops_fallback_is_flagged():
    from mxnet_tpu.analysis import Op

    # no parsed dims: the sqrt fallback prices an unbatched dot exactly
    op = Op("dot_general", "f32", (8, 10), ("f32",) * 3, 1,
            shapes=((8, 32), (32, 10), (8, 10)))
    assert gp.op_flops(op) == pytest.approx(2 * 8 * 10 * 32)
    # parsed dims inconsistent with the operand shapes: STILL the
    # fallback, and still flagged approx (not reported as exact)
    bad = Op("dot_general", "f32", (8, 10), ("f32",) * 3, 1,
             shapes=((8, 32), (32, 10), (8, 10)),
             dot_meta={"lhs_contracting": (7,), "lhs_batching": ()})
    # an unparseable convolution has no usable fallback: unpriced
    conv = Op("convolution", "f32", (8, 6, 28, 28), ("f32",) * 3, 1,
              shapes=((8, 1, 28, 28), (6, 1, 5, 5), (8, 6, 28, 28)))
    assert gp.op_flops(conv) is None
    rep_like = type("R", (), {"ops": [op, bad, conv]})
    est = gp.program_flops(rep_like)
    assert est.n_dots == 2 and est.n_approx == 2
    assert est.n_unpriced == 1


# -- train_mfu gauge ---------------------------------------------------------
def test_train_mfu_gauge_from_flops(tmp_path):
    config.set("peak_flops", 1e9)
    try:
        obs.enable(str(tmp_path / "run"))
        ts, (x, y) = _dense_step(seed=3)
        ts(x, y)
        ts(x, y)
        flops = obs.REGISTRY.get("train_model_flops_per_step").value()
        assert flops == ts.model_flops_per_step(x, y)
        mfu = obs.REGISTRY.get("train_mfu").value()
        assert mfu is not None and mfu > 0
        # mfu = flops / dt / peak for the LAST step
        assert mfu < 1e9  # sanity: finite, scaled by the configured peak
    finally:
        config.set("peak_flops", 0.0)
        obs.disable()


# -- goodput ledger ----------------------------------------------------------
def test_goodput_ledger_buckets_sum_to_wall():
    ev = [
        _step_event(1, 1.0, ts=101.0),
        _step_event(2, 1.0, ts=102.0),
        {"ts": 104.0, "event": "checkpoint_save", "seconds": 1.5},
        _step_event(3, 1.0, ts=106.0),
        {"ts": 107.5, "event": "data_stall", "wait_seconds": 1.0},
    ]
    for e in ev:
        e.setdefault("_gen", 0)
    rep = gp.goodput_ledger(ev)
    assert rep.wall_start == 100.0 and rep.wall_end == 107.5
    assert sum(rep.buckets.values()) == pytest.approx(rep.wall, rel=1e-9)
    assert rep.buckets["train"] == pytest.approx(3.0)
    assert rep.buckets["checkpoint"] == pytest.approx(1.5)
    assert rep.buckets["data_stall"] == pytest.approx(1.0)
    assert rep.buckets["idle"] == pytest.approx(2.0)
    assert rep.goodput == pytest.approx(3.0 / 7.5)


def test_goodput_ledger_overlap_priority_no_double_count():
    # a checkpoint overlapping a train step: the overlap is counted ONCE,
    # for the higher-priority category
    ev = [_step_event(1, 2.0, ts=102.0),
          {"ts": 102.0, "event": "checkpoint_save", "seconds": 1.0,
           "_gen": 0}]
    ev[0]["_gen"] = 0
    rep = gp.goodput_ledger(ev)
    assert sum(rep.buckets.values()) == pytest.approx(rep.wall)
    assert rep.buckets["checkpoint"] == pytest.approx(1.0)
    assert rep.buckets["train"] == pytest.approx(1.0)


def test_goodput_ledger_reformation_gap_between_generations():
    ev = ([_step_event(i, 0.5, ts=100.0 + i) for i in (1, 2, 3)]
          + [{"ts": 110.0, "event": "elastic_restore", "seconds": 1.0,
              "_gen": 1}]
          + [_step_event(i, 0.5, ts=108.0 + i) for i in (3, 4)])
    for e in ev[:3]:
        e["_gen"] = 0
    for e in ev[4:]:
        e["_gen"] = 1
    rep = gp.goodput_ledger(ev)
    # gen-0 ends at 103, gen-1 starts at 109 (restore event interval
    # [109,110] claims its share) -> downtime attributed to re-formation
    assert rep.buckets["reformation"] == pytest.approx(6.0)
    assert rep.buckets["restore"] == pytest.approx(1.0)
    assert rep.goodput < 1.0
    assert sum(rep.buckets.values()) == pytest.approx(rep.wall)


def test_goodput_ledger_empty():
    assert gp.goodput_ledger([]) is None
    assert gp.goodput_ledger([{"event": "x"}]) is None


# -- straggler detection -----------------------------------------------------
def test_detect_stragglers_flags_slow_rank():
    events = []
    for step in range(1, 6):
        for rank in range(4):
            dt = 1.2 if (rank == 2 and step == 3) else 0.1
            e = _step_event(step, dt, ts=100.0 + step)
            e["_rank"], e["_gen"] = rank, 0
            events.append(e)
    stragglers, timeline = fleet_mod.detect_stragglers(events, factor=3.0)
    assert len(stragglers) == 1
    s = stragglers[0]
    assert s["rank"] == 2 and s["step"] == 3 and s["kind"] == "step"
    assert s["ratio"] == pytest.approx(12.0)
    skews = {t["step"]: t for t in timeline}
    assert skews[3]["skew_seconds"] == pytest.approx(1.1)
    assert skews[3]["slowest_rank"] == 2
    assert skews[1]["skew_seconds"] == pytest.approx(0.0)


def test_detect_stragglers_needs_two_ranks_and_absolute_floor():
    # single-rank steps never flag; microsecond skew under the absolute
    # floor never flags even at a huge ratio
    solo = [dict(_step_event(1, 5.0, ts=100.0), _rank=0, _gen=0)]
    assert fleet_mod.detect_stragglers(solo, factor=2.0) == ([], [])
    tiny = []
    for rank in range(3):
        dt = 1e-5 if rank != 2 else 9e-5
        tiny.append(dict(_step_event(1, dt, ts=100.0), _rank=rank, _gen=0))
    stragglers, _tl = fleet_mod.detect_stragglers(tiny, factor=2.0)
    assert stragglers == []


# -- snapshot + aggregation --------------------------------------------------
def test_snapshotter_roundtrip(tmp_path):
    run = tmp_path / "run"
    fdir = tmp_path / "fleet"
    obs.REGISTRY.reset()
    try:
        obs.enable(str(run))
        obs.histogram("train_step_seconds").observe(0.2, loop="train_step")
        obs.emit("train_step", step=1, step_seconds=0.2, loss=1.0)
        snap = FleetSnapshotter(str(fdir), rank=0, generation=0,
                                interval=60.0)
        assert snap.snapshot()
        d = fdir / "telemetry-h0"
        payload = json.loads((d / "metrics-g0.json").read_text())
        assert payload["meta"]["rank"] == 0
        assert "train_step_seconds" in payload["metrics"]
        lines = (d / "events-g0.jsonl").read_text().splitlines()
        assert any(json.loads(ln)["event"] == "train_step" for ln in lines)
        # throttled step-boundary variant: a fresh snapshot just landed
        assert snap.maybe_snapshot() is False
    finally:
        obs.disable()
        obs.REGISTRY.reset()

    agg = FleetAggregator(str(fdir))
    report = agg.collect()
    assert report is not None
    assert set(report.ranks) == {0}
    rs = report.ranks[0]
    assert rs.step_hist["count"] == 1
    assert report.events and report.events[0]["_rank"] == 0


def test_aggregator_merges_ranks_and_generations(tmp_path):
    # rank 0 lived through generations 0 and 1; rank 1 joined at gen 1
    _write_snapshot(tmp_path, 0, 0, metrics=_step_hist([0.1, 0.1]),
                    events=[_step_event(1, 0.1, 100.1),
                            _step_event(2, 0.1, 100.2)], ts=100.2)
    _write_snapshot(tmp_path, 0, 1, metrics=_step_hist([0.1]),
                    events=[_step_event(3, 0.1, 105.0)], ts=105.0)
    _write_snapshot(tmp_path, 1, 1, metrics=_step_hist([0.3]),
                    events=[_step_event(3, 0.3, 105.2)], ts=105.2)
    report = FleetAggregator(str(tmp_path)).collect()
    assert report.generations == [0, 1]
    assert set(report.ranks) == {0, 1}
    assert sorted(report.ranks[0].generations) == [0, 1]
    assert report.ranks[0].step_hist["count"] == 3  # merged across gens
    assert report.ranks[1].generations == [1]
    # the gen-0 -> gen-1 gap lands in the reformation bucket
    assert report.goodput.buckets["reformation"] > 0
    gens = {e["_gen"] for e in report.events}
    assert gens == {0, 1}


def test_aggregator_skips_torn_snapshot_and_counts_it(tmp_path):
    _write_snapshot(tmp_path, 0, 0, metrics=_step_hist([0.1]),
                    events=[_step_event(1, 0.1, 100.1)])
    d1 = os.path.join(str(tmp_path), "telemetry-h1")
    os.makedirs(d1)
    with open(os.path.join(d1, "metrics-g0.json"), "w") as f:
        f.write('{"meta": {"rank": 1}, "metr')  # torn mid-write
    agg = FleetAggregator(str(tmp_path))
    report = agg.collect()
    assert report is not None  # the torn rank never crashes the merge
    assert report.torn_snapshots == 1
    assert report.ranks[0].step_hist["count"] == 1
    before = obs.REGISTRY.counter("fleet_torn_snapshots_total").total()
    agg.poll()
    agg.poll()  # second poll must not double count the same torn file
    after = obs.REGISTRY.counter("fleet_torn_snapshots_total").total()
    assert after - before == 1


def test_aggregator_empty_dir(tmp_path):
    assert FleetAggregator(str(tmp_path)).collect() is None
    (tmp_path / "telemetry-h0").mkdir()  # rank dir with no snapshots yet
    assert FleetAggregator(str(tmp_path)).collect() is None


def test_aggregator_torn_snapshots_under_writer_churn(tmp_path):
    """A non-atomic writer killed mid-write, over and over: each torn
    generation is counted (once), never fatal, and a torn file claiming
    a newer heartbeat must not advance the rank's last_ts — a crashed
    replica's half-written snapshot cannot resurrect it (ISSUE 16)."""
    _write_snapshot(tmp_path, 0, 0, metrics=_step_hist([0.1]),
                    events=[_step_event(1, 0.1, 100.1)], ts=100.0)
    d = os.path.join(str(tmp_path), "telemetry-h0")
    agg = FleetAggregator(str(tmp_path))
    before = obs.REGISTRY.counter("fleet_torn_snapshots_total").total()
    torn_written = 0
    # churn: generations 1..4 each appear torn first (writer died
    # mid-write, bogus fresh ts visible in the fragment), get polled,
    # then the writer's replacement completes them
    for gen in range(1, 5):
        path = os.path.join(d, f"metrics-g{gen}.json")
        with open(path, "w") as f:
            f.write('{"meta": {"rank": 0, "generation": %d, '
                    '"ts": 9999.0}, "metr' % gen)
        torn_written += 1
        report, _ = agg.poll()
        assert report is not None  # counted, never fatal
        assert report.torn_snapshots == 1  # only the current fragment
        # the bogus 9999.0 heartbeat in the torn fragment must not leak
        assert report.ranks[0].last_ts == 100.0 + (gen - 1)
        agg.poll()  # re-polling the same torn file never double counts
        _write_snapshot(tmp_path, 0, gen, metrics=_step_hist([0.1]),
                        ts=100.0 + gen)
        report, _ = agg.poll()
        # completed: the generation now folds in and advances the clock
        assert sorted(report.ranks[0].generations) == list(range(gen + 1))
        assert report.ranks[0].last_ts == 100.0 + gen
    after = obs.REGISTRY.counter("fleet_torn_snapshots_total").total()
    assert after - before == torn_written


def test_aggregator_poll_emits_straggler_telemetry(tmp_path):
    events = []
    for step in (1, 2):
        for rank in range(3):
            dt = 2.0 if (rank == 1 and step == 2) else 0.1
            events.append(_step_event(step, dt, ts=100.0 + step))
            events[-1]["host"] = rank
    by_rank = {}
    for e in events:
        by_rank.setdefault(e["host"], []).append(e)
    for rank, evs in by_rank.items():
        _write_snapshot(tmp_path, rank, 0, metrics=_step_hist(
            [e["step_seconds"] for e in evs]), events=evs)
    agg = FleetAggregator(str(tmp_path), straggler_factor=3.0)
    report, new = agg.poll()
    assert [s["rank"] for s in new] == [1]
    assert report.stragglers and report.stragglers[0]["rank"] == 1
    assert obs.REGISTRY.get("straggler_rank").value() == 1
    skew = obs.REGISTRY.get("fleet_step_skew_seconds")
    assert skew is not None and skew.total_count() >= 2
    _report2, new2 = agg.poll()  # same findings: nothing new emitted
    assert new2 == []


def test_merged_percentile_overflow_bucket_is_max_not_inf(tmp_path):
    # the +Inf overflow edge must never become a finite percentile edge:
    # a quantile landing in the overflow bucket reads the observed max
    r = Registry()
    h = r.histogram("decode_tokens_per_s")  # DEFAULT_BUCKETS top edge 60
    for _ in range(10):
        h.observe(120.0)  # every sample past the last edge
    _write_snapshot(tmp_path, 0, 0, metrics=r.snapshot(),
                    events=[_step_event(1, 0.1, 100.1)])
    report = FleetAggregator(str(tmp_path)).collect()
    p99 = report.serving["decode_tokens_per_s"]["p99"]
    assert p99 == 120.0 and np.isfinite(p99)


def test_merge_hist_survives_mismatched_bucket_layouts(tmp_path):
    from mxnet_tpu.observability.fleet import _hist_acc, _hist_pct, \
        _merge_hist

    a = Registry().histogram("train_step_seconds", buckets=(0.1, 1.0))
    b = Registry().histogram("train_step_seconds", buckets=(0.5, 2.0))
    acc = _hist_acc()
    snaps = []
    for hist, v in ((a, 0.05), (b, 0.3), (a, 0.07)):
        hist.observe(v)
        snaps.append(hist._snapshot_value(hist._series[()]))
        hist._series.clear()
    # match, mismatch, then match again: count/sum survive, percentiles
    # degrade to None — never a TypeError
    for s in snaps:
        _merge_hist(acc, s)
    assert acc["count"] == 3
    assert acc["sum"] == pytest.approx(0.42)
    assert acc["buckets"] is None
    assert _hist_pct(acc, 0.5) is None


def test_gen_sorted_orders_numerically():
    from mxnet_tpu.observability.fleet import _gen_sorted

    paths = [f"metrics-g{g}.json" for g in (0, 1, 2, 10, 11)]
    shuffled = sorted(paths)  # lexicographic puts g10/g11 before g2
    assert shuffled != paths
    assert _gen_sorted(shuffled) == paths


def test_snapshot_event_copy_is_incremental(tmp_path):
    run = tmp_path / "run"
    fdir = tmp_path / "fleet"
    obs.REGISTRY.reset()
    try:
        obs.enable(str(run))
        obs.emit("train_step", step=1, step_seconds=0.1, loss=1.0)
        snap = FleetSnapshotter(str(fdir), rank=0, generation=0,
                                interval=60.0)
        assert snap.snapshot()
        obs.emit("train_step", step=2, step_seconds=0.1, loss=1.0)
        assert snap.snapshot()
        lines = (fdir / "telemetry-h0" / "events-g0.jsonl") \
            .read_text().splitlines()
        steps = [json.loads(ln)["step"] for ln in lines
                 if json.loads(ln)["event"] == "train_step"]
        assert steps == [1, 2]  # appended once each, never re-copied
    finally:
        obs.disable()
        obs.REGISTRY.reset()


def test_serving_rollup_percentiles(tmp_path):
    r = Registry()
    h = r.histogram("ttft_seconds")
    for v in (0.02, 0.03, 0.04, 0.4):
        h.observe(v)
    r.gauge("gen_slot_utilization").set(0.75)
    r.counter("gen_requests_total").inc(3, reason="eos")
    _write_snapshot(tmp_path, 0, 0, metrics=r.snapshot(),
                    events=[_step_event(1, 0.1, 100.1)])
    report = FleetAggregator(str(tmp_path)).collect()
    sv = report.serving
    assert sv["ttft_seconds"]["count"] == 4
    assert sv["ttft_seconds"]["p50"] is not None
    assert sv["slot_utilization"] == 0.75
    assert sv["requests"] == {"eos": 3}


# -- fleetreport CLI ---------------------------------------------------------
def test_fleetreport_cli(tmp_path, capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fleetreport", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "fleetreport.py"))
    fr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fr)

    assert fr.main([str(tmp_path / "nothing")]) == 1
    capsys.readouterr()

    for rank in range(2):
        _write_snapshot(tmp_path, rank, 0, metrics=_step_hist([0.1, 0.2]),
                        events=[_step_event(1, 0.1, 100.1),
                                _step_event(2, 0.2, 100.4)])
    assert fr.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "== fleet report" in out and "-- per-rank" in out
    assert "-- goodput" in out
    assert fr.main([str(tmp_path), "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert set(s["ranks"]) == {"0", "1"}
    assert s["goodput"]["buckets"]["train"] > 0


# -- telemetry-off hot path stays one bool check (satellite 2) ---------------
def test_telemetry_off_branch_single_gate():
    """The telemetry-off step must do exactly one ``_obs.enabled()`` read
    and touch neither the registry, the event log, nor the fleet
    snapshot writer."""
    import inspect

    src = inspect.getsource(TrainStep.__call__)
    assert src.count("_obs.enabled()") == 1
    wsrc = inspect.getsource(TrainStep._run_window)
    assert wsrc.count("_obs.enabled()") == 1
    # the snapshot writer is never reachable from the TrainStep hot path:
    # it rides the elastic step-boundary probe / cadence thread instead
    for fn_src in (src, wsrc):
        assert "fleet" not in fn_src and "snapshot" not in fn_src

    was_enabled = obs.enabled()
    obs.disable()
    try:
        ts, (x, y) = _dense_step(seed=7)
        ts(x, y)  # warm + compile outside the probed window
        before = json.dumps(obs.REGISTRY.snapshot(), sort_keys=True)
        ts(x, y)
        after = json.dumps(obs.REGISTRY.snapshot(), sort_keys=True)
        assert before == after  # zero registry mutation with telemetry off
        assert fleet_mod.snapshotter() is None
    finally:
        if was_enabled:  # this suite runs telemetry-off; stay defensive
            obs.disable()


def test_extra_hot_paths_cover_snapshot_writer():
    """Lint contract (satellite 2): the fleet snapshot writer is a
    registered hot path, so JH001/JH002/JH003 hazards in it fail CI."""
    from mxnet_tpu.analysis.astlint import EXTRA_HOT_PATHS

    quals = EXTRA_HOT_PATHS.get("observability/fleet.py")
    assert quals, "fleet snapshot writer must be a registered hot path"
    assert "FleetSnapshotter.maybe_snapshot" in quals
    assert "FleetSnapshotter.snapshot" in quals
    for q in quals:  # every registered qualname must actually exist
        cls_name, meth = q.split(".")
        assert hasattr(getattr(fleet_mod, cls_name), meth)


def test_snapshotter_maybe_snapshot_throttles(tmp_path):
    snap = FleetSnapshotter(str(tmp_path), rank=0, generation=0,
                            interval=30.0)
    assert snap.snapshot()
    t0 = time.perf_counter()
    for _ in range(200):
        assert snap.maybe_snapshot() is False
    per_call = (time.perf_counter() - t0) / 200
    assert per_call < 1e-4  # throttled probe: a clock read + compare
