"""Gluon-facing pipeline / MoE layers over the declarative layout axes.

The shard_map machinery in :mod:`~mxnet_tpu.parallel.pipeline` and
:mod:`~mxnet_tpu.parallel.moe` is functional (params in, acts out); these
blocks wrap it in the Gluon parameter/registration idiom so a pipelined or
expert-parallel model trains through the unchanged ``TrainStep`` path:

  - parameters register under names the :class:`~mxnet_tpu.parallel.Layout`
    rules target (``stages_weight`` -> ``P('pp', ...)``; ``expert_w1/2`` ->
    ``P('ep', 'fsdp', None)`` storage, the ep x fsdp ZeRO composition);
  - the forward reads the *active mesh* (the one ``TrainStep`` stages the
    loss under, from its layout) and dispatches to the sharded formulation
    when the relevant axis is actually there; eager single-device runs
    (init forwards, tests) fall back to the mathematically equivalent
    dense loop, so block construction needs no mesh at all.

docs/PARALLELISM.md walks the composed layouts these enable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .._mesh_state import current_mesh
from ..gluon.block import HybridBlock
from ..ndarray import NDArray
from .moe import _route, moe_ffn
from .pipeline import pipeline_apply

__all__ = ["PipelineStages", "MoEFFN"]

_ACTS = {
    None: lambda a: a,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
}


def _raw(a):
    return a._data if isinstance(a, NDArray) else a


class PipelineStages(HybridBlock):
    """S homogeneous Dense stages, GPipe-pipelined over the ``pp`` axis.

    The stage weights are ONE stacked parameter pair (``stages_weight``
    [S, units, units], ``stages_bias`` [S, units]) so the layout rule
    ``(r"stages_weight$", ("pp", None, None))`` shards stage dispatch as
    data movement GSPMD can see. With an active mesh whose ``pp`` size
    equals S the forward runs :func:`pipeline_apply` (microbatched scan +
    ppermute ring); otherwise the same stages run as a sequential loop —
    identical math, so eager init/eval parity holds.
    """

    def __init__(self, num_stages, units, activation="relu", microbatches=0,
                 dtype="float32", weight_initializer=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if num_stages < 1:
            raise ValueError("num_stages must be >= 1")
        if activation not in _ACTS:
            raise ValueError(f"unknown activation {activation!r}")
        self._S = int(num_stages)
        self._units = int(units)
        self._act = activation
        self._M = int(microbatches)
        with self.name_scope():
            self.stages_weight = self.params.get(
                "stages_weight", shape=(self._S, self._units, self._units),
                dtype=dtype, init=weight_initializer)
            self.stages_bias = self.params.get(
                "stages_bias", shape=(self._S, self._units), dtype=dtype,
                init="zeros")

    def _stage(self, p, act):
        return _ACTS[self._act](act @ p["w"].T + p["b"])

    def hybrid_forward(self, F, x, stages_weight, stages_bias):
        xr, w, b = _raw(x), _raw(stages_weight), _raw(stages_bias)
        mesh = current_mesh()
        if mesh is not None and dict(mesh.shape).get("pp", 1) == self._S \
                and self._S > 1:
            out = pipeline_apply(self._stage, {"w": w, "b": b}, xr, mesh,
                                 axis="pp",
                                 num_microbatches=self._M or None)
        else:
            out = xr
            for s in range(self._S):
                out = self._stage({"w": w[s], "b": b[s]}, out)
        return NDArray(out)


class MoEFFN(HybridBlock):
    """Switch-style top-1 MoE FFN, expert-parallel over the ``ep`` axis.

    Parameters register as ``gate_weight`` [d, E] (replicated compute),
    ``expert_w1`` [E, d, h] and ``expert_w2`` [E, h, d]. The intended
    layout composes ep with ZeRO storage: rule ``(r"expert_w[12]$",
    ("ep", "fsdp", None))`` stores each expert shard fsdp-sliced and
    gathers the fsdp axis for compute, while tokens ride the ``ep`` axis
    (``batch_axes=("ep",)``, the fused dp==ep layout) into
    :func:`moe_ffn`'s all_to_all dispatch/return pair. Without an active
    ep axis the same routing runs dense on one device.

    The Switch load-balance aux loss is available from :func:`moe_ffn`
    for custom training loops; this block returns activations only (the
    gate still trains through the combine weights).
    """

    def __init__(self, d_model, d_hidden, num_experts, capacity_factor=1.25,
                 dtype="float32", weight_initializer=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if num_experts < 1:
            raise ValueError("num_experts must be >= 1")
        self._E = int(num_experts)
        self._cf = float(capacity_factor)
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(d_model, self._E), dtype=dtype,
                init=weight_initializer)
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(self._E, d_model, d_hidden), dtype=dtype,
                init=weight_initializer)
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(self._E, d_hidden, d_model), dtype=dtype,
                init=weight_initializer)

    def _dense(self, x, gate, w1, w2):
        d = x.shape[-1]
        xt = x.reshape(-1, d)
        capacity = int(math.ceil(xt.shape[0] / self._E * self._cf))
        dispatch, combine, _aux = _route(xt, gate, self._E, capacity)
        packed = jnp.einsum("nec,nd->ecd", dispatch, xt.astype(jnp.float32))
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", packed,
                                   w1.astype(jnp.float32)))
        y = jnp.einsum("ech,ehd->ecd", h, w2.astype(jnp.float32))
        out = jnp.einsum("nec,ecd->nd", combine, y)
        return out.reshape(x.shape).astype(x.dtype)

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_w2):
        xr, g, w1, w2 = (_raw(x), _raw(gate_weight), _raw(expert_w1),
                         _raw(expert_w2))
        mesh = current_mesh()
        if mesh is not None and dict(mesh.shape).get("ep", 1) > 1:
            out, _aux = moe_ffn(xr, {"gate": g, "w1": w1, "w2": w2}, mesh,
                                axis="ep", capacity_factor=self._cf)
        else:
            out = self._dense(xr, g, w1, w2)
        return NDArray(out)
