#!/usr/bin/env python
"""Build RecordIO image packs (reference: ``tools/im2rec.py`` /
``tools/im2rec.cc``).

Reads a ``.lst`` file (``idx\\tlabel\\tpath`` per line) and writes
``prefix.rec`` + ``prefix.idx`` in the dmlc RecordIO format readable by both
the Python and native readers. Without OpenCV, images are stored as lossless
npy payloads (PIL-decoded when available); downstream readers detect the
payload format by magic.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(float(parts[0])), float(parts[1]), parts[-1]


def load_image(path, resize=0):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        import PIL.Image

        img = PIL.Image.open(path).convert("RGB")
        if resize:
            w, h = img.size
            scale = resize / min(w, h)
            img = img.resize((int(w * scale), int(h * scale)))
        return np.asarray(img)
    except Exception:
        return np.fromfile(path, dtype=np.uint8)  # raw passthrough


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (writes prefix.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", dest="lst", required=True, help=".lst file")
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--pass-through", action="store_true",
                    help="store original jpeg bytes unmodified (no re-encode)")
    ap.add_argument("--num-thread", type=int, default=1)
    args = ap.parse_args()

    from mxnet_tpu.io.recordio import IndexedRecordIO, IRHeader, pack, pack_img

    rec = IndexedRecordIO(args.prefix + ".idx", args.prefix + ".rec", "w")
    n = 0
    for idx, label, rel in read_list(args.lst):
        path = os.path.join(args.root, rel)
        hdr = IRHeader(0, label, idx, 0)
        if args.pass_through and rel.lower().endswith((".jpg", ".jpeg")):
            with open(path, "rb") as f:
                rec.write_idx(idx, pack(hdr, f.read()))
        else:
            img = load_image(path, args.resize)
            rec.write_idx(idx, pack_img(hdr, img, quality=args.quality))
        n += 1
        if n % 1000 == 0:
            print(f"packed {n} images", file=sys.stderr)
    rec.close()
    print(f"wrote {n} records to {args.prefix}.rec")


if __name__ == "__main__":
    main()
