"""Vision transforms (reference: ``python/mxnet/gluon/data/vision/transforms.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ...block import Block, HybridBlock
from ...nn.basic_layers import HybridSequential
from ....ndarray import NDArray

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "RandomResizedCrop",
           "Resize", "CenterCrop", "RandomFlipLeftRight"]


class Compose(HybridSequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return x.transpose((2, 0, 1))
        return x.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean, self._std = mean, std

    def hybrid_forward(self, F, x):
        mean = jnp.asarray(self._mean, jnp.float32).reshape(-1, 1, 1)
        std = jnp.asarray(self._std, jnp.float32).reshape(-1, 1, 1)
        return (x - NDArray(mean)) / NDArray(std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax

        h, w = self._size
        if x.ndim == 3:
            out = jax.image.resize(x._data.astype(jnp.float32), (h, w, x.shape[2]), "linear")
        else:
            out = jax.image.resize(x._data.astype(jnp.float32), (x.shape[0], h, w, x.shape[3]), "linear")
        return NDArray(out.astype(x._data.dtype))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        ch, cw = self._size
        h, w = x.shape[-3], x.shape[-2]
        y0, x0 = (h - ch) // 2, (w - cw) // 2
        return x[..., y0:y0 + ch, x0:x0 + cw, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation=1):
        super().__init__()
        self._resize = Resize(size)

    def forward(self, x):
        import numpy as np

        h, w = x.shape[-3], x.shape[-2]
        ch = np.random.randint(h // 2, h + 1)
        cw = np.random.randint(w // 2, w + 1)
        y0 = np.random.randint(0, h - ch + 1)
        x0 = np.random.randint(0, w - cw + 1)
        return self._resize(x[..., y0:y0 + ch, x0:x0 + cw, :])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        import numpy as np

        if np.random.rand() < 0.5:
            return NDArray(jnp.flip(x._data, axis=-2))
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        import numpy as np

        if np.random.rand() < 0.5:
            return NDArray(jnp.flip(x._data, axis=-3))
        return x


def _blend(a, b, alpha):
    return a * alpha + b * (1.0 - alpha)


def _gray(x):
    # HWC float; shared BT.601 luma constants (single source: mx.image)
    from ....image import GRAY_COEF

    return (x * jnp.asarray(GRAY_COEF, x.dtype)).sum(axis=-1, keepdims=True)


class RandomBrightness(Block):
    """Scale pixel values by U(1-b, 1+b) (reference transforms)."""

    def __init__(self, brightness, **kwargs):
        super().__init__(**kwargs)
        self._b = float(brightness)

    def forward(self, x):
        import numpy as np

        alpha = 1.0 + np.random.uniform(-self._b, self._b)
        return NDArray(x._data * alpha)


class RandomContrast(Block):
    def __init__(self, contrast, **kwargs):
        super().__init__(**kwargs)
        self._c = float(contrast)

    def forward(self, x):
        import numpy as np

        alpha = 1.0 + np.random.uniform(-self._c, self._c)
        d = x._data.astype(jnp.float32)
        mean = _gray(d).mean()
        return NDArray(_blend(d, mean, alpha).astype(x._data.dtype))


class RandomSaturation(Block):
    def __init__(self, saturation, **kwargs):
        super().__init__(**kwargs)
        self._s = float(saturation)

    def forward(self, x):
        import numpy as np

        alpha = 1.0 + np.random.uniform(-self._s, self._s)
        d = x._data.astype(jnp.float32)
        return NDArray(_blend(d, _gray(d), alpha).astype(x._data.dtype))


class RandomHue(Block):
    """Rotate hue by U(-h, h) via the YIQ approximation the reference's
    image_aug uses."""

    def __init__(self, hue, **kwargs):
        super().__init__(**kwargs)
        self._h = float(hue)

    def forward(self, x):
        import numpy as np

        from ....image import hue_rotation_matrix

        alpha = np.random.uniform(-self._h, self._h)
        m = jnp.asarray(hue_rotation_matrix(alpha))
        d = x._data.astype(jnp.float32)
        return NDArray((d @ m.T).astype(x._data.dtype))


class RandomColorJitter(Block):
    """Brightness/contrast/saturation/hue jitter in one transform."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        import numpy as np

        # reference semantics: sub-transforms applied in random order
        for i in np.random.permutation(len(self._ts)):
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference RandomLighting).

    Constants stay plain Python at class level — jnp arrays here would
    force backend init at import time (bad in DataLoader workers)."""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._a = float(alpha)

    def forward(self, x):
        import numpy as np

        from ....image import PCA_EIGVAL, PCA_EIGVEC

        a = np.random.normal(0, self._a, size=(3,)).astype(np.float32)
        rgb = (np.asarray(PCA_EIGVEC, np.float32) * a
               * np.asarray(PCA_EIGVAL, np.float32)).sum(axis=1)
        return NDArray(x._data + jnp.asarray(rgb, x._data.dtype))


__all__ += ["RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
            "RandomSaturation", "RandomHue", "RandomColorJitter",
            "RandomLighting"]
