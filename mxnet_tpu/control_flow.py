"""Control-flow operators: ``foreach`` / ``while_loop`` / ``cond``.

Reference: ``src/operator/control_flow.cc`` + ``python/mxnet/ndarray/
contrib.py`` — MXNet 1.x runs the body as a *subgraph op* so the loop can
live inside a Symbol and be differentiated. The TPU-native design maps each
construct onto its XLA structured-control-flow primitive (``lax.scan`` /
``lax.while_loop`` / ``lax.cond``): one traced body, compiler-schedulable,
no Python re-entry per iteration — exactly what the task's "no
data-dependent Python control flow inside jit" rule demands.

Autograd: each construct is invoked through the op registry's ``invoke``
path as a dynamically-built OpDef (the same mechanism ``nd.Custom`` uses),
so the replay tape differentiates straight through the ``lax`` primitive
(scan/cond have full VJPs; while_loop is forward-only, as in the reference).
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["foreach", "while_loop", "cond"]


def _as_nd(x):
    from .ndarray import NDArray

    return x if isinstance(x, NDArray) else NDArray(x)


def _raw(x):
    from .ndarray import NDArray

    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _listify(x) -> List:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _tape_call(name, raw_fn, arg_nds):
    """Invoke ``raw_fn(*raw_args)`` through the autograd tape.

    The body closure may capture NDArrays (e.g. weights) that must receive
    gradients — the reference handles this by turning free variables of the
    loop subgraph into implicit op inputs (``control_flow.cc`` subgraph
    cut). Here ``jax.closure_convert`` hoists the captured buffers, and each
    hoisted constant is matched back (by buffer identity) to its live
    NDArray handle so ``backward()`` can reach its ``.grad``.
    """
    from .ndarray import NDArray, _live_ndarrays, invoke
    from .registry import OpDef

    flat = [_raw(a) for a in arg_nds]
    closed = jax.make_jaxpr(raw_fn)(*flat)
    consts = list(closed.consts)
    # match hoisted constants back to live handles by buffer identity so
    # e.g. a closed-over weight's .grad is populated by backward()
    const_nds = []
    for c in consts:
        handle = None
        if isinstance(c, jax.Array):
            handle = next((a for a in _live_ndarrays if a._data is c), None)
        const_nds.append(handle if handle is not None else NDArray(jnp.asarray(c)))
    n_args = len(arg_nds)

    def fn(*all_flat):
        out = jax.core.eval_jaxpr(closed.jaxpr, all_flat[n_args:],
                                  *all_flat[:n_args])
        return tuple(out)

    nout = len(closed.jaxpr.outvars)
    opdef = OpDef(name=name, fn=fn, nout=nout)
    res = invoke(opdef, tuple(list(arg_nds) + const_nds), {})
    return (list(res) if isinstance(res, tuple) else [res]), nout


def foreach(body: Callable, data, init_states):
    """Scan ``body`` over axis 0 of ``data``.

    ``body(data_slice, states) -> (outputs, new_states)`` with NDArray
    inputs/outputs; mirrors ``mx.nd.contrib.foreach``. Returns
    ``(outputs, final_states)`` where each output is stacked along axis 0.
    Lowered to one ``lax.scan`` — a single XLA While with a traced body.
    """
    data_l = _listify(data)
    states_l = _listify(init_states)
    data_was_seq = isinstance(data, (list, tuple))
    states_was_seq = isinstance(init_states, (list, tuple))
    n_data = len(data_l)

    def raw_fn(*flat):
        d_raw = flat[:n_data]
        s_raw = flat[n_data:]

        def step(carry, xs):
            ss = [_as_nd(c) for c in carry]
            xx = [_as_nd(x) for x in xs]
            out, new_s = body(xx if data_was_seq else xx[0],
                              ss if states_was_seq else ss[0])
            out_l = [_raw(o) for o in _listify(out)]
            new_l = [_raw(s) for s in _listify(new_s)]
            return tuple(new_l), tuple(out_l)

        final, stacked = lax.scan(step, tuple(s_raw), tuple(d_raw))
        return tuple(stacked) + tuple(final)

    res, nout = _tape_call("foreach", raw_fn, data_l + states_l)
    n_out = nout - len(states_l)
    outs, finals = res[:n_out], res[n_out:]
    outs = outs if len(outs) != 1 else outs[0]
    finals = finals if states_was_seq else (finals[0] if finals else [])
    return outs, finals


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int):
    """``mx.nd.contrib.while_loop`` over a bounded ``lax.scan``.

    ``cond_fn(*loop_vars) -> scalar bool``; ``func(*loop_vars) ->
    (step_output, new_loop_vars)``. Runs at most ``max_iterations`` steps;
    rows of the stacked outputs beyond the real iteration count are zeros
    (the reference leaves them undefined). Returns ``(outputs,
    final_loop_vars)``.

    Bounded scan (not a raw ``lax.while_loop``) because XLA requires static
    output shapes — the same reason the reference demands
    ``max_iterations`` up front.
    """
    vars_l = _listify(loop_vars)
    n_vars = len(vars_l)
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations (static shapes)")

    def raw_fn(*flat):
        def step(carry, _):
            alive, vs = carry
            nd_vs = [_as_nd(v) for v in vs]
            pred = jnp.logical_and(alive, _raw(cond_fn(*nd_vs)).astype(bool).reshape(()))

            def do(vs_in):
                out, new_vs = func(*[_as_nd(v) for v in vs_in])
                return (tuple(_raw(v) for v in _listify(new_vs)),
                        tuple(_raw(o) for o in _listify(out)))

            def skip(vs_in):
                out, new_vs = func(*[_as_nd(v) for v in vs_in])  # shape probe
                zeros = tuple(jnp.zeros_like(_raw(o)) for o in _listify(out))
                return tuple(vs_in), zeros

            new_vs, outs = lax.cond(pred, do, skip, tuple(vs))
            return (pred, new_vs), outs

        (_, final_vs), stacked = lax.scan(
            step, (jnp.bool_(True), tuple(flat)), None, length=max_iterations)
        return tuple(stacked) + tuple(final_vs)

    res, nout = _tape_call("while_loop", raw_fn, vars_l)
    n_out = nout - n_vars
    outs, finals = res[:n_out], res[n_out:]
    outs = outs if len(outs) != 1 else outs[0]
    return outs, finals


def cond(pred, then_func: Callable, else_func: Callable):
    """``mx.nd.contrib.cond``.

    Eager (concrete predicate): run exactly one branch in Python, like the
    reference's imperative path — no wasted compute, branch ops recorded on
    the autograd tape as usual. Traced (predicate is a jit tracer, e.g.
    inside ``hybridize``): lower to one ``lax.cond`` — both branches traced,
    one executed at runtime, no host sync on the predicate.
    """
    p_raw = _raw(pred)
    if not isinstance(p_raw, jax.core.Tracer):
        out = _listify((then_func if bool(p_raw.reshape(())) else else_func)())
        return out if len(out) != 1 else out[0]

    out = lax.cond(
        p_raw.astype(bool).reshape(()),
        lambda _: tuple(_raw(o) for o in _listify(then_func())),
        lambda _: tuple(_raw(o) for o in _listify(else_func())),
        None)
    res = [_as_nd(o) for o in out]
    return res if len(res) != 1 else res[0]
