"""``mx.contrib.onnx`` — ONNX export/import.

Reference: ``python/mxnet/contrib/onnx/`` (mx2onnx exporter + onnx2mx
importer). The reference requires the ``onnx`` pip package; this build
speaks the protobuf wire format directly (``proto.py``), so the files it
writes are standard ONNX and no third-party dependency is needed.
"""
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model  # noqa: F401

__all__ = ["export_model", "import_model"]
